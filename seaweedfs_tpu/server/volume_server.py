"""Volume server: data-plane node.

Reference: weed/server/volume_server.go:18-35 (public needle HTTP +
admin RPC), volume_server_handlers_read.go:30-169 (GET incl. cookie/TTL
checks, mime, etag), volume_server_handlers_write.go:19-73 (POST/DELETE w/
replication), volume_grpc_client_to_master.go:23-177 (heartbeat loop w/
leader chasing), volume_grpc_erasure_coding.go (EC shard lifecycle RPCs),
topology/store_replicate.go (replica fan-out).
"""

from __future__ import annotations

import asyncio
import gzip
import json
import os
import random
import re
import time
import urllib.parse

import aiohttp
from aiohttp import web

from .. import qos
from ..ec import gf
from ..ec import pipeline as ecpl
from ..ec.ec_volume import EcVolumeError
from ..pb import messages as pb
from ..util import batchframe, failpoints, glog, tracing
from ..util.resilience import BreakerRegistry
from ..storage import types as t
from ..storage.needle import CrcMismatch, Needle, NeedleError
from ..storage.backend import BackendError
from ..storage.store import Store
from ..storage.volume import AlreadyDeleted, NotFound, VolumeError
from ..security import tls
from . import wire


def _wk():
    """Lazy server.workers import (only -workers mode pays for it)."""
    from . import workers
    return workers


_FID_PATH = re.compile(r"^/(\d+),")


def _request_vid(req: "web.Request") -> int | None:
    """Volume id a request targets, for worker-partition routing:
    needle paths (`/<vid>,<fid>`) and admin routes carrying a
    volume/volumeId query param."""
    m = _FID_PATH.match(req.path)
    if m:
        return int(m.group(1))
    v = req.query.get("volume", "") or req.query.get("volumeId", "")
    return int(v) if v.isdigit() else None


class VolumeServer:
    def __init__(self, store: Store, master_url: str,
                 ip: str = "127.0.0.1", port: int = 8080,
                 data_center: str = "", rack: str = "",
                 pulse_seconds: float = 5.0,
                 read_redirect: bool = True,
                 jwt_key: str = "",
                 white_list: list[str] | None = None,
                 public_url: str = "",
                 worker_ctx=None,
                 batch_max: int = wire.BATCH_MAX_DEFAULT,
                 sendfile_min: int = wire.SENDFILE_MIN,
                 scrub_mbps: float = 8.0,
                 scrub_interval: float = 0.0,
                 scrub_pause_ms: float = 50.0,
                 scrub_batch: int | None = None):
        # -workers N process-per-core mode (server/workers.py): this
        # server is worker `ctx.index` of `ctx.total`, sharing the
        # public port via SO_REUSEPORT and owning vids % total == index
        self.worker_ctx = worker_ctx
        self.public_url = public_url
        # unified-wire knobs: most fids per /batch request (-batch.max),
        # the buffered-response byte budget one batch may hold, and the
        # zero-copy floor for raw-listener cold reads
        self.batch_max = batch_max
        self.batch_bytes_max = 64 << 20
        self.sendfile_min = sendfile_min
        from ..security.guard import Guard
        # -whiteList (volume.go:87,125): IP guard over the admin surface
        # and needle writes; reads stay open like the reference's public
        # port
        self.guard = Guard(white_list or ())
        self.jwt_key = jwt_key
        self.store = store
        # comma-separated seed list: chase the leader hint, rotate seeds on
        # total failure (volume_grpc_client_to_master.go:33-53)
        from ..util.client import parse_master_seeds
        self.master_seeds = parse_master_seeds(master_url)
        self.master_url = self.master_seeds[0]
        self._seed_idx = 0
        self.ip = ip
        self.port = port
        self.data_center = data_center
        self.rack = rack
        self.pulse_seconds = pulse_seconds
        self.read_redirect = read_redirect
        self.volume_size_limit = 30_000 * 1024 * 1024
        self._runner: web.AppRunner | None = None
        self._tasks: list[asyncio.Task] = []
        self._http: aiohttp.ClientSession | None = None
        self._hb_lock = asyncio.Lock()
        # per-sibling breakers: a crashed worker is answered 503 in
        # microseconds instead of a connect timeout per proxied request
        self._sibling_breakers = BreakerRegistry(
            threshold=3, reset_timeout=2.0)
        from .ec_locations import EcLocationCache
        self._ec_locations = EcLocationCache(self._lookup_ec_locations)
        # shared keep-alive pool for SYNC (executor-thread) shard/meta
        # fetches — one handshake per holder, not one per interval
        from ..util.connpool import SyncFramePool, SyncHttpPool
        self._sync_pool = SyncHttpPool(timeout=30.0)
        # binary sibling wire (util/frame.py): persistent multiplexed
        # frame channels to sibling workers (and, for the EC gather,
        # to remote shard holders) with automatic HTTP fallback
        from ..util.frame import FrameHub
        self.frame_hub = FrameHub(
            token=worker_ctx.token if worker_ctx is not None else "",
            ssl=tls.client_ctx(), jwt_key=jwt_key)
        self._sync_frames = SyncFramePool(
            timeout=30.0,
            token=worker_ctx.token if worker_ctx is not None else "",
            jwt_key=jwt_key)
        # targets that refused the frame handshake: jittered-backoff
        # re-probe gate (journals `frame_downgrade`), replacing the
        # old sticky 60s HTTP downgrade
        from ..util.connpool import FrameProbeGate
        self._frame_gate = FrameProbeGate()
        self._frame_uds = ""
        self._frame_server = None
        # per-vid serialization for /admin/ec/rebuild_shard: an
        # executor retry racing a still-running rebuild of the same
        # volume must queue behind it, never interleave fetches and
        # output writes into the same shard files
        self._rebuild_locks: dict[int, asyncio.Lock] = {}
        # paced background parity scrubber (-scrub.interval > 0 starts
        # the loop; the object always exists so POST /debug/scrub?run=1
        # can force a cycle even when the loop is off)
        from ..ec.scrub import Scrubber
        self.scrubber = Scrubber(store, mbps=scrub_mbps,
                                 interval_s=scrub_interval,
                                 pause_ms=scrub_pause_ms,
                                 batch_windows=scrub_batch)
        # bandwidth arbiter adoption (-qos.mbps): scrub pacing becomes
        # foreground-aware — the bucket swap is invisible to Scrubber
        arb = qos.arbiter()
        if arb is not None:
            self.scrubber.bucket = arb.adopt(
                "scrub", self.scrubber.bucket)
        self.app = self._build_app()
        store.fetch_remote_shard = None  # wired after start (needs loop)

    def _guarded_request(self, req: web.Request) -> bool:
        # needle writes: /admin/* is the inter-server mesh (master
        # allocate/vacuum, peer copy/EC — mTLS-scoped like the
        # reference's gRPC), so it is exempt ONLY while mTLS is actually
        # active; with -whiteList but no security.toml, an unlisted
        # client 401'd on public DELETE could otherwise still tombstone
        # needles via /admin/batch_delete or drop volumes via
        # /admin/volume/delete. When mTLS is off, /admin mutations are
        # guarded too and the master/peers must be whitelisted (warned
        # at start()). Replica forwards come from peer volume servers an
        # operator's client whitelist won't include, so they are exempt
        # ONLY when the cluster enforces write JWTs (the forwarded
        # per-fid token still authenticates them); without a jwt key the
        # exemption would be a trivial guard bypass, so peers must then
        # be whitelisted
        if req.method not in ("POST", "PUT", "DELETE"):
            return False
        if self.worker_ctx is not None and self.worker_ctx.token_ok(
                req.headers.get(_wk().WORKER_HEADER)):
            # intra-host worker hop: the entry worker already ran the
            # guard against the real client IP before proxying
            return False
        if req.path.startswith("/admin/") and tls.server_ctx() is not None:
            return False
        if req.query.get("type") == "replicate" and self.jwt_key:
            return False
        return True

    _TRACE_OPS = {"GET": "read", "HEAD": "read", "POST": "write",
                  "PUT": "write", "DELETE": "delete"}

    @web.middleware
    async def _trace_mw(self, req: web.Request, handler):
        """Volume-tier entry span for the aiohttp (cold) path — needle
        requests and the /admin mesh, never the introspection surface
        (/debug, /metrics, /status, ...). Outermost middleware, so the
        guard and the sibling-proxy hop are both inside the span.
        Only REGISTERED /admin routes derive an op label: the op feeds
        prometheus label values, and a scanner probing /admin/<junk>
        (this runs before the guard, and for 404s) must not mint
        unbounded label children in the registry."""
        p = req.path
        if _FID_PATH.match(p):
            op = self._TRACE_OPS.get(req.method, req.method.lower())
        elif p == "/batch":
            op = "batch"
        elif p in self._traced_admin:
            op = p[len("/admin/"):].replace("/", ".")
        else:
            return await handler(req)
        sp = tracing.start_root("volume", op, headers=req.headers)
        if not sp:
            return await handler(req)
        with sp:
            try:
                resp = await handler(req)
            except web.HTTPException as e:
                sp.status = str(e.status)
                raise
            sp.status = "ok" if resp.status < 400 else str(resp.status)
            if resp.content_length:
                sp.nbytes = resp.content_length
            return resp

    async def _in_executor(self, fn, *args):
        """Executor round-trip that carries the tracing context into
        the worker thread, so store/EC spans parent under the request
        span (tracing.run_in_executor)."""
        return await tracing.run_in_executor(fn, *args)

    @web.middleware
    async def _worker_route_mw(self, req: web.Request, handler):
        """-workers partition routing: a request for a volume owned by
        a sibling worker is proxied to that sibling's private listener.
        Runs AFTER the guard middleware so the entry worker enforces
        the whitelist against the real client IP; the hop itself is
        authenticated by the launch token (never re-proxied)."""
        wk = _wk()
        wc = self.worker_ctx
        if wc is None or wc.token_ok(req.headers.get(wk.WORKER_HEADER)):
            return await handler(req)
        vid = _request_vid(req)
        if vid is None or wc.owns(vid):
            return await handler(req)
        target = wc.owner_addr(vid)
        if target is None:
            return web.json_response(
                {"error": f"worker {wc.owner_index(vid)} (owner of "
                          f"volume {vid}) unavailable"}, status=503)
        br = self._sibling_breakers.get(target)
        if not br.allow():
            sp = tracing.current()
            sp.event("breaker_open", upstream=target)
            return web.json_response(
                {"error": f"worker {wc.owner_index(vid)} (owner of "
                          f"volume {vid}) circuit open"}, status=503)
        # the cross-worker hop is its own span, and proxy_request stamps
        # its traceparent on the forwarded request so the sibling's
        # server span nests under it — one trace across both workers.
        # The binary frame hop is tried first (transport=frame on the
        # span); any channel failure falls back to the HTTP hop, which
        # is also where streaming/oversized bodies always go.
        with tracing.start("proxy", "sibling", target=target,
                           worker=wc.owner_index(vid)) as sp:
            # the sibling-hop chaos site fires for BOTH transports —
            # an armed worker.proxy fault must keep tripping this
            # breaker exactly as it did when the hop was HTTP-only
            # (tools/soak.py slo depends on it), so it runs before
            # the frame attempt and takes the same 502 path
            try:
                await failpoints.fail("worker.proxy")
            except OSError as e:
                br.record_failure()
                sp.status = "502"
                return web.json_response(
                    {"error": f"worker proxy to {target}: {e}"},
                    status=502)
            from ..util.frame import FrameChannelError
            ch = self.sibling_frame_channel(wc.owner_index(vid))
            if ch is not None and wk.frame_eligible(req):
                try:
                    resp = await wk.proxy_request_frame(req, ch)
                except FrameChannelError as e:
                    # dead channel / peer predates frames / injected
                    # worker.frame fault: the HTTP hop is authoritative
                    sp.event("frame_fallback", error=str(e)[:120])
                else:
                    sp.set("transport", "frame")
                    br.record_success()
                    sp.status = "ok" if resp.status < 400 \
                        else str(resp.status)
                    return resp
            sp.set("transport", "http")
            resp = await wk.proxy_request(req, self._http, target,
                                          wc.token,
                                          fire_failpoint=False)
            if resp.status == 502:
                br.record_failure()
                sp.status = "502"
            else:
                br.record_success()
                sp.status = "ok" if resp.status < 400 else str(resp.status)
            return resp

    def _build_app(self) -> web.Application:
        from ..security.guard import middleware as guard_mw
        middlewares = [self._trace_mw,
                       guard_mw(lambda: self.guard,
                                self._guarded_request)]
        if self.worker_ctx is not None:
            middlewares.append(self._worker_route_mw)
        app = web.Application(
            client_max_size=1024 * 1024 * 1024,
            middlewares=middlewares)
        # admin API (gRPC-analog)
        app.router.add_post("/admin/volume/allocate", self.h_allocate)
        app.router.add_post("/admin/volume/delete", self.h_volume_delete)
        app.router.add_post("/admin/volume/readonly", self.h_readonly)
        app.router.add_post("/admin/volume/mount", self.h_volume_mount)
        app.router.add_post("/admin/volume/unmount", self.h_volume_unmount)
        app.router.add_post("/admin/volume/copy", self.h_volume_copy)
        app.router.add_get("/admin/volume/status", self.h_volume_status)
        app.router.add_get("/admin/volume/tail", self.h_volume_tail)
        app.router.add_post("/admin/volume/tail_receive",
                            self.h_volume_tail_receive)
        app.router.add_post("/admin/vacuum/check", self.h_vacuum_check)
        app.router.add_post("/admin/vacuum/compact", self.h_vacuum_compact)
        app.router.add_post("/admin/vacuum/commit", self.h_vacuum_commit)
        app.router.add_post("/admin/vacuum/cleanup", self.h_vacuum_cleanup)
        app.router.add_post("/admin/ec/generate", self.h_ec_generate)
        app.router.add_post("/admin/ec/generate_batch",
                            self.h_ec_generate_batch)
        app.router.add_post("/admin/ec/rebuild", self.h_ec_rebuild)
        app.router.add_post("/admin/ec/rebuild_shard",
                            self.h_ec_rebuild_shard)
        app.router.add_post("/admin/ec/verify", self.h_ec_verify)
        app.router.add_post("/admin/ec/mount", self.h_ec_mount)
        app.router.add_post("/admin/ec/unmount", self.h_ec_unmount)
        app.router.add_post("/admin/ec/copy", self.h_ec_copy)
        app.router.add_post("/admin/ec/delete_shards", self.h_ec_delete_shards)
        app.router.add_post("/admin/ec/to_volume", self.h_ec_to_volume)
        app.router.add_get("/admin/ec/shard_read", self.h_ec_shard_read)
        app.router.add_post("/admin/batch_delete", self.h_batch_delete)
        app.router.add_get("/admin/file", self.h_admin_file)
        app.router.add_post("/admin/query", self.h_query)
        app.router.add_post("/admin/tier/upload", self.h_tier_upload)
        app.router.add_post("/admin/tier/download", self.h_tier_download)
        app.router.add_route("*", "/debug/failpoints", self.h_failpoints)
        app.router.add_route("*", "/debug/scrub", self.h_scrub)
        app.router.add_get("/debug/breakers", self.h_breakers)
        app.router.add_get("/debug/traces", self.h_traces)
        app.router.add_get("/debug/requests", self.h_requests)
        # flight recorder: metrics timelines, event journal, SLO health
        app.router.add_get("/debug/timeline", self.h_timeline)
        app.router.add_post("/debug/timeline", self.h_timeline)
        app.router.add_get("/debug/events", self.h_events)
        app.router.add_get("/debug/health", self.h_health)
        app.router.add_get("/debug/qos", self.h_qos)
        # continuous sampling profiler + on-demand pprof dumps, both
        # -workers merged/fanned like every debug surface
        app.router.add_get("/debug/profile", self.h_profile)
        app.router.add_get("/debug/pprof", self.h_pprof)
        app.router.add_get("/status", self.h_status)
        app.router.add_get("/metrics", self.h_metrics)
        app.router.add_get("/stats/workers", self.h_stats_workers)
        app.router.add_get("/ui", self.h_ui)
        # pipelined multi-needle GET (unified wire batch path); POST
        # form carries long fid lists as a JSON body
        app.router.add_get("/batch", self.h_batch)
        app.router.add_post("/batch", self.h_batch)
        # public needle API — catch-all LAST
        app.router.add_route("GET", "/{fid:[^/]+}", self.h_get)
        app.router.add_route("HEAD", "/{fid:[^/]+}", self.h_get)
        app.router.add_route("POST", "/{fid:[^/]+}", self.h_post)
        app.router.add_route("PUT", "/{fid:[^/]+}", self.h_post)
        app.router.add_route("DELETE", "/{fid:[^/]+}", self.h_delete)
        # the registered admin routes are the ONLY paths the trace
        # middleware will turn into an op label (bounded cardinality)
        self._traced_admin = frozenset(
            res.canonical for res in app.router.resources()
            if res.canonical.startswith("/admin/"))
        return app

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    async def start(self) -> None:
        if not self.guard.empty and tls.server_ctx() is None:
            glog.warning(
                "-whiteList without security.toml mTLS: /admin "
                "mutations are whitelist-guarded too — the master and "
                "peer volume servers must be in the whitelist")
        self._http = tls.make_session(
            timeout=aiohttp.ClientTimeout(total=60))
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        # the public listener speaks the hand-rolled needle fast path
        # (fasthttp.py); cold requests upgrade in place onto the aiohttp
        # app served by self._runner
        from .fasthttp import FastNeedleProtocol
        loop = asyncio.get_running_loop()
        wc = self.worker_ctx
        self._server = await loop.create_server(
            lambda: FastNeedleProtocol(self), self.ip,
            wc.public_port if wc is not None else self.port,
            ssl=tls.server_ctx(), reuse_address=True,
            reuse_port=wc is not None)
        if wc is not None:
            # worker mode: the shared SO_REUSEPORT port is the public
            # face; a second private listener is this worker's identity
            # — the master registers it as its own node, so
            # master-directed traffic goes straight to the owner and
            # siblings/supervisor can address this worker specifically
            self._priv_server = await loop.create_server(
                lambda: FastNeedleProtocol(self), self.ip, 0,
                ssl=tls.server_ctx(), reuse_address=True)
            self.port = self._priv_server.sockets[0].getsockname()[1]
        elif self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]
        self.store.ip = self.ip
        self.store.port = self.port
        if self.public_url:
            # -publicUrl (volume.go:60): the externally reachable
            # address advertised in heartbeats/locations
            self.store.public_url = self.public_url
        elif wc is not None:
            self.store.public_url = f"{self.ip}:{wc.public_port}"
        elif not self.store.public_url or \
                self.store.public_url.endswith(":0"):
            self.store.public_url = self.url
        if wc is not None:
            # per-worker unix-socket frame listener: the preferred
            # intra-host transport for the binary sibling wire (TCP to
            # the private port, magic-sniffed, is the fallback). Bound
            # only when the path fits sockaddr_un.
            from .frameserver import FrameServerProtocol
            sock_path = os.path.join(wc.state_dir,
                                     f"w{wc.index}.sock")
            if len(sock_path) < 100 and hasattr(loop,
                                                "create_unix_server"):
                try:
                    await self._in_executor(self._unlink_quiet,
                                            sock_path)
                    self._frame_server = await loop.create_unix_server(
                        lambda: FrameServerProtocol(self), sock_path)
                    self._frame_uds = sock_path
                except OSError as e:
                    glog.warning("frame unix listener %s: %s (TCP "
                                 "fallback only)", sock_path, e)
            wc.write_state(ip=self.ip, port=self.port, role="volume",
                           frame_sock=self._frame_uds)
        # remote EC shard reads run inside executor threads, so they use a
        # synchronous client (readRemoteEcShardInterval, store_ec.go:211+);
        # the batched form gathers one request per holder
        self.store.fetch_remote_shard = self._sync_fetch_remote_shard
        self.store.fetch_remote_shard_batch = \
            self._sync_fetch_remote_shard_batch
        # repair-planning hooks: holder grouping from the location
        # cache (no I/O) and the refresh-once-on-failed-batch-gather
        # re-resolve (ec_volume._recover_interval)
        self.store.ec_holder_peek = self._peek_ec_holders
        self.store.ec_refresh_holders = self._ec_locations.invalidate
        self._tasks.append(asyncio.create_task(self._heartbeat_loop()))
        if self.scrubber.interval_s > 0:
            # long-lived paced loop: handle retained here and cancelled
            # in stop() (the orphan-task discipline for background
            # scrub-pattern tasks)
            self._tasks.append(asyncio.create_task(self.scrubber.run()))

    async def stop(self) -> None:
        for task in self._tasks:
            task.cancel()
        if self._http:
            await self._http.close()
        if getattr(self, "_server", None) is not None:
            self._server.close()
            # NOT wait_closed(): since 3.12 it waits for every open
            # keep-alive connection; drop fast-path transports directly
            for tr in list(getattr(self, "_fast_conns", ())):
                tr.close()
        if getattr(self, "_priv_server", None) is not None:
            self._priv_server.close()
        if self._frame_server is not None:
            self._frame_server.close()
        await self.frame_hub.close()
        if self._runner:
            await self._runner.cleanup()
        if self._frame_uds:
            await self._in_executor(self._unlink_quiet, self._frame_uds)
        self._sync_pool.close()
        self._sync_frames.close()
        self.store.close()

    @staticmethod
    def _unlink_quiet(path: str) -> None:
        try:
            os.unlink(path)
        except OSError:
            pass

    def sibling_frame_channel(self, idx: int):
        """Persistent frame channel to sibling worker `idx` (unix
        socket preferred, private TCP fallback), or None while the
        sibling is down / frames are unavailable. Channels are cached
        per destination, so a respawned sibling (new socket path or
        port) transparently gets a fresh channel."""
        wc = self.worker_ctx
        if wc is None:
            return None
        uds, tcp = wc.sibling_frame(idx)
        if not uds and not tcp:
            return None
        return self.frame_hub.get(target=tcp, uds_path=uds)

    _counters: dict = None  # type: ignore[assignment]

    def count(self, op: str, status: str) -> None:
        """Cheap request-counter hook for the fast path (labels cached)."""
        from ..stats import metrics
        if not metrics.HAVE_PROMETHEUS:
            return
        if self._counters is None:
            self._counters = {}
        c = self._counters.get((op, status))
        if c is None:
            c = self._counters[(op, status)] = \
                metrics.VOLUME_REQUEST_COUNTER.labels(op, status)
        c.inc()

    def _lookup_ec_locations(self, vid: int) -> dict | None:
        """One master /vol/ec_lookup call (executor threads only),
        over the shared keep-alive pool."""
        import json as _json
        failpoints.sync_fail("volume.ec_fetch")
        status, body = self._sync_pool.request(
            self.master_url, f"/vol/ec_lookup?volumeId={vid}")
        if status != 200:
            raise OSError(f"ec_lookup {vid}: http {status}")
        return _json.loads(body)["shards"]

    def _peek_ec_holders(self, vid: int) -> dict | None:
        """{sid: first non-self holder} from the location cache with NO
        lookup I/O — the repair planner's grouping input. None when the
        cache has nothing yet (the plan degrades to sid order and the
        actual fetch resolves holders as before)."""
        locs = self._ec_locations.peek(vid)
        if locs is None:
            return None
        out: dict[int, str] = {}
        for sid_s, urls in locs.items():
            for u in urls:
                if u != self.url:
                    out[int(sid_s)] = u
                    break
        return out

    def _sync_shard_fetch(self, target: str, query: dict,
                          headers: dict) -> tuple[int, bytes]:
        """One /admin/ec/shard_read fetch (executor threads only):
        frame path first — tens of bytes of protocol overhead per
        gather instead of HTTP headers — with a jittered-backoff
        re-probe gate when the holder refused the frame handshake
        (predates the protocol; journaled as `frame_downgrade`), and a
        one-shot HTTP retry when the frame transport failed
        mid-flight."""
        from ..util.connpool import FrameUnsupported, PoolError
        path = "/admin/ec/shard_read"
        http_path = path + "?" + urllib.parse.urlencode(query)
        if self._frame_gate.allow(target):
            try:
                # chaos site: injected inter-host EC gather frame
                # faults take the exact ride-HTTP-this-request path a
                # mid-flight transport failure does
                failpoints.sync_fail("ec.fetch.frame")
                out = self._sync_frames.request(
                    target, path, headers=headers, query=query)
                self._frame_gate.ok(target)
                return out
            except FrameUnsupported as e:
                self._frame_gate.refused(target, str(e))
            except (PoolError, OSError) as e:
                # transport failure, not a protocol refusal: this
                # request rides HTTP, the next one retries frames
                glog.V(1).infof("shard fetch %s over frames: %s; "
                                "retrying over HTTP", target, e)
        return self._sync_pool.request(target, http_path,
                                       headers=headers)

    def _sync_fetch_remote_shard(self, vid: int, shard_id: int,
                                 offset: int, size: int) -> bytes | None:
        """Blocking remote shard interval fetch; locations come from the
        staleness-tiered cache (store_ec.go:218-259) so a degraded-read
        burst costs one master lookup, not one per interval, and the
        connection comes from the shared keep-alive pool so it costs
        one handshake per holder, not one per interval."""
        shards = self._ec_locations.get(vid)
        if shards is None:
            return None
        # runs inside the executor thread whose context the read path
        # copied in, so the store span is current here — stamping the
        # traceparent keeps the remote holder's shard_read span in THIS
        # request's trace
        trace_headers: dict = {}
        tracing.inject(trace_headers)
        attempted = False
        for target in shards.get(str(shard_id), []):
            if target == self.url:
                continue
            attempted = True
            try:
                failpoints.sync_fail("volume.ec_fetch")
                status, body = self._sync_shard_fetch(
                    target,
                    {"volume": str(vid),
                     "reads": f"{shard_id}:{offset}:{size}"},
                    trace_headers)
                if status == 200:
                    rows = batchframe.parse_all(body)
                    if rows and rows[0][0].get("status") == 200 \
                            and len(rows[0][1]) == size:
                        return rows[0][1]
                glog.warning("remote ec shard %d.%d from %s: "
                             "status %d, %d bytes", vid, shard_id,
                             target, status, len(body))
            except (OSError, ValueError) as e:
                # PoolError/timeouts/torn framing: a swallowed holder
                # failure must be visible
                glog.warning("remote ec shard %d.%d from %s: %s",
                             vid, shard_id, target, e)
                continue
        if attempted or not shards.get(str(shard_id)):
            # a listed holder failed to serve — or the map lists NO
            # holder for this shard: either way the topology may have
            # moved under us (the autopilot re-hosts lost shards, and
            # a map cached during the outage window would otherwise
            # hide the repaired shard for the full TTL), so make the
            # next read re-resolve. invalidate() is rate-bounded to
            # one forced lookup per FRESH_S per vid, so a genuinely
            # lost shard costs one master round trip per 11s, not one
            # per probe; this read still reconstructs as before.
            self._ec_locations.invalidate(vid)
        return None

    def _sync_fetch_remote_shard_batch(
            self, vid: int, reads: "list[tuple[int, int, int]]"
            ) -> "dict[int, bytes] | None":
        """Batched remote shard gather for the recover path: group the
        wanted (shard, offset, size) intervals by HOLDER and issue one
        `/admin/ec/shard_read?reads=...` per holder — the k-fetch
        network fan-out of a degraded read collapses to one round trip
        per surviving server (arxiv 1309.0186's recovery-cost shape)."""
        shards = self._ec_locations.get(vid)
        if shards is None:
            return None
        by_holder: dict[str, list[tuple[int, int, int]]] = {}
        for sid, off, size in reads:
            for target in shards.get(str(sid), []):
                if target != self.url:
                    by_holder.setdefault(target, []).append(
                        (sid, off, size))
                    break
        if not by_holder:
            # none of the wanted shards has a (non-self) listed holder:
            # same stale-outage-map hazard as the single fetch above —
            # schedule a rate-bounded re-resolve so a just-re-hosted
            # shard becomes visible within FRESH_S, not TTL_S
            self._ec_locations.invalidate(vid)
            return None
        trace_headers: dict = {}
        tracing.inject(trace_headers)
        out: dict[int, bytes] = {}
        failed = False
        for target, group in by_holder.items():
            spec = ",".join(f"{sid}:{off}:{size}"
                            for sid, off, size in group)
            try:
                failpoints.sync_fail("volume.ec_fetch")
                status, body = self._sync_shard_fetch(
                    target, {"volume": str(vid), "reads": spec},
                    trace_headers)
                if status != 200:
                    raise OSError(f"status {status}")
                rows = batchframe.parse_all(body)
            except (OSError, ValueError) as e:
                glog.warning("batched ec gather %d from %s (%d "
                             "intervals): %s", vid, target,
                             len(group), e)
                failed = True
                continue
            for (sid, _, size), (meta, data) in zip(group, rows):
                if meta.get("status") == 200 and len(data) == size:
                    out[sid] = data
                else:
                    failed = True
        if failed:
            self._ec_locations.invalidate(vid)
        return out or None

    # ---- heartbeat loop ----

    def _requeue_deltas(self, hb) -> None:
        """Put consumed heartbeat deltas back so they reach the master on
        the next successful pulse."""
        self.store.new_volumes.extend(hb.new_volumes)
        self.store.deleted_volumes.extend(hb.deleted_volumes)
        self.store.new_ec_shards.extend(hb.new_ec_shards)
        self.store.deleted_ec_shards.extend(hb.deleted_ec_shards)

    async def _frame_master_json(self, method: str, path: str,
                                 query: dict | None = None,
                                 payload: dict | None = None,
                                 deadline: float = 10.0):
        """One master control-plane request over the persistent frame
        channel, parsed as JSON; None when the frame leg is
        unavailable (peer predates frames, channel severed, breaker
        open, non-JSON answer) so the caller rides HTTP. Failure here
        never raises: the HTTP leg is the one whose errors drive
        seed rotation / retry policy."""
        try:
            # chaos site: worker.frame (also armed inside the channel
            # send itself) severs this control-plane frame leg so the
            # HTTP fallback is exercised
            await failpoints.fail("worker.frame")
            chan = self.frame_hub.get(target=self.master_url)
            status, _, raw = await chan.request(
                method, path, query=query,
                headers={"content-type": "application/json"}
                if payload is not None else None,
                body=json.dumps(payload).encode()
                if payload is not None else b"",
                timeout=deadline)
            if status >= 500:
                return None
            return json.loads(raw)
        except (asyncio.TimeoutError, OSError, ValueError):
            return None

    async def _frame_master_post(self, path: str, payload: dict,
                                 deadline: float):
        return await self._frame_master_json("POST", path,
                                             payload=payload,
                                             deadline=deadline)

    async def heartbeat_once(self) -> bool:
        """Returns True when the (leader) master accepted the state;
        False when a follower redirected us (deltas requeued, master_url
        now points at the leader). Serialized: a stale full-state
        snapshot posted concurrently could land AFTER a newer one and
        un-register just-mounted shards (register_heartbeat replaces the
        node's state wholesale)."""
        async with self._hb_lock:
            from ..stats import metrics
            if metrics.HAVE_PROMETHEUS:
                metrics.VOLUME_COUNT.set(len(self.store.volumes))
            hb = self.store.collect_heartbeat(self.data_center, self.rack)
            hb_dict = hb.to_dict()
            # ride the pulse: report this node's foreground byte rate
            # so the leader's bandwidth arbiter sees cluster-wide
            # pressure, and pick up the published budget on the way back
            arb = qos.arbiter()
            if arb is not None:
                hb_dict["qos_fg_bps"] = round(arb.foreground_bps(), 1)
            try:
                # injected heartbeat faults (FailpointError is an
                # OSError) take the exact requeue-and-rotate path a
                # real dead master does
                await failpoints.fail("volume.heartbeat")
                # per-request timeout: a master that accepts the TCP
                # connect but never answers must not wedge the pulse
                # loop for the session default
                deadline = max(10.0, 4 * self.pulse_seconds)
                body = await self._frame_master_post(
                    "/cluster/heartbeat", hb_dict, deadline)
                if body is not None and body.get("rejected") \
                        and body.get("leader") \
                        and body["leader"] != self.master_url:
                    # follower hint over frames: re-home and re-send
                    # THIS pulse on the leader's channel, so frame
                    # re-homing costs zero pulses exactly like the
                    # HTTP path's auto-followed 307
                    self.master_url = body["leader"]
                    body = await self._frame_master_post(
                        "/cluster/heartbeat", hb_dict, deadline)
                if body is None:
                    async with self._http.post(
                            tls.url(self.master_url,
                                    "/cluster/heartbeat"),
                            json=hb_dict,
                            timeout=aiohttp.ClientTimeout(
                                total=deadline,
                                connect=5, sock_read=max(
                                    5.0, 2 * self.pulse_seconds))) as resp:
                        body = await resp.json()
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
                self._requeue_deltas(hb)
                raise
            leader = body.get("leader")
            if body.get("rejected"):
                # a follower master refused registration: requeue deltas
                # and chase the leader it pointed at
                self._requeue_deltas(hb)
                if leader:
                    self.master_url = leader
                    return False
                # rejected with no leader known: treat as failure so the
                # heartbeat loop rotates to another seed master
                raise OSError(
                    f"master {self.master_url} rejected heartbeat, "
                    f"no leader")
            self.volume_size_limit = body.get(
                "volume_size_limit", self.volume_size_limit)
            if arb is not None and "qos_mbps" in body:
                arb.set_budget_mbps(body["qos_mbps"])
            if leader and leader != self.master_url:
                glog.info("volume %s: chasing new master leader %s "
                          "(was %s)", self.url, leader, self.master_url)
                self.master_url = leader
            return True

    async def _heartbeat_loop(self) -> None:
        while True:
            try:
                await self.heartbeat_once()
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
                # current master unreachable: rotate through seed masters
                # (with one seed this still resets master_url back to the
                # configured seed after a learned leader dies)
                glog.V(1).infof("volume %s: heartbeat to %s failed (%s); "
                                "rotating seed", self.url,
                                self.master_url, e)
                self._seed_idx = (self._seed_idx + 1) \
                    % len(self.master_seeds)
                self.master_url = self.master_seeds[self._seed_idx]
            # ±20% jitter: a restarted master must not be hit by the
            # whole fleet's pulses in one synchronized herd
            await asyncio.sleep(
                self.pulse_seconds * random.uniform(0.8, 1.2))

    # ---- public needle handlers (adapters over server/wire.py) ----

    @staticmethod
    def _parse_fid(fid: str) -> t.FileId:
        return t.FileId.parse(fid)

    def _wire_request(self, req: web.Request,
                      body: bytes | None = None) -> wire.WireRequest:
        return wire.WireRequest(
            method=req.method, fid_s=req.match_info.get("fid", ""),
            query=dict(req.query),
            headers={k.lower(): v for k, v in req.headers.items()},
            peer_ip=req.remote, body=body, raw=False,
            worker_hop=self._is_worker_hop(req))

    async def _wire_response(self, req: web.Request,
                             resp: wire.WireResponse
                             ) -> web.StreamResponse:
        """Render a WireResponse through aiohttp — the transport-level
        twin of the raw listener's byte renderer."""
        if resp.drop:
            # injected connection drop: sever, don't answer
            if req.transport is not None:
                req.transport.close()
            return web.Response(status=500)
        if resp.manifest is not None:
            # resolve the manifest into the assembled file
            # (tryHandleChunkedFile, volume_server_handlers_read.go:170)
            return await self._serve_chunked_file(req, resp.manifest,
                                                  resp.headers)
        if resp.truncate_to >= 0:
            # chaos truncate: full Content-Length, partial body, dead
            # socket — the mid-read death degraded reads must survive
            sr = web.StreamResponse(status=resp.status, headers={
                **resp.headers, "Content-Length": str(len(resp.body))})
            sr.content_type = resp.content_type
            await sr.prepare(req)
            await sr.write(resp.body[:resp.truncate_to])
            if req.transport is not None:
                req.transport.close()
            return sr
        if resp.sendfile is not None:
            # zero-copy on the aiohttp listener too: a StreamResponse
            # drains the NeedleRef region via loop.sendfile (the same
            # shape aiohttp's own FileResponse uses), with a buffered
            # executor-pread fallback where the transport refuses
            return await self._respond_sendfile_web(req, resp)
        ct, _, rest = resp.content_type.partition(";")
        charset = rest.partition("charset=")[2].strip() or None
        if resp.head or resp.status in (304, 301):
            if not resp.head:
                return web.Response(status=resp.status,
                                    headers=resp.headers)
            return web.Response(status=resp.status, headers=resp.headers,
                                content_type=ct, charset=charset)
        return web.Response(body=resp.body, status=resp.status,
                            headers=resp.headers,
                            content_type=ct, charset=charset)

    async def _respond_sendfile_web(self, req: web.Request,
                                    resp: wire.WireResponse
                                    ) -> web.StreamResponse:
        """Drain a NeedleRef through aiohttp: kernel sendfile on plain
        TCP transports (after the headers flush, exactly like
        web.FileResponse), executor-chunked preads through the normal
        writer where the transport refuses (TLS, tests' mocked
        transports)."""
        ref = resp.sendfile
        try:
            ct, _, rest = resp.content_type.partition(";")
            sr = web.StreamResponse(status=resp.status,
                                    headers=resp.headers)
            sr.content_type = ct
            charset = rest.partition("charset=")[2].strip()
            if charset:
                sr.charset = charset
            sr.content_length = ref.length
            await sr.prepare(req)
            transport = req.transport
            kernel_ok = (transport is not None
                         and transport.get_extra_info("sslcontext")
                         is None
                         and transport.get_extra_info("socket")
                         is not None)
            if kernel_ok:
                try:
                    await asyncio.get_running_loop().sendfile(
                        transport, ref.file, ref.offset, ref.length,
                        fallback=False)
                    await sr.write_eof()
                    return sr
                except (NotImplementedError, RuntimeError,
                        AttributeError):
                    pass              # transport refused: buffered path
                except OSError:
                    # mid-send tear: the declared Content-Length can no
                    # longer be honored — sever like a buffered tear
                    transport.close()
                    return sr
            off, remaining = ref.offset, ref.length
            fd = ref.file.fileno()
            while remaining:
                chunk = await self._in_executor(
                    os.pread, fd, min(1 << 20, remaining), off)
                if not chunk:
                    break             # truncated under us: short body
                await sr.write(chunk)
                off += len(chunk)
                remaining -= len(chunk)
            await sr.write_eof()
            return sr
        finally:
            ref.close()

    async def h_get(self, req: web.Request) -> web.StreamResponse:
        wr = self._wire_request(req)
        return await self._wire_response(
            req, await wire.serve_read(self, wr))

    async def h_batch(self, req: web.Request) -> web.StreamResponse:
        """Pipelined multi-needle GET (`/batch?fids=...` or a POSTed
        {"fileIds": [...]}) — wire.serve_batch: cache hits inline,
        cold preads coalesced, sibling fan-out by vid ownership."""
        body = None
        if req.method == "POST" and req.can_read_body:
            body = await req.read()
        wr = self._wire_request(req, body)
        return await self._wire_response(
            req, await wire.serve_batch(self, wr))

    def _weed_client(self):
        """Lazily-built client for chunk fetches (lookup-cached)."""
        if getattr(self, "_wclient", None) is None:
            from ..util.client import WeedClient
            self._wclient = WeedClient(self.master_url,
                                       session=self._http,
                                       jwt_key=self.jwt_key)
        # track master failover: the heartbeat loop reassigns
        # self.master_url when the leader changes
        self._wclient.master_url = self.master_url
        return self._wclient

    async def _serve_chunked_file(self, req: web.Request, n: Needle,
                                  extra_headers: dict | None = None
                                  ) -> web.StreamResponse:
        """tryHandleChunkedFile (volume_server_handlers_read.go:170-199):
        the needle body is a ChunkManifest; stream the assembled bytes,
        honoring Range so large files never fully buffer."""
        from ..util.chunked import ChunkManifest
        from ..util.client import OperationError
        from ..util.httprange import RangeError, parse_range
        try:
            cm = ChunkManifest.load(n.data, n.is_gzipped)
        except (ValueError, KeyError) as e:
            return web.json_response(
                {"error": f"bad chunk manifest: {e}"}, status=500)
        headers = {"Accept-Ranges": "bytes", "Etag": f'"{n.etag()}"'}
        if extra_headers:
            # pairs + Last-Modified computed by h_get ride along
            headers.update(extra_headers)
        ct = cm.mime or (n.mime.decode() if n.mime
                         else "application/octet-stream")
        if cm.name:
            if not cm.mime and not n.mime:
                ct = wire._guess_mime(cm.name, ct)
            headers["Content-Disposition"] = wire._disposition(
                dict(req.query), cm.name)
        try:
            rng = parse_range(req.headers.get("Range", ""), cm.size)
        except RangeError:
            return web.Response(
                status=416,
                headers={"Content-Range": f"bytes */{cm.size}"})
        off, ln = rng if rng is not None else (0, cm.size)
        status = 206 if rng is not None else 200
        if rng is not None:
            headers["Content-Range"] = f"bytes {off}-{off+ln-1}/{cm.size}"
        headers["Content-Length"] = str(ln)
        if req.method == "HEAD":
            return web.Response(status=status, headers=headers,
                                content_type=ct)
        resp = web.StreamResponse(status=status, headers=headers)
        resp.content_type = ct
        await resp.prepare(req)
        client = self._weed_client()
        pieces = cm.resolve(off, ln)
        sizes = {c.fid: c.size for c in cm.chunks}
        i = 0
        truncated = False
        while i < len(pieces) and not truncated:
            # WHOLE small chunks batch into one multi-needle GET per
            # window (bounded bytes so large files never fully buffer);
            # partial/large pieces keep the ranged single-GET path
            win: list = []
            win_bytes = 0
            while i < len(pieces) and len(win) < 32 \
                    and win_bytes < (4 << 20):
                fid, c_off, c_len, _ = pieces[i]
                if c_off == 0 and c_len == sizes.get(fid) \
                        and c_len <= (1 << 20):
                    win.append(pieces[i])
                    win_bytes += c_len
                    i += 1
                else:
                    break
            if len(win) > 1:
                got = await client.batch_read([p[0] for p in win])
                for fid, _, _, _ in win:
                    piece = got.get(fid)
                    if piece is None:
                        truncated = True
                        break  # stream truncates; client sees short body
                    await resp.write(piece)
                continue
            if win:
                fid, c_off, c_len, _ = win[0]
            else:
                fid, c_off, c_len, _ = pieces[i]
                i += 1
            try:
                piece = await client.read(fid, offset=c_off, size=c_len)
            except OperationError:
                break  # stream truncates; client sees short body
            await resp.write(piece)
        await resp.write_eof()
        return resp

    async def h_post(self, req: web.Request) -> web.StreamResponse:
        """Write adapter: only TRANSPORT framing is unpacked here
        (multipart parts vs raw body); needle build, jwt guard, the
        group-commit store append and replication fan-out are
        wire.serve_write — the same code the raw listener runs."""
        # token guard BEFORE any body parsing: an unauthenticated
        # client must not get to drive multipart/EXIF work (or read
        # build-time diagnostics) on a jwt-protected server
        denied = wire.check_jwt(self, self._wire_request(req))
        if denied is not None:
            return await self._wire_response(req, denied)
        ctype = req.headers.get("Content-Type", "")
        n = None
        body = None
        if req.headers.get("X-Raw-Needle") == "1":
            body = await req.read()
        elif ctype.startswith("multipart/form-data"):
            name = b""
            mime = b""
            data = b""
            reader = await req.multipart()
            async for part in reader:
                if part.name in ("file", "upload", None) or part.filename:
                    data = await part.read(decode=False)
                    if part.filename:
                        name = part.filename.encode()
                    pct = part.headers.get("Content-Type", "")
                    if pct and pct != "application/octet-stream":
                        mime = pct.encode()
                    break
            try:
                fid = self._parse_fid(req.match_info["fid"])
            except ValueError as e:
                return web.json_response({"error": str(e)}, status=400)
            wr = self._wire_request(req)
            try:
                n = wire.build_needle(fid, wr, data, name=name,
                                      mime=mime)
            except (NeedleError, ValueError) as e:
                return web.json_response({"error": str(e)}, status=400)
        else:
            body = await req.read()
        wr = self._wire_request(req, body)
        return await self._wire_response(
            req, await wire.serve_write(self, wr, n))

    async def h_delete(self, req: web.Request) -> web.StreamResponse:
        wr = self._wire_request(req)
        return await self._wire_response(
            req, await wire.serve_delete(self, wr))

    async def h_batch_delete(self, req: web.Request) -> web.Response:
        """One request tombstones many needles locally, with a per-fid
        result row (BatchDelete, volume_grpc_batch_delete.go:13-75).
        Replica/EC fan-out is the CLIENT's job — delete_content.go groups
        fids by holding server — so this endpoint never cascades; chunk
        manifests are rejected for the same reason."""
        try:
            body = await req.json()
        except ValueError:
            body = None
        if not isinstance(body, dict) or \
                not isinstance(body.get("fileIds", []), list):
            return web.json_response({"error": "bad json body"},
                                     status=400)
        fids = body.get("fileIds", [])
        tokens = body.get("tokens", {})
        if not isinstance(tokens, dict):
            tokens = {}

        def one(fid_s) -> dict:
            if not isinstance(fid_s, str):
                return {"fileId": str(fid_s), "status": 400,
                        "error": "fileId must be a string"}
            if self.jwt_key:
                # the batch path must not bypass the write-token guard
                # the public DELETE enforces (handlers_write.go:41-44)
                from ..security.jwt import JwtError, check_write_jwt
                try:
                    check_write_jwt(self.jwt_key,
                                    str(tokens.get(fid_s, "")), fid_s)
                except JwtError as e:
                    return {"fileId": fid_s, "status": 401,
                            "error": str(e)}
            try:
                fid = self._parse_fid(fid_s)
            except ValueError as e:
                return {"fileId": fid_s, "status": 400, "error": str(e)}
            try:
                existing = self.store.read_needle(
                    fid.volume_id, fid.key, fid.cookie)
            except (NotFound, AlreadyDeleted) as e:
                return {"fileId": fid_s, "status": 404,
                        "error": str(e) or "not found"}
            except (CrcMismatch, VolumeError, BackendError) as e:
                return {"fileId": fid_s, "status": 500, "error": str(e)}
            if existing.is_chunked_manifest:
                return {"fileId": fid_s, "status": 406, "error":
                        "ChunkManifest: not allowed in batch delete mode."}
            try:
                size = self.store.delete_needle(
                    fid.volume_id, Needle(cookie=fid.cookie, id=fid.key))
            except (NotFound, VolumeError) as e:
                return {"fileId": fid_s, "status": 500, "error": str(e)}
            return {"fileId": fid_s, "status": 202, "size": size}

        wc = self.worker_ctx
        if wc is None or self._is_worker_hop(req):
            results = await self._in_executor(lambda: [one(f) for f in fids])
            return web.json_response({"results": results})
        # -workers: a batch spans partitions — split by owning worker,
        # delete the local group here, forward each sibling its group,
        # and reassemble results in request order
        groups: dict[int, list] = {}
        for f in fids:
            try:
                idx = wc.owner_index(int(str(f).split(",")[0]))
            except ValueError:
                idx = wc.index       # malformed: local path 400s it
            groups.setdefault(idx, []).append(f)
        by_fid: dict[str, dict] = {}
        local = groups.pop(wc.index, [])
        for r in await self._in_executor(lambda: [one(f) for f in local]):
            by_fid[r["fileId"]] = r

        async def forward(idx: int, group: list) -> None:
            addr = wc.sibling_addr(idx)
            sub = {"fileIds": group,
                   "tokens": {str(f): tokens[str(f)] for f in group
                              if str(f) in tokens}}
            rows = None
            if addr is not None:
                try:
                    await failpoints.fail("worker.forward")
                    async with self._http.post(
                            tls.url(addr, "/admin/batch_delete"),
                            json=sub,
                            headers={_wk().WORKER_HEADER: wc.token},
                            timeout=aiohttp.ClientTimeout(
                                total=30)) as resp:
                        if resp.status == 200:
                            rows = (await resp.json())["results"]
                except (aiohttp.ClientError, asyncio.TimeoutError,
                        OSError, ValueError, KeyError):
                    rows = None
            if rows is None:
                rows = [{"fileId": str(f), "status": 503,
                         "error": f"worker {idx} unavailable"}
                        for f in group]
            for r in rows:
                by_fid[r["fileId"]] = r

        await asyncio.gather(*(forward(i, g) for i, g in groups.items()))
        results = [by_fid.get(str(f),
                              {"fileId": str(f), "status": 500,
                               "error": "no result"}) for f in fids]
        return web.json_response({"results": results})

    async def _ec_delete_broadcast(self, vid: int, fid: str,
                                   auth: str = "") -> None:
        try:
            await failpoints.fail("volume.ec_broadcast")
            async with self._http.get(
                    tls.url(self.master_url, "/vol/ec_lookup"),
                    params={"volumeId": str(vid)}) as resp:
                if resp.status != 200:
                    return
                shards = (await resp.json())["shards"]
        except aiohttp.ClientError:
            return
        targets = {u for urls in shards.values() for u in urls} - {self.url}

        headers = {"Authorization": auth} if auth else {}

        async def one(target: str) -> None:
            try:
                await failpoints.fail("volume.ec_broadcast")
                async with self._http.delete(
                        tls.url(target, f"/{fid}"),
                        params={"type": "replicate"},
                        headers=headers) as r:
                    await r.read()
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
                # best-effort broadcast, but a holder that kept its
                # shard tombstone-free must be visible in the logs
                glog.warning("ec delete %s: broadcast to %s failed: %s",
                             fid, target, e)

        await asyncio.gather(*(one(u) for u in targets))

    async def _replicate(self, fid: str, method: str,
                         raw_needle: bytes | None,
                         auth: str = "") -> bool:
        """Fan out to the other replica locations
        (distributedOperation, store_replicate.go:140-155)."""
        vid = fid.split(",")[0]
        locs = None
        body = await self._frame_master_json("GET", "/dir/lookup",
                                             query={"volumeId": vid},
                                             deadline=10.0)
        if isinstance(body, dict):
            locs = body.get("locations")
        if locs is None:
            try:
                async with self._http.get(
                        tls.url(self.master_url, "/dir/lookup"),
                        params={"volumeId": vid}) as resp:
                    if resp.status != 200:
                        return False
                    locs = (await resp.json())["locations"]
            except aiohttp.ClientError:
                return False
        targets = [l["url"] for l in locs if l["url"] != self.url]

        extra = {"Authorization": auth} if auth else {}
        # the fan-out is one replicate-tier span; each replica hop is
        # an event, and the forwarded traceparent makes every replica's
        # own (volume, store) spans part of the same trace
        rsp = tracing.start("replicate", "fanout", fid=fid,
                            targets=len(targets))
        if rsp:
            tracing.inject(extra, rsp)

        async def frame_one(target: str, body: bytes | None) -> bool | None:
            """The fan-out hop over the persistent frame channel to
            `target`; None means the frame leg is unavailable and the
            caller rides HTTP (the channel breaker fails fast here, so
            a severed peer costs microseconds, not a connect timeout).
            The replica end enforces the same per-fid jwt and
            -whiteList policy wire applies to the HTTP form."""
            from ..util.frame import FrameChannelError
            try:
                # chaos site: forces the inter-host replication frame
                # leg down so chaos/soak prove the fan-out stays
                # correct on the HTTP fallback
                await failpoints.fail("replication.frame")
                chan = self.frame_hub.get(target=target)
                if method == "POST":
                    status, _, _b = await chan.request(
                        "POST", f"/{fid}",
                        query={"type": "replicate"},
                        headers={"X-Raw-Needle": "1", **extra},
                        body=body or b"", timeout=30.0)
                    ok = status in (200, 201)
                else:
                    status, _, _b = await chan.request(
                        "DELETE", f"/{fid}",
                        query={"type": "replicate"},
                        headers=extra, timeout=30.0)
                    ok = status == 200
                if not ok:
                    glog.warning("replicate %s to %s (frame): "
                                 "status %d", fid, target, status)
                    rsp.event("replica_failed", target=target,
                              status=status)
                return ok
            except (FrameChannelError, asyncio.TimeoutError, OSError):
                return None     # severed/refused/breaker-open -> HTTP

        async def one(target: str) -> bool:
            try:
                # chaos sites: `volume.replicate` injects transport
                # faults on the fan-out hop; `volume.replicate.body`
                # truncates the serialized needle so the replica's CRC
                # check rejects the torn write (the acknowledged copy
                # is then the only durable one — exactly the shape the
                # degraded-read soak must survive)
                await failpoints.fail("volume.replicate")
                body = None
                if method == "POST":
                    body = failpoints.corrupt("volume.replicate.body",
                                              raw_needle)
                framed = await frame_one(target, body)
                if framed is not None:
                    return framed
                if method == "POST":
                    async with self._http.post(
                            tls.url(target, f"/{fid}"),
                            params={"type": "replicate"},
                            data=body,
                            headers={"X-Raw-Needle": "1", **extra}) as r:
                        ok = r.status in (200, 201)
                        if not ok:
                            glog.warning(
                                "replicate %s to %s: http %d", fid,
                                target, r.status)
                            rsp.event("replica_failed", target=target,
                                      status=r.status)
                        return ok
                async with self._http.delete(
                        tls.url(target, f"/{fid}"),
                        params={"type": "replicate"},
                        headers=extra) as r:
                    return r.status == 200
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
                glog.warning("replicate %s to %s: %s", fid, target, e)
                rsp.event("replica_failed", target=target,
                          error=f"{type(e).__name__} {e}"[:120])
                return False

        try:
            results = await asyncio.gather(*(one(x) for x in targets))
            rsp.status = "ok" if all(results) else "error"
            return all(results)
        finally:
            rsp.finish()

    # ---- admin handlers ----

    def _is_worker_hop(self, req: web.Request) -> bool:
        wc = self.worker_ctx
        return wc is not None and \
            wc.token_ok(req.headers.get(_wk().WORKER_HEADER))

    async def _sibling_fetch(self, path: str, method: str,
                             timeout_s: float) -> "list[tuple[int, bytes]]":
        """Fetch `path` from every live sibling worker (token-marked so
        they answer locally instead of re-aggregating)."""
        wc = self.worker_ctx
        out: list[tuple[int, bytes]] = []

        async def one(i: int) -> None:
            addr = wc.sibling_addr(i)
            if addr is None:
                return
            try:
                await failpoints.fail("worker.fanout")
                async with self._http.request(
                        method, tls.url(addr, path),
                        headers={_wk().WORKER_HEADER: wc.token},
                        timeout=aiohttp.ClientTimeout(
                            total=timeout_s)) as r:
                    if r.status == 200:
                        out.append((i, await r.read()))
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
                # aggregation proceeds without the sibling, but the gap
                # must be visible: an operator reading summed /metrics
                # would otherwise see a silently smaller fleet
                glog.V(1).infof("sibling %d %s unreachable: %s",
                                i, path, e)

        await asyncio.gather(*(one(i) for i in range(wc.total)
                               if i != wc.index))
        return out

    async def _sibling_get(self, path: str) -> "list[tuple[int, bytes]]":
        return await self._sibling_fetch(path, "GET", 3)

    async def h_metrics(self, req: web.Request) -> web.Response:
        """/metrics; under -workers, any worker answers for the whole
        host by summing its siblings' registries, so scrapers keep one
        whole-host target on the shared public port."""
        from ..stats.metrics import merge_metrics_texts, metrics_text
        if self.worker_ctx is None or self._is_worker_hop(req):
            return web.Response(body=metrics_text(),
                                content_type="text/plain")
        texts = [metrics_text()]
        texts += [body for _, body in await self._sibling_get("/metrics")]
        return web.Response(body=merge_metrics_texts(texts),
                            content_type="text/plain")

    async def h_failpoints(self, req: web.Request) -> web.Response:
        """/debug/failpoints with -workers fan-out: failpoint state is
        per-process, and the public port is SO_REUSEPORT-balanced, so
        an arm/disarm that landed on one worker must propagate to every
        sibling or the fleet would inject faults on ~1/N of requests
        (and a follow-up GET would report nothing armed). Query-param
        arming only — a consumed JSON body is not replayed."""
        resp = await failpoints.handle_debug(req)
        wc = self.worker_ctx
        if wc is None or self._is_worker_hop(req) \
                or req.method == "GET" or resp.status != 200:
            return resp

        async def one(i: int) -> None:
            addr = wc.sibling_addr(i)
            if addr is None:
                return
            try:
                # weedlint: ignore[failpoint-site] this IS the failpoint arming fan-out; a fault injected into arming would leave chaos runs unable to arm sites at all
                async with self._http.request(
                        req.method, tls.url(addr, "/debug/failpoints"),
                        params=req.query,
                        headers={_wk().WORKER_HEADER: wc.token},
                        timeout=aiohttp.ClientTimeout(total=5)) as r:
                    await r.read()
            except (aiohttp.ClientError, asyncio.TimeoutError,
                    OSError) as e:
                glog.warning("failpoint fan-out to worker %d: %s", i, e)

        await asyncio.gather(*(one(i) for i in range(wc.total)
                               if i != wc.index))
        return resp

    async def h_traces(self, req: web.Request) -> web.Response:
        """/debug/traces: recent + slowest-N traces from the in-memory
        span ring; under -workers, any worker answers for the whole
        host by merging its siblings' rings (like /metrics).
        ``?trace=<id>`` instead pulls every span of ONE trace (ring +
        in-flight) — the per-node feed cluster assembly fans over."""
        tid = str(req.query.get("trace", "") or "").strip()[:64]
        if tid:
            payload = tracing.trace_spans_dict(tid)
            wc = self.worker_ctx
            if wc is not None and not self._is_worker_hop(req):
                payloads = [payload]
                for _, body in await self._sibling_get(
                        f"/debug/traces?trace={tid}"):
                    try:
                        payloads.append(json.loads(body))
                    except ValueError:
                        continue
                payload = tracing.merge_trace_payloads(payloads)
            return web.json_response(payload)
        try:
            recent = tracing.clamp_count(req.query.get("n", 20))
            slowest = tracing.clamp_count(req.query.get("slowest", 10))
            payload = tracing.traces_dict(recent=recent, slowest=slowest)
        except ValueError:
            return web.json_response({"error": "bad n/slowest"},
                                     status=400)
        wc = self.worker_ctx
        if wc is not None and not self._is_worker_hop(req):
            payloads = [payload]
            for _, body in await self._sibling_get(
                    f"/debug/traces?n={recent}&slowest={slowest}"):
                try:
                    payloads.append(json.loads(body))
                except ValueError:
                    continue
            payload = tracing.merge_payloads(payloads, recent=recent,
                                             slowest=slowest)
        return web.json_response(payload)

    async def h_requests(self, req: web.Request) -> web.Response:
        """/debug/requests: currently in-flight spans with their age —
        the wedged-request detector; -workers aggregated like above."""
        payload = tracing.requests_dict()
        wc = self.worker_ctx
        if wc is not None and not self._is_worker_hop(req):
            rows = payload["requests"]
            for i, body in await self._sibling_get("/debug/requests"):
                try:
                    sib = json.loads(body)
                except ValueError:
                    continue
                for r in sib.get("requests", ()):
                    r["worker"] = i
                    rows.append(r)
            rows.sort(key=lambda r: -r.get("age_ms", 0))
            payload = {"inflight": len(rows), "requests": rows}
        return web.json_response(payload)

    # ---- flight recorder (stats/timeline.py, util/events.py,
    # stats/slo.py): every surface whole-host merged under -workers
    # with the same discipline as /metrics ----

    async def _merged_timeline(self, req: web.Request, n: int,
                               force_snap: bool = False,
                               render: bool = True) -> dict:
        """This worker's timeline, merged with every sibling's under
        -workers (rates/gauges/histogram buckets summed per wall
        bucket, quantiles recomputed from the summed buckets).

        ``render=False`` (the h_health path) skips the per-window
        quantile interpolation end-to-end: the SLO engine reads only
        the raw hist deltas, and the merge recomputes quantiles from
        summed buckets anyway, so rendering inputs is pure waste."""
        from ..stats import timeline
        if force_snap:
            timeline.snap()
        wc = self.worker_ctx
        if wc is None or self._is_worker_hop(req):
            # merge INPUTS never need rendering (the entry worker
            # recomputes from the summed buckets); only a final
            # payload handed straight to a caller does
            merging = wc is not None
            return timeline.timeline_dict(
                n=n, render=render and not merging)
        payloads = [timeline.timeline_dict(n=n, render=False)]
        snap_q = "&snap=1" if force_snap else ""
        path = f"/debug/timeline?n={n}{snap_q}"
        for _, body in await (
                self._sibling_post(path) if force_snap
                else self._sibling_get(path)):
            try:
                payloads.append(json.loads(body))
            except ValueError:
                continue
        return timeline.merge_payloads(payloads, n=n, render=render)

    async def _sibling_post(self, path: str) -> "list[tuple[int, bytes]]":
        """POST twin of _sibling_get (forced timeline snapshots; the
        longer timeout pays for the sibling's synchronous snap)."""
        return await self._sibling_fetch(path, "POST", 5)

    async def h_timeline(self, req: web.Request) -> web.Response:
        """/debug/timeline: the metrics-timeline ring; GET ?n= windows,
        POST ?snap=1 forces a snapshot NOW (fanned out to siblings so a
        forced whole-host window aligns)."""
        from ..stats import timeline
        force = req.method == "POST"
        if force and req.query.get("snap", "") not in ("1", "true"):
            return web.json_response({"error": "POST wants ?snap=1"},
                                     status=400)
        try:
            n = tracing.clamp_count(req.query.get("n", 60), cap=10_000)
        except ValueError:
            return web.json_response({"error": "bad n"}, status=400)
        return web.json_response(
            await self._merged_timeline(req, n, force_snap=force))

    async def _merged_events(self, req: web.Request,
                             query) -> dict:
        from ..util import events
        payload = events.events_query(query)
        wc = self.worker_ctx
        if wc is None or self._is_worker_hop(req):
            return payload
        # tag COPIES: events_query hands out the live journal rows, and
        # stamping them in place would rewrite every ring entry's shape
        # for all later surfaces (worker-hop responses, slo evidence)
        payload["events"] = [{**r, "worker": wc.index}
                             for r in payload["events"]]
        payloads = [payload]
        # urlencode, not raw interpolation: a type/since_ms value with
        # a reserved char would 400 on the sibling and its rows would
        # silently vanish from the merged journal
        qs = urllib.parse.urlencode(query)
        for i, body in await self._sibling_get(
                "/debug/events" + (f"?{qs}" if qs else "")):
            try:
                sib = json.loads(body)
            except ValueError:
                continue
            for r in sib.get("events", ()):
                r["worker"] = i
            payloads.append(sib)
        return events.merge_payloads(
            payloads, n=int(query.get("n", 100) or 100))

    async def h_events(self, req: web.Request) -> web.Response:
        """/debug/events: the structured event journal (breaker trips,
        holder refreshes, scrub corruptions, mounts, respawns, ...)
        with wall stamps and trace ids; -workers merged, rows tagged
        with their worker index."""
        try:
            payload = await self._merged_events(req, dict(req.query))
        except ValueError:
            return web.json_response({"error": "bad n/type/since_ms"},
                                     status=400)
        return web.json_response(payload)

    async def h_health(self, req: web.Request) -> web.Response:
        """/debug/health: the SLO burn-rate verdict (ok/warn/page) with
        evidence, evaluated over the WHOLE-HOST merged timeline and
        journal under -workers — the one machine-readable answer soaks
        and operators assert against."""
        from ..stats import slo
        eng = slo.engine()
        if eng is None or not eng.specs:
            # no objectives armed: health_dict ignores its arguments
            # and returns the ok stub — don't pay the sibling
            # timeline/journal fan-out just to discard it
            return web.json_response(slo.health_dict([]))
        timeline_payload, events_payload = await asyncio.gather(
            self._merged_timeline(req, slo.windows_needed(),
                                  render=False),
            self._merged_events(req, {"n": "500"}))
        return web.json_response(slo.health_dict(
            timeline_payload["windows"],
            events=events_payload["events"]))

    async def h_qos(self, req: web.Request) -> web.Response:
        """/debug/qos: per-tenant admission counters, shed level and
        bandwidth-arbiter ledger; -workers merged (counters sum, shed
        level takes the worst worker) like /debug/timeline."""
        payload = qos.qos_dict()
        wc = self.worker_ctx
        if wc is None or self._is_worker_hop(req):
            return web.json_response(payload)
        payloads = [payload]
        for _, body in await self._sibling_get("/debug/qos"):
            try:
                payloads.append(json.loads(body))
            except ValueError:
                continue
        return web.json_response(qos.merge_payloads(payloads))

    async def h_profile(self, req: web.Request) -> web.Response:
        """/debug/profile: the continuous sampling profiler's folded
        stacks (?seconds=N records a fresh window; ?format=folded for
        flamegraph-ready text); -workers merged by summing folded
        counts — each worker samples only itself."""
        from ..stats import profiler
        try:
            payload = await profiler.profile_query(req.query)
        except ValueError:
            return web.json_response({"error": "bad seconds/hz"},
                                     status=400)
        wc = self.worker_ctx
        if wc is not None and not self._is_worker_hop(req):
            payloads = [payload]
            qs = urllib.parse.urlencode(
                {k: req.query[k] for k in ("seconds", "hz")
                 if k in req.query})
            # a ?seconds=N window makes the sibling block for N: pad
            # the fan-out timeout past the window instead of 3s
            secs = float(req.query.get("seconds", 0) or 0)
            for _, body in await self._sibling_fetch(
                    "/debug/profile" + (f"?{qs}" if qs else ""),
                    "GET", max(3.0, secs + 5.0)):
                try:
                    payloads.append(json.loads(body))
                except ValueError:
                    continue
            payload = profiler.merge_payloads(payloads)
        if req.query.get("format") == "folded":
            from ..stats.profiler import folded_text
            return web.Response(text=folded_text(payload),
                                content_type="text/plain")
        return web.json_response(payload)

    async def h_pprof(self, req: web.Request) -> web.Response:
        """/debug/pprof: which -cpuprofile/-memprofile collectors are
        armed; ?dump=1 snapshots them to disk NOW (fanned across
        -workers so every sibling's profile lands, not just the worker
        the balancer picked)."""
        from ..util import pprof
        dump = req.query.get("dump", "") in ("1", "true")
        payload: dict = {"workers": {}} if self.worker_ctx else {}
        # executor hop: the mem dump writes a file
        body = await tracing.run_in_executor(
            lambda: pprof.pprof_dict(dump=dump))
        wc = self.worker_ctx
        if wc is None or self._is_worker_hop(req):
            return web.json_response(body)
        payload["workers"][str(wc.index)] = body
        qs = "?dump=1" if dump else ""
        for i, raw in await self._sibling_get("/debug/pprof" + qs):
            try:
                payload["workers"][str(i)] = json.loads(raw)
            except ValueError:
                continue
        return web.json_response(payload)

    async def h_scrub(self, req: web.Request) -> web.Response:
        """/debug/scrub: paced-scrubber status; POST ?run=1 forces one
        full cycle NOW and returns its report (how tests and the scrub
        soak drive deterministic passes). Under -workers, GET merges
        every sibling's status like /status — each worker scrubs its
        own partition."""
        if req.method == "POST":
            if req.query.get("run", "") not in ("1", "true"):
                return web.json_response(
                    {"error": "POST wants ?run=1"}, status=400)
            report = await self.scrubber.run_cycle()
            out = {"cycle": report, "status": self.scrubber.status()}
            wc = self.worker_ctx
            if wc is not None and not self._is_worker_hop(req):
                # each worker scrubs only its own vid partition: a
                # forced cycle must fan out to every sibling or ~1/N
                # of the host's volumes silently go unscanned
                out = {"workers": {str(wc.index): out}}

                async def one(i: int) -> None:
                    addr = wc.sibling_addr(i)
                    if addr is None:
                        return
                    try:
                        await failpoints.fail("scrub.fanout")
                        async with self._http.post(
                                tls.url(addr, "/debug/scrub"),
                                params={"run": "1"},
                                headers={_wk().WORKER_HEADER: wc.token},
                                timeout=aiohttp.ClientTimeout(
                                    total=600)) as r:
                            out["workers"][str(i)] = await r.json()
                    except (aiohttp.ClientError, asyncio.TimeoutError,
                            OSError, ValueError) as e:
                        glog.warning("scrub fan-out to worker %d: %s",
                                     i, e)
                        out["workers"][str(i)] = {"error": str(e)}

                await asyncio.gather(*(one(i) for i in range(wc.total)
                                       if i != wc.index))
            return web.json_response(out)
        if req.method != "GET":
            return web.json_response({"error": "method not allowed"},
                                     status=405)
        payload: dict = {"scrub": self.scrubber.status()}
        wc = self.worker_ctx
        if wc is not None and not self._is_worker_hop(req):
            payload["workers"] = {str(wc.index): payload.pop("scrub")}
            for i, body in await self._sibling_get("/debug/scrub"):
                try:
                    sib = json.loads(body)
                except ValueError:
                    continue
                if "scrub" in sib:
                    payload["workers"][str(i)] = sib["scrub"]
        return web.json_response(payload)

    async def h_breakers(self, req: web.Request) -> web.Response:
        """Circuit-breaker states of this server's upstream hops
        (sibling workers + the lazily-built weed client), for chaos
        runs and operators probing a brown-out."""
        out = {"siblings": self._sibling_breakers.to_dict()}
        wc = getattr(self, "_wclient", None)
        if wc is not None:
            out["client"] = wc.breakers.to_dict()
        return web.json_response(out)

    async def h_status(self, req: web.Request) -> web.Response:
        vols = [self.store._volume_message(v).to_dict()
                for v in self.store.volumes.values()]
        ec = {vid: sorted(ev.shards)
              for vid, ev in self.store.ec_volumes.items()}
        out = {"version": "seaweedfs_tpu 0.1", "volumes": vols,
               "ecVolumes": ec}
        caches = {}
        if self.store.needle_cache is not None:
            caches["needle"] = self.store.needle_cache.to_dict()
        if self.store.ec_recover_cache is not None:
            caches["ec_recover"] = \
                self.store.ec_recover_cache.counters.to_dict()
        if caches:
            out["caches"] = caches
        gc = self.store.group_commit_stats()
        if gc["batches"]:
            out["group_commit"] = gc
        wc = self.worker_ctx
        frames = self.frame_hub.stats_dict()
        if frames:
            # this worker's outbound frame channels (sibling hops +
            # EC gathers), nested per worker index: the deterministic
            # accounting the sibling bench scrapes
            out["frames"] = {f"w{wc.index if wc else 0}": frames}
        if wc is not None and not self._is_worker_hop(req):
            # whole-host view: fold in every sibling's partition
            out["workers"] = wc.total
            out["worker"] = wc.index
            for _, body in await self._sibling_get("/status"):
                try:
                    sib = json.loads(body)
                except ValueError:
                    continue
                vols.extend(sib.get("volumes", []))
                ec.update(sib.get("ecVolumes", {}))
                if sib.get("frames"):
                    out.setdefault("frames", {}).update(sib["frames"])
            vols.sort(key=lambda m: m.get("id", 0))
        return web.json_response(out)

    async def h_stats_workers(self, req: web.Request) -> web.Response:
        """Worker-fleet view: one row per configured worker slot, from
        the shared state files (works no matter which worker answers)."""
        wc = self.worker_ctx
        if wc is None:
            return web.json_response({"workers": [], "total": 1})
        rows = []
        for i, st in enumerate(wc.all_states()):
            row = {"index": i, "alive": False}
            if st:
                row.update({k: st[k] for k in
                            ("pid", "ip", "port", "public_port", "role")
                            if k in st})
                try:
                    os.kill(st["pid"], 0)
                    row["alive"] = True
                except ProcessLookupError:
                    row["stale_state"] = True  # dead pid: alive=False IS
                    # the signal; marked so operators can tell a dead
                    # worker from a never-started one
                except (PermissionError, KeyError):
                    # EPERM: the pid exists but isn't ours to signal
                    row["alive"] = "pid" in st
            if i == wc.index:
                row["volumes"] = sorted(self.store.volumes)
            rows.append(row)
        return web.json_response({"workers": rows, "total": wc.total})

    async def h_ui(self, req: web.Request) -> web.Response:
        """Live volume status page (server/volume_server_ui/)."""
        from html import escape
        rows = []
        for v in self.store.volumes.values():
            m = self.store._volume_message(v)
            # collection names come from user-controlled assign params:
            # escape to keep the admin page XSS-free
            rows.append(
                f"<tr><td>{m.id}</td><td>{escape(m.collection) or '-'}</td>"
                f"<td>{m.size}</td><td>{m.file_count}</td>"
                f"<td>{m.delete_count}</td>"
                f"<td>{'ro' if m.read_only else 'rw'}</td></tr>")
        ec_rows = [f"<tr><td>{vid}</td><td>{sorted(ev.shards)}</td></tr>"
                   for vid, ev in self.store.ec_volumes.items()]
        html = f"""<!DOCTYPE html><html><head><title>seaweedfs_tpu volume
</title></head><body><h1>seaweedfs_tpu volume server {escape(self.url)}</h1>
<p>master: {escape(self.master_url)} | dc: {escape(self.data_center) or '-'}
| rack: {escape(self.rack) or '-'}</p>
<h2>Volumes</h2><table border=1 cellpadding=4><tr><th>Id</th>
<th>Collection</th><th>Size</th><th>Files</th><th>Deleted</th><th>Mode</th>
</tr>{''.join(rows)}</table>
<h2>EC shards</h2><table border=1 cellpadding=4><tr><th>Volume</th>
<th>Shards</th></tr>{''.join(ec_rows)}</table></body></html>"""
        return web.Response(text=html, content_type="text/html")

    async def h_allocate(self, req: web.Request) -> web.Response:
        q = req.query
        try:
            self.store.add_volume(
                int(q["volume"]), q.get("collection", ""),
                q.get("replication", ""), q.get("ttl", ""),
                int(q.get("preallocate", 0) or 0))
        except VolumeError as e:
            return web.json_response({"error": str(e)}, status=409)
        return web.json_response({"ok": True})

    async def h_volume_delete(self, req: web.Request) -> web.Response:
        try:
            self.store.delete_volume(int(req.query["volume"]),
                                     req.query.get("collection", ""))
        except VolumeError as e:
            # a delete that found nothing must not report success
            return web.json_response({"error": str(e)}, status=404)
        # the master must drop this location before the next pulse, or
        # lookups keep routing reads at a volume that no longer exists
        await self._heartbeat_now()
        return web.json_response({"ok": True})

    async def h_readonly(self, req: web.Request) -> web.Response:
        self.store.mark_readonly(int(req.query["volume"]))
        return web.json_response({"ok": True})

    async def h_volume_mount(self, req: web.Request) -> web.Response:
        """Load an on-disk volume into the store (VolumeMount)."""
        vid = int(req.query["volume"])
        collection = req.query.get("collection", "")
        try:
            await self._in_executor(lambda: self.store.mount_volume(collection, vid))
        except VolumeError as e:
            return web.json_response({"error": str(e)}, status=404)
        await self._heartbeat_now()
        return web.json_response({"ok": True})

    async def h_volume_unmount(self, req: web.Request) -> web.Response:
        self.store.unmount_volume(int(req.query["volume"]))
        await self._heartbeat_now()
        return web.json_response({"ok": True})

    async def h_volume_copy(self, req: web.Request) -> web.Response:
        """Pull .idx then .dat from a source server, then mount
        (VolumeCopy, server/volume_grpc_copy.go). .idx is copied first so a
        racing write at most leaves extra .dat tail beyond the last copied
        index entry, which the mount-time integrity check truncates."""
        q = req.query
        vid = int(q["volume"])
        collection = q.get("collection", "")
        source = q["source"]
        if vid in self.store.volumes:
            return web.json_response({"error": "already have volume"},
                                     status=409)
        d = self.store.dirs[0]
        base = os.path.join(
            d, f"{collection}_{vid}" if collection else str(vid))

        async def fetch(ext: str) -> str | None:
            try:
                await failpoints.fail("volume.copy.fetch")
                async with self._http.get(
                        tls.url(source, "/admin/file"),
                        params={"volume": str(vid), "collection": collection,
                                "ext": ext}) as resp:
                    if resp.status != 200:
                        return f"fetch {ext}: {resp.status}"
                    # a .dat can be GBs: open/write/close all leave the
                    # event loop so in-flight reads don't stall behind
                    # this admin copy
                    f = await self._in_executor(open, base + ext, "wb")
                    try:
                        async for chunk in resp.content.iter_chunked(
                                1 << 20):
                            await self._in_executor(f.write, chunk)
                    finally:
                        await self._in_executor(f.close)
                    return None
            except (aiohttp.ClientError, OSError) as e:
                return str(e)

        err = await fetch(".idx") or await fetch(".dat")
        if err:
            for ext in (".idx", ".dat"):
                if os.path.exists(base + ext):
                    await self._in_executor(os.remove, base + ext)
            return web.json_response({"error": err}, status=502)
        return await self.h_volume_mount(req)

    # ---- incremental backup / tail (volume_backup.go) ----

    async def h_volume_status(self, req: web.Request) -> web.Response:
        """Per-volume sync metadata (VolumeSyncStatus RPC analog)."""
        vid = int(req.query["volume"])
        v = self.store.volumes.get(vid)
        if v is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response({
            "volume": vid,
            "collection": v.collection,
            "last_append_at_ns": v.last_append_at_ns,
            "compaction_revision": v.super_block.compaction_revision,
            "replication": str(v.super_block.replica_placement),
            "ttl": str(v.ttl),
            "data_size": v.data_size(),
        })

    async def h_volume_tail(self, req: web.Request) -> web.StreamResponse:
        """VolumeTailSender (volume_server.proto:47-50): stream framed
        needle records appended after since_ns."""
        from ..storage import volume_backup as vb
        vid = int(req.query["volume"])
        since_ns = int(req.query.get("since_ns", 0))
        v = self.store.volumes.get(vid)
        if v is None:
            return web.json_response({"error": "not found"}, status=404)
        resp = web.StreamResponse(
            headers={"Content-Type": "application/octet-stream"})
        await resp.prepare(req)
        # stream record-by-record: each iteration does one short locked
        # read in the executor, so large tails neither hold the volume
        # lock across awaits nor buffer the whole tail in RAM
        it = vb.tail_records(v, since_ns)
        while True:
            item = await self._in_executor(lambda: next(it, None))
            if item is None:
                break
            n, is_delete = item
            await resp.write(vb.frame_needle(n, is_delete))
        await resp.write_eof()
        return resp

    async def h_volume_tail_receive(self, req: web.Request) -> web.Response:
        """VolumeTailReceiver: pull a source volume's tail into the local
        copy (used by replica catch-up)."""
        from ..storage import volume_backup as vb
        q = req.query
        vid = int(q["volume"])
        source = q["source"]
        v = self.store.volumes.get(vid)
        if v is None:
            return web.json_response({"error": "not found"}, status=404)
        since = v.last_append_at_ns
        applied = 0
        dec = vb.FrameDecoder()

        def apply_batch(recs) -> int:
            nc = self.store.needle_cache
            for n, is_delete in recs:
                vb.apply_needle(v, n, is_delete)
                if nc is not None:
                    # tail apply bypasses store.write/delete: each
                    # replayed record must still invalidate its entry
                    nc.invalidate(vid, n.id)
            return len(recs)

        try:
            await failpoints.fail("volume.tail")
            async with self._http.get(
                    tls.url(source, "/admin/volume/tail"),
                    params={"volume": str(vid),
                            "since_ns": str(since)}) as resp:
                if resp.status != 200:
                    return web.json_response(
                        {"error": f"tail from {source}: {resp.status}"},
                        status=502)
                # apply as chunks arrive — no whole-tail buffering
                async for chunk in resp.content.iter_chunked(1 << 20):
                    recs = dec.feed(chunk)
                    if recs:
                        applied += await self._in_executor(lambda: apply_batch(recs))
        except (aiohttp.ClientError, OSError) as e:
            return web.json_response({"error": str(e)}, status=502)
        return web.json_response({"applied": applied})

    # ---- tiered storage (volume_grpc_tier_upload.go/_download.go) ----

    async def h_tier_upload(self, req: web.Request) -> web.Response:
        """VolumeTierMoveDatToRemote: ship .dat to a configured backend."""
        from ..storage import volume_tier
        from ..storage.backend import BackendError
        q = req.query
        vid = int(q["volume"])
        backend_id = q.get("backend", "s3.default")
        keep_local = q.get("keep_local", "") == "1"
        v = self.store.volumes.get(vid)
        if v is None:
            return web.json_response({"error": "not found"}, status=404)
        try:
            size = await self._in_executor(lambda: volume_tier.tier_upload(
                    v, backend_id, keep_local))
        except (BackendError, VolumeError) as e:
            return web.json_response({"error": str(e)}, status=502)
        return web.json_response({"uploaded": size, "backend": backend_id})

    async def h_tier_download(self, req: web.Request) -> web.Response:
        """VolumeTierMoveDatFromRemote: bring the .dat back to disk."""
        from ..storage import volume_tier
        from ..storage.backend import BackendError
        vid = int(req.query["volume"])
        v = self.store.volumes.get(vid)
        if v is None:
            return web.json_response({"error": "not found"}, status=404)
        try:
            size = await self._in_executor(lambda: volume_tier.tier_download(v))
        except (BackendError, VolumeError) as e:
            return web.json_response({"error": str(e)}, status=502)
        return web.json_response({"downloaded": size})

    # ---- vacuum (volume_vacuum.go + topology_vacuum.go protocol) ----

    async def h_vacuum_check(self, req: web.Request) -> web.Response:
        vid = int(req.query["volume"])
        v = self.store.volumes.get(vid)
        if v is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response({"garbage_ratio": v.garbage_level()})

    async def h_vacuum_compact(self, req: web.Request) -> web.Response:
        from ..storage import vacuum
        vid = int(req.query["volume"])
        v = self.store.volumes.get(vid)
        if v is None:
            return web.json_response({"error": "not found"}, status=404)
        await self._in_executor(lambda: vacuum.compact(v))
        return web.json_response({"ok": True})

    async def h_vacuum_commit(self, req: web.Request) -> web.Response:
        from ..storage import vacuum
        vid = int(req.query["volume"])
        v = self.store.volumes.get(vid)
        if v is None:
            return web.json_response({"error": "not found"}, status=404)
        try:
            # store-level commit: swaps .dat/.idx AND drops this
            # volume's hot-needle cache entries (offsets all moved)
            await self._in_executor(lambda: self.store.commit_compaction(vid))
        except vacuum.VacuumError as e:
            return web.json_response({"error": str(e)}, status=500)
        return web.json_response({"ok": True})

    async def h_vacuum_cleanup(self, req: web.Request) -> web.Response:
        from ..storage import vacuum
        vid = int(req.query["volume"])
        v = self.store.volumes.get(vid)
        if v is not None:
            # unlinks .cpd/.cpx leftovers — disk metadata ops belong
            # on the executor like every other blocking call here
            await self._in_executor(vacuum.cleanup_compact, v)
        return web.json_response({"ok": True})

    def _base_name(self, vid: int, collection: str) -> str | None:
        for d in self.store.dirs:
            base = os.path.join(
                d, f"{collection}_{vid}" if collection else str(vid))
            if os.path.exists(base + ".dat") or os.path.exists(base + ".ecx") \
                    or any(os.path.exists(base + ecpl.to_ext(i))
                           for i in range(gf.TOTAL_SHARDS)):
                return base
        return None

    async def h_ec_generate(self, req: web.Request) -> web.Response:
        """VolumeEcShardsGenerate (volume_grpc_erasure_coding.go:39-67):
        .dat -> 14 shards + .ecx, via the TPU encoder."""
        vid = int(req.query["volume"])
        collection = req.query.get("collection", "")
        v = self.store.volumes.get(vid)
        base = v.file_name() if v else self._base_name(vid, collection)
        if base is None:
            return web.json_response({"error": f"volume {vid} not found"},
                                     status=404)

        stats: dict = {}

        def work():
            ecpl.encode_volume(base,
                               large_block=self.store.ec_large_block,
                               small_block=self.store.ec_small_block,
                               stats=stats)
            ecpl.write_sorted_file_from_idx(base)
        await self._in_executor(work)
        return web.json_response({"ok": True,
                                  "windows": stats.get("windows", 0),
                                  "dispatches": stats.get("dispatches", 0)})

    async def h_ec_generate_batch(self, req: web.Request) -> web.Response:
        """Batched VolumeEcShardsGenerate over several local volumes: one
        kernel launch carries buffer groups from every volume (the
        rack-encode shape; pipeline.write_ec_files_batched)."""
        vids = [int(x) for x in req.query["volumes"].split(",") if x]
        collection = req.query.get("collection", "")
        wc = self.worker_ctx
        if wc is not None and not self._is_worker_hop(req):
            # split the batch across owning workers; each owner still
            # batches ITS volumes through one kernel launch
            mine = [v for v in vids if wc.owns(v)]
            failed: list[str] = []

            async def forward(idx: int, group: list[int]) -> None:
                addr = wc.sibling_addr(idx)
                try:
                    if addr is None:
                        raise OSError(f"worker {idx} unavailable")
                    await failpoints.fail("worker.forward")
                    async with self._http.post(
                            tls.url(addr, "/admin/ec/generate_batch"),
                            params={"volumes": ",".join(map(str, group)),
                                    "collection": collection},
                            headers={_wk().WORKER_HEADER: wc.token},
                            timeout=aiohttp.ClientTimeout(
                                total=600)) as resp:
                        if resp.status != 200:
                            failed.append(await resp.text())
                except (aiohttp.ClientError, asyncio.TimeoutError,
                        OSError) as e:
                    failed.append(str(e))

            groups: dict[int, list[int]] = {}
            for v in vids:
                if not wc.owns(v):
                    groups.setdefault(wc.owner_index(v), []).append(v)
            jobs = [forward(i, g) for i, g in groups.items()]
            if mine:
                from multidict import CIMultiDict
                h = CIMultiDict(req.headers)
                h[_wk().WORKER_HEADER] = wc.token
                sub = req.clone(
                    rel_url=req.rel_url.update_query(
                        volumes=",".join(map(str, mine))),
                    headers=h)
                jobs.append(self.h_ec_generate_batch(sub))
            done = await asyncio.gather(*jobs, return_exceptions=True)
            for d in done:
                if isinstance(d, Exception):
                    failed.append(str(d))
                elif isinstance(d, web.Response) and d.status != 200:
                    failed.append(d.text or "")
            if failed:
                return web.json_response(
                    {"error": "; ".join(failed)}, status=502)
            return web.json_response({"ok": True, "volumes": vids})
        bases = []
        for vid in vids:
            v = self.store.volumes.get(vid)
            base = v.file_name() if v else self._base_name(vid, collection)
            if base is None:
                return web.json_response(
                    {"error": f"volume {vid} not found"}, status=404)
            bases.append(base)

        def work():
            ecpl.write_ec_files_batched(
                bases, large_block=self.store.ec_large_block,
                small_block=self.store.ec_small_block)
            for base in bases:
                ecpl.write_sorted_file_from_idx(base)
        await self._in_executor(work)
        return web.json_response({"ok": True, "volumes": vids})

    async def h_ec_rebuild(self, req: web.Request) -> web.Response:
        """VolumeEcShardsRebuild (volume_grpc_erasure_coding.go:70-97)."""
        vid = int(req.query["volume"])
        collection = req.query.get("collection", "")
        base = self._base_name(vid, collection)
        if base is None:
            return web.json_response({"error": f"ec volume {vid} not found"},
                                     status=404)
        try:
            rebuilt = await self._in_executor(lambda: ecpl.rebuild_ec_files(base))
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=500)
        return web.json_response({"rebuilt": rebuilt})

    async def _fetch_shard_file(self, source: str, vid: int,
                                collection: str, ext: str,
                                base: str) -> str | None:
        """Pull one shard/index file from a holder via /admin/file
        (the ec.copy fetch shape); returns an error string or None.
        Streams into a temp file and renames only on a COMPLETE body:
        a source dying mid-stream must never leave a truncated shard
        file that a later pass would mount or feed into a rebuild."""
        tmp = base + ext + ".fetch"
        try:
            await failpoints.fail("volume.ec_copy.fetch")
            async with self._http.get(
                    tls.url(source, "/admin/file"),
                    params={"volume": str(vid),
                            "collection": collection, "ext": ext},
                    timeout=aiohttp.ClientTimeout(total=600)) as resp:
                if resp.status != 200:
                    return f"fetch {ext} from {source}: {resp.status}"
                f = await self._in_executor(open, tmp, "wb")
                try:
                    async for chunk in resp.content.iter_chunked(1 << 20):
                        await self._in_executor(f.write, chunk)
                finally:
                    await self._in_executor(f.close)
            await self._in_executor(os.replace, tmp, base + ext)
            return None
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
            await self._in_executor(self._unlink_quiet, tmp)
            return str(e)

    async def h_ec_rebuild_shard(self, req: web.Request) -> web.Response:
        """Rebuild-to-target shard placement — the autopilot's repair
        primitive (no reference RPC; command_ec_rebuild.go gathers on
        the freest node via N shell round trips, this does the same
        server-side in ONE call so the planner owns placement):

        POST ?volume=&collection=&shards=2,5&sources=0:host:port,...

        This node regenerates the requested shards HERE: k clean
        inputs are gathered from the source holders (currently-MOUNTED
        local survivors are free), a local (rotten) copy of a
        requested shard is set aside only once the inputs are
        confirmed, the requested rows come out of one stripe-batched
        rebuild over exactly the validated inputs, borrowed inputs are
        dropped, and the result is mounted + registered. Serialized
        per vid: an executor retry racing a still-running rebuild of
        the same volume queues instead of interleaving file writes."""
        q = req.query
        vid = int(q["volume"])
        collection = q.get("collection", "")
        if not self.store.owns(vid):
            # wrong -workers partition: refuse BEFORE streaming GBs of
            # inputs the final mount would reject anyway — the
            # executor falls over to its next ranked target
            return web.json_response(
                {"error": f"volume {vid} not in this worker's "
                          f"partition"}, status=409)
        want = sorted({int(x) for x in q.get("shards", "").split(",")
                       if x})
        if not want:
            return web.json_response({"error": "no shards requested"},
                                     status=400)
        sources: dict[int, str] = {}
        for part in q.get("sources", "").split(","):
            if not part:
                continue
            sid_s, _, url = part.partition(":")
            try:
                sources[int(sid_s)] = url
            except ValueError:
                return web.json_response(
                    {"error": f"bad sources entry {part!r}"}, status=400)
        if len(self._rebuild_locks) > 1024:  # id-space leak bound
            self._rebuild_locks = {
                v: lk for v, lk in self._rebuild_locks.items()
                if lk.locked()}
        lock = self._rebuild_locks.setdefault(vid, asyncio.Lock())
        async with lock:
            return await self._rebuild_shard_locked(
                vid, collection, want, sources)

    async def _rebuild_shard_locked(self, vid: int, collection: str,
                                    want: list,
                                    sources: dict) -> web.Response:
        base = self._base_name(vid, collection)
        if base is None:
            base = os.path.join(
                self.store.dirs[0],
                f"{collection}_{vid}" if collection else str(vid))
        # 1. the sorted needle index must exist locally before mount
        if not os.path.exists(base + ".ecx"):
            holders = sorted(set(sources.values()))
            err = f"no source holders for .ecx of volume {vid}"
            for holder in holders:
                err = await self._fetch_shard_file(
                    holder, vid, collection, ".ecx", base)
                if err is None:
                    # the delete journal may legitimately not exist
                    await self._fetch_shard_file(
                        holder, vid, collection, ".ecj", base)
                    break
            if err is not None:
                return web.json_response({"error": err}, status=502)
        # 2. gather until k distinct clean inputs are on local disk
        # (planner-listed survivors only; currently-MOUNTED local
        # shards are free — an unmounted leftover file could be a
        # stale generation, so anything else streams fresh from its
        # listed holder, overwriting the leftover via the tmp+rename).
        # Gathering runs BEFORE any local copy of a requested shard is
        # touched: a failed gather must leave a rotten-but-mostly-good
        # shard serving, never convert one corrupt window into a lost
        # shard
        ev0 = self.store.ec_volumes.get(vid)
        mounted_now = set(ev0.shards) if ev0 is not None else set()
        fetched: list[int] = []
        inputs: list[int] = []
        for sid in sorted(sources):
            if sid in want:
                continue
            if len(inputs) >= gf.DATA_SHARDS:
                break
            if sid in mounted_now \
                    and os.path.exists(base + ecpl.to_ext(sid)):
                inputs.append(sid)
                continue
            err = await self._fetch_shard_file(
                sources[sid], vid, collection, ecpl.to_ext(sid), base)
            if err is not None:
                glog.warning("rebuild_shard vid=%d: input %d: %s",
                             vid, sid, err)
                continue
            fetched.append(sid)
            inputs.append(sid)

        async def drop_fetched() -> None:
            for sid in fetched:
                p = base + ecpl.to_ext(sid)
                if os.path.exists(p):
                    await self._in_executor(os.remove, p)

        if len(inputs) < gf.DATA_SHARDS:
            await drop_fetched()
            return web.json_response(
                {"error": f"unrepairable here: only {len(inputs)} "
                          f"inputs gathered, need {gf.DATA_SHARDS}"},
                status=409)
        # 3. k inputs confirmed on disk — NOW a requested shard hosted
        # here (in-place rot repair) is unmounted and its file set
        # ASIDE (atomic rename, not delete): if the rebuild itself
        # fails, the rotten-but-mostly-good copy is restored and
        # remounted rather than lost outright
        ev = self.store.ec_volumes.get(vid)
        mounted_want = [sid for sid in want
                        if ev is not None and sid in ev.shards]
        if mounted_want:
            self.store.unmount_ec_shards(vid, mounted_want)
        aside: list[int] = []
        for sid in want:
            p = base + ecpl.to_ext(sid)
            if os.path.exists(p):
                await self._in_executor(os.replace, p, p + ".rot")
                aside.append(sid)
        # 4. regenerate ONLY the requested rows from ONLY the
        # validated clean inputs (one batched dispatch per window
        # block), 5. return the borrowed inputs' disk space
        try:
            rebuilt = await self._in_executor(
                lambda: ecpl.rebuild_ec_files(base, targets=want,
                                              use=inputs))
        except (ValueError, OSError) as e:
            for sid in aside:       # restore + remount the old copies
                p = base + ecpl.to_ext(sid)
                await self._in_executor(os.replace, p + ".rot", p)
            await drop_fetched()
            if aside:
                try:
                    self.store.mount_ec_shards(collection, vid)
                except VolumeError as e2:
                    glog.warning("rebuild_shard vid=%d: restore "
                                 "remount: %s", vid, e2)
                await self._heartbeat_now()
            return web.json_response({"error": str(e)}, status=500)
        for sid in aside:
            await self._in_executor(self._unlink_quiet,
                                    base + ecpl.to_ext(sid) + ".rot")
        await drop_fetched()
        # 6. mount what this node now hosts and register it NOW — a
        # degraded read anywhere in the cluster must find the repaired
        # shard without waiting out a pulse
        try:
            shards = self.store.mount_ec_shards(collection, vid)
        except VolumeError as e:
            # nothing got mounted: don't leave just-rebuilt shard
            # files orphaned on disk to be resurrected as a stale
            # generation later
            for sid in rebuilt:
                await self._in_executor(self._unlink_quiet,
                                        base + ecpl.to_ext(sid))
            return web.json_response({"error": str(e)}, status=409)
        await self._heartbeat_now()
        return web.json_response({"rebuilt": rebuilt, "mounted": shards,
                                  "fetched_inputs": fetched})

    async def h_ec_verify(self, req: web.Request) -> web.Response:
        """Parity scrub of a mounted EC volume (EcVolume.verify_parity):
        recomputes RS(10,4) parity for every stripe window through the
        configured encoder (TPU when attached) and reports corrupt
        window offsets. No reference RPC — its integrity checking stops
        at per-needle CRC on read (needle/crc.go)."""
        vid = int(req.query["volume"])
        ev = self.store.ec_volumes.get(vid)
        if ev is None:
            return web.json_response({"error": f"ec volume {vid} not "
                                      f"mounted"}, status=404)
        window = int(req.query.get("windowMB", 1)) << 20
        try:
            report = await self._in_executor(lambda: ev.verify_parity(window))
        except (OSError, EcVolumeError) as e:
            return web.json_response({"error": str(e)}, status=500)
        report["volume"] = vid
        return web.json_response(report)

    async def h_ec_mount(self, req: web.Request) -> web.Response:
        vid = int(req.query["volume"])
        collection = req.query.get("collection", "")
        try:
            shards = self.store.mount_ec_shards(collection, vid)
        except VolumeError as e:
            return web.json_response({"error": str(e)}, status=404)
        # push the registration NOW, not at the next pulse: a read that
        # lands anywhere in the cluster within the pulse window needs
        # the master to know these shard locations, or reconstruction
        # fails with too few sources (the reference's delta heartbeat
        # channel, volume_grpc_client_to_master.go:120-177)
        await self._heartbeat_now()
        return web.json_response({"shards": shards})

    async def _heartbeat_now(self) -> None:
        try:
            if not await self.heartbeat_once():
                # a follower redirected us: the LEADER must learn the
                # new state now, not at the next pulse
                await self.heartbeat_once()
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
            glog.warning("immediate heartbeat failed: %s", e)

    async def h_ec_unmount(self, req: web.Request) -> web.Response:
        vid = int(req.query["volume"])
        ids = req.query.get("shards", "")
        shard_ids = [int(x) for x in ids.split(",") if x] if ids else None
        self.store.unmount_ec_shards(vid, shard_ids)
        await self._heartbeat_now()
        return web.json_response({"ok": True})

    async def h_ec_copy(self, req: web.Request) -> web.Response:
        """VolumeEcShardsCopy (volume_grpc_erasure_coding.go:100-148):
        pull shard files (and optionally .ecx/.ecj) from a source server."""
        q = req.query
        vid = int(q["volume"])
        collection = q.get("collection", "")
        source = q["source"]
        shard_ids = [int(x) for x in q.get("shards", "").split(",") if x]
        copy_ecx = q.get("copy_ecx", "") == "1"
        d = self.store.dirs[0]
        base = os.path.join(
            d, f"{collection}_{vid}" if collection else str(vid))
        exts = [ecpl.to_ext(sid) for sid in shard_ids]
        if copy_ecx:
            exts += [".ecx", ".ecj"]
        for ext in exts:
            try:
                await failpoints.fail("volume.ec_copy.fetch")
                async with self._http.get(
                        tls.url(source, "/admin/file"),
                        params={"volume": str(vid),
                                "collection": collection,
                                "ext": ext}) as resp:
                    if resp.status != 200:
                        if ext == ".ecj":  # journal may not exist yet
                            continue
                        return web.json_response(
                            {"error": f"fetch {ext} from {source}: "
                                      f"{resp.status}"}, status=502)
                    # shard files are volume-sized: file I/O off-loop
                    f = await self._in_executor(open, base + ext, "wb")
                    try:
                        async for chunk in resp.content.iter_chunked(
                                1 << 20):
                            await self._in_executor(f.write, chunk)
                    finally:
                        await self._in_executor(f.close)
            except (aiohttp.ClientError, OSError) as e:
                return web.json_response({"error": str(e)}, status=502)
        return web.json_response({"ok": True})

    async def h_ec_delete_shards(self, req: web.Request) -> web.Response:
        q = req.query
        vid = int(q["volume"])
        collection = q.get("collection", "")
        shard_ids = [int(x) for x in q.get("shards", "").split(",") if x]
        base = self._base_name(vid, collection)
        if base:
            exts = [ecpl.to_ext(sid) for sid in shard_ids]
            if q.get("ecx", "") == "1":  # full teardown (ec.decode)
                exts += [".ecx", ".ecj"]
            for ext in exts:
                p = base + ext
                if os.path.exists(p):
                    await self._in_executor(os.remove, p)
        return web.json_response({"ok": True})

    async def h_ec_to_volume(self, req: web.Request) -> web.Response:
        """VolumeEcShardsToVolume (volume_grpc_erasure_coding.go:350-379):
        collected data shards + .ecx -> .dat + .idx on disk, ready for
        /admin/volume/mount. The ec.decode shell command gathers the
        shards here first (command_ec_decode.go)."""
        vid = int(req.query["volume"])
        collection = req.query.get("collection", "")
        base = self._base_name(vid, collection)
        if base is None:
            return web.json_response({"error": f"ec volume {vid} not found"},
                                     status=404)

        def work():
            dat_size = ecpl.find_dat_file_size(base)
            ecpl.write_dat_file(base, dat_size,
                                large_block=self.store.ec_large_block,
                                small_block=self.store.ec_small_block)
            ecpl.write_idx_file_from_ec_index(base)
            return dat_size
        try:
            dat_size = await self._in_executor(work)
        except FileNotFoundError as e:
            # a data shard is absent on this node: the caller must gather
            # or rebuild shards 0..9 here first
            return web.json_response({"error": str(e)}, status=409)
        return web.json_response({"ok": True, "dat_size": dat_size})

    async def h_ec_shard_read(self, req: web.Request) -> web.Response:
        """VolumeEcShardRead (volume_grpc_erasure_coding.go:254-320).
        The batched form `?reads=sid:off:size,...` answers many
        intervals in one round trip using the shared batch framing —
        a degraded read's gather costs one request per holder."""
        q = req.query
        vid = int(q["volume"])
        if "reads" in q:
            try:
                reads = batchframe.parse_reads_spec(q["reads"])
            except ValueError:
                return web.json_response(
                    {"error": "bad reads spec"}, status=400)
            datas = await self._in_executor(
                self.store.read_ec_shard_intervals, vid, reads)
            return web.Response(
                body=batchframe.encode_shard_rows(reads, datas),
                content_type=batchframe.CONTENT_TYPE)
        data = await self._in_executor(lambda: self.store.read_ec_shard_interval(
                vid, int(q["shard"]), int(q["offset"]), int(q["size"])))
        if data is None:
            return web.json_response({"error": "shard not found"},
                                     status=404)
        return web.Response(body=data,
                            content_type="application/octet-stream")

    async def h_query(self, req: web.Request) -> web.StreamResponse:
        """Query pushdown (server/volume_grpc_query.go:12-67): stream
        JSONL of records from the listed fids matching a JSON filter."""
        from ..query import Filter, query_json
        from ..query.json_query import OPERANDS
        body = await req.json()
        fids = body.get("fromFileIds", body.get("fids", []))
        flt = Filter.from_dict(body.get("filter"))
        if flt is not None and flt.operand not in OPERANDS:
            return web.json_response(
                {"error": f"unknown operand {flt.operand!r}"}, status=400)
        selections = body.get("selections") or []
        resp = web.StreamResponse(
            headers={"Content-Type": "application/x-ndjson"})
        await resp.prepare(req)
        import json as _json

        def read_and_query(f: t.FileId) -> list[dict]:
            n = self.store.read_needle(f.volume_id, f.key, f.cookie)
            data = n.data
            if n.is_gzipped:
                data = gzip.decompress(data)
            return query_json(data, flt, selections)

        for fid_str in fids:
            try:
                fid = self._parse_fid(fid_str)
                recs = await self._in_executor(lambda: read_and_query(fid))
            except (ValueError, NotFound, AlreadyDeleted, VolumeError,
                    CrcMismatch, gzip.BadGzipFile, OSError, BackendError):
                continue
            for rec in recs:
                await resp.write(_json.dumps(rec).encode() + b"\n")
        await resp.write_eof()
        return resp

    async def h_admin_file(self, req: web.Request) -> web.Response:
        """Stream a raw volume/shard file (CopyFile analog for ec.copy)."""
        q = req.query
        vid = int(q["volume"])
        collection = q.get("collection", "")
        ext = q["ext"]
        allowed = {".dat", ".idx", ".ecx", ".ecj"} | {
            ecpl.to_ext(i) for i in range(gf.TOTAL_SHARDS)}
        if ext not in allowed:
            return web.json_response({"error": "bad ext"}, status=400)
        base = self._base_name(vid, collection)
        path = (base + ext) if base else None
        if not path or not os.path.exists(path):
            return web.json_response({"error": "file not found"}, status=404)
        return web.FileResponse(path)
