"""WebDAV gateway over the filer metadata tier.

Reference: weed/server/webdav_server.go (`WebDavFileSystem` implementing
golang.org/x/net/webdav on filer gRPC: webdav_server.go:64-366, chunked
WebDavFile.Write/Read :368-500) + weed/command/webdav.go. Here the DAV
protocol surface (OPTIONS/PROPFIND/MKCOL/GET/PUT/DELETE/MOVE/COPY and
class-2 advisory LOCK) is implemented directly on aiohttp; file bodies
are chunked into volume-server blobs exactly like the filer's own
auto-chunking write path.
"""

from __future__ import annotations

import asyncio
import time
import uuid
import xml.etree.ElementTree as ET
from urllib.parse import quote, unquote, urlparse

from aiohttp import web

from ..filer.entry import Attr, Entry, new_directory_entry
from ..filer.filechunks import FileChunk, view_from_chunks
from ..filer.stream import stream_chunk_views
from ..filer.filer import Filer, FilerError
from ..util.client import OperationError, WeedClient
from ..util.httprange import RangeError, parse_range
from ..security import tls

DAV_NS = "DAV:"
ET.register_namespace("D", DAV_NS)


def _rfc1123(ts: float) -> str:
    return time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime(ts or 0))


def _rfc3339(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts or 0))


class WebDavServer:
    def __init__(self, filer: Filer, master_url: str,
                 ip: str = "127.0.0.1", port: int = 7333,
                 collection: str = "", replication: str = "",
                 chunk_size: int = 16 * 1024 * 1024,
                 jwt_key: str = "",
                 cache_mem_bytes: int = 0,
                 cache_dir: str = "",
                 shard_router=None):
        # sharded gateway fleet (filer/shard.py GatewayRouter): the
        # WebDAV namespace IS the filer namespace, so foreign paths
        # bounce straight to the owning sibling
        self.shard_router = shard_router
        self._shard_http = None
        self.filer = filer
        self.master_url = master_url
        self.ip = ip
        self.port = port
        self.collection = collection
        self.replication = replication
        self.chunk_size = chunk_size
        cc = None
        if cache_mem_bytes > 0:
            # -cache.mem/-cache.dir chunk read cache (see FilerServer)
            from ..util.chunk_cache import TieredChunkCache
            cc = TieredChunkCache(cache_mem_bytes,
                                  disk_dir=cache_dir or None)
        self.client = WeedClient(master_url, jwt_key=jwt_key,
                                 chunk_cache=cc)
        self._locks: dict[str, str] = {}  # path -> token (advisory)
        self._runner: web.AppRunner | None = None
        self._tasks: list[asyncio.Task] = []
        self.app = self._build_app()

    def _build_app(self) -> web.Application:
        app = web.Application(client_max_size=1024 * 1024 * 1024)
        # POST is not a WebDAV verb, but the flight-recorder twin
        # POST /__debug__/timeline?snap=1 needs it; dispatch confines
        # POST to that one path (anything else 405s, as before)
        for method in ("OPTIONS", "PROPFIND", "PROPPATCH", "MKCOL", "GET",
                       "HEAD", "PUT", "DELETE", "MOVE", "COPY", "LOCK",
                       "UNLOCK", "POST"):
            app.router.add_route(method, "/{path:.*}", self.dispatch)
        return app

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    async def start(self) -> None:
        await self.client.__aenter__()
        if self.shard_router is not None:
            import aiohttp
            self._shard_http = tls.make_session(
                timeout=aiohttp.ClientTimeout(total=10))
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.ip, self.port)
        await site.start()
        if self.port == 0:
            self.port = site._server.sockets[0].getsockname()[1]
        self._tasks.append(asyncio.create_task(self._chunk_gc_loop()))

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        if self._shard_http is not None:
            await self._shard_http.close()
        await self.client.__aexit__(None, None, None)
        if self._runner:
            await self._runner.cleanup()

    async def _chunk_gc_loop(self) -> None:
        """Delete orphaned chunks of overwritten/deleted files
        (filer_deletion.go:11-52 analog)."""
        while True:
            await asyncio.sleep(1.0)
            fids = self.filer.drain_pending_chunk_deletes()
            if fids:
                try:
                    await self.client.delete_fids(fids)
                except Exception:
                    # requeue so a transient volume-server outage doesn't
                    # leak the chunks forever (filer_server.py loop)
                    self.filer.delete_chunks(fids)

    # ---- dispatch ----

    async def dispatch(self, req: web.Request) -> web.StreamResponse:
        from ..util import tracing
        path = "/" + unquote(req.match_info["path"])
        while "//" in path:
            path = path.replace("//", "/")
        if path != "/":
            path = path.rstrip("/")
        if req.method == "GET" and path in ("/__debug__/traces",
                                            "/__debug__/requests"):
            # same shared handlers the filer/S3 surfaces register
            h_traces, h_requests = tracing.debug_handlers()
            return await (h_traces if path.endswith("traces")
                          else h_requests)(req)
        if req.method == "GET" and path in ("/__debug__/profile",
                                            "/__debug__/pprof"):
            from ..stats import profiler
            from ..util import pprof
            return await (profiler.debug_handler()
                          if path.endswith("profile")
                          else pprof.debug_handler())(req)
        if (req.method == "GET" and path in (
                "/__debug__/timeline", "/__debug__/events",
                "/__debug__/health", "/__debug__/qos")) or (
                req.method == "POST" and path == "/__debug__/timeline"):
            # flight-recorder twins: shared trio, no drift vs filer/S3
            # (POST only on timeline — ?snap=1 — exactly like the
            # add_get/add_post registrations on every other daemon)
            from .. import qos
            from ..stats.timeline import recorder_handlers
            h_tl, h_ev, h_hl = recorder_handlers()
            return await {"/__debug__/timeline": h_tl,
                          "/__debug__/events": h_ev,
                          "/__debug__/health": h_hl,
                          "/__debug__/qos": qos.debug_handler}[path](req)
        if self.shard_router is not None \
                and not path.startswith("/__debug__"):
            owner = await self.shard_router.foreign_owner(
                self._shard_http, path)
            if owner:
                self.shard_router.redirects += 1
                return web.Response(
                    status=307,
                    headers={"Location": tls.url(owner, req.path_qs),
                             "X-Shard-Owner": owner,
                             "X-Shard-Prefix":
                                 self.shard_router.matched_prefix(path),
                             "X-Shard-Epoch": str(
                                 self.shard_router.routes.map.epoch)})
        handler = getattr(self, f"h_{req.method.lower()}", None)
        if handler is None:
            return web.Response(status=405)
        from .. import qos
        op = req.method.lower()
        # tenant admission (seaweedfs_tpu/qos/): JWT / AWS-credential
        # identity when present, else the default class
        ctrl = qos.admission()
        dec = None
        if ctrl is not None:
            # weedlint: ignore[lock-acquire] admission decision, not a mutex: a denied Decision holds nothing, and the admitted path releases in the finally below
            dec = await ctrl.acquire(
                "webdav", op, qos.tenant_from_headers(req.headers))
            if not dec.admitted:
                return web.Response(
                    status=dec.status, text="request shed\n",
                    headers={"Retry-After": str(
                        max(1, int(dec.retry_after_s + 0.999)))})
            qos.set_current_class(dec.cls)
        t0 = time.perf_counter()
        try:
            # webdav-tier entry span: child client/volume/store spans
            # hang off it exactly as on the filer/S3 read paths
            with tracing.start_root(
                    "webdav", op, headers=req.headers,
                    **({"tenant": dec.tenant}
                       if dec is not None else {})) as sp:
                resp = await handler(req, path)
                sp.status = "ok" if resp.status < 400 \
                    else str(resp.status)
                return resp
        finally:
            if dec is not None:
                ctrl.release(dec)
                ctrl.observe("webdav", op, dec,
                             time.perf_counter() - t0)

    # ---- methods ----

    async def h_options(self, req: web.Request, path: str) -> web.Response:
        return web.Response(headers={
            "Allow": "OPTIONS, PROPFIND, PROPPATCH, MKCOL, GET, HEAD, PUT, "
                     "DELETE, MOVE, COPY, LOCK, UNLOCK",
            "DAV": "1, 2",
            "MS-Author-Via": "DAV",
        })

    def _prop_response(self, href: str, e: Entry) -> ET.Element:
        r = ET.Element(f"{{{DAV_NS}}}response")
        # percent-encode: names with '#', '%', spaces must form valid URIs
        ET.SubElement(r, f"{{{DAV_NS}}}href").text = quote(href)
        ps = ET.SubElement(r, f"{{{DAV_NS}}}propstat")
        prop = ET.SubElement(ps, f"{{{DAV_NS}}}prop")
        ET.SubElement(prop, f"{{{DAV_NS}}}displayname").text = \
            e.name if e.full_path != "/" else "/"
        ET.SubElement(prop, f"{{{DAV_NS}}}creationdate").text = \
            _rfc3339(e.attr.crtime)
        ET.SubElement(prop, f"{{{DAV_NS}}}getlastmodified").text = \
            _rfc1123(e.attr.mtime)
        rt = ET.SubElement(prop, f"{{{DAV_NS}}}resourcetype")
        if e.is_directory:
            ET.SubElement(rt, f"{{{DAV_NS}}}collection")
        else:
            ET.SubElement(prop, f"{{{DAV_NS}}}getcontentlength").text = \
                str(e.size)
            ET.SubElement(prop, f"{{{DAV_NS}}}getcontenttype").text = \
                e.attr.mime or "application/octet-stream"
        ET.SubElement(ps, f"{{{DAV_NS}}}status").text = "HTTP/1.1 200 OK"
        return r

    async def h_propfind(self, req: web.Request, path: str) -> web.Response:
        entry = self.filer.find_entry(path)
        if entry is None:
            return web.Response(status=404)
        depth = req.headers.get("Depth", "1")
        ms = ET.Element(f"{{{DAV_NS}}}multistatus")
        href = path + ("/" if entry.is_directory and path != "/" else "")
        ms.append(self._prop_response(href, entry))
        if entry.is_directory and depth != "0":
            for child in self.filer.list_directory_entries(
                    path, "", False, 10000):
                chref = child.full_path + \
                    ("/" if child.is_directory else "")
                ms.append(self._prop_response(chref, child))
        body = b'<?xml version="1.0" encoding="utf-8"?>' + \
            ET.tostring(ms)
        return web.Response(body=body, status=207,
                            content_type="application/xml")

    async def h_proppatch(self, req: web.Request, path: str) -> web.Response:
        if self.filer.find_entry(path) is None:
            return web.Response(status=404)
        # properties are not persisted (matches the reference's minimal
        # webdav.FileSystem which has no property store either)
        ms = ET.Element(f"{{{DAV_NS}}}multistatus")
        r = ET.SubElement(ms, f"{{{DAV_NS}}}response")
        ET.SubElement(r, f"{{{DAV_NS}}}href").text = path
        ps = ET.SubElement(r, f"{{{DAV_NS}}}propstat")
        ET.SubElement(ps, f"{{{DAV_NS}}}status").text = \
            "HTTP/1.1 403 Forbidden"
        return web.Response(
            body=b'<?xml version="1.0" encoding="utf-8"?>' +
            ET.tostring(ms),
            status=207, content_type="application/xml")

    async def h_mkcol(self, req: web.Request, path: str) -> web.Response:
        if self.filer.find_entry(path) is not None:
            return web.Response(status=405)  # already exists
        if self.filer.find_entry(self._parent(path)) is None:
            return web.Response(status=409)  # missing intermediate
        self.filer.create_entry(new_directory_entry(path))
        return web.Response(status=201)

    async def h_get(self, req: web.Request, path: str) -> web.StreamResponse:
        entry = self.filer.find_entry(path)
        if entry is None:
            return web.Response(status=404)
        if entry.is_directory:
            names = [e.name + ("/" if e.is_directory else "")
                     for e in self.filer.list_directory_entries(
                         path, "", False, 10000)]
            return web.Response(text="\n".join(names),
                                content_type="text/plain")
        size = entry.size
        status, offset, length = 200, 0, size
        try:
            rng = parse_range(req.headers.get("Range", ""), size)
        except RangeError:
            return web.Response(status=416)
        if rng is not None:
            offset, length = rng
            status = 206
        headers = {"Content-Length": str(length),
                   "Accept-Ranges": "bytes",
                   "Last-Modified": _rfc1123(entry.attr.mtime)}
        if status == 206:
            headers["Content-Range"] = \
                f"bytes {offset}-{offset+length-1}/{size}"
        ct = entry.attr.mime or "application/octet-stream"
        if req.method == "HEAD":
            return web.Response(status=status, headers=headers,
                                content_type=ct)
        resp = web.StreamResponse(status=status, headers=headers)
        resp.content_type = ct
        await resp.prepare(req)
        try:
            async for data in stream_chunk_views(self.client, entry.chunks,
                                                 offset, length):
                await resp.write(data)
        except OperationError:
            if req.transport is not None:
                req.transport.close()
            return resp
        await resp.write_eof()
        return resp

    h_head = h_get

    async def h_put(self, req: web.Request, path: str) -> web.Response:
        if self.filer.find_entry(self._parent(path)) is None:
            return web.Response(status=409)
        existing = self.filer.find_entry(path)
        if existing is not None and existing.is_directory:
            return web.Response(status=405)
        # chunk the body as it streams in (WebDavFile.Write :444-480)
        chunks: list[FileChunk] = []
        offset = 0
        reader = req.content
        while True:
            data = await reader.read(self.chunk_size)
            if not data:
                break
            fid = await self.client.upload_data(
                data, collection=self.collection,
                replication=self.replication)
            chunks.append(FileChunk(file_id=fid, offset=offset,
                                    size=len(data),
                                    mtime=time.time_ns()))
            offset += len(data)
        now = time.time()
        entry = Entry(full_path=path,
                      attr=Attr(mtime=now, crtime=now, mode=0o660,
                                mime=req.headers.get("Content-Type", ""),
                                collection=self.collection,
                                replication=self.replication),
                      chunks=chunks)
        if existing is not None:
            self.filer.update_entry(existing, entry)
            self.filer.delete_chunks(
                [c.file_id for c in existing.chunks])
        else:
            self.filer.create_entry(entry)
        return web.Response(status=201 if existing is None else 204)

    async def h_delete(self, req: web.Request, path: str) -> web.Response:
        entry = self.filer.find_entry(path)
        if entry is None:
            return web.Response(status=404)
        try:
            self.filer.delete_entry(path, recursive=True)
        except FilerError as e:
            return web.Response(status=409, text=str(e))
        self._locks.pop(path, None)
        return web.Response(status=204)

    def _dest_path(self, req: web.Request) -> str | None:
        dest = req.headers.get("Destination", "")
        if not dest:
            return None
        p = unquote(urlparse(dest).path)
        if p != "/":
            p = p.rstrip("/")
        return p or None

    async def h_move(self, req: web.Request, path: str) -> web.Response:
        dest = self._dest_path(req)
        if dest is None:
            return web.Response(status=400)
        if self.filer.find_entry(path) is None:
            return web.Response(status=404)
        overwrite = req.headers.get("Overwrite", "T").upper() != "F"
        existing = self.filer.find_entry(dest)
        if existing is not None:
            if not overwrite:
                return web.Response(status=412)
            self.filer.delete_entry(dest, recursive=True)
        try:
            self.filer.rename_entry(path, dest)
        except FilerError as e:
            return web.Response(status=409, text=str(e))
        return web.Response(status=204 if existing else 201)

    async def h_copy(self, req: web.Request, path: str) -> web.Response:
        dest = self._dest_path(req)
        if dest is None:
            return web.Response(status=400)
        src = self.filer.find_entry(path)
        if src is None:
            return web.Response(status=404)
        overwrite = req.headers.get("Overwrite", "T").upper() != "F"
        existing = self.filer.find_entry(dest)
        if existing is not None:
            if not overwrite:
                return web.Response(status=412)
            self.filer.delete_entry(dest, recursive=True)
        await self._copy_recursive(src, dest)
        return web.Response(status=204 if existing else 201)

    async def _copy_recursive(self, src: Entry, dest: str) -> None:
        if src.is_directory:
            self.filer.create_entry(new_directory_entry(dest))
            for child in self.filer.list_directory_entries(
                    src.full_path, "", False, 10000):
                await self._copy_recursive(
                    child, dest + "/" + child.name)
            return
        # re-upload data so source and copy have independent chunks;
        # place each copied view at its logical offset so sparse holes
        # survive the copy
        chunks: list[FileChunk] = []
        for view in view_from_chunks(src.chunks, 0, src.size):
            data = await self.client.read(view.file_id, view.offset,
                                          view.size)
            fid = await self.client.upload_data(
                data, collection=self.collection,
                replication=self.replication)
            chunks.append(FileChunk(file_id=fid, offset=view.logic_offset,
                                    size=len(data),
                                    mtime=time.time_ns()))
        now = time.time()
        self.filer.create_entry(Entry(
            full_path=dest,
            attr=Attr(mtime=now, crtime=now, mode=src.attr.mode,
                      mime=src.attr.mime, collection=self.collection,
                      replication=self.replication),
            chunks=chunks))

    async def h_lock(self, req: web.Request, path: str) -> web.Response:
        """Advisory class-2 locks (enough for macOS/Windows clients that
        refuse to write without LOCK support)."""
        token = self._locks.get(path) or f"opaquelocktoken:{uuid.uuid4()}"
        self._locks[path] = token
        prop = ET.Element(f"{{{DAV_NS}}}prop")
        ld = ET.SubElement(prop, f"{{{DAV_NS}}}lockdiscovery")
        al = ET.SubElement(ld, f"{{{DAV_NS}}}activelock")
        lt = ET.SubElement(al, f"{{{DAV_NS}}}locktoken")
        ET.SubElement(lt, f"{{{DAV_NS}}}href").text = token
        ET.SubElement(al, f"{{{DAV_NS}}}timeout").text = "Second-3600"
        body = b'<?xml version="1.0" encoding="utf-8"?>' + \
            ET.tostring(prop)
        return web.Response(body=body, status=200,
                            content_type="application/xml",
                            headers={"Lock-Token": f"<{token}>"})

    async def h_unlock(self, req: web.Request, path: str) -> web.Response:
        self._locks.pop(path, None)
        return web.Response(status=204)

    @staticmethod
    def _parent(path: str) -> str:
        p = path.rsplit("/", 1)[0]
        return p or "/"
