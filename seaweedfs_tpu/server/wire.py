"""Unified volume data-plane protocol layer: ONE wire.

Both volume listeners — the hand-rolled raw HTTP/1.1 fast protocol
(server/fasthttp.py) and the aiohttp application (server/volume_server)
— feed the SAME parse/handle/respond functions here for the public
needle API: GET, POST/PUT, DELETE and the pipelined multi-needle
``/batch`` endpoint. The hot-needle cache peek, the tracing
attribution, the ``volume.read.http`` failpoint, Range/conditional
semantics, replication fan-out and group-commit writes are therefore
wired exactly once; a listener is only a transport adapter that builds
a :class:`WireRequest` and renders a :class:`WireResponse`.

Zero-copy: a cold read of a large plain needle resolves to a
:class:`NeedleRef` (storage/volume.py) instead of bytes — the raw
listener then moves the body disk->socket with ``loop.sendfile`` and
the span carries ``source=sendfile``. Responses the shared layer cannot
express for a given transport degrade explicitly: ``upgrade=True``
tells the raw listener to replay the request into aiohttp (chunked
manifests, multipart), ``manifest`` tells the aiohttp adapter to
stream the assembled file.
"""

from __future__ import annotations

import asyncio
import gzip

import aiohttp
import json
import re
import time
from dataclasses import dataclass, field

from ..storage import types as t
from ..storage.backend import BackendError
from ..storage.needle import (FLAG_HAS_LAST_MODIFIED,
                              FLAG_IS_CHUNK_MANIFEST, CrcMismatch, Needle,
                              NeedleError)
from ..storage.store import BatchBudgetExceeded
from ..storage.volume import AlreadyDeleted, NotFound, VolumeError
from ..ec.ec_volume import EcVolumeError
from ..ec import scrub as ec_scrub
from ..util import batchframe, failpoints, glog, tracing
from ..util.httprange import RangeError, parse_range
from ..security import tls

# cold bodies at least this large go disk->socket via sendfile on the
# raw listener; smaller ones aren't worth the extra header/meta preads
SENDFILE_MIN = 64 * 1024

# most fids a single /batch request may carry (overridable per server
# with -batch.max)
BATCH_MAX_DEFAULT = 256

OCTET = "application/octet-stream"


@dataclass
class WireRequest:
    """Transport-agnostic request: both listeners build one of these."""

    method: str                       # GET / POST / PUT / DELETE / HEAD
    fid_s: str = ""                   # "" for /batch
    query: dict = field(default_factory=dict)
    headers: dict = field(default_factory=dict)   # LOWER-CASED keys
    peer_ip: str | None = None
    body: bytes | None = None
    raw: bool = False                 # serving on the raw fast listener
    worker_hop: bool = False          # token-authenticated sibling hop


@dataclass
class WireResponse:
    status: int = 200
    headers: dict = field(default_factory=dict)
    body: bytes = b""
    content_type: str = OCTET
    head: bool = False                # HEAD: emit headers, no body
    # -- transport escape hatches --
    upgrade: bool = False             # raw listener: replay via aiohttp
    manifest: Needle | None = None    # aiohttp: stream assembled file
    drop: bool = False                # sever the connection, no answer
    truncate_to: int = -1             # failpoint: full CL, partial body
    sendfile: object | None = None    # storage.volume.NeedleRef

    @property
    def content_length(self) -> int:
        if self.sendfile is not None:
            return self.sendfile.length
        return len(self.body)


_REASONS = {200: "OK", 201: "Created", 206: "Partial Content",
            301: "Moved Permanently", 304: "Not Modified",
            400: "Bad Request", 401: "Unauthorized", 404: "Not Found",
            406: "Not Acceptable", 409: "Conflict",
            413: "Payload Too Large", 416: "Range Not Satisfiable",
            500: "Internal Server Error", 503: "Service Unavailable"}


def reason(status: int) -> str:
    return _REASONS.get(status, "Status")


def json_err(status: int, msg: str) -> WireResponse:
    return WireResponse(
        status=status, body=json.dumps({"error": msg}).encode(),
        content_type="application/json; charset=utf-8")


def json_ok(obj: dict, status: int = 200) -> WireResponse:
    return WireResponse(
        status=status, body=json.dumps(obj).encode(),
        content_type="application/json; charset=utf-8")


def observe(vs, op: str, t0: float, nbytes: int = 0) -> None:
    dur = time.perf_counter() - t0
    # the scrub pacer's pause-on-foreground-latency signal is THIS
    # feed — the same durations the request-seconds histogram sees, so
    # the pacer and the dashboards agree on what "foreground latency"
    # means (one lock-free deque append; see ec/scrub.ForegroundLoad)
    ec_scrub.foreground.note(dur)
    # ... and the bandwidth arbiter's foreground-PRESSURE feed is the
    # served bytes (qos/arbiter.py: background repair yields to this)
    from .. import qos
    qos.note_foreground(nbytes)
    from ..stats import metrics
    if metrics.HAVE_PROMETHEUS:
        metrics.VOLUME_REQUEST_TIME.labels(op).observe(dur)


# tiny cache of formatted Last-Modified values: needles written in the
# same second share the string, and strftime is the priciest call left
# on the cache-hot read path (carried over from the pre-unification
# fast listener, which measured exactly that)
_LM_CACHE: dict = {}


def http_date(ts: int) -> str:
    v = _LM_CACHE.get(ts)
    if v is None:
        v = time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime(ts))
        if len(_LM_CACHE) > 64:
            _LM_CACHE.clear()
        _LM_CACHE[ts] = v
    return v


def _guess_mime(fname: str, default: str) -> str:
    """Extension-derived mime, ONLY for plain extensions: guess_type
    splits 'a.tar.gz' into (application/x-tar, gzip) and serving the
    inner type for compressed bytes would mislabel the body."""
    import mimetypes
    guess, enc = mimetypes.guess_type(fname)
    return guess if guess and enc is None else default


def _disposition(query: dict, fname: str) -> str:
    """Content-Disposition with ?dl=true attachment support
    (volume_server_handlers_read.go:239-247); control characters
    stripped so a CR/LF in a stored name can't split the header."""
    fname = "".join(ch for ch in fname if ch >= " ")
    disp = ("attachment"
            if str(query.get("dl", "")).lower() in ("1", "true")
            else "inline")
    escaped = fname.replace("\\", "\\\\").replace('"', '\\"')
    return f'{disp}; filename="{escaped}"'


def check_jwt(vs, wr: WireRequest) -> WireResponse | None:
    """Write-token guard (volume_server_handlers_write.go:41-44),
    shared by POST/DELETE on both listeners. Replica writes must carry
    the forwarded per-fid token — a bare ?type=replicate does NOT
    bypass the guard."""
    if not vs.jwt_key:
        return None
    from ..security.jwt import (JwtError, check_write_jwt,
                                get_jwt_from_request)
    # the shared extractor expects canonical header casing; WireRequest
    # headers are lower-cased by contract
    token = get_jwt_from_request(
        {"Authorization": wr.headers.get("authorization", "")},
        wr.query)
    if not token:
        return json_err(401, "missing jwt")
    try:
        check_write_jwt(vs.jwt_key, token, wr.fid_s)
    except JwtError as e:
        return json_err(401, str(e))
    return None


# ---- GET ----

async def serve_read(vs, wr: WireRequest) -> WireResponse:
    """The one needle-GET implementation behind both listeners."""
    t0 = time.perf_counter()
    sp = tracing.current()
    try:
        fid = t.FileId.parse(wr.fid_s)
    except ValueError as e:
        return json_err(400, str(e))
    store = vs.store
    vid = fid.volume_id
    wc = vs.worker_ctx
    if wr.raw and wc is not None and not wc.owns(vid) \
            and not wr.worker_hop:
        # a sibling worker's partition: the aiohttp worker-routing
        # middleware owns the proxy hop — replay the request there
        return WireResponse(upgrade=True)
    if not store.has_volume(vid):
        if not vs.read_redirect:
            vs.count("read", "404")
            return json_err(404, "not found")
        # misrouted read: redirect via master lookup (handlers_read.go:46)
        try:
            async with vs._http.get(
                    tls.url(vs.master_url, "/dir/lookup"),
                    params={"volumeId": str(vid)},
                    timeout=aiohttp.ClientTimeout(total=5)) as resp:
                if resp.status != 200:
                    return json_err(404, "volume not found")
                locs = (await resp.json())["locations"]
        except (OSError, ValueError, KeyError,
                asyncio.TimeoutError, aiohttp.ClientError):
            # asyncio.TimeoutError is NOT an OSError on py3.10 — a
            # wedged master must produce the 404, not a 500
            return json_err(404, "volume not found")
        others = [l for l in locs if l["url"] != vs.url]
        if not others:
            return json_err(404, "volume not found")
        return WireResponse(
            status=301,
            headers={"Location": tls.url(others[0]["publicUrl"],
                                         f"/{wr.fid_s}")})
    # hot-needle cache peek: a hit answers on the event loop with zero
    # disk I/O and no executor round trip. count=False: accounting is
    # deferred until we know this layer actually serves the request
    # (a manifest replayed into aiohttp must not count twice).
    n = store.cached_needle(vid, fid.key, fid.cookie, count=False)
    from_cache = n is not None
    ref = None
    try:
        if n is None:
            # zero-copy eligibility is decided from REQUEST shape here
            # (body-shape checks below fall back): any listener — the
            # raw path and the frame adapter sendfile into the socket,
            # the aiohttp app drains the ref through a StreamResponse —
            # but nothing that forces the bytes through Python
            want_ref = (wr.method == "GET"
                        and wr.headers.get("etag-md5") != "True"
                        and "width" not in wr.query
                        and "height" not in wr.query
                        and not failpoints.pending("volume.read.http"))
            if want_ref:
                n, ref = await vs._in_executor(
                    store.read_needle_ex, vid, fid.key, fid.cookie,
                    vs.sendfile_min)
            else:
                n = await vs._in_executor(
                    store.read_needle, vid, fid.key, fid.cookie)
    except (NotFound, AlreadyDeleted):
        vs.count("read", "404")
        sp.status = "404"
        return WireResponse(status=404)
    except failpoints.FailpointDrop:
        sp.status = "drop"
        return WireResponse(drop=True)
    except failpoints.FailpointError as e:
        sp.status = str(e.status)
        return json_err(e.status, str(e))
    except CrcMismatch as e:
        sp.status = "500"
        return json_err(500, str(e))
    except (EcVolumeError, BackendError) as e:
        # retryable server-side degradation: an EC read that could not
        # gather enough shards or a tiered volume whose remote tier is
        # down — clean 503, never a traceback
        vs.count("read", "error")
        sp.status = "503"
        return json_err(503, str(e))
    try:
        return await _render_needle(vs, wr, fid, n, ref, from_cache,
                                    sp, t0)
    except BaseException:
        if ref is not None:
            ref.close()
        raise


async def _render_needle(vs, wr: WireRequest, fid, n: Needle, ref,
                         from_cache: bool, sp, t0: float
                         ) -> WireResponse:
    """Headers/conditionals/Range/response for one resolved needle.
    Owns ``ref``: every early return that doesn't hand it to the
    response closes it (the caller backstops on exceptions)."""
    store = vs.store
    is_manifest = n.is_chunked_manifest and wr.query.get("cm") != "false"
    if is_manifest and wr.raw:
        # manifest assembly streams through the aiohttp machinery; the
        # raw listener replays the request there (the full handler
        # does its own accounting, and its adapter cancels this span)
        if ref is not None:
            ref.close()
        return WireResponse(upgrade=True)
    headers: dict = {"Etag": f'"{n.etag()}"', "Accept-Ranges": "bytes"}
    if n.pairs:
        # stored pairs come back as response headers
        # (volume_server_handlers_read.go:123-132)
        try:
            pair_map = json.loads(n.pairs)
            if isinstance(pair_map, dict):
                headers.update({k: str(v) for k, v in pair_map.items()})
            else:
                glog.warning("pairs of %s: not a JSON object", wr.fid_s)
        except ValueError:
            glog.warning("unmarshal pairs of %s: bad json", wr.fid_s)
    # conditional checks BEFORE body work, as in the reference
    # (read.go:102-121 precede tryHandleChunkedFile)
    if n.last_modified:
        headers["Last-Modified"] = http_date(int(n.last_modified))
        ims = wr.headers.get("if-modified-since", "")
        if ims:
            import calendar
            try:
                # calendar.timegm, NOT mktime: the header is GMT and
                # mktime applies the host zone (DST included)
                at = calendar.timegm(time.strptime(
                    ims, "%a, %d %b %Y %H:%M:%S GMT"))
                if at >= int(n.last_modified):
                    if ref is not None:
                        ref.close()
                    _count_served(vs, store, n, from_cache, sp, t0)
                    return WireResponse(status=304, headers=headers,
                                        head=True)
            except ValueError:
                pass  # unparseable date: serve normally (ref parity)
    if wr.headers.get("if-none-match", "") == f'"{n.etag()}"':
        if ref is not None:
            ref.close()
        _count_served(vs, store, n, from_cache, sp, t0)
        return WireResponse(status=304, headers=headers, head=True)
    if wr.headers.get("etag-md5") == "True":
        # content-MD5 etag instead of the CRC one (read.go:117-121);
        # needs the bytes, so never on the ref path (see want_ref)
        import hashlib
        headers["Etag"] = f'"{hashlib.md5(n.data).hexdigest()}"'
    if is_manifest:
        # conditional checks ran ABOVE, as in the reference
        # (read.go:102-121 precede tryHandleChunkedFile — assembled
        # files are where a 304 saves the most); pairs + Last-Modified
        # ride into the streamed response's headers
        if ref is not None:
            # meta-only ref resolution can't feed the manifest parser
            ref.close()
            ref = None
            n = await vs._in_executor(store.read_needle, fid.volume_id,
                                      fid.key, fid.cookie)
        _count_served(vs, store, n, from_cache, sp, t0)
        return WireResponse(manifest=n, headers=headers)
    body = n.data
    if n.is_gzipped:
        if "gzip" in wr.headers.get("accept-encoding", ""):
            headers["Content-Encoding"] = "gzip"
        else:
            if ref is not None:
                # stored-gzipped body must be inflated in userspace:
                # fall back to the buffered read (rare: gzip + cold +
                # large)
                ref.close()
                ref = None
                n = await vs._in_executor(
                    store.read_needle, fid.volume_id, fid.key,
                    fid.cookie)
            body = gzip.decompress(n.data)
    ct = n.mime.decode() if n.mime else OCTET
    if n.name:
        fname = n.name.decode(errors="replace")
        ct = _guess_mime(fname, ct) if not n.mime else ct
        headers["Content-Disposition"] = _disposition(wr.query, fname)
    # on-read image resize (volume_server_handlers_read.go:211-227);
    # resize queries are excluded from the ref path by want_ref
    if ("width" in wr.query or "height" in wr.query) \
            and "Content-Encoding" not in headers \
            and wr.method != "HEAD":
        from ..images import resizing
        if resizing.resizable(ct):
            try:
                w = int(wr.query.get("width", 0) or 0)
                h = int(wr.query.get("height", 0) or 0)
            except ValueError:
                w = h = 0  # bad params: serve the original (ref parity)
            mode = wr.query.get("mode", "")
            if w > 0 or h > 0:
                data = body
                body = await vs._in_executor(
                    lambda: resizing.resized(ct, data, w, h, mode))
                headers.pop("Etag", None)
    status = 200
    if "Content-Encoding" not in headers:
        # serve byte ranges of the (plain) body — suffix, open-ended
        # and mid-body resume ranges included; 416 carries the total
        total = ref.length if ref is not None else len(body)
        try:
            rng = parse_range(wr.headers.get("range", ""), total)
        except RangeError:
            if ref is not None:
                ref.close()
            return WireResponse(
                status=416,
                headers={"Content-Range": f"bytes */{total}"})
        if rng is not None:
            off, ln = rng
            headers["Content-Range"] = f"bytes {off}-{off+ln-1}/{total}"
            status = 206
            if ref is not None:
                ref.slice(off, ln)
            else:
                body = body[off:off + ln]
    _count_served(vs, store, n, from_cache, sp, t0)
    if wr.method == "HEAD":
        if ref is not None:
            ref.close()
            ref = None
        sp.nbytes = 0
        return WireResponse(status=status, headers=headers,
                            content_type=ct, head=True)
    # chaos site volume.read.http: response-level error / latency /
    # drop / truncate (full Content-Length, partial body, dead socket —
    # the mid-read death degraded reads must survive). The ref path is
    # excluded while armed (want_ref), so body is always real here.
    if failpoints.armed():
        a = failpoints.take("volume.read.http")
        if a is not None:
            if a.action == "latency":
                await asyncio.sleep(float(a.arg or 0) / 1000.0)
            elif a.action == "error":
                if ref is not None:
                    ref.close()
                return json_err(int(a.arg or 500),
                                f"failpoint volume.read.http")
            elif a.action == "drop":
                if ref is not None:
                    ref.close()
                sp.status = "drop"
                return WireResponse(drop=True)
            else:  # truncate
                if ref is not None:
                    ref.close()
                keep = float(a.arg) if a.arg else 0.5
                return WireResponse(
                    status=status, headers=headers, content_type=ct,
                    body=body, truncate_to=int(len(body) * keep))
    if ref is not None:
        sp.set("source", "sendfile")
        sp.nbytes = ref.length
        return WireResponse(status=status, headers=headers,
                            content_type=ct, sendfile=ref)
    sp.nbytes = len(body)
    return WireResponse(status=status, headers=headers,
                        content_type=ct, body=body)


def _count_served(vs, store, n: Needle, from_cache: bool, sp,
                  t0: float) -> None:
    if from_cache:
        # deferred accounting for the served cache hit
        store.needle_cache.hit(n)
        sp.set("source", "cache")
    vs.count("read", "ok")
    observe(vs, "read", t0, nbytes=len(n.data or b""))


# ---- POST / PUT ----

def build_needle(fid, wr: WireRequest, data: bytes, name: bytes = b"",
                 mime: bytes = b"") -> Needle:
    """ParseUpload analog (needle.go:54) minus transport framing: the
    adapters extract (data, name, mime) — raw body or multipart part —
    and everything else (EXIF fix, pairs, ts/ttl validation, flags) is
    decided here once."""
    if not mime:
        ctype = wr.headers.get("content-type", "")
        if ctype and ctype != OCTET and not ctype.startswith("multipart/"):
            mime = ctype.split(";")[0].encode()
    if mime in (b"image/jpeg", b"image/jpg") or \
            (name.lower().endswith((b".jpg", b".jpeg")) and not mime):
        # bake EXIF rotation into stored bytes (needle.go ParseUpload)
        from ..images import fix_jpeg_orientation
        data = fix_jpeg_orientation(data)
    # Seaweed-* request headers ride along as needle pairs
    # (needle.go:19,55-60 PairNamePrefix), canonicalized like Go's
    # net/http does before the prefix check
    pair_map = {k.title(): v for k, v in wr.headers.items()
                if k.title().startswith("Seaweed-") and v}
    try:
        # client-supplied modified time (needle.go:80 "ts")
        last_modified = int(wr.query.get("ts", "") or time.time())
    except ValueError:
        last_modified = int(time.time())
    if not 0 <= last_modified < (1 << 40):
        # out of the 5-byte on-disk range: a negative/overflowed ts
        # must not crash serialization or corrupt TTL math
        last_modified = int(time.time())
    n = Needle(cookie=fid.cookie, id=fid.key, data=data, name=name,
               mime=mime, ttl=t.TTL.parse(wr.query.get("ttl", "")),
               pairs=(json.dumps(pair_map).encode() if pair_map else b""),
               last_modified=last_modified)
    n.set_flag(FLAG_HAS_LAST_MODIFIED)
    if wr.query.get("cm") in ("true", "1"):
        # chunk-manifest needle (needle_parse_multipart.go:86)
        n.set_flag(FLAG_IS_CHUNK_MANIFEST)
    return n


async def serve_write(vs, wr: WireRequest,
                      n: Needle | None = None) -> WireResponse:
    """The one needle-write implementation: jwt guard, needle build
    (unless the adapter pre-parsed a multipart upload into ``n``),
    group-commit store append, replication fan-out, 201."""
    t0 = time.perf_counter()
    sp = tracing.current()
    denied = check_jwt(vs, wr)
    if denied is not None:
        return denied
    try:
        fid = t.FileId.parse(wr.fid_s)
    except ValueError as e:
        return json_err(400, str(e))
    if n is None:
        if wr.headers.get("x-raw-needle") == "1":
            # replica write: body is the serialized needle record
            n = Needle.from_bytes(wr.body or b"", t.CURRENT_VERSION)
        else:
            try:
                n = build_needle(fid, wr, wr.body or b"")
            except (NeedleError, ValueError) as e:
                return json_err(400, str(e))
    try:
        _, size = await vs._in_executor(
            vs.store.write_needle, fid.volume_id, n)
    except NotFound:
        sp.status = "404"
        return json_err(404, "volume not found")
    except failpoints.FailpointDrop:
        sp.status = "drop"
        return WireResponse(drop=True)
    except failpoints.FailpointError as e:
        sp.status = str(e.status)
        return json_err(e.status, str(e))
    except NeedleError as e:
        # e.g. >64KB of Seaweed-* pair headers: a client error, not an
        # unhandled 500 (needle.py pairs-size limit)
        sp.status = "400"
        return json_err(400, str(e))
    except VolumeError as e:
        sp.status = "409"
        return json_err(409, str(e))
    sp.nbytes = len(n.data)
    vs.count("write", "ok")
    observe(vs, "write", t0, nbytes=len(n.data))
    # replicate unless this IS a replica write (store_replicate.go:21)
    if wr.query.get("type") != "replicate":
        v = vs.store.volumes.get(fid.volume_id)
        rp = v.super_block.replica_placement if v else None
        if rp and rp.copy_count > 1:
            ok = await vs._replicate(
                wr.fid_s, "POST", n.to_bytes(3),
                auth=wr.headers.get("authorization", ""))
            if not ok:
                return json_err(500, "replication failed")
    return json_ok({"name": n.name.decode(errors="replace"),
                    "size": size, "eTag": n.etag()}, status=201)


# ---- DELETE ----

async def serve_delete(vs, wr: WireRequest) -> WireResponse:
    """The one needle-delete implementation: jwt guard, chunk-manifest
    cascade, tombstone, replica/EC-shard fan-out."""
    sp = tracing.current()
    denied = check_jwt(vs, wr)
    if denied is not None:
        return denied
    try:
        fid = t.FileId.parse(wr.fid_s)
    except ValueError as e:
        return json_err(400, str(e))
    store = vs.store
    n = Needle(cookie=fid.cookie, id=fid.key)
    is_ec = fid.volume_id in store.ec_volumes
    # a chunk-manifest delete cascades to its chunks — also through the
    # EC read path, or a manifest in an EC-encoded volume would orphan
    # every chunk (volume_server_handlers_write.go DeleteHandler)
    if wr.query.get("type") != "replicate":
        try:
            existing = await vs._in_executor(
                lambda: store.read_needle(fid.volume_id, fid.key,
                                          fid.cookie))
            if existing.is_chunked_manifest:
                from ..util.chunked import ChunkManifest
                cm = ChunkManifest.load(existing.data,
                                        existing.is_gzipped)
                await cm.delete_chunks(vs._weed_client())
        except (NotFound, AlreadyDeleted):
            pass  # nothing stored: plain tombstone below
        except (ValueError, KeyError, BackendError) as e:
            # tier outage / corrupt manifest: still tombstone, but the
            # skipped cascade must be visible — its chunks may now be
            # orphaned
            glog.warning("delete %s: manifest cascade skipped: %s",
                         wr.fid_s, e)
    try:
        size = await vs._in_executor(
            lambda: store.delete_needle(fid.volume_id, n))
    except NotFound:
        sp.status = "404"
        return json_err(404, "volume not found")
    if wr.query.get("type") != "replicate":
        auth = wr.headers.get("authorization", "")
        if is_ec:
            # tombstone every shard holder's .ecx (DeleteEcShardNeedle
            # broadcast, store_ec_delete.go:15-101)
            await vs._ec_delete_broadcast(fid.volume_id, wr.fid_s, auth)
        else:
            v = store.volumes.get(fid.volume_id)
            rp = v.super_block.replica_placement if v else None
            if rp and rp.copy_count > 1:
                await vs._replicate(wr.fid_s, "DELETE", None, auth=auth)
    vs.count("delete", "ok")
    return json_ok({"size": size})


# ---- batch GET ----

_FID_TOKEN = re.compile(r"\d+,[0-9a-fA-F]+")


def _batch_fids(wr: WireRequest) -> list[str] | WireResponse:
    """fids from ?fids=... or a JSON body {"fileIds": [...]}. A fid
    itself contains a comma (vid,keycookie), so the query form is
    parsed structurally: every vid,hex token in order. Garbage between
    tokens is a client error, not a silent drop."""
    raw = wr.query.get("fids", "")
    if raw:
        fids = _FID_TOKEN.findall(raw)
        if not fids or ",".join(fids) != raw:
            return json_err(400, "bad fids list (want fid,fid,...)")
        return fids
    if wr.body:
        try:
            body = json.loads(wr.body)
            fids = body.get("fileIds", [])
            if isinstance(fids, list) and \
                    all(isinstance(f, str) for f in fids):
                return fids
        except ValueError:
            pass
        return json_err(400, "bad json body")
    return json_err(400, "no fids given")


def _row_for(vs, fid_s: str, n: Needle | Exception,
             from_cache: bool = False) -> tuple[dict, bytes]:
    """(meta, body) for one batch row; counts per-needle like a
    single GET so hit rates and read counters stay meaningful."""
    if isinstance(n, Exception):
        if isinstance(n, BatchBudgetExceeded):
            # over the response byte budget: the client re-fetches
            # this row as a streamed single GET
            return {"fid": fid_s, "status": 413, "error": str(n)}, b""
        if isinstance(n, (NotFound, AlreadyDeleted)):
            vs.count("read", "404")
            return {"fid": fid_s, "status": 404,
                    "error": str(n) or "not found"}, b""
        if isinstance(n, (EcVolumeError, BackendError)):
            vs.count("read", "error")
            return {"fid": fid_s, "status": 503, "error": str(n)}, b""
        if isinstance(n, failpoints.FailpointError):
            return {"fid": fid_s, "status": n.status, "error": str(n)}, b""
        vs.count("read", "error")
        return {"fid": fid_s, "status": 500, "error": str(n)}, b""
    if n.is_chunked_manifest:
        # assembly needs the full streaming machinery: the client
        # falls back to a single GET for this fid
        return {"fid": fid_s, "status": 406,
                "error": "chunked manifest: use single GET"}, b""
    if from_cache:
        vs.store.needle_cache.hit(n)
    meta = {"fid": fid_s, "status": 200, "etag": n.etag()}
    if n.mime:
        meta["mime"] = n.mime.decode(errors="replace")
    if n.is_gzipped:
        # stored-compressed bytes travel as-is; the flag tells the
        # client to inflate (batch is an SDK/bench surface, not a
        # browser one)
        meta["gzip"] = True
    vs.count("read", "ok")
    return meta, n.data


async def serve_batch(vs, wr: WireRequest) -> WireResponse:
    """Pipelined multi-needle GET: cache hits answer inline on the
    event loop, the cold remainder coalesces into ONE executor round
    trip, and under -workers the batch splits by vid ownership — each
    sibling gets one sub-batch request and the rows reassemble in
    request order."""
    t0 = time.perf_counter()
    sp = tracing.current()
    fids = _batch_fids(wr)
    if isinstance(fids, WireResponse):
        return fids
    if len(fids) > vs.batch_max:
        return json_err(413, f"batch of {len(fids)} exceeds "
                             f"-batch.max {vs.batch_max}")
    store = vs.store
    wc = vs.worker_ctx
    rows: list[tuple[dict, bytes] | None] = [None] * len(fids)
    local: list[tuple[int, object]] = []          # (row idx, FileId)
    sibling: dict[int, list[int]] = {}            # worker -> row idxs
    for i, fid_s in enumerate(fids):
        try:
            fid = t.FileId.parse(str(fid_s))
        except ValueError as e:
            rows[i] = ({"fid": str(fid_s), "status": 400,
                        "error": str(e)}, b"")
            continue
        if wc is not None and not wr.worker_hop \
                and not wc.owns(fid.volume_id):
            sibling.setdefault(wc.owner_index(fid.volume_id),
                               []).append(i)
            continue
        local.append((i, fid))
    # cache hits answer inline; misses coalesce into one executor
    # trip. A BYTE budget bounds the buffered response (reads are an
    # open endpoint — one request must not hold batch_max full bodies
    # in memory): over-budget rows answer 413 and the client re-reads
    # them as streamed single GETs.
    hits = 0
    used = 0
    misses: list[tuple[int, object]] = []
    for i, fid in local:
        n = store.cached_needle(fid.volume_id, fid.key, fid.cookie,
                                count=False)
        if n is None:
            misses.append((i, fid))
            continue
        if used + len(n.data) > vs.batch_bytes_max:
            rows[i] = ({"fid": fids[i], "status": 413,
                        "error": "batch byte budget exceeded"}, b"")
            continue
        used += len(n.data)
        rows[i] = _row_for(vs, fids[i], n, from_cache=True)
        hits += 1
    if misses:
        got = await vs._in_executor(
            store.read_needles,
            [(f.volume_id, f.key, f.cookie) for _, f in misses],
            max(0, vs.batch_bytes_max - used))
        for (i, _), n in zip(misses, got):
            rows[i] = _row_for(vs, fids[i], n)

    async def fan_out(idx: int, row_idxs: list[int]) -> None:
        addr = wc.sibling_addr(idx)
        sub = [fids[i] for i in row_idxs]
        parsed: list[tuple[dict, bytes]] | None = None
        # frame hop first: one multiplexed frame per sibling sub-batch
        # instead of a full HTTP request (the channel carries the
        # launch token; worker.frame faults and dead channels fall
        # back to the HTTP hop below)
        ch = vs.sibling_frame_channel(idx) \
            if hasattr(vs, "sibling_frame_channel") else None
        if ch is not None:
            headers: dict = {}
            tracing.inject(headers)
            try:
                status, _, payload = await ch.request(
                    "GET", "/batch", query={"fids": ",".join(sub)},
                    headers=headers)
                if status == 200:
                    parsed = batchframe.parse_all(payload)
            except (OSError, ValueError):
                parsed = None
            if parsed is not None:
                sp.event("sibling_batch", worker=idx,
                         transport="frame")
        if parsed is None and addr is not None:
            wk = _wk()
            headers = {wk.WORKER_HEADER: wc.token}
            tracing.inject(headers)
            try:
                await failpoints.fail("worker.forward")
                async with vs._http.get(
                        tls.url(addr, "/batch"),
                        params={"fids": ",".join(sub)},
                        headers=headers,
                        timeout=aiohttp.ClientTimeout(total=30)) as r:
                    if r.status == 200:
                        parsed = batchframe.parse_all(await r.read())
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                    ValueError):
                parsed = None
            if parsed is not None:
                sp.event("sibling_batch", worker=idx,
                         transport="http")
        if parsed is None or len(parsed) != len(row_idxs):
            for i in row_idxs:
                rows[i] = ({"fid": fids[i], "status": 503,
                            "error": f"worker {idx} unavailable"}, b"")
            return
        for i, rec in zip(row_idxs, parsed):
            rows[i] = rec

    if sibling:
        await asyncio.gather(*(fan_out(i, g) for i, g in
                               sibling.items()))
    out = bytearray()
    for i, row in enumerate(rows):
        if row is None:       # unreachable, but never emit a hole
            row = ({"fid": str(fids[i]), "status": 500,
                    "error": "no result"}, b"")
        out += batchframe.encode_record(row[0], row[1])
    sp.set("n", len(fids))
    sp.set("hits", hits)
    if sibling:
        sp.set("proxied", sum(len(g) for g in sibling.values()))
    sp.nbytes = len(out)
    vs.count("batch", "ok")
    observe(vs, "batch", t0, nbytes=len(out))
    return WireResponse(body=bytes(out),
                        content_type=batchframe.CONTENT_TYPE,
                        headers={"X-Batch-Count": str(len(fids))})


def _wk():
    """Lazy server.workers import (only -workers mode pays for it)."""
    from . import workers
    return workers
