"""Multi-core data plane: SO_REUSEPORT process-per-core workers.

Reference: the reference scales one process across cores via the Go
runtime (README.md:457-507 benchmarks on 4 cores); CPython cannot, so
`weed-tpu volume -workers N` (and `master -workers N`) runs N forked
worker PROCESSES that all listen on the same public port with
SO_REUSEPORT — the kernel load-balances accepted connections across
them and the hot path shares no state between cores at all.

Volume side: ownership is partitioned `volume_id % N` (storage/store.py
`partition`). Each worker is a full volume server with its own needle
maps and file handles (shared-nothing), registered with the master
under its own private port so master-directed traffic goes straight to
the owner; a request that lands on the wrong worker (kernel balancing
is connection-, not volume-aware) is proxied to the owning sibling over
loopback, authenticated by a per-launch shared token.

Master side: worker 0 is the full master (topology, raft, heartbeats —
necessarily single-process state); workers 1..N-1 are *assign
accelerators* that answer `GET /dir/assign` from a leased block of file
ids plus a sub-second cache of the writable-volume set, and
transparently proxy every other request to the primary. The hot
assign+write path therefore never serializes on one core.

The parent process is a plain supervisor: it spawns the workers,
restarts the ones that die (with backoff), and owns no socket — worker
state files under the state dir are the discovery plane for siblings,
metrics aggregation, and operators.
"""

from __future__ import annotations

import asyncio
import hmac
import json
import os
import re
import signal
import time

from ..security import tls
from ..util import failpoints, glog

# Shared-secret header marking an intra-host worker-to-worker hop. The
# token is minted per launch by the supervisor and travels via this
# environment variable, never argv (argv is world-readable in /proc).
WORKER_TOKEN_ENV = "SWTPU_WORKER_TOKEN"
WORKER_HEADER = "X-Swtpu-Worker"
FORWARDED_HEADER = "X-Forwarded-For"

# hop-by-hop (plus hop-specific entity) headers never forwarded verbatim
_HOP_HEADERS = {
    "connection", "keep-alive", "proxy-authenticate",
    "proxy-authorization", "te", "trailer", "transfer-encoding",
    "upgrade", "host", "content-length",
}
_HOP_RESPONSE_EXTRA = {"content-encoding", "date", "server"}


class WorkerContext:
    """One worker's identity + sibling discovery.

    Sibling addresses come from per-worker JSON state files in
    `state_dir` (written atomically on start/restart), so discovery
    survives a sibling respawning on a new ephemeral private port."""

    STATE_TTL = 0.5  # seconds a cached sibling state file read lives

    def __init__(self, index: int, total: int, public_port: int,
                 state_dir: str, token: str = ""):
        if not 0 <= index < total:
            raise ValueError(f"worker index {index} not in [0, {total})")
        self.index = index
        self.total = total
        self.public_port = public_port
        self.state_dir = state_dir
        self.token = token or os.environ.get(WORKER_TOKEN_ENV, "")
        self._cache: dict[int, tuple[float, dict | None]] = {}

    # -- partition --

    def owns(self, vid: int) -> bool:
        return vid % self.total == self.index

    def owner_index(self, vid: int) -> int:
        return vid % self.total

    def token_ok(self, value: str | None) -> bool:
        return bool(self.token) and \
            hmac.compare_digest(self.token, value or "")

    # -- state files --

    def state_path(self, index: int | None = None) -> str:
        i = self.index if index is None else index
        return os.path.join(self.state_dir, f"worker{i}.json")

    def write_state(self, **info) -> None:
        os.makedirs(self.state_dir, exist_ok=True)
        info = {"index": self.index, "pid": os.getpid(),
                "public_port": self.public_port, **info}
        path = self.state_path()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(info, f)
        os.replace(tmp, path)
        self._cache.pop(self.index, None)

    def read_state(self, index: int) -> dict | None:
        now = time.monotonic()
        hit = self._cache.get(index)
        if hit is not None and now - hit[0] < self.STATE_TTL:
            return hit[1]
        st: dict | None = None
        try:
            with open(self.state_path(index)) as f:
                st = json.load(f)
        except (OSError, ValueError):
            st = None
        self._cache[index] = (now, st)
        return st

    def sibling_addr(self, index: int) -> str | None:
        """ip:private_port of worker `index`, or None while it is down
        or still starting."""
        st = self.read_state(index)
        if not st or "ip" not in st or "port" not in st:
            return None
        return f"{st['ip']}:{st['port']}"

    def sibling_frame(self, index: int) -> tuple[str, str]:
        """(unix socket path, tcp ip:port) for worker `index`'s frame
        listener — the intra-host binary wire. Either may be empty:
        the unix socket when the worker could not bind one (path too
        long for sockaddr_un), both while the worker is down."""
        st = self.read_state(index)
        if not st:
            return "", ""
        tcp = f"{st['ip']}:{st['port']}" \
            if "ip" in st and "port" in st else ""
        return str(st.get("frame_sock", "") or ""), tcp

    def owner_addr(self, vid: int) -> str | None:
        return self.sibling_addr(self.owner_index(vid))

    def all_states(self) -> list[dict | None]:
        return [self.read_state(i) for i in range(self.total)]


async def proxy_request(req, session, target: str, token: str,
                        fire_failpoint: bool = True):
    """Stream one aiohttp request to a sibling worker and its response
    back — the in-worker proxy for needles/volumes owned by another
    partition. Small bodies are buffered so the sibling's raw fast path
    can serve them; large ones stream (chunked) and land on the
    sibling's aiohttp app."""
    import aiohttp
    from aiohttp import web
    from ..util import failpoints
    if fire_failpoint:
        try:
            # chaos site: injected sibling-hop faults (FailpointError
            # and FailpointDrop are OSErrors) take the same 502 path a
            # crashed worker does, which is what trips the caller's
            # breaker. The volume worker middleware fires this site
            # ITSELF (before its frame-first attempt) and passes
            # fire_failpoint=False so one hop never burns two counts.
            await failpoints.fail("worker.proxy")
        except OSError as e:
            return web.json_response(
                {"error": f"worker proxy to {target}: {e}"}, status=502)
    headers = {k: v for k, v in req.headers.items()
               if k.lower() not in _HOP_HEADERS
               and k.lower() != "accept-encoding"}
    headers[WORKER_HEADER] = token
    if req.remote:
        headers[FORWARDED_HEADER] = req.remote
    # trace propagation: the caller's proxy span (set on the context by
    # the routing middleware) becomes the parent of the sibling's
    # server span, so a cross-worker hop stays ONE trace — this
    # overrides the client's original traceparent, which the proxy
    # span already chains to
    from ..util import tracing
    tracing.inject(headers)
    body = None
    if req.method not in ("GET", "HEAD"):
        cl = req.headers.get("Content-Length", "")
        if cl.isdigit() and int(cl) <= (8 << 20):
            body = await req.read()
        else:
            body = req.content           # stream large/unsized bodies
    resp = None
    try:
        async with session.request(
                req.method, tls.url(target, req.path_qs),
                data=body, headers=headers,
                allow_redirects=False) as r:
            out_headers = [
                (k, v) for k, v in r.headers.items()
                if k.lower() not in _HOP_HEADERS
                and k.lower() not in _HOP_RESPONSE_EXTRA]
            resp = web.StreamResponse(status=r.status, reason=r.reason)
            for k, v in out_headers:
                resp.headers.add(k, v)
            if "Content-Length" in r.headers and \
                    "Content-Encoding" not in r.headers:
                resp.content_length = int(r.headers["Content-Length"])
            await resp.prepare(req)
            async for chunk in r.content.iter_chunked(1 << 16):
                await resp.write(chunk)
            await resp.write_eof()
            return resp
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
        if resp is not None and resp.prepared:
            # the sibling died MID-BODY: headers (and part of the
            # body) are already on the wire — abort the connection so
            # the client sees a transport error, never a 502 JSON
            # spliced into the needle bytes
            glog.warning("worker proxy to %s died mid-body: %s",
                         target, e)
            if req.transport is not None:
                req.transport.close()
            return resp
        return web.json_response(
            {"error": f"worker proxy to {target}: {e}"}, status=502)


# frame-path proxy ceiling: bodies above this stream over the HTTP
# hop (frames buffer one request per frame)
FRAME_PROXY_MAX_BODY = 8 << 20


def frame_eligible(req) -> bool:
    """May this sibling-bound request ride the binary frame hop?
    Needle-path methods only (admin tail/copy stream GBs and keep the
    chunked HTTP hop), with a small declared body."""
    if not re.match(r"^/\d+,", req.path):
        return False
    if req.method in ("GET", "HEAD"):
        return True
    if req.method in ("POST", "PUT"):
        cl = req.headers.get("Content-Length", "")
        return cl.isdigit() and int(cl) <= FRAME_PROXY_MAX_BODY
    if req.method == "DELETE":
        # normally bodyless (no Content-Length), but any declared
        # body is buffered into ONE frame — cap it like writes, and
        # refuse chunked (unsized) bodies outright, so an oversized
        # payload can never emit a frame the peer's decoder must
        # reject (tearing the multiplexed channel)
        if "Transfer-Encoding" in req.headers:
            return False
        cl = req.headers.get("Content-Length", "") or "0"
        return cl.isdigit() and int(cl) <= FRAME_PROXY_MAX_BODY
    return False


async def proxy_request_frame(req, ch):
    """Frame-path twin of :func:`proxy_request`: one multiplexed frame
    to the owning sibling instead of a full HTTP request. Hop-by-hop
    (and hop-specific entity) headers are stripped in BOTH directions
    exactly like the HTTP hop. Raises FrameChannelError/FrameFallback
    for the caller's HTTP fallback — nothing has touched the client
    connection yet at that point."""
    from aiohttp import web
    from ..util import tracing
    headers = {k.lower(): v for k, v in req.headers.items()
               if k.lower() not in _HOP_HEADERS
               and k.lower() != "accept-encoding"}
    if req.remote:
        headers[FORWARDED_HEADER.lower()] = req.remote
    # trace propagation: same discipline as the HTTP hop — the proxy
    # span on the context parents the sibling's server span
    tracing.inject(headers)
    body = b""
    if req.method not in ("GET", "HEAD"):
        body = await req.read()
    status, out_headers, payload = await ch.request(
        req.method, req.path, query=dict(req.query), headers=headers,
        body=body)
    resp = web.Response(status=status, body=payload)
    ct = None
    for k, v in out_headers.items():
        lk = k.lower()
        if lk in _HOP_HEADERS or lk in _HOP_RESPONSE_EXTRA:
            continue
        if lk == "content-type":
            ct = v
            continue
        resp.headers.add(k, v)
    if ct:
        resp.content_type = ct.partition(";")[0]
        charset = ct.partition("charset=")[2].strip()
        if charset:
            resp.charset = charset
    return resp


class Supervisor:
    """Parent of the worker fleet: spawn, monitor, respawn with backoff.

    No socket lives here — the workers own the SO_REUSEPORT listeners —
    so a supervisor restart (or even its death) never drops the data
    plane; it only suspends crash recovery."""

    def __init__(self, build_argv, total: int, env: dict | None = None,
                 min_backoff: float = 0.5, max_backoff: float = 10.0,
                 stable_s: float = 30.0):
        self.build_argv = build_argv       # callable(index) -> argv list
        self.total = total
        self.env = env
        self.min_backoff = min_backoff
        self.max_backoff = max_backoff
        self.stable_s = stable_s
        self.procs: dict[int, asyncio.subprocess.Process] = {}
        self.restarts = 0
        self._respawns: dict[int, int] = {}
        self._stopping = False
        self._tasks: list[asyncio.Task] = []

    async def start(self) -> None:
        for i in range(self.total):
            await self._spawn(i)
        self._tasks = [asyncio.get_running_loop().create_task(
            self._monitor(i)) for i in range(self.total)]

    async def _spawn(self, index: int) -> None:
        argv = self.build_argv(index)
        env = self.env
        n = self._respawns.get(index, 0)
        if n:
            # the respawned worker journals its own worker_respawn
            # event at boot (cli.main): the supervisor serves no HTTP,
            # so an event recorded HERE would be unobservable
            env = dict(env if env is not None else os.environ)
            env["WEED_WORKER_RESPAWNS"] = str(n)
        self.procs[index] = await asyncio.create_subprocess_exec(
            *argv, env=env)
        glog.info("worker %d spawned (pid %d)", index,
                  self.procs[index].pid)

    async def _monitor(self, index: int) -> None:
        backoff = self.min_backoff
        while not self._stopping:
            p = self.procs[index]
            t0 = time.monotonic()
            rc = await p.wait()
            if self._stopping:
                return
            if time.monotonic() - t0 > self.stable_s:
                backoff = self.min_backoff   # it ran fine for a while
            glog.warning("worker %d (pid %d) exited rc=%s; respawning "
                         "in %.1fs", index, p.pid, rc, backoff)
            self.restarts += 1
            # the respawn event is journaled by the respawned worker at
            # boot (WEED_WORKER_RESPAWNS via _spawn): the supervisor
            # serves no HTTP, so a ring entry here would be unobservable
            self._respawns[index] = self._respawns.get(index, 0) + 1
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, self.max_backoff)
            if not self._stopping:
                await self._spawn(index)

    async def stop(self, sig: int = signal.SIGTERM) -> None:
        self._stopping = True
        for task in self._tasks:
            task.cancel()
        for p in self.procs.values():
            if p.returncode is None:
                try:
                    p.send_signal(sig)
                except ProcessLookupError:
                    pass
        for p in self.procs.values():
            try:
                await asyncio.wait_for(p.wait(), timeout=10)
            except asyncio.TimeoutError:
                p.kill()
                await p.wait()


def fresh_state_dir(path: str) -> str:
    """Create the worker-state directory, dropping stale state files
    from a previous launch (their private ports are dead)."""
    os.makedirs(path, exist_ok=True)
    for name in os.listdir(path):
        if name.startswith("worker") and name.endswith(".json"):
            try:
                os.remove(os.path.join(path, name))
            except OSError:
                pass
    return path


# ---------------------------------------------------------------------------
# master-side assign accelerator (workers 1..N-1 of `master -workers N`)


class _AssignState:
    """Writable-volume snapshot for one layout key."""

    __slots__ = ("ts", "entries", "rr")

    def __init__(self, entries: list[dict]):
        self.ts = time.monotonic()
        self.entries = entries
        self.rr = 0


class AssignAccelerator:
    """SO_REUSEPORT sibling of the primary master that serves the one
    hot master route — `GET /dir/assign` — without touching the
    primary: file ids come from leased blocks (`/cluster/seq_lease`)
    and volume picks from a sub-second snapshot of the writable set
    (`/cluster/assign_state`). Anything it cannot answer (growth
    needed, unknown knobs, cold routes, heartbeats, raft) is
    transparently proxied to the primary's private listener, so the
    cluster behaves exactly like a single master."""

    STATE_TTL = 0.7          # seconds an assign-state snapshot stays hot
    LEASE_BLOCK = 4096       # file ids leased per refill round-trip
    LEASE_LOW = 256          # refill in the background below this

    def __init__(self, ip: str, port: int, ctx: WorkerContext,
                 white_list: list[str] | None = None, jwt_key: str = "",
                 default_replication: str = "000"):
        from aiohttp import web
        from ..security.guard import Guard
        self.ip = ip
        self.port = port
        self.ctx = ctx
        self.guard = Guard(white_list or ())
        self.jwt_key = jwt_key
        self.default_replication = default_replication
        self._states: dict[tuple, _AssignState] = {}
        self._lease_next = 0
        self._lease_end = 0
        self._jobs: set = set()          # in-flight refresh/refill keys
        self._job_tasks: set = set()     # strong refs (loop holds weak)
        self._http = None
        self._runner = None
        self._server = None
        self.assigned = 0                # fast assigns answered here
        self.proxied = 0                 # requests handed to the primary
        app = web.Application(client_max_size=64 * 1024 * 1024)
        app.router.add_route("*", "/{tail:.*}", self._h_proxy)
        self.app = app

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    def primary_addr(self) -> str | None:
        return self.ctx.sibling_addr(0)

    async def start(self) -> None:
        import aiohttp
        from aiohttp import web
        # total=None: /cluster/watch subscribers stream through this
        # proxy for their whole lifetime
        self._http = tls.make_session(
            timeout=aiohttp.ClientTimeout(total=None, sock_connect=10))
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        from .fasthttp import AcceleratorAssignProtocol
        self._server = await asyncio.get_running_loop().create_server(
            lambda: AcceleratorAssignProtocol(self), self.ip, self.port,
            ssl=tls.server_ctx(), reuse_address=True, reuse_port=True)
        self.ctx.write_state(ip=self.ip, port=self.port, role="assign")
        self._schedule(("lease",), self._refill())
        self._schedule(("state", "", self.default_replication, ""),
                       self._refresh("", self.default_replication, ""))

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            for tr in list(getattr(self, "_fast_conns", ())):
                tr.close()
        if self._http:
            await self._http.close()
        if self._runner:
            await self._runner.cleanup()

    # -- background state/lease maintenance --

    def _schedule(self, key: tuple, coro) -> None:
        """At most one in-flight job per key; the task handle is
        retained until done (an unreferenced task may be GC'd)."""
        if key in self._jobs:
            coro.close()
            return
        self._jobs.add(key)
        task = asyncio.get_running_loop().create_task(coro)
        self._job_tasks.add(task)

        def done(_t) -> None:
            self._jobs.discard(key)
            self._job_tasks.discard(task)

        task.add_done_callback(done)

    async def _refresh(self, collection: str, replication: str,
                       ttl: str) -> None:
        import aiohttp
        target = self.primary_addr()
        if target is None:
            return
        try:
            await failpoints.fail("master.lease")
            async with self._http.get(
                    tls.url(target, "/cluster/assign_state"),
                    params={"collection": collection,
                            "replication": replication, "ttl": ttl},
                    headers={WORKER_HEADER: self.ctx.token},
                    timeout=aiohttp.ClientTimeout(total=5)) as r:
                if r.status != 200:
                    return
                body = await r.json()
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
            return
        if len(self._states) > 256:
            # layout keys come from client query params: bound the cache
            self._states.clear()
        self._states[(collection, replication, ttl)] = \
            _AssignState(body.get("entries", []))

    async def _refill(self) -> None:
        import aiohttp
        target = self.primary_addr()
        if target is None:
            return
        try:
            await failpoints.fail("master.lease")
            async with self._http.get(
                    tls.url(target, "/cluster/seq_lease"),
                    params={"count": str(self.LEASE_BLOCK)},
                    headers={WORKER_HEADER: self.ctx.token},
                    timeout=aiohttp.ClientTimeout(total=5)) as r:
                if r.status != 200:
                    return
                body = await r.json()
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
            return
        # the remainder of the old lease is abandoned — ids are sparse
        # by design and a gap is cheaper than interleaving blocks
        self._lease_next = int(body["start"])
        self._lease_end = self._lease_next + int(body["count"])

    # -- the synchronous hot path (called from the raw protocol) --

    def fast_assign(self, q: bytes, peer_ip: str | None) -> bytes | None:
        """Answer GET /dir/assign from local state; None => proxy."""
        from ..storage import types as t
        from .fasthttp import _R401_IP
        # guard FIRST, against the real client socket: every later
        # `return None` proxies to the primary with this worker's token,
        # and the primary trusts that hop — so nothing may be proxied
        # that did not already pass the whitelist here
        if not self.guard.empty and not self.guard.allows(peer_ip):
            return _R401_IP
        count_s = collection = replication = ttl = b""
        if q not in (b"", b"?"):
            if b"%" in q or b"+" in q:
                return None
            for kv in q[1:].split(b"&"):
                k, _, val = kv.partition(b"=")
                if k == b"count":
                    count_s = val
                elif k == b"collection":
                    collection = val
                elif k == b"replication":
                    replication = val
                elif k == b"ttl":
                    ttl = val
                elif k not in (b"",):
                    return None       # dataCenter etc: primary decides
        try:
            count = int(count_s or 1)
        except ValueError:
            return None
        if count < 1:
            return None
        key = (collection.decode(),
               replication.decode() or self.default_replication,
               ttl.decode())
        st = self._states.get(key)
        now = time.monotonic()
        if st is None or now - st.ts > self.STATE_TTL:
            self._schedule(("state",) + key,
                           self._refresh(*key))
        if st is None or not st.entries:
            return None               # growth / first touch: primary
        if self._lease_end - self._lease_next < count:
            self._schedule(("lease",), self._refill())
            return None
        if self._lease_end - self._lease_next < self.LEASE_LOW:
            self._schedule(("lease",), self._refill())
        pick = st.entries[st.rr % len(st.entries)]
        st.rr += 1
        file_key = self._lease_next
        self._lease_next += count
        fid = str(t.FileId(int(pick["vid"]), file_key,
                           t.random_cookie()))
        out = {"fid": fid, "url": pick["url"],
               "publicUrl": pick["publicUrl"], "count": count}
        if self.jwt_key:
            from ..security.jwt import gen_jwt
            out["auth"] = gen_jwt(self.jwt_key, fid)
        self.assigned += 1
        body = json.dumps(out).encode()
        return (b"HTTP/1.1 200 OK\r\n"
                b"Content-Type: application/json; charset=utf-8\r\n"
                b"Content-Length: " + str(len(body)).encode()
                + b"\r\n\r\n" + body)

    async def _h_proxy(self, req):
        from aiohttp import web
        target = self.primary_addr()
        if target is None:
            return web.json_response(
                {"error": "primary master worker unavailable"},
                status=503)
        self.proxied += 1
        return await proxy_request(req, self._http, target,
                                   self.ctx.token)
