"""shell subpackage."""
