"""Cluster-wide EC workflows: ec.encode / ec.rebuild / ec.balance.

Reference: weed/shell/command_ec_encode.go (pick quiet+full volumes, mark
readonly, generate shards on the source, spread 14 shards round-robin by
free slots, delete the original volume), command_ec_rebuild.go (find
deficient EC volumes, gather inputs on the freest node, regenerate, mount),
command_ec_balance.go:29-100 (dedup -> spread across racks -> within racks;
the help text is the spec), command_ec_common.go (node collection/moves).
"""

from __future__ import annotations

import asyncio
import time

from ..ec import gf
from ..pb import messages as pb
from .env import CommandEnv


async def collect_ec_nodes(env: CommandEnv) -> list[dict]:
    """EC-capable nodes sorted by free slots desc (collectEcNodes,
    command_ec_common.go:181)."""
    nodes = await env.list_nodes()
    nodes.sort(key=lambda n: -n["freeSlots"])
    return nodes


async def collect_volume_ids_for_ec_encode(
        env: CommandEnv, collection: str = "",
        quiet_seconds: float = 3600.0,
        fullness: float = 0.95,
        volume_size_limit: int | None = None) -> list[int]:
    """Quiet + almost-full volume selection (command_ec_encode.go:258-290).

    Without per-volume mtime on the wire we use size >= fullness * limit;
    quiet_seconds=0 disables the quiet filter (used by tests/admin force).
    """
    if volume_size_limit is None:
        status = await env.master_get("/cluster/status")
        volume_size_limit = status.get("volume_size_limit", 0) or 0
    vids = []
    for node in await env.list_nodes():
        for m in node["volumes"]:
            if collection and m["collection"] != collection:
                continue
            if volume_size_limit and \
                    m["size"] < fullness * volume_size_limit:
                continue
            vids.append(m["id"])
    return sorted(set(vids))


async def ec_encode_volume(env: CommandEnv, vid: int,
                           collection: str = "",
                           generate: bool = True,
                           locations: list[str] | None = None) -> dict:
    """doEcEncode for one volume (command_ec_encode.go:89-117).

    generate=False skips steps 1-2 (already done by the batched generate
    in ec_encode); `locations` passes replica urls already looked up by
    the caller."""
    if locations is None:
        lookup = await env.master_get("/dir/lookup", volumeId=str(vid))
        if "locations" not in lookup:
            raise RuntimeError(f"volume {vid} not found")
        locations = [l["url"] for l in lookup["locations"]]
    source = locations[0]

    if generate:
        # 1. mark readonly everywhere (:119)
        for url in locations:
            await env.node_post(url, "/admin/volume/readonly",
                                volume=str(vid))
        # 2. generate 14 shards + .ecx on the source (:139)
        await env.node_post(source, "/admin/ec/generate", volume=str(vid),
                            collection=collection)

    # 3. spread shards across servers round-robin by free slots (:153-256)
    nodes = await collect_ec_nodes(env)
    assignments = balanced_ec_distribution(nodes, source)
    copies = []
    for target, shard_ids in assignments.items():
        if target == source or not shard_ids:
            continue
        copies.append(env.node_post(
            target, "/admin/ec/copy", volume=str(vid),
            collection=collection, source=source,
            shards=",".join(map(str, shard_ids)), copy_ecx="1"))
    await asyncio.gather(*copies)

    # 4. on every holder (copies are complete): drop the shard files not
    # assigned to it, then mount what remains (:177)
    for target, shard_ids in assignments.items():
        if not shard_ids:
            continue
        extras = [s for s in range(gf.TOTAL_SHARDS) if s not in shard_ids]
        if extras:
            await env.node_post(target, "/admin/ec/delete_shards",
                                volume=str(vid), collection=collection,
                                shards=",".join(map(str, extras)))
        await env.node_post(target, "/admin/ec/mount", volume=str(vid),
                            collection=collection)

    # 5. delete the original volume on all replicas (:177-195)
    for url in locations:
        await env.node_post(url, "/admin/volume/delete", volume=str(vid))
    return {"volume": vid, "assignments": assignments}


def balanced_ec_distribution(nodes: list[dict],
                             source: str) -> dict[str, list[int]]:
    """Round-robin the 14 shards over servers by free slots
    (balancedEcDistribution, command_ec_encode.go:240-256)."""
    if not nodes:
        return {source: list(range(gf.TOTAL_SHARDS))}
    alloc: dict[str, list[int]] = {n["url"]: [] for n in nodes}
    free = {n["url"]: max(n["freeSlots"], 0) for n in nodes}
    urls = list(alloc)
    i = 0
    for sid in range(gf.TOTAL_SHARDS):
        # next node with capacity, preferring emptier ones round-robin
        for _ in range(len(urls)):
            url = urls[i % len(urls)]
            i += 1
            if free[url] > 0 or all(f <= 0 for f in free.values()):
                alloc[url].append(sid)
                free[url] -= 1
                break
    return {u: s for u, s in alloc.items() if s}


async def ec_encode(env: CommandEnv, collection: str = "",
                    vids: list[int] | None = None,
                    fullness: float = 0.95) -> list[dict]:
    """ec.encode command entry (command_ec_encode.go:55).

    Volumes co-located on one server generate their shards through ONE
    batched device call (/admin/ec/generate_batch): the rack-encode shape
    where the kernel launch amortises over every co-located volume
    (parallel/mesh.py's "vol" axis; the reference loops serially)."""
    if vids is None:
        vids = await collect_volume_ids_for_ec_encode(
            env, collection, fullness=fullness)
    # group volumes by their generating (first-replica) server
    by_source: dict[str, list[int]] = {}
    locations: dict[int, list[str]] = {}
    for vid in vids:
        lookup = await env.master_get("/dir/lookup", volumeId=str(vid))
        if "locations" not in lookup:
            raise RuntimeError(f"volume {vid} not found")
        locations[vid] = [l["url"] for l in lookup["locations"]]
        by_source.setdefault(locations[vid][0], []).append(vid)
    for vid, urls in locations.items():
        for url in urls:
            await env.node_post(url, "/admin/volume/readonly",
                                volume=str(vid))
    await asyncio.gather(*(
        env.node_post(source, "/admin/ec/generate_batch",
                      volumes=",".join(map(str, svids)),
                      collection=collection)
        for source, svids in by_source.items()))
    results = []
    for vid in vids:
        results.append(await ec_encode_volume(env, vid, collection,
                                              generate=False,
                                              locations=locations[vid]))
    return results


# ---------------------------------------------------------------------------
# ec.rebuild (command_ec_rebuild.go)
# ---------------------------------------------------------------------------


async def ec_shard_map(env: CommandEnv) -> dict[int, dict]:
    """vid -> {collection, shards: {sid: [urls]}} from node ec registries."""
    out: dict[int, dict] = {}
    for node in await env.list_nodes():
        for m in node["ecShards"]:
            e = out.setdefault(m["id"], {"collection": m["collection"],
                                         "shards": {}})
            for sid in pb.shard_bits_list(m["ec_index_bits"]):
                e["shards"].setdefault(sid, []).append(node["url"])
    return out


async def ec_verify(env: CommandEnv, collection: str = "",
                    volume_id: int | None = None,
                    window_mb: int = 4) -> list[dict]:
    """Parity-scrub EC volumes cluster-wide: for each EC volume, ask a
    shard-holding server to recompute RS(10,4) parity over every stripe
    window (/admin/ec/verify -> EcVolume.verify_parity, TPU-backed when
    a chip is attached) and report corrupt windows. A no-reference-
    -equivalent capability: the reference's integrity checking stops at
    per-needle CRCs on read (needle/crc.go)."""
    results: list[dict] = []
    for vid, info in sorted((await ec_shard_map(env)).items()):
        if volume_id is not None and vid != volume_id:
            continue
        if collection and info["collection"] != collection:
            continue
        # the server holding the most shards verifies the most locally
        counts: dict[str, int] = {}
        for urls in info["shards"].values():
            for u in urls:
                counts[u] = counts.get(u, 0) + 1
        if not counts:
            continue
        node = max(counts, key=counts.get)  # type: ignore[arg-type]
        try:
            report = await env.node_post(node, "/admin/ec/verify",
                                         volume=str(vid),
                                         windowMB=str(window_mb))
        except RuntimeError as e:
            report = {"volume": vid, "error": str(e)[:200]}
        report["node"] = node
        results.append(report)
    return results


async def ec_rebuild(env: CommandEnv, collection: str = "",
                     apply_changes: bool = True) -> list[dict]:
    """Rebuild every deficient EC volume (10 <= shards < 14); <10 shards is
    unrepairable (command_ec_rebuild.go:93-243)."""
    results = []
    shard_map = await ec_shard_map(env)
    nodes = await collect_ec_nodes(env)
    for vid, info in sorted(shard_map.items()):
        if collection and info["collection"] != collection:
            continue
        have = sorted(info["shards"])
        if len(have) == gf.TOTAL_SHARDS:
            continue
        if len(have) < gf.DATA_SHARDS:
            results.append({"volume": vid, "error":
                            f"unrepairable: only {len(have)} shards"})
            continue
        if not apply_changes:
            results.append({"volume": vid, "missing":
                            [s for s in range(gf.TOTAL_SHARDS)
                             if s not in have]})
            continue
        rebuilder = nodes[0]["url"]
        # gather >=10 input shards onto the rebuilder (prepareDataToRecover)
        copied = []
        for sid in have:
            holders = info["shards"][sid]
            if rebuilder in holders:
                continue
            await env.node_post(rebuilder, "/admin/ec/copy",
                                volume=str(vid),
                                collection=info["collection"],
                                source=holders[0],
                                shards=str(sid), copy_ecx="1")
            copied.append(sid)
        # regenerate missing (VolumeEcShardsRebuild)
        resp = await env.node_post(rebuilder, "/admin/ec/rebuild",
                                   volume=str(vid),
                                   collection=info["collection"])
        rebuilt = resp.get("rebuilt", [])
        # drop the borrowed input shards, keep the rebuilt ones
        if copied:
            await env.node_post(rebuilder, "/admin/ec/delete_shards",
                                volume=str(vid),
                                collection=info["collection"],
                                shards=",".join(map(str, copied)))
        await env.node_post(rebuilder, "/admin/ec/mount", volume=str(vid),
                            collection=info["collection"])
        results.append({"volume": vid, "rebuilt": rebuilt,
                        "node": rebuilder})
    return results


# ---------------------------------------------------------------------------
# ec.decode (command_ec_decode.go): sealed EC volume -> normal volume
# ---------------------------------------------------------------------------


async def ec_decode_volume(env: CommandEnv, vid: int, info: dict) -> dict:
    """doEcDecode for one volume (command_ec_decode.go:71-99): gather the
    data shards on the holder with the most of them, reassemble
    .dat/.idx there (VolumeEcShardsToVolume), mount it as a normal
    volume, then tear down every EC shard."""
    coll = info["collection"]
    per_node: dict[str, set[int]] = {}
    for sid, holders in info["shards"].items():
        for url in holders:
            per_node.setdefault(url, set()).add(sid)
    if not per_node:
        return {"volume": vid, "error": "no shard holders"}
    # target = server already holding the most shards (collectEcShards)
    target = max(per_node, key=lambda u: len(per_node[u]))
    have = set(per_node[target])

    # if any data shard exists nowhere, it must be rebuilt on the target
    # (needs >=10 gathered shards); otherwise just copy the missing data
    # shards over
    absent_data = [s for s in range(gf.DATA_SHARDS)
                   if s not in info["shards"]]
    needed = (sorted(info["shards"]) if absent_data
              else [s for s in range(gf.DATA_SHARDS)])
    for sid in needed:
        if absent_data and len(have) >= gf.DATA_SHARDS:
            break  # rebuild needs only 10 gathered shards
        if sid in have or sid not in info["shards"]:
            continue
        await env.node_post(target, "/admin/ec/copy", volume=str(vid),
                            collection=coll,
                            source=info["shards"][sid][0],
                            shards=str(sid), copy_ecx="1")
        have.add(sid)
    if absent_data:
        if len(have) < gf.DATA_SHARDS:
            return {"volume": vid, "error":
                    f"unrepairable: only {len(have)} shards"}
        await env.node_post(target, "/admin/ec/rebuild", volume=str(vid),
                            collection=coll)

    # reassemble .dat/.idx (VolumeEcShardsToVolume)
    await env.node_post(target, "/admin/ec/to_volume", volume=str(vid),
                        collection=coll)
    # mount the normal volume, then unmount + delete EC state everywhere
    # (mountVolumeAndDeleteEcShards order: mount first, teardown after)
    await env.node_post(target, "/admin/volume/mount", volume=str(vid),
                        collection=coll)
    all_shards = ",".join(map(str, range(gf.TOTAL_SHARDS)))
    for url in per_node:
        await env.node_post(url, "/admin/ec/unmount", volume=str(vid))
        await env.node_post(url, "/admin/ec/delete_shards",
                            volume=str(vid), collection=coll,
                            shards=all_shards, ecx="1")
    return {"volume": vid, "node": target}


async def ec_decode(env: CommandEnv, collection: str = "",
                    vids: list[int] | None = None) -> list[dict]:
    """ec.decode command entry (command_ec_decode.go:37-69)."""
    shard_map = await ec_shard_map(env)
    results = []
    for vid, info in sorted(shard_map.items()):
        if collection and info["collection"] != collection:
            continue
        if vids and vid not in vids:
            continue
        try:
            results.append(await ec_decode_volume(env, vid, info))
        except RuntimeError as e:
            # one volume failing (e.g. 409 missing shard) must not
            # abort the rest of the batch (ec_rebuild reports the same
            # way)
            results.append({"volume": vid, "error": str(e)})
    return results


# ---------------------------------------------------------------------------
# ec.balance (command_ec_balance.go)
# ---------------------------------------------------------------------------


async def ec_balance(env: CommandEnv, collection: str = "",
                     apply_changes: bool = True) -> list[dict]:
    """Spread shards: no duplicate shard copies on one node, then even
    counts per node (dedup + spread steps of command_ec_balance.go:29-100).
    """
    moves = []
    shard_map = await ec_shard_map(env)
    nodes = await collect_ec_nodes(env)
    if not nodes:
        return moves
    url_free = {n["url"]: n["freeSlots"] for n in nodes}
    for vid, info in sorted(shard_map.items()):
        if collection and info["collection"] != collection:
            continue
        # count shards per node for this volume
        per_node: dict[str, list[int]] = {}
        for sid, holders in info["shards"].items():
            for url in holders:
                per_node.setdefault(url, []).append(sid)
        total = sum(len(s) for s in per_node.values())
        fair = -(-total // max(len(nodes), 1))
        over = {u: sorted(s) for u, s in per_node.items() if len(s) > fair}
        for src, sids in over.items():
            excess = sids[fair:]
            for sid in excess:
                # move to the node with the fewest shards of this volume
                candidates = sorted(
                    (u for u in url_free if u != src),
                    key=lambda u: (len(per_node.get(u, [])),
                                   -url_free.get(u, 0)))
                dst = next((u for u in candidates
                            if sid not in per_node.get(u, [])), None)
                if dst is None:
                    continue
                moves.append({"volume": vid, "shard": sid,
                              "from": src, "to": dst})
                if apply_changes:
                    await move_ec_shard(env, vid, info["collection"],
                                        sid, src, dst)
                per_node.setdefault(dst, []).append(sid)
                per_node[src].remove(sid)
    return moves


async def move_ec_shard(env: CommandEnv, vid: int, collection: str,
                        sid: int, src: str, dst: str) -> None:
    """moveMountedShardToEcNode (command_ec_common.go:18-75): copy to dst,
    mount there, unmount + delete on src."""
    await env.node_post(dst, "/admin/ec/copy", volume=str(vid),
                        collection=collection, source=src,
                        shards=str(sid), copy_ecx="1")
    await env.node_post(dst, "/admin/ec/mount", volume=str(vid),
                        collection=collection)
    await env.node_post(src, "/admin/ec/unmount", volume=str(vid),
                        shards=str(sid))
    await env.node_post(src, "/admin/ec/delete_shards", volume=str(vid),
                        collection=collection, shards=str(sid))
