"""Shell command environment: master connection + cluster queries.

Reference: weed/shell/commands.go (CommandEnv wraps a wdclient master
connection used by every command).
"""

from __future__ import annotations

from ..security import tls

import aiohttp


class CommandEnv:
    def __init__(self, master_url: str,
                 session: aiohttp.ClientSession | None = None):
        self.master_url = master_url
        self._session = session
        self._own_session = session is None
        # REPL working-directory state (fs.cd / fs.pwd,
        # shell/command_fs_cd.go + command_fs_pwd.go): fs.* commands
        # default their -filer/-path to these when a session reuses one
        # env across commands
        self.filer = ""
        self.wd = "/"

    async def __aenter__(self) -> "CommandEnv":
        if self._session is None:
            self._session = tls.make_session(
                timeout=aiohttp.ClientTimeout(total=300))
        return self

    async def __aexit__(self, *exc) -> None:
        if self._own_session and self._session:
            await self._session.close()

    @property
    def http(self) -> aiohttp.ClientSession:
        assert self._session is not None, "use 'async with CommandEnv(...)'"
        return self._session

    async def master_get(self, path: str, **params) -> dict:
        async with self.http.get(tls.url(self.master_url, f"{path}"),
                                 params=params) as resp:
            return await resp.json()

    async def node_post(self, url: str, path: str, **params) -> dict:
        async with self.http.post(tls.url(url, f"{path}"),
                                  params=params) as resp:
            body = await resp.json()
            if resp.status != 200:
                raise RuntimeError(f"POST {url}{path}: {body}")
            return body

    async def list_nodes(self) -> list[dict]:
        return (await self.master_get("/vol/volumes"))["nodes"]
