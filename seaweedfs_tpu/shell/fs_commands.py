"""fs.* and collection.* admin shell commands.

Reference: weed/shell/command_fs_ls.go, _cat.go, _du.go, _tree.go, _mv.go,
command_fs_meta_save.go/_load.go (filer metadata backup/restore to a pb
file — here JSON-lines), command_collection_list.go/_delete.go.
"""

from __future__ import annotations

import json
import posixpath

from ..security import tls
from ..util import tracing
from .env import CommandEnv


def _filer_url(filer: str, path: str) -> str:
    # the filer HTTP surface is deliberately plaintext even when the
    # master/volume mesh runs mTLS (client-facing, like the reference)
    return f"http://{filer}" + (path if path.startswith("/")
                                else "/" + path)


_PAGE = 1024


async def _list_dir(env: CommandEnv, filer: str, path: str) -> list[dict]:
    """Full directory listing, paginating past the server's per-request
    cap with startFile (fs.meta.save must never silently truncate)."""
    out: list[dict] = []
    start = ""
    while True:
        async with env.http.get(_filer_url(filer, "/__api__/list"),
                                params={"path": path, "startFile": start,
                                        "limit": str(_PAGE)}) as resp:
            page = (await resp.json()).get("entries", [])
        out.extend(page)
        if len(page) < _PAGE:
            return out
        start = posixpath.basename(page[-1]["FullPath"])


def _is_dir(e: dict) -> bool:
    return bool(e.get("IsDirectory"))


def _size(e: dict) -> int:
    return sum(c.get("size", 0) for c in e.get("chunks", []))


async def fs_ls(env: CommandEnv, filer: str, path: str = "/",
                long_format: bool = False) -> list[dict] | list[str]:
    entries = await _list_dir(env, filer, path)
    if long_format:
        return [{
            "name": posixpath.basename(e["FullPath"]) +
            ("/" if _is_dir(e) else ""),
            "size": _size(e),
            "mode": e.get("Mode", 0),
            "mtime": e.get("Mtime", 0),
        } for e in entries]
    return [posixpath.basename(e["FullPath"]) + ("/" if _is_dir(e) else "")
            for e in entries]


async def fs_cat(env: CommandEnv, filer: str, path: str) -> bytes:
    async with env.http.get(_filer_url(filer, path)) as resp:
        if resp.status != 200:
            raise RuntimeError(f"cat {path}: http {resp.status}")
        return await resp.read()


async def _walk(env: CommandEnv, filer: str, path: str):
    """Yield (entry, depth) over the whole subtree, depth-first."""
    stack = [(path, 0)]
    while stack:
        cur, depth = stack.pop()
        entries = await _list_dir(env, filer, cur)
        for e in sorted(entries, key=lambda x: x["FullPath"], reverse=True):
            yield e, depth
            if _is_dir(e):
                stack.append((e["FullPath"], depth + 1))


async def fs_du(env: CommandEnv, filer: str, path: str = "/") -> dict:
    files = dirs = size = 0
    async for e, _ in _walk(env, filer, path):
        if _is_dir(e):
            dirs += 1
        else:
            files += 1
            size += _size(e)
    return {"path": path, "files": files, "dirs": dirs, "bytes": size}


async def fs_tree(env: CommandEnv, filer: str, path: str = "/") -> str:
    lines = [path]
    # re-walk with correct ordering for display (small trees only)
    async def rec(cur: str, prefix: str) -> None:
        entries = sorted(await _list_dir(env, filer, cur),
                         key=lambda e: e["FullPath"])
        for i, e in enumerate(entries):
            last = i == len(entries) - 1
            name = posixpath.basename(e["FullPath"])
            lines.append(prefix + ("└── " if last else "├── ") + name
                         + ("/" if _is_dir(e) else ""))
            if _is_dir(e):
                await rec(e["FullPath"],
                          prefix + ("    " if last else "│   "))
    await rec(path, "")
    return "\n".join(lines)


async def fs_mv(env: CommandEnv, filer: str, src: str, dst: str) -> dict:
    async with env.http.post(_filer_url(filer, "/__api__/rename"),
                             params={"from": src, "to": dst}) as resp:
        body = await resp.json()
        if resp.status != 200:
            raise RuntimeError(f"mv: {body.get('error')}")
    return {"moved": src, "to": dst}


async def fs_rm(env: CommandEnv, filer: str, path: str,
                recursive: bool = False) -> dict:
    async with env.http.delete(
            _filer_url(filer, path),
            params={"recursive": "true" if recursive else "false"}) as resp:
        if resp.status not in (204, 404):
            raise RuntimeError(f"rm {path}: http {resp.status} "
                               f"{await resp.text()}")
    return {"removed": path}


async def fs_meta_save(env: CommandEnv, filer: str, path: str,
                       out_file: str) -> dict:
    """Dump the subtree's metadata to JSON-lines
    (fs.meta.save, command_fs_meta_save.go)."""
    # streamed in batches: the shell shares its loop with the env's
    # http session (writes must not stall it), and a multi-million
    # entry namespace must not accumulate in RAM
    n = 0
    f = await tracing.run_in_executor(open, out_file, "w")
    try:
        batch: list[str] = []
        async for e, _ in _walk(env, filer, path):
            batch.append(json.dumps(e) + "\n")
            n += 1
            if len(batch) >= 512:
                lines, batch = batch, []
                await tracing.run_in_executor(f.writelines, lines)
        if batch:
            await tracing.run_in_executor(f.writelines, batch)
    finally:
        await tracing.run_in_executor(f.close)
    return {"saved": n, "file": out_file}


async def fs_meta_load(env: CommandEnv, filer: str, in_file: str) -> dict:
    """Recreate entries from a fs.meta.save dump. Chunks keep their fids:
    restoring onto the same cluster restores files, onto a fresh cluster
    restores the namespace (command_fs_meta_load.go semantics)."""
    n = 0
    failures: list[str] = []
    # bounded batches of lines per executor round-trip: dumps can be
    # namespace-sized, so neither whole-file buffering nor on-loop reads
    f = await tracing.run_in_executor(open, in_file)
    try:
        while True:
            lines = await tracing.run_in_executor(f.readlines, 1 << 16)
            if not lines:
                break
            for line in lines:
                if not line.strip():
                    continue
                e = json.loads(line)
                async with env.http.post(
                        _filer_url(filer, "/__api__/entry"),
                        json=e) as resp:
                    if resp.status == 200:
                        n += 1
                    else:
                        # a partial restore must never look like success
                        failures.append(
                            f"{e.get('FullPath')}: http {resp.status} "
                            f"{(await resp.text())[:120]}")
    finally:
        await tracing.run_in_executor(f.close)
    out = {"loaded": n, "failed": len(failures), "file": in_file}
    if failures:
        out["errors"] = failures[:10]
    return out


async def fs_meta_cat(env: CommandEnv, filer: str, path: str) -> dict:
    """Full stored metadata of one entry (command_fs_meta_cat.go)."""
    async with env.http.get(_filer_url(filer, "/__api__/lookup"),
                            params={"path": path}) as resp:
        body = await resp.json()
        if resp.status != 200:
            raise ValueError(f"{path}: {body.get('error', 'lookup failed')}")
        return body


def _api_to_entry_dict(e: dict) -> dict:
    """FilerServer._entry_json wire shape -> filer Entry.to_dict shape
    (what EventNotification payloads carry, pb/filer.proto analog)."""
    return {
        "full_path": e["FullPath"],
        "attr": {
            "mtime": e.get("Mtime", 0), "crtime": e.get("Crtime", 0),
            "mode": e.get("Mode", 0o660),
            "uid": e.get("Uid", 0), "gid": e.get("Gid", 0),
            "mime": e.get("Mime", ""),
            "replication": e.get("Replication", ""),
            "collection": e.get("Collection", ""),
            "ttl_sec": e.get("TtlSec", 0),
        },
        "chunks": e.get("chunks", []),
        "extended": e.get("extended", {}),
    }


async def fs_meta_notify(env: CommandEnv, filer: str, path: str,
                         queue) -> dict:
    """Re-publish create events for a whole subtree into a notification
    queue, so a replicator can be primed with data that predates the
    queue (command_fs_meta_notify.go). Events go through the same
    event_of producer the live filer listeners use, so the wire shape
    cannot drift from what Replicator consumes."""
    from ..filer.entry import Entry
    from ..notification.queues import event_of

    dirs = files = 0
    async for e, _ in _walk(env, filer, path):
        entry = Entry.from_dict(_api_to_entry_dict(e))
        queue.send_message(e["FullPath"],
                           event_of(None, entry, delete_chunks=False))
        if _is_dir(e):
            dirs += 1
        else:
            files += 1
    return {"notified_dirs": dirs, "notified_files": files}


async def collection_list(env: CommandEnv) -> list[str]:
    body = await env.master_get("/vol/volumes")
    cols = set()
    for node in body.get("nodes", []):
        for m in node.get("volumes", []) + node.get("ecShards", []):
            cols.add(m.get("collection", ""))
    return sorted(cols)


async def collection_delete(env: CommandEnv, name: str) -> dict:
    async with env.http.post(tls.url(env.master_url, "/col/delete"),
                             params={"collection": name}) as resp:
        return await resp.json()
