"""Shell command dispatch: parse 'ec.encode -collection x' style lines.

Reference: weed/shell/commands.go registry + shell_liner.go REPL.
"""

from __future__ import annotations

import json
import posixpath
import shlex

from . import ec_commands as ec
from . import fs_commands as fs
from . import volume_commands as vc
from .env import CommandEnv

HELP = """commands:
  ec.encode    [-collection c] [-volumeId n] [-fullPercent 95]
  ec.rebuild   [-collection c] [-force]
  ec.verify    [-collection c] [-volumeId n] [-windowMB 4]
  ec.decode    [-collection c] [-volumeId n]
  ec.balance   [-collection c] [-force]
  volume.vacuum          [-garbageThreshold 0.3] [-collection c]
  volume.fix.replication [-force]
  volume.balance         [-force] [-collection ALL_COLLECTIONS|EACH_COLLECTION|name] [-dataCenter dc]
  volume.move   -volumeId n -source host:port -target host:port
  volume.copy   -volumeId n -source host:port -target host:port
  volume.mount   -volumeId n -node host:port [-collection c]
  volume.unmount -volumeId n -node host:port
  volume.delete  -volumeId n -node host:port [-collection c]
  volume.tier.upload   -volumeId n [-backend s3.default] [-keepLocal]
  volume.tier.download -volumeId n
  volume.list
  collection.list
  collection.delete -collection c
  fs.cd   -filer host:port [-path /dir]   (sets session default filer+dir)
  fs.pwd
  fs.ls   [-filer host:port] [-path /dir] [-l]
  fs.cat  [-filer host:port] -path /f
  fs.du   [-filer host:port] [-path /dir]
  fs.tree [-filer host:port] [-path /dir]
  fs.mv   [-filer host:port] -from /a -to /b
  fs.rm   [-filer host:port] -path /f [-recursive]
  fs.meta.cat    [-filer host:port] -path /f
  fs.meta.save   [-filer host:port] [-path /] [-o meta.jsonl]
  fs.meta.load   [-filer host:port] [-i meta.jsonl]
  fs.meta.notify [-filer host:port] [-path /] -notify file:<p>|sqlite:<p>|log
fs.* also accept the path positionally (fs.ls /dir) and resolve relative
paths against the fs.cd working directory.
"""


def _resolve_path(env: CommandEnv, p: str | None) -> str:
    """Resolve an fs.* path against the session working directory
    (fs.cd semantics, shell/command_fs_cd.go)."""
    if not p:
        return env.wd
    if not p.startswith("/"):
        p = posixpath.join(env.wd, p)
    return posixpath.normpath(p)


# flags that never take a free-form value: a following bare token is the
# positional path, not the flag's value (`fs.rm -recursive /f` must not
# parse as recursive="/f"); an explicit true/false is still honored
_BOOL_FLAGS = {"force", "keepLocal", "l", "recursive"}


def _flags(tokens: list[str]) -> tuple[dict[str, str], list[str]]:
    """Returns (flags, positionals). A bare token not consumed as a flag
    value is positional — the reference's fs.* commands take their path
    that way (`fs.ls /dir`, commandEnv.parseUrl)."""
    out = {}
    pos = []
    i = 0
    while i < len(tokens):
        tok = tokens[i]
        if tok.startswith("-"):
            key = tok.lstrip("-")
            nxt = tokens[i + 1] if i + 1 < len(tokens) else "-"
            if "=" in tok:  # -fullPercent=95 (reference admin-script style)
                key, _, val = key.partition("=")
                out[key] = val
                i += 1
            elif key in _BOOL_FLAGS:
                if nxt in ("true", "false"):
                    out[key] = nxt
                    i += 2
                else:
                    out[key] = "true"
                    i += 1
            elif not nxt.startswith("-"):
                out[key] = nxt
                i += 2
            else:
                out[key] = "true"
                i += 1
        else:
            pos.append(tok)
            i += 1
    return out, pos


async def run_command(master_url: str, line: str) -> object:
    """Interactive/CLI entry: own session + printed result."""
    async with CommandEnv(master_url) as env:
        res = await dispatch(env, line)
    if res is not None:
        print(json.dumps(res, indent=2, default=str))
    return res


async def dispatch(env: CommandEnv, line: str) -> object:
    """Parse + run one shell command line against an existing env (no
    result printing) — the master's maintenance loop drives admin scripts
    through this (master_server.go:186-250 startAdminScripts analog)."""
    tokens = shlex.split(line)
    if not tokens:
        return None
    cmd, (flags, positional) = tokens[0], _flags(tokens[1:])
    if positional and cmd.startswith("fs.") and "path" not in flags:
        # reference style: `fs.ls /dir`, `fs.cd /x` (fs.mv keeps its
        # explicit -from/-to; a positional never silently becomes one)
        flags["path"] = positional[0]
    if cmd == "ec.encode":
        vids = [int(flags["volumeId"])] if "volumeId" in flags else None
        res = await ec.ec_encode(
            env, collection=flags.get("collection", ""), vids=vids,
            fullness=float(flags.get("fullPercent", 95)) / 100)
    elif cmd == "ec.verify":
        vid_s = flags.get("volumeId")
        res = await ec.ec_verify(
            env, collection=flags.get("collection", ""),
            volume_id=int(vid_s) if vid_s else None,
            window_mb=int(flags.get("windowMB", 4)))
    elif cmd == "ec.rebuild":
        res = await ec.ec_rebuild(
            env, collection=flags.get("collection", ""),
            apply_changes=flags.get("force") == "true")
    elif cmd == "ec.decode":
        vids = [int(flags["volumeId"])] if "volumeId" in flags else None
        res = await ec.ec_decode(
            env, collection=flags.get("collection", ""), vids=vids)
    elif cmd == "ec.balance":
        res = await ec.ec_balance(
            env, collection=flags.get("collection", ""),
            apply_changes=flags.get("force") == "true")
    elif cmd == "volume.vacuum":
        res = await vc.volume_vacuum(
            env, float(flags.get("garbageThreshold", 0.3)),
            flags.get("collection"))
    elif cmd == "volume.fix.replication":
        res = await vc.volume_fix_replication(
            env, apply_changes=flags.get("force") == "true")
    elif cmd == "volume.balance":
        res = await vc.volume_balance(
            env, apply_changes=flags.get("force") == "true",
            collection=flags.get("collection", "EACH_COLLECTION"),
            data_center=flags.get("dataCenter", ""))
    elif cmd == "volume.move":
        await vc.volume_move(env, int(flags["volumeId"]),
                             flags.get("collection", ""),
                             flags["source"], flags["target"])
        res = {"moved": flags["volumeId"]}
    elif cmd == "volume.copy":
        await vc.volume_copy(env, int(flags["volumeId"]),
                             flags.get("collection", ""),
                             flags["source"], flags["target"])
        res = {"copied": flags["volumeId"], "to": flags["target"]}
    elif cmd == "volume.mount":
        res = await vc.volume_mount(env, int(flags["volumeId"]),
                                    flags["node"],
                                    flags.get("collection", ""))
    elif cmd == "volume.unmount":
        res = await vc.volume_unmount(env, int(flags["volumeId"]),
                                      flags["node"])
    elif cmd == "volume.delete":
        res = await vc.volume_delete(env, int(flags["volumeId"]),
                                     flags["node"],
                                     flags.get("collection", ""))
    elif cmd == "volume.tier.upload":
        res = await vc.volume_tier_upload(
            env, int(flags["volumeId"]),
            backend=flags.get("backend", "s3.default"),
            keep_local=flags.get("keepLocal") == "true")
    elif cmd == "volume.tier.download":
        res = await vc.volume_tier_download(env, int(flags["volumeId"]))
    elif cmd == "volume.list":
        res = await env.list_nodes()
    elif cmd == "collection.list":
        res = await fs.collection_list(env)
    elif cmd == "collection.delete":
        res = await fs.collection_delete(env, flags["collection"])
    elif cmd.startswith("fs."):
        filer = flags.get("filer", "") or env.filer
        if cmd == "fs.pwd":
            return {"filer": filer, "cwd": env.wd}
        if not filer:
            raise ValueError(
                "fs.* commands need -filer host:port (or a prior fs.cd)")
        path = _resolve_path(env, flags.get("path"))
        if cmd == "fs.cd":
            if path != "/":
                # validate before committing the session default
                meta = await fs.fs_meta_cat(env, filer, path)
                if not meta.get("IsDirectory"):
                    raise ValueError(f"{path} is not a directory")
            env.filer, env.wd = filer, path
            return {"filer": filer, "cwd": path}
        if cmd == "fs.ls":
            res = await fs.fs_ls(env, filer, path,
                                 long_format=flags.get("l") == "true")
        elif cmd == "fs.cat":
            data = await fs.fs_cat(env, filer, path)
            print(data.decode(errors="replace"))
            return None
        elif cmd == "fs.du":
            res = await fs.fs_du(env, filer, path)
        elif cmd == "fs.tree":
            print(await fs.fs_tree(env, filer, path))
            return None
        elif cmd == "fs.mv":
            res = await fs.fs_mv(env, filer,
                                 _resolve_path(env, flags["from"]),
                                 _resolve_path(env, flags["to"]))
        elif cmd == "fs.rm":
            if "path" not in flags:
                # never let a forgotten -path default to deleting "/"
                raise ValueError("fs.rm requires an explicit -path")
            res = await fs.fs_rm(env, filer,
                                 _resolve_path(env, flags["path"]),
                                 recursive=flags.get(
                                     "recursive") == "true")
        elif cmd == "fs.meta.cat":
            if "path" not in flags:
                raise ValueError("fs.meta.cat requires -path")
            res = await fs.fs_meta_cat(env, filer, path)
        elif cmd == "fs.meta.notify":
            from ..notification.queues import queue_from_spec
            from ..util import tracing
            if "notify" not in flags:
                raise ValueError("fs.meta.notify requires "
                                 "-notify file:<p>|sqlite:<p>|log")
            # FileQueue's ctor makedirs/creates its backing file — off
            # the loop, the shell may be driving live-cluster commands
            queue = await tracing.run_in_executor(
                queue_from_spec, flags["notify"])
            try:
                res = await fs.fs_meta_notify(env, filer, path, queue)
            finally:
                queue.close()
        elif cmd == "fs.meta.save":
            res = await fs.fs_meta_save(env, filer, path,
                                        flags.get("o", "meta.jsonl"))
        elif cmd == "fs.meta.load":
            res = await fs.fs_meta_load(env, filer,
                                        flags.get("i", "meta.jsonl"))
        else:
            raise ValueError(f"unknown command {cmd!r}; try 'help'")
    else:
        raise ValueError(f"unknown command {cmd!r}; try 'help'")
    return res
