"""Volume admin workflows: volume.vacuum / volume.fix.replication /
volume.balance / volume.move.

Reference: weed/topology/topology_vacuum.go:16-120 (check -> compact ->
commit across replicas), shell/command_volume_fix_replication.go
(re-replicate under-replicated volumes rack-aware), command_volume_balance.go
(even out volume counts), command_volume_move.go.
"""

from __future__ import annotations

import asyncio

from ..storage.super_block import ReplicaPlacement
from .env import CommandEnv


async def volume_vacuum(env: CommandEnv, garbage_threshold: float = 0.3,
                        collection: str | None = None) -> list[dict]:
    """check -> compact -> commit on every replica of dirty volumes."""
    results = []
    nodes = await env.list_nodes()
    # vid -> [(url, msg)]
    vols: dict[int, list[tuple[str, dict]]] = {}
    for n in nodes:
        for m in n["volumes"]:
            if collection is not None and m["collection"] != collection:
                continue
            vols.setdefault(m["id"], []).append((n["url"], m))
    for vid, holders in sorted(vols.items()):
        checks = await asyncio.gather(*(
            env.node_post(url, "/admin/vacuum/check", volume=str(vid))
            for url, _ in holders), return_exceptions=True)
        ratios = [c.get("garbage_ratio", 0.0) for c in checks
                  if isinstance(c, dict)]
        if not ratios or max(ratios) < garbage_threshold:
            continue
        try:
            await asyncio.gather(*(
                env.node_post(url, "/admin/vacuum/compact", volume=str(vid))
                for url, _ in holders))
            await asyncio.gather(*(
                env.node_post(url, "/admin/vacuum/commit", volume=str(vid))
                for url, _ in holders))
            results.append({"volume": vid, "garbage": max(ratios),
                            "vacuumed": True})
        except RuntimeError as e:
            await asyncio.gather(*(
                env.node_post(url, "/admin/vacuum/cleanup", volume=str(vid))
                for url, _ in holders), return_exceptions=True)
            results.append({"volume": vid, "error": str(e)})
    return results


async def volume_fix_replication(env: CommandEnv,
                                 apply_changes: bool = True) -> list[dict]:
    """Re-replicate volumes with fewer live copies than their placement
    demands (command_volume_fix_replication.go)."""
    actions = []
    nodes = await env.list_nodes()
    by_url = {n["url"]: n for n in nodes}
    vols: dict[int, list[tuple[str, dict]]] = {}
    for n in nodes:
        for m in n["volumes"]:
            vols.setdefault(m["id"], []).append((n["url"], m))
    for vid, holders in sorted(vols.items()):
        msg = holders[0][1]
        rp = ReplicaPlacement.from_byte(msg["replica_placement"])
        want, have = rp.copy_count, len(holders)
        if have >= want:
            continue
        holder_urls = {u for u, _ in holders}
        holder_racks = {(by_url[u]["dataCenter"], by_url[u]["rack"])
                        for u in holder_urls if u in by_url}
        # prefer a rack not already holding a replica, then most free slots
        candidates = sorted(
            (n for n in nodes
             if n["url"] not in holder_urls and n["freeSlots"] > 0),
            key=lambda n: ((n["dataCenter"], n["rack"]) in holder_racks,
                           -n["freeSlots"]))
        if not candidates:
            actions.append({"volume": vid, "error": "no candidate node"})
            continue
        target = candidates[0]["url"]
        actions.append({"volume": vid, "copy_to": target,
                        "from": holders[0][0]})
        if apply_changes:
            await env.node_post(target, "/admin/volume/copy",
                                volume=str(vid),
                                collection=msg["collection"],
                                source=holders[0][0])
    return actions


def plan_balance(nodes: list[dict], volume_size_limit: int,
                 collection: str = "EACH_COLLECTION",
                 data_center: str = "") -> list[dict]:
    """Pure balance planner, the reference's documented algorithm
    (command_volume_balance.go:29-100):

      * volume servers are grouped by TYPE (their max-volume capacity;
        collectVolumeServersByType), optionally filtered by -dataCenter;
        a type with fewer than two nodes is skipped;
      * -collection selects one collection, ALL_COLLECTIONS, or
        EACH_COLLECTION (default: one balancing pass per collection);
      * per scope, WRITABLE volumes (not read-only, under the size
        limit; move candidates ordered by size ascending) are balanced
        first, then READ-ONLY volumes (ordered by id);
      * balanceSelectedVolume: ideal = ceil(selected / nodes); while the
        fullest node is above ideal and the emptiest fits one more,
        move the first candidate the emptiest node does not already
        hold (never co-locating replicas of one volume).

    Operates on a /vol/volumes snapshot; returns the move plan."""
    import math

    by_type: dict[int, list[dict]] = {}
    for n in nodes:
        if data_center and n.get("dataCenter", "") != data_center:
            continue
        by_type.setdefault(n.get("maxVolumes", 0), []).append(
            {"url": n["url"],
             "volumes": {m["id"]: m for m in n["volumes"]},
             "selected": {}})
    moves: list[dict] = []

    def balance_selected(group: list[dict], order_key) -> None:
        total = sum(len(n["selected"]) for n in group)
        ideal = math.ceil(total / len(group))
        while True:
            group.sort(key=lambda n: len(n["selected"]))
            empty, full = group[0], group[-1]
            if not (len(full["selected"]) > ideal
                    and len(empty["selected"]) + 1 <= ideal):
                return
            candidates = sorted(full["selected"].values(), key=order_key)
            for m in candidates:
                if m["id"] not in empty["volumes"]:
                    moves.append({"volume": m["id"],
                                  "collection": m["collection"],
                                  "from": full["url"],
                                  "to": empty["url"]})
                    del full["selected"][m["id"]]
                    del full["volumes"][m["id"]]
                    empty["selected"][m["id"]] = m
                    empty["volumes"][m["id"]] = m
                    break
            else:
                return  # every candidate already has a copy on `empty`

    for group in by_type.values():
        if len(group) < 2:
            continue
        if collection == "EACH_COLLECTION":
            scopes = sorted({m["collection"] for n in group
                             for m in n["volumes"].values()})
        elif collection == "ALL_COLLECTIONS":
            scopes = [None]
        else:
            scopes = [collection]
        for scope in scopes:
            for sel, order_key in (
                    (lambda m: not m.get("read_only")
                     and m.get("size", 0) < volume_size_limit,
                     lambda m: m.get("size", 0)),
                    (lambda m: m.get("read_only")
                     or m.get("size", 0) >= volume_size_limit,
                     lambda m: m["id"])):
                for n in group:
                    n["selected"] = {
                        vid: m for vid, m in n["volumes"].items()
                        if (scope is None or m["collection"] == scope)
                        and sel(m)}
                balance_selected(group, order_key)
    return moves


async def volume_balance(env: CommandEnv,
                         apply_changes: bool = True,
                         collection: str = "EACH_COLLECTION",
                         data_center: str = "") -> list[dict]:
    """Plan per-type/per-collection balance moves (plan_balance), then
    apply them with volume.move. Planned against one topology snapshot
    (the master registry lags moves until the next heartbeat)."""
    body = await env.master_get("/vol/volumes")
    limit = int(body.get("volumeSizeLimitMB", 30_000)) * 1024 * 1024
    moves = plan_balance(body["nodes"], limit,
                         collection=collection, data_center=data_center)
    if apply_changes:
        for mv in moves:
            await volume_move(env, mv["volume"], mv["collection"],
                              mv["from"], mv["to"])
    return moves


async def volume_copy(env: CommandEnv, vid: int, collection: str,
                      src: str, dst: str) -> dict:
    """Copy a volume to another node, source kept
    (command_volume_copy.go)."""
    return await env.node_post(dst, "/admin/volume/copy", volume=str(vid),
                               collection=collection, source=src)


async def volume_move(env: CommandEnv, vid: int, collection: str,
                      src: str, dst: str) -> None:
    """copy to dst + mount, then unmount + delete on src
    (command_volume_move.go)."""
    await volume_copy(env, vid, collection, src, dst)
    # delete while still mounted so the store destroys the on-disk files
    # (unmount-then-delete would leave .dat/.idx to resurrect on restart)
    await env.node_post(src, "/admin/volume/delete", volume=str(vid))


async def volume_mount(env: CommandEnv, vid: int, node: str,
                       collection: str = "") -> dict:
    """Mount a volume already on the node's disk
    (command_volume_mount.go). The collection names the on-disk file
    (<collection>_<vid>.dat), so it must travel with the request."""
    return await env.node_post(node, "/admin/volume/mount",
                               volume=str(vid), collection=collection)


async def volume_unmount(env: CommandEnv, vid: int, node: str) -> dict:
    """Unmount a volume, keeping its files on disk
    (command_volume_unmount.go)."""
    return await env.node_post(node, "/admin/volume/unmount",
                               volume=str(vid))


async def volume_delete(env: CommandEnv, vid: int, node: str,
                        collection: str = "") -> dict:
    """Delete a volume from a node, destroying its files — including an
    unmounted volume's (command_volume_delete.go)."""
    return await env.node_post(node, "/admin/volume/delete",
                               volume=str(vid), collection=collection)


async def volume_tier_upload(env: CommandEnv, vid: int,
                             backend: str = "s3.default",
                             keep_local: bool = False) -> dict:
    """Ship a volume's .dat to remote storage
    (shell/command_volume_tier_upload.go)."""
    locs = await env.master_get("/dir/lookup", volumeId=str(vid))
    if "locations" not in locs:
        raise ValueError(f"volume {vid} not found")
    out = {}
    for loc in locs["locations"]:
        out[loc["url"]] = await env.node_post(
            loc["url"], "/admin/tier/upload", volume=str(vid),
            backend=backend, keep_local="1" if keep_local else "")
    return out


async def volume_tier_download(env: CommandEnv, vid: int) -> dict:
    """Bring a tiered volume's .dat back to local disk
    (shell/command_volume_tier_download.go)."""
    locs = await env.master_get("/dir/lookup", volumeId=str(vid))
    if "locations" not in locs:
        raise ValueError(f"volume {vid} not found")
    out = {}
    for loc in locs["locations"]:
        out[loc["url"]] = await env.node_post(
            loc["url"], "/admin/tier/download", volume=str(vid))
    return out
