"""Volume admin workflows: volume.vacuum / volume.fix.replication /
volume.balance / volume.move.

Reference: weed/topology/topology_vacuum.go:16-120 (check -> compact ->
commit across replicas), shell/command_volume_fix_replication.go
(re-replicate under-replicated volumes rack-aware), command_volume_balance.go
(even out volume counts), command_volume_move.go.
"""

from __future__ import annotations

import asyncio

from ..storage.super_block import ReplicaPlacement
from .env import CommandEnv


async def volume_vacuum(env: CommandEnv, garbage_threshold: float = 0.3,
                        collection: str | None = None) -> list[dict]:
    """check -> compact -> commit on every replica of dirty volumes."""
    results = []
    nodes = await env.list_nodes()
    # vid -> [(url, msg)]
    vols: dict[int, list[tuple[str, dict]]] = {}
    for n in nodes:
        for m in n["volumes"]:
            if collection is not None and m["collection"] != collection:
                continue
            vols.setdefault(m["id"], []).append((n["url"], m))
    for vid, holders in sorted(vols.items()):
        checks = await asyncio.gather(*(
            env.node_post(url, "/admin/vacuum/check", volume=str(vid))
            for url, _ in holders), return_exceptions=True)
        ratios = [c.get("garbage_ratio", 0.0) for c in checks
                  if isinstance(c, dict)]
        if not ratios or max(ratios) < garbage_threshold:
            continue
        try:
            await asyncio.gather(*(
                env.node_post(url, "/admin/vacuum/compact", volume=str(vid))
                for url, _ in holders))
            await asyncio.gather(*(
                env.node_post(url, "/admin/vacuum/commit", volume=str(vid))
                for url, _ in holders))
            results.append({"volume": vid, "garbage": max(ratios),
                            "vacuumed": True})
        except RuntimeError as e:
            await asyncio.gather(*(
                env.node_post(url, "/admin/vacuum/cleanup", volume=str(vid))
                for url, _ in holders), return_exceptions=True)
            results.append({"volume": vid, "error": str(e)})
    return results


async def volume_fix_replication(env: CommandEnv,
                                 apply_changes: bool = True) -> list[dict]:
    """Re-replicate volumes with fewer live copies than their placement
    demands (command_volume_fix_replication.go)."""
    actions = []
    nodes = await env.list_nodes()
    by_url = {n["url"]: n for n in nodes}
    vols: dict[int, list[tuple[str, dict]]] = {}
    for n in nodes:
        for m in n["volumes"]:
            vols.setdefault(m["id"], []).append((n["url"], m))
    for vid, holders in sorted(vols.items()):
        msg = holders[0][1]
        rp = ReplicaPlacement.from_byte(msg["replica_placement"])
        want, have = rp.copy_count, len(holders)
        if have >= want:
            continue
        holder_urls = {u for u, _ in holders}
        holder_racks = {(by_url[u]["dataCenter"], by_url[u]["rack"])
                        for u in holder_urls if u in by_url}
        # prefer a rack not already holding a replica, then most free slots
        candidates = sorted(
            (n for n in nodes
             if n["url"] not in holder_urls and n["freeSlots"] > 0),
            key=lambda n: ((n["dataCenter"], n["rack"]) in holder_racks,
                           -n["freeSlots"]))
        if not candidates:
            actions.append({"volume": vid, "error": "no candidate node"})
            continue
        target = candidates[0]["url"]
        actions.append({"volume": vid, "copy_to": target,
                        "from": holders[0][0]})
        if apply_changes:
            await env.node_post(target, "/admin/volume/copy",
                                volume=str(vid),
                                collection=msg["collection"],
                                source=holders[0][0])
    return actions


async def volume_balance(env: CommandEnv,
                         apply_changes: bool = True) -> list[dict]:
    """Plan moves from the fullest to the emptiest nodes until counts are
    within one of each other, then apply (command_volume_balance.go).
    Planned against one topology snapshot (the master registry lags moves
    until the next heartbeat)."""
    snapshot = {n["url"]: {"volumes": {m["id"]: m for m in n["volumes"]},
                           "free": n["freeSlots"]}
                for n in await env.list_nodes()}
    moves: list[dict] = []
    while len(snapshot) >= 2:
        ordered = sorted(snapshot.items(), key=lambda kv: len(kv[1]["volumes"]))
        (low_url, low), (high_url, high) = ordered[0], ordered[-1]
        if len(high["volumes"]) - len(low["volumes"]) <= 1 or low["free"] <= 0:
            break
        movable = [m for vid, m in high["volumes"].items()
                   if vid not in low["volumes"]]
        if not movable:
            break
        m = movable[0]
        moves.append({"volume": m["id"], "collection": m["collection"],
                      "from": high_url, "to": low_url})
        low["volumes"][m["id"]] = m
        low["free"] -= 1
        del high["volumes"][m["id"]]
        high["free"] += 1
    if apply_changes:
        for mv in moves:
            await volume_move(env, mv["volume"], mv["collection"],
                              mv["from"], mv["to"])
    return moves


async def volume_copy(env: CommandEnv, vid: int, collection: str,
                      src: str, dst: str) -> dict:
    """Copy a volume to another node, source kept
    (command_volume_copy.go)."""
    return await env.node_post(dst, "/admin/volume/copy", volume=str(vid),
                               collection=collection, source=src)


async def volume_move(env: CommandEnv, vid: int, collection: str,
                      src: str, dst: str) -> None:
    """copy to dst + mount, then unmount + delete on src
    (command_volume_move.go)."""
    await volume_copy(env, vid, collection, src, dst)
    # delete while still mounted so the store destroys the on-disk files
    # (unmount-then-delete would leave .dat/.idx to resurrect on restart)
    await env.node_post(src, "/admin/volume/delete", volume=str(vid))


async def volume_mount(env: CommandEnv, vid: int, node: str,
                       collection: str = "") -> dict:
    """Mount a volume already on the node's disk
    (command_volume_mount.go). The collection names the on-disk file
    (<collection>_<vid>.dat), so it must travel with the request."""
    return await env.node_post(node, "/admin/volume/mount",
                               volume=str(vid), collection=collection)


async def volume_unmount(env: CommandEnv, vid: int, node: str) -> dict:
    """Unmount a volume, keeping its files on disk
    (command_volume_unmount.go)."""
    return await env.node_post(node, "/admin/volume/unmount",
                               volume=str(vid))


async def volume_delete(env: CommandEnv, vid: int, node: str,
                        collection: str = "") -> dict:
    """Delete a volume from a node, destroying its files — including an
    unmounted volume's (command_volume_delete.go)."""
    return await env.node_post(node, "/admin/volume/delete",
                               volume=str(vid), collection=collection)


async def volume_tier_upload(env: CommandEnv, vid: int,
                             backend: str = "s3.default",
                             keep_local: bool = False) -> dict:
    """Ship a volume's .dat to remote storage
    (shell/command_volume_tier_upload.go)."""
    locs = await env.master_get("/dir/lookup", volumeId=str(vid))
    if "locations" not in locs:
        raise ValueError(f"volume {vid} not found")
    out = {}
    for loc in locs["locations"]:
        out[loc["url"]] = await env.node_post(
            loc["url"], "/admin/tier/upload", volume=str(vid),
            backend=backend, keep_local="1" if keep_local else "")
    return out


async def volume_tier_download(env: CommandEnv, vid: int) -> dict:
    """Bring a tiered volume's .dat back to local disk
    (shell/command_volume_tier_download.go)."""
    locs = await env.master_get("/dir/lookup", volumeId=str(vid))
    if "locations" not in locs:
        raise ValueError(f"volume {vid} not found")
    out = {}
    for loc in locs["locations"]:
        out[loc["url"]] = await env.node_post(
            loc["url"], "/admin/tier/download", volume=str(vid))
    return out
