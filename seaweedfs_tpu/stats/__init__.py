"""stats subpackage."""
