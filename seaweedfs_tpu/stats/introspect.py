"""Cluster-scope introspection: fan-out + cross-host trace assembly.

Every ``/debug/*`` surface merges only across ``-workers`` siblings of
ONE host, so a request that crosses s3 → filer shard → owner volume →
replica on three hosts fragments into three disconnected span rings.
This module is the leader-side glue that makes the recorder speak for
the CLUSTER:

- :func:`cluster_nodes` enumerates every debug-capable member the
  leader knows about — quorum peers (``-peers``), topology-fed volume
  servers (heartbeats), shard-map-fed filers — in deterministic order;
- :func:`fanout` pulls one debug path from each of them, frame-first
  over the existing fabric with HTTP fallback, under a bounded
  per-node deadline (``-introspect.deadline``) and the
  ``introspect.fanout`` failpoint, so a dead member degrades its row
  and can NEVER hang the endpoint. Every hop is counted in
  ``SeaweedFS_introspect_fanout_total{result}``;
- :func:`assemble_trace` folds the per-node span pulls into ONE tree
  with host/tier attribution, per-hop self-time, and explicit
  ``missing_nodes`` annotations — deterministically ordered, so the
  same completed trace assembles byte-identically on retry.

The timeline/events/health cluster views reuse the PR 8 whole-host
merge discipline verbatim (stats/timeline.merge_payloads,
util/events.merge_payloads, stats/slo.health_dict): sum rates and
histogram buckets, MAX the ``NON_ADDITIVE_GAUGE_PREFIXES``, recompute
quantiles from merged buckets — never average.
"""

from __future__ import annotations

import asyncio
import json

import aiohttp

from ..security import tls
from ..util import failpoints

DEFAULT_DEADLINE_S = 3.0

_deadline_s = DEFAULT_DEADLINE_S

# lazily-bound prometheus counter (same shape as tracing._observe)
_counter: object = None

# extra-node kinds -> debug path prefix (the path-shadowing gateways
# serve /__debug__/ so a stored object named "debug" can't shadow it)
KIND_PREFIX = {"master": "/debug", "volume": "/debug",
               "filer": "/__debug__", "s3": "/__debug__",
               "webdav": "/__debug__"}
# kinds that terminate frame connections (master/frameadapter.py,
# server/frameserver.py): a frame attempt against anything else would
# burn the node's whole deadline waiting on a HELLO no one answers
FRAME_KINDS = frozenset(("master", "volume"))


def init(deadline_s: float = DEFAULT_DEADLINE_S) -> None:
    """Wire from the CLI flag: -introspect.deadline (per-node budget
    for every cluster fan-out hop)."""
    global _deadline_s
    _deadline_s = max(0.1, float(deadline_s))


def deadline_s() -> float:
    return _deadline_s


def _count(result: str) -> None:
    global _counter
    if _counter is None:
        try:
            from . import metrics
            _counter = (metrics.INTROSPECT_FANOUT
                        if metrics.HAVE_PROMETHEUS else False)
        except ImportError:
            _counter = False
    if _counter:
        _counter.labels(result).inc()


# ---------------------------------------------------------------------------
# node enumeration


def cluster_nodes(ms, extra: str = "") -> "list[dict]":
    """Every debug-capable member from the leader's vantage, deduped
    by address, deterministic order: this master first, then quorum
    peers, topology volume servers, shard-map filer owners, then any
    ``extra`` nodes (comma-separated ``[kind:]host:port`` — the hook
    for members the master has no registry for, e.g. an S3 gateway).
    ``ms`` is the MasterServer (duck-typed for tests)."""
    nodes = [{"node": ms.url, "kind": "master", "prefix": "/debug",
              "local": True}]
    seen = {ms.url}
    for p in ms._peers:
        if p in seen:
            continue
        seen.add(p)
        nodes.append({"node": p, "kind": "master", "prefix": "/debug"})
    for n in ms.topo.all_nodes():
        if n.url in seen:
            continue
        seen.add(n.url)
        nodes.append({"node": n.url, "kind": "volume",
                      "prefix": "/debug"})
    owners = (ms._shard_map_dict().get("owners") or {})
    for sid in sorted(owners, key=lambda s: int(s)):
        addr = owners[sid]
        if addr in seen:
            continue
        seen.add(addr)
        nodes.append({"node": addr, "kind": "filer",
                      "prefix": "/__debug__"})
    for item in (extra or "").split(","):
        item = item.strip()
        if not item:
            continue
        kind, addr = "volume", item
        head, _, rest = item.partition(":")
        if head in KIND_PREFIX and rest:
            kind, addr = head, rest
        if addr in seen:
            continue
        seen.add(addr)
        nodes.append({"node": addr, "kind": kind,
                      "prefix": KIND_PREFIX[kind]})
    return nodes


# ---------------------------------------------------------------------------
# bounded fan-out


async def _pull(http, frame_hub, addr: str, path: str,
                params: "dict | None", timeout: float):
    """One per-node debug pull: frame-first when a hub is wired (the
    master's raft peers terminate whitelisted debug routes over
    frames; everything else answers FLAG_FALLBACK), HTTP fallback.
    Raises on failure — fanout() turns that into a missing_nodes row."""
    # chaos site: the cluster-assembly hop — error/latency/drop here
    # must degrade to a missing_node row inside the deadline, never
    # hang or 500 the whole endpoint
    await failpoints.fail("introspect.fanout")
    if frame_hub is not None:
        from ..util.frame import FrameChannelError
        try:
            chan = frame_hub.get(target=addr)
            # half the budget: a wedged frame channel must leave room
            # for the HTTP leg inside the same per-node deadline
            status, _hdrs, raw = await chan.request(
                "GET", path, query=params, timeout=timeout / 2)
            if status == 200:
                return json.loads(raw or b"{}")
        except (FrameChannelError, asyncio.TimeoutError, OSError,
                ValueError):
            pass            # the HTTP leg below is the resilient one
    async with http.get(
            tls.url(addr, path), params=params,
            timeout=aiohttp.ClientTimeout(total=timeout)) as resp:
        if resp.status != 200:
            raise OSError(f"HTTP {resp.status}")
        return await resp.json(content_type=None)


async def fanout(nodes: "list[dict]", path: str, http,
                 frame_hub=None, params: "dict | None" = None,
                 deadline: "float | None" = None,
                 local=None):
    """Pull ``prefix + path`` from every node in parallel, each under
    its own deadline. Returns ``(results, missing)`` where results is
    ``[(node_dict, payload)]`` and missing is the degraded rows —
    sorted by address, so downstream assembly is deterministic. A
    node marked ``local`` is answered by the ``local()`` callable (or
    awaitable result) instead of the network."""
    deadline = deadline if deadline is not None else _deadline_s
    results: "list[tuple[dict, dict]]" = []
    missing: "list[dict]" = []

    async def one(nd: dict) -> None:
        if nd.get("local") and local is not None:
            payload = local()
            if asyncio.iscoroutine(payload):
                payload = await payload
            results.append((nd, payload))
            return
        hub = frame_hub if nd["kind"] in FRAME_KINDS else None
        try:
            payload = await asyncio.wait_for(
                _pull(http, hub, nd["node"], nd["prefix"] + path,
                      params, deadline),
                timeout=deadline)
            _count("ok")
            results.append((nd, payload))
        except asyncio.TimeoutError:
            _count("timeout")
            missing.append({"node": nd["node"], "kind": nd["kind"],
                            "error": "timeout"})
        except (aiohttp.ClientError, OSError, ValueError) as e:
            _count("error")
            missing.append({"node": nd["node"], "kind": nd["kind"],
                            "error": str(e) or type(e).__name__})

    await asyncio.gather(*(one(nd) for nd in nodes))
    results.sort(key=lambda r: r[0]["node"])
    missing.sort(key=lambda m: m["node"])
    return results, missing


# ---------------------------------------------------------------------------
# trace assembly (pure — unit-testable without a cluster)


def assemble_trace(trace_id: str,
                   node_payloads: "list[tuple[str, dict]]",
                   missing: "list[dict] | None" = None) -> dict:
    """ONE tree from per-node ``?trace=`` span pulls.

    ``node_payloads`` is ``[(host, payload)]``; every span is stamped
    with the host that reported it, span ids dedupe across nodes (a
    finished record beats an in-flight sighting), per-span self-time
    is ``dur - Σ(direct children)`` and rolls up per tier AND per
    host — the "which host ate the time" attribution. Ordering is
    deterministic everywhere (sorted spans, sorted rollup keys,
    sorted missing rows): the same completed trace assembles
    byte-identically on retry."""
    by_id: dict[str, dict] = {}
    for host, payload in sorted(node_payloads, key=lambda hp: hp[0]):
        for d in payload.get("spans", ()):
            row = dict(d)
            row["host"] = host
            sid = row.get("span", "")
            cur = by_id.get(sid)
            if cur is None or (cur.get("inflight")
                               and not row.get("inflight")):
                by_id[sid] = row
    spans = sorted(by_id.values(),
                   key=lambda d: (d.get("start_ms", 0.0),
                                  d.get("span", "")))
    child_ms: dict[str, float] = {}
    for d in spans:
        p = d.get("parent", "")
        if p in by_id:
            child_ms[p] = child_ms.get(p, 0.0) + d.get("dur_ms", 0.0)
    tiers: dict[str, float] = {}
    hosts: dict[str, float] = {}
    children: dict[str, list] = {}
    roots: list[dict] = []
    for d in spans:
        d["self_ms"] = round(
            max(0.0, d.get("dur_ms", 0.0)
                - child_ms.get(d["span"], 0.0)), 3)
        tiers[d["tier"]] = round(
            tiers.get(d["tier"], 0.0) + d["self_ms"], 3)
        hosts[d["host"]] = round(
            hosts.get(d["host"], 0.0) + d["self_ms"], 3)
        p = d.get("parent", "")
        if p and p in by_id:
            children.setdefault(p, []).append(d)
        else:
            roots.append(d)

    visited: set = set()

    def nest(d: dict) -> dict:
        node = dict(d)
        visited.add(d["span"])
        kids = [k for k in children.get(d["span"], ())
                if k["span"] not in visited]
        if kids:
            node["children"] = [nest(k) for k in kids]
        return node

    tree = [nest(r) for r in roots if r["span"] not in visited]
    missing = sorted(missing or [], key=lambda m: m.get("node", ""))
    return {
        "trace_id": trace_id,
        "spans": len(spans),
        "start_ms": min((d.get("start_ms", 0.0) for d in spans),
                        default=0.0),
        "dur_ms": max((d.get("dur_ms", 0.0) for d in spans),
                      default=0.0),
        "inflight": sum(1 for d in spans if d.get("inflight")),
        "tiers": {k: tiers[k] for k in sorted(tiers)},
        "hosts": {k: hosts[k] for k in sorted(hosts)},
        "complete": not missing,
        "missing_nodes": missing,
        "tree": tree,
    }
