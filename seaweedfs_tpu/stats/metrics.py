"""Prometheus metrics.

Reference: weed/stats/metrics.go:13-92 (per-tier counters/histograms/
gauges) and :109-137 (push-gateway loop; the master hands the gateway
address to nodes via heartbeat responses). Exposed here both as a /metrics
scrape endpoint on every server and an optional push loop.
"""

from __future__ import annotations

import asyncio

try:
    from prometheus_client import (CollectorRegistry, Counter, Gauge,
                                   Histogram, generate_latest,
                                   push_to_gateway)
    HAVE_PROMETHEUS = True
except ImportError:  # pragma: no cover
    HAVE_PROMETHEUS = False

if HAVE_PROMETHEUS:
    REGISTRY = CollectorRegistry()

    MASTER_RECEIVED_HEARTBEATS = Counter(
        "SeaweedFS_master_received_heartbeats", "heartbeats received",
        registry=REGISTRY)
    MASTER_ASSIGN_REQUESTS = Counter(
        "SeaweedFS_master_assign_requests", "assign requests",
        ["status"], registry=REGISTRY)
    VOLUME_REQUEST_TIME = Histogram(
        "SeaweedFS_volumeServer_request_seconds", "needle request time",
        ["type"], registry=REGISTRY)
    VOLUME_REQUEST_COUNTER = Counter(
        "SeaweedFS_volumeServer_request_total", "needle requests",
        ["type", "status"], registry=REGISTRY)
    VOLUME_COUNT = Gauge(
        "SeaweedFS_volumeServer_volumes", "volumes on this server",
        registry=REGISTRY)
    FILER_REQUEST_TIME = Histogram(
        "SeaweedFS_filer_request_seconds", "filer request time",
        ["type"], registry=REGISTRY)
    EC_ENCODE_BYTES = Counter(
        "SeaweedFS_ec_encode_bytes_total", "bytes erasure-encoded",
        registry=REGISTRY)
    EC_THROUGHPUT = Gauge(
        "SeaweedFS_ec_encode_GBps", "last measured EC encode GB/s/chip",
        registry=REGISTRY)
    # tiered read caches (util/chunk_cache.py): one label per cache —
    # "needle" (volume hot needles), "chunk" (filer/s3/webdav whole
    # chunks), "ec_recover" (degraded-read reconstructions),
    # "lookup_neg" (client negative volume lookups)
    CACHE_HITS = Counter(
        "SeaweedFS_cache_hits_total", "read-cache hits",
        ["cache"], registry=REGISTRY)
    CACHE_MISSES = Counter(
        "SeaweedFS_cache_misses_total", "read-cache misses",
        ["cache"], registry=REGISTRY)
    CACHE_HIT_BYTES = Counter(
        "SeaweedFS_cache_hit_bytes_total", "bytes served from read caches",
        ["cache"], registry=REGISTRY)
    CACHE_EVICTIONS = Counter(
        "SeaweedFS_cache_evictions_total", "read-cache evictions",
        ["cache"], registry=REGISTRY)
    CACHE_USED_BYTES = Gauge(
        "SeaweedFS_cache_used_bytes", "bytes currently held per cache",
        ["cache"], registry=REGISTRY)
    # distributed tracing (util/tracing.py): every finished span feeds
    # this, so the trace ring and Prometheus agree by construction.
    # tier: s3|webdav|filer|client|proxy|volume|store|ec|replicate
    REQUEST_DURATION = Histogram(
        "SeaweedFS_request_duration_seconds",
        "traced span duration per tier/op/status",
        ["tier", "op", "status"], registry=REGISTRY)
    METRICS_PUSH_ERRORS = Counter(
        "SeaweedFS_metrics_push_errors_total",
        "failed pushes to the configured metrics gateway",
        registry=REGISTRY)
    # background EC parity scrubber (ec/scrub.py)
    SCRUB_BYTES = Counter(
        "SeaweedFS_scrub_scanned_bytes_total",
        "shard bytes read by the EC parity scrubber",
        registry=REGISTRY)
    SCRUB_WINDOWS = Counter(
        "SeaweedFS_scrub_windows_total",
        "stripe windows scrubbed, by parity-check result",
        ["result"], registry=REGISTRY)
    SCRUB_CORRUPTIONS = Counter(
        "SeaweedFS_scrub_corruptions_total",
        "corrupt stripe windows detected by the scrubber",
        registry=REGISTRY)
    SCRUB_PAUSES = Counter(
        "SeaweedFS_scrub_pauses_total",
        "scrub pauses yielding to hot foreground traffic",
        registry=REGISTRY)
    SCRUB_CYCLES = Counter(
        "SeaweedFS_scrub_cycles_total",
        "completed whole-store scrub cycles",
        registry=REGISTRY)

    def metrics_text() -> bytes:
        return generate_latest(REGISTRY)
else:  # pragma: no cover
    def metrics_text() -> bytes:
        return b"# prometheus_client unavailable\n"


def merge_metrics_texts(texts: "list[bytes]") -> bytes:
    """Sum Prometheus text expositions from several worker processes
    into one whole-host view (server/workers.py: each -workers worker
    has its own registry; any worker answers /metrics for all).

    Counters, gauges, and histogram buckets are summed per
    (name, labels); `*_created` timestamps take the min (first birth);
    HELP/TYPE comments are kept from their first appearance.

    Integral sums are emitted WITHOUT a trailing `.0` and never in
    exponent notation: `repr(float)` rendered a summed counter of 123
    as `123.0` and a large one as `1.2e+16`, both of which surprise
    text-format consumers that treat counters as integers."""
    order: list[tuple[str, bytes]] = []   # ("comment"|"sample", key)
    seen_comments: set[bytes] = set()
    sums: dict[bytes, float] = {}
    for text in texts:
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith(b"#"):
                if line not in seen_comments:
                    seen_comments.add(line)
                    order.append(("comment", line))
                continue
            i = line.rfind(b" ")
            if i <= 0:
                continue
            key, raw = line[:i], line[i + 1:]
            try:
                val = float(raw)
            except ValueError:
                continue
            if key not in sums:
                order.append(("sample", key))
                sums[key] = val
            elif key.split(b"{", 1)[0].endswith(b"_created"):
                sums[key] = min(sums[key], val)
            else:
                sums[key] += val
    out = []
    for kind, item in order:
        if kind == "comment":
            out.append(item)
        else:
            out.append(item + b" " + _fmt_value(sums[item]))
    return b"\n".join(out) + b"\n" if out else b""


def _fmt_value(val: float) -> bytes:
    """Prometheus text-format value: integral floats render as plain
    integers (no `.0`, no exponent — `int(float)` is exact for any
    float that is_integer()); fractional values keep full precision."""
    if val != val or val in (float("inf"), float("-inf")):
        return repr(val).encode()
    if float(val).is_integer():
        return b"%d" % int(val)
    return repr(val).encode()


async def push_loop(gateway: str, job: str,
                    interval_seconds: float = 15.0,
                    max_backoff_seconds: float = 300.0) -> None:
    """LoopPushingMetric (metrics.go:109-137).

    Failures are COUNTED (SeaweedFS_metrics_push_errors_total) and
    LOGGED — the first failure and every healthy<->failing transition
    at WARNING/INFO — while the push interval backs off exponentially
    so a long-dead gateway neither floods the log nor gets hammered."""
    from ..util import glog, tracing
    if not HAVE_PROMETHEUS or not gateway:
        return
    failing = False
    delay = interval_seconds
    while True:
        try:
            await tracing.run_in_executor(
                lambda: push_to_gateway(gateway, job=job,
                                        registry=REGISTRY))
            if failing:
                glog.info("metrics push to %s recovered (job=%s)",
                          gateway, job)
            failing = False
            delay = interval_seconds
        except Exception as e:  # noqa: BLE001 — the pusher must outlive
            # any gateway-side failure shape, but never silently
            METRICS_PUSH_ERRORS.inc()
            if not failing:
                glog.warning(
                    "metrics push to %s failed: %s %s (backing off; "
                    "logged once until recovery)", gateway,
                    type(e).__name__, e)
            failing = True
            delay = min(delay * 2, max_backoff_seconds)
        await asyncio.sleep(delay)
