"""Prometheus metrics.

Reference: weed/stats/metrics.go:13-92 (per-tier counters/histograms/
gauges) and :109-137 (push-gateway loop; the master hands the gateway
address to nodes via heartbeat responses). Exposed here both as a /metrics
scrape endpoint on every server and an optional push loop.
"""

from __future__ import annotations

import asyncio
import platform
import time

try:
    from prometheus_client import (CollectorRegistry, Counter, Gauge,
                                   Histogram, generate_latest,
                                   push_to_gateway)
    HAVE_PROMETHEUS = True
except ImportError:  # pragma: no cover
    HAVE_PROMETHEUS = False

if HAVE_PROMETHEUS:
    REGISTRY = CollectorRegistry()

    MASTER_RECEIVED_HEARTBEATS = Counter(
        "SeaweedFS_master_received_heartbeats", "heartbeats received",
        registry=REGISTRY)
    # HA master quorum (master/election.py): the raft state every
    # failover dashboard needs — whose term, how far committed, and
    # who leads. All three are identities of ONE process, not additive
    # quantities: they join NON_ADDITIVE_GAUGE_PREFIXES below so a
    # -workers merged host reports the max (one leader), never a sum
    # (two "leaders", a doubled term)
    RAFT_TERM = Gauge(
        "SeaweedFS_raft_term",
        "current raft term of this master", registry=REGISTRY)
    RAFT_COMMIT_INDEX = Gauge(
        "SeaweedFS_raft_commit_index",
        "highest raft log index known committed on this master",
        registry=REGISTRY)
    RAFT_IS_LEADER = Gauge(
        "SeaweedFS_raft_is_leader",
        "1 when this master is the elected (or single-mode) leader",
        registry=REGISTRY)
    MASTER_ASSIGN_REQUESTS = Counter(
        "SeaweedFS_master_assign_requests", "assign requests",
        ["status"], registry=REGISTRY)
    VOLUME_REQUEST_TIME = Histogram(
        "SeaweedFS_volumeServer_request_seconds", "needle request time",
        ["type"], registry=REGISTRY)
    VOLUME_REQUEST_COUNTER = Counter(
        "SeaweedFS_volumeServer_request_total", "needle requests",
        ["type", "status"], registry=REGISTRY)
    VOLUME_COUNT = Gauge(
        "SeaweedFS_volumeServer_volumes", "volumes on this server",
        registry=REGISTRY)
    FILER_REQUEST_TIME = Histogram(
        "SeaweedFS_filer_request_seconds", "filer request time",
        ["type"], registry=REGISTRY)
    # sharded filer metadata plane (filer/shard.py): routing outcomes
    # per request, the committed map epoch this shard has adopted, and
    # entries streamed out by split/move migrations
    FILER_SHARD_REQUESTS = Counter(
        "SeaweedFS_filer_shard_requests_total",
        "shard routing outcomes",
        ["result"], registry=REGISTRY)
    FILER_SHARD_EPOCH = Gauge(
        "SeaweedFS_filer_shard_map_epoch",
        "adopted shard map epoch", registry=REGISTRY)
    FILER_SHARD_MOVED = Counter(
        "SeaweedFS_filer_shard_moved_entries_total",
        "entries migrated out by shard split/move",
        registry=REGISTRY)
    EC_ENCODE_BYTES = Counter(
        "SeaweedFS_ec_encode_bytes_total", "bytes erasure-encoded",
        registry=REGISTRY)
    EC_THROUGHPUT = Gauge(
        "SeaweedFS_ec_encode_GBps", "last measured EC encode GB/s/chip",
        registry=REGISTRY)
    # tiered read caches (util/chunk_cache.py): one label per cache —
    # "needle" (volume hot needles), "chunk" (filer/s3/webdav whole
    # chunks), "ec_recover" (degraded-read reconstructions),
    # "lookup_neg" (client negative volume lookups)
    CACHE_HITS = Counter(
        "SeaweedFS_cache_hits_total", "read-cache hits",
        ["cache"], registry=REGISTRY)
    CACHE_MISSES = Counter(
        "SeaweedFS_cache_misses_total", "read-cache misses",
        ["cache"], registry=REGISTRY)
    CACHE_HIT_BYTES = Counter(
        "SeaweedFS_cache_hit_bytes_total", "bytes served from read caches",
        ["cache"], registry=REGISTRY)
    CACHE_EVICTIONS = Counter(
        "SeaweedFS_cache_evictions_total", "read-cache evictions",
        ["cache"], registry=REGISTRY)
    CACHE_USED_BYTES = Gauge(
        "SeaweedFS_cache_used_bytes", "bytes currently held per cache",
        ["cache"], registry=REGISTRY)
    # distributed tracing (util/tracing.py): every finished span feeds
    # this, so the trace ring and Prometheus agree by construction.
    # tier: s3|webdav|filer|client|proxy|volume|store|ec|replicate
    REQUEST_DURATION = Histogram(
        "SeaweedFS_request_duration_seconds",
        "traced span duration per tier/op/status",
        ["tier", "op", "status"], registry=REGISTRY)
    METRICS_PUSH_ERRORS = Counter(
        "SeaweedFS_metrics_push_errors_total",
        "failed pushes to the configured metrics gateway",
        registry=REGISTRY)
    # background EC parity scrubber (ec/scrub.py)
    SCRUB_BYTES = Counter(
        "SeaweedFS_scrub_scanned_bytes_total",
        "shard bytes read by the EC parity scrubber",
        registry=REGISTRY)
    SCRUB_WINDOWS = Counter(
        "SeaweedFS_scrub_windows_total",
        "stripe windows scrubbed, by parity-check result",
        ["result"], registry=REGISTRY)
    SCRUB_CORRUPTIONS = Counter(
        "SeaweedFS_scrub_corruptions_total",
        "corrupt stripe windows detected by the scrubber",
        registry=REGISTRY)
    SCRUB_PAUSES = Counter(
        "SeaweedFS_scrub_pauses_total",
        "scrub pauses yielding to hot foreground traffic",
        registry=REGISTRY)
    SCRUB_CYCLES = Counter(
        "SeaweedFS_scrub_cycles_total",
        "completed whole-store scrub cycles",
        registry=REGISTRY)
    SCRUB_BATCHES = Counter(
        "SeaweedFS_scrub_batches_total",
        "stripe-window blocks scrubbed (one GF transform dispatch each)",
        registry=REGISTRY)
    # autopilot maintenance plane (autopilot/): the leader's
    # observe -> plan -> execute loop — cycles, per-kind action
    # outcomes, why actions were deferred, the paced repair bytes the
    # token bucket admitted, and whether repair is parked behind a
    # paging fleet
    AUTOPILOT_CYCLES = Counter(
        "SeaweedFS_autopilot_cycles_total",
        "completed observe->plan->execute maintenance cycles",
        registry=REGISTRY)
    AUTOPILOT_ACTIONS = Counter(
        "SeaweedFS_autopilot_actions_total",
        "maintenance actions by kind and outcome (ok/error/dryrun)",
        ["kind", "result"], registry=REGISTRY)
    AUTOPILOT_DEFERRALS = Counter(
        "SeaweedFS_autopilot_deferrals_total",
        "planned-but-not-executed actions, by deferral reason",
        ["reason"], registry=REGISTRY)
    AUTOPILOT_REPAIR_BYTES = Counter(
        "SeaweedFS_autopilot_repair_bytes_total",
        "estimated bytes admitted through the repair token bucket",
        registry=REGISTRY)
    AUTOPILOT_PAUSES = Counter(
        "SeaweedFS_autopilot_pauses_total",
        "times the executor parked because /debug/health paged",
        registry=REGISTRY)
    AUTOPILOT_QUEUE_DEPTH = Gauge(
        "SeaweedFS_autopilot_queue_depth",
        "actions waiting in the current cycle's plan queue",
        registry=REGISTRY)
    AUTOPILOT_PAUSED = Gauge(
        "SeaweedFS_autopilot_paused",
        "1 while repair is parked behind a paging fleet",
        registry=REGISTRY)
    # binary frame wire (util/frame.py): the frame fabric's request
    # volume and its HTTP downgrades — a rising fallback rate means
    # the frame path is being severed (chaos or a peer that predates
    # the protocol). hop is low-cardinality by construction:
    # sibling (intra-host worker hop) or interhost (the cluster fabric)
    FRAME_REQUESTS = Counter(
        "SeaweedFS_frame_requests_total",
        "frame-RPC requests, by side (client = issued, server = "
        "served) and hop (sibling = intra-host, interhost = fabric)",
        ["side", "hop"], registry=REGISTRY)
    FRAME_FALLBACKS = Counter(
        "SeaweedFS_frame_fallbacks_total",
        "frame requests downgraded to the HTTP hop (server-advised "
        "FLAG_FALLBACK answers + client-observed channel failures), "
        "by hop",
        ["hop"], registry=REGISTRY)
    FRAME_OPEN_CHANNELS = Gauge(
        "SeaweedFS_frame_open_channels",
        "currently-connected frame channels this process holds, per "
        "peer target (bounded by FrameHub.MAX_CHANNELS)",
        ["peer"], registry=REGISTRY)
    # build/restart detection (scrapes and timelines both need to tell
    # a counter reset apart from a rate dip): every daemon exports who
    # it is and when this process was born
    BUILD_INFO = Gauge(
        "SeaweedFS_build_info",
        "constant 1, labeled with the build version and python version",
        ["version", "pyver"], registry=REGISTRY)
    PROCESS_START_TIME = Gauge(
        "SeaweedFS_process_start_time_seconds",
        "unix time this process imported the metrics registry",
        registry=REGISTRY)
    # cluster-scope introspection (stats/introspect.py): every
    # fan-out hop the leader makes to assemble /debug/cluster/* views,
    # by result — a rising error/timeout share means some member's
    # debug plane is dark (result is a closed set: ok|error|timeout)
    INTROSPECT_FANOUT = Counter(
        "SeaweedFS_introspect_fanout_total",
        "per-node debug pulls issued by cluster-scope assembly, "
        "by result",
        ["result"], registry=REGISTRY)
    # continuous sampling profiler (stats/profiler.py): one count per
    # sampler tick, so `samples ≈ -profile.hz × uptime` is checkable
    # and the overhead accounting is deterministic
    PROFILE_SAMPLES = Counter(
        "SeaweedFS_profile_samples_total",
        "stack-sampler ticks taken by the continuous profiler",
        registry=REGISTRY)
    # structured event journal (util/events.py): one count per recorded
    # cluster state transition, so the ring and Prometheus agree
    EVENTS_TOTAL = Counter(
        "SeaweedFS_events_total",
        "cluster state transitions recorded in the event journal",
        ["type"], registry=REGISTRY)
    # saturation probes (stats/saturation.py), sampled into the
    # timeline ring so "slow" is attributable to a saturated resource
    EVENTLOOP_LAG = Gauge(
        "SeaweedFS_eventloop_lag_seconds",
        "max asyncio scheduling delay observed since the last sample",
        registry=REGISTRY)
    EXECUTOR_WAIT = Gauge(
        "SeaweedFS_executor_wait_seconds",
        "queue wait of a probe task submitted to the default executor",
        registry=REGISTRY)
    EXECUTOR_QUEUE_DEPTH = Gauge(
        "SeaweedFS_executor_queue_depth",
        "tasks waiting in the default executor work queue",
        registry=REGISTRY)
    OPEN_FDS = Gauge(
        "SeaweedFS_open_fds",
        "file descriptors currently open in this process",
        registry=REGISTRY)
    DISK_FREE_BYTES = Gauge(
        "SeaweedFS_disk_free_bytes",
        "free bytes on the filesystem holding a data dir",
        ["path"], registry=REGISTRY)
    DISK_USED_BYTES = Gauge(
        "SeaweedFS_disk_used_bytes",
        "used bytes on the filesystem holding a data dir",
        ["path"], registry=REGISTRY)
    CACHE_BUDGET_BYTES = Gauge(
        "SeaweedFS_cache_budget_bytes",
        "configured byte budget per read cache (occupancy vs budget)",
        ["cache"], registry=REGISTRY)
    # multi-tenant QoS (seaweedfs_tpu/qos/): admission decisions,
    # weighted-fair queue depths, per-tenant latency attribution, and
    # the background-bandwidth arbiter's grant accounting. Tenant
    # labels are BOUNDED (BoundedLabelSet below): configured tenants
    # always keep their own label, unconfigured identities fold into
    # `other` past the cap — an access-key scan cannot blow up the
    # registry, the timeline ring, or merge payloads
    QOS_DECISIONS = Counter(
        "SeaweedFS_qos_decisions_total",
        "admission decisions per tenant and outcome "
        "(admit/throttle/shed)",
        ["tenant", "decision"], registry=REGISTRY)
    QOS_QUEUE_DEPTH = Gauge(
        "SeaweedFS_qos_queue_depth",
        "requests parked in the weighted-fair admission queue, per "
        "tenant class",
        ["tenant"], registry=REGISTRY)
    QOS_TENANT_REQUEST_TIME = Histogram(
        "SeaweedFS_qos_tenant_request_seconds",
        "entry-tier request duration attributed to a tenant "
        "(per-tenant -slo objectives evaluate against this)",
        ["tier", "op", "tenant"], registry=REGISTRY)
    QOS_ARBITER_GRANTED = Counter(
        "SeaweedFS_qos_arbiter_granted_bytes_total",
        "background bytes admitted through the bandwidth arbiter, "
        "per consumer",
        ["kind"], registry=REGISTRY)
    QOS_ARBITER_YIELDS = Counter(
        "SeaweedFS_qos_arbiter_yields_total",
        "arbiter grants squeezed below base rate by foreground "
        "pressure, per consumer",
        ["kind"], registry=REGISTRY)
    QOS_ARBITER_RATE = Gauge(
        "SeaweedFS_qos_arbiter_rate_bytes_s",
        "currently-granted background rate per arbiter consumer",
        ["kind"], registry=REGISTRY)
    QOS_FOREGROUND_BPS = Gauge(
        "SeaweedFS_qos_foreground_bytes_s",
        "foreground byte rate observed by the bandwidth arbiter",
        registry=REGISTRY)
    # SLO burn-rate engine (stats/slo.py)
    SLO_STATUS = Gauge(
        "SeaweedFS_slo_status",
        "health verdict per objective: 0=ok 1=warn 2=page",
        ["objective"], registry=REGISTRY)
    SLO_BURN_RATE = Gauge(
        "SeaweedFS_slo_burn_rate",
        "error-budget burn rate per objective and evaluation window",
        ["objective", "window"], registry=REGISTRY)

    from .. import __version__
    BUILD_INFO.labels(__version__, platform.python_version()).set(1)
    PROCESS_START_TIME.set(time.time())

    def metrics_text() -> bytes:
        return generate_latest(REGISTRY)
else:  # pragma: no cover
    def metrics_text() -> bytes:
        return b"# prometheus_client unavailable\n"


class BoundedLabelSet:
    """Cardinality armor for identity-derived metric labels.

    The first `cap` distinct keys keep their own label value; every
    key after that maps to `"other"`. Seed keys (the configured
    tenants) are reserved up front and can never be displaced by a
    scan — a client hammering 10k random access keys costs at most
    `cap` label values in the registry, not 10k.

    Thread-free by design: admission runs on the event loop; the set
    only grows, so a racy double-add is harmless anyway."""

    OTHER = "other"

    __slots__ = ("cap", "_seen")

    def __init__(self, cap: int = 32, seed=()):
        self.cap = max(int(cap), 1)
        self._seen = set(seed)
        self._seen.add(self.OTHER)

    def get(self, key: str) -> str:
        if key in self._seen:
            return key
        if len(self._seen) < self.cap:
            self._seen.add(key)
            return key
        return self.OTHER

    def __len__(self) -> int:
        return len(self._seen)


# Gauges where summing across workers fabricates a value no process
# ever observed: every worker samples the SAME filesystem (sum doubles
# free/used space), scheduling-delay probes are per-loop latencies (the
# host's honest number is the WORST worker), build_info is a constant 1
# per process, and process_start_time is a unix timestamp (dashboards
# compute `time() - start`; max = the most recent birth, so ANY worker
# respawn moves it — exactly the restart signal the gauge exists for).
# The SLO verdict gauges are per-process VERDICTS, not quantities: two
# workers both at warn (1+1) must merge to warn=1, not page=2, and two
# sub-threshold burn rates must not sum past the page threshold — the
# host's honest health is the WORST worker's, i.e. max.
# Everything else — open fds, queue depth, cache bytes — is a genuinely
# per-process resource and sums like counters do. Shared by this
# /metrics merge and the /debug/timeline whole-host merge.
NON_ADDITIVE_GAUGE_PREFIXES = (
    "SeaweedFS_disk_",
    "SeaweedFS_eventloop_lag_seconds",
    "SeaweedFS_executor_wait_seconds",
    "SeaweedFS_build_info",
    "SeaweedFS_process_start_time_seconds",
    "SeaweedFS_slo_",
    # raft identity gauges (term / commit_index / is_leader): summing
    # across a merged host would report 2 leaders the moment any two
    # workers each said "1" — the host's honest answer is the max
    "SeaweedFS_raft_",
    # the adopted shard-map epoch is likewise an identity, not a
    # quantity — a merged host answers with the furthest-along worker
    "SeaweedFS_filer_shard_map_epoch",
)
_NON_ADDITIVE_B = tuple(p.encode() for p in NON_ADDITIVE_GAUGE_PREFIXES)


def merge_metrics_texts(texts: "list[bytes]") -> bytes:
    """Sum Prometheus text expositions from several worker processes
    into one whole-host view (server/workers.py: each -workers worker
    has its own registry; any worker answers /metrics for all).

    Counters, gauges, and histogram buckets are summed per
    (name, labels); `*_created` timestamps take the min (first birth);
    the non-additive gauges above take the max; HELP/TYPE comments are
    kept from their first appearance.

    Integral sums are emitted WITHOUT a trailing `.0` and never in
    exponent notation: `repr(float)` rendered a summed counter of 123
    as `123.0` and a large one as `1.2e+16`, both of which surprise
    text-format consumers that treat counters as integers."""
    order: list[tuple[str, bytes]] = []   # ("comment"|"sample", key)
    seen_comments: set[bytes] = set()
    sums: dict[bytes, float] = {}
    for text in texts:
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith(b"#"):
                if line not in seen_comments:
                    seen_comments.add(line)
                    order.append(("comment", line))
                continue
            i = line.rfind(b" ")
            if i <= 0:
                continue
            key, raw = line[:i], line[i + 1:]
            try:
                val = float(raw)
            except ValueError:
                continue
            if key not in sums:
                order.append(("sample", key))
                sums[key] = val
            elif key.split(b"{", 1)[0].endswith(b"_created"):
                sums[key] = min(sums[key], val)
            elif key.startswith(_NON_ADDITIVE_B):
                sums[key] = max(sums[key], val)
            else:
                sums[key] += val
    out = []
    for kind, item in order:
        if kind == "comment":
            out.append(item)
        else:
            out.append(item + b" " + _fmt_value(sums[item]))
    return b"\n".join(out) + b"\n" if out else b""


def _fmt_value(val: float) -> bytes:
    """Prometheus text-format value: integral floats render as plain
    integers (no `.0`, no exponent — `int(float)` is exact for any
    float that is_integer()); fractional values keep full precision."""
    if val != val or val in (float("inf"), float("-inf")):
        return repr(val).encode()
    if float(val).is_integer():
        return b"%d" % int(val)
    return repr(val).encode()


async def push_loop(gateway: str, job: str,
                    interval_seconds: float = 15.0,
                    max_backoff_seconds: float = 300.0) -> None:
    """LoopPushingMetric (metrics.go:109-137).

    Failures are COUNTED (SeaweedFS_metrics_push_errors_total) and
    LOGGED — the first failure and every healthy<->failing transition
    at WARNING/INFO — while the push interval backs off exponentially
    so a long-dead gateway neither floods the log nor gets hammered."""
    from ..util import glog, tracing
    if not HAVE_PROMETHEUS or not gateway:
        return
    failing = False
    delay = interval_seconds
    while True:
        try:
            await tracing.run_in_executor(
                lambda: push_to_gateway(gateway, job=job,
                                        registry=REGISTRY))
            if failing:
                glog.info("metrics push to %s recovered (job=%s)",
                          gateway, job)
            failing = False
            delay = interval_seconds
        except Exception as e:  # noqa: BLE001 — the pusher must outlive
            # any gateway-side failure shape, but never silently
            METRICS_PUSH_ERRORS.inc()
            if not failing:
                glog.warning(
                    "metrics push to %s failed: %s %s (backing off; "
                    "logged once until recovery)", gateway,
                    type(e).__name__, e)
            failing = True
            delay = min(delay * 2, max_backoff_seconds)
        await asyncio.sleep(delay)
