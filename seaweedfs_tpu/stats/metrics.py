"""Prometheus metrics.

Reference: weed/stats/metrics.go:13-92 (per-tier counters/histograms/
gauges) and :109-137 (push-gateway loop; the master hands the gateway
address to nodes via heartbeat responses). Exposed here both as a /metrics
scrape endpoint on every server and an optional push loop.
"""

from __future__ import annotations

import asyncio

try:
    from prometheus_client import (CollectorRegistry, Counter, Gauge,
                                   Histogram, generate_latest,
                                   push_to_gateway)
    HAVE_PROMETHEUS = True
except ImportError:  # pragma: no cover
    HAVE_PROMETHEUS = False

if HAVE_PROMETHEUS:
    REGISTRY = CollectorRegistry()

    MASTER_RECEIVED_HEARTBEATS = Counter(
        "SeaweedFS_master_received_heartbeats", "heartbeats received",
        registry=REGISTRY)
    MASTER_ASSIGN_REQUESTS = Counter(
        "SeaweedFS_master_assign_requests", "assign requests",
        ["status"], registry=REGISTRY)
    VOLUME_REQUEST_TIME = Histogram(
        "SeaweedFS_volumeServer_request_seconds", "needle request time",
        ["type"], registry=REGISTRY)
    VOLUME_REQUEST_COUNTER = Counter(
        "SeaweedFS_volumeServer_request_total", "needle requests",
        ["type", "status"], registry=REGISTRY)
    VOLUME_COUNT = Gauge(
        "SeaweedFS_volumeServer_volumes", "volumes on this server",
        registry=REGISTRY)
    FILER_REQUEST_TIME = Histogram(
        "SeaweedFS_filer_request_seconds", "filer request time",
        ["type"], registry=REGISTRY)
    EC_ENCODE_BYTES = Counter(
        "SeaweedFS_ec_encode_bytes_total", "bytes erasure-encoded",
        registry=REGISTRY)
    EC_THROUGHPUT = Gauge(
        "SeaweedFS_ec_encode_GBps", "last measured EC encode GB/s/chip",
        registry=REGISTRY)
    # tiered read caches (util/chunk_cache.py): one label per cache —
    # "needle" (volume hot needles), "chunk" (filer/s3/webdav whole
    # chunks), "ec_recover" (degraded-read reconstructions),
    # "lookup_neg" (client negative volume lookups)
    CACHE_HITS = Counter(
        "SeaweedFS_cache_hits_total", "read-cache hits",
        ["cache"], registry=REGISTRY)
    CACHE_MISSES = Counter(
        "SeaweedFS_cache_misses_total", "read-cache misses",
        ["cache"], registry=REGISTRY)
    CACHE_HIT_BYTES = Counter(
        "SeaweedFS_cache_hit_bytes_total", "bytes served from read caches",
        ["cache"], registry=REGISTRY)
    CACHE_EVICTIONS = Counter(
        "SeaweedFS_cache_evictions_total", "read-cache evictions",
        ["cache"], registry=REGISTRY)
    CACHE_USED_BYTES = Gauge(
        "SeaweedFS_cache_used_bytes", "bytes currently held per cache",
        ["cache"], registry=REGISTRY)

    def metrics_text() -> bytes:
        return generate_latest(REGISTRY)
else:  # pragma: no cover
    def metrics_text() -> bytes:
        return b"# prometheus_client unavailable\n"


def merge_metrics_texts(texts: "list[bytes]") -> bytes:
    """Sum Prometheus text expositions from several worker processes
    into one whole-host view (server/workers.py: each -workers worker
    has its own registry; any worker answers /metrics for all).

    Counters, gauges, and histogram buckets are summed per
    (name, labels); `*_created` timestamps take the min (first birth);
    HELP/TYPE comments are kept from their first appearance."""
    order: list[tuple[str, bytes]] = []   # ("comment"|"sample", key)
    seen_comments: set[bytes] = set()
    sums: dict[bytes, float] = {}
    for text in texts:
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith(b"#"):
                if line not in seen_comments:
                    seen_comments.add(line)
                    order.append(("comment", line))
                continue
            i = line.rfind(b" ")
            if i <= 0:
                continue
            key, raw = line[:i], line[i + 1:]
            try:
                val = float(raw)
            except ValueError:
                continue
            if key not in sums:
                order.append(("sample", key))
                sums[key] = val
            elif key.split(b"{", 1)[0].endswith(b"_created"):
                sums[key] = min(sums[key], val)
            else:
                sums[key] += val
    out = []
    for kind, item in order:
        if kind == "comment":
            out.append(item)
        else:
            out.append(item + b" " + repr(sums[item]).encode())
    return b"\n".join(out) + b"\n" if out else b""


async def push_loop(gateway: str, job: str,
                    interval_seconds: float = 15.0) -> None:
    """LoopPushingMetric (metrics.go:109-137)."""
    if not HAVE_PROMETHEUS or not gateway:
        return
    loop = asyncio.get_running_loop()
    while True:
        try:
            await loop.run_in_executor(
                None, lambda: push_to_gateway(gateway, job=job,
                                              registry=REGISTRY))
        except Exception:
            pass
        await asyncio.sleep(interval_seconds)
