"""Continuous sampling profiler: low-overhead, trace-tier-attributed.

``-cpuprofile`` answers "where did this PROCESS spend its life", but
only at exit and only with cProfile's per-call overhead. This module
is the always-on complement: a daemon thread samples every thread's
Python stack via ``sys._current_frames()`` at ``-profile.hz`` (default
0 = off), folds each stack into a bounded per-process aggregate, and
prefixes every folded stack with the TIER of the trace span active on
that thread (util/tracing.py maintains the per-thread tier map while
the profiler is armed) — so "30% of samples under ``s3;…gather_chunks``
while the fleet pages" reads straight off the flamegraph.

Design constraints:

- deterministic accounting: the sampler schedules ticks on absolute
  deadlines (``next += period``), so ``samples ≈ hz × uptime`` within
  scheduler jitter — the overhead gate in tests asserts this, and
  ``SeaweedFS_profile_samples_total`` exports the same count;
- bounded memory: at most :data:`MAX_FOLDED` distinct folded stacks
  per collector; overflow folds into the ``(other)`` bucket;
- the sampler thread never takes the GIL for long: one
  ``sys._current_frames()`` call plus pure-Python frame walking, no
  allocation proportional to anything but stack depth;
- served at ``/debug/profile`` (``/__debug__/profile`` on the
  path-shadowing gateways): the always-on aggregate by default,
  ``?seconds=N`` records a fresh on-demand window (spinning a
  temporary sampler at :data:`DEFAULT_WINDOW_HZ` when ``-profile.hz``
  is 0), ``?format=folded`` renders flamegraph-ready folded lines.
  Under ``-workers`` the volume server merges siblings by summing
  folded counts — the same whole-host discipline as every surface.
"""

from __future__ import annotations

import sys
import threading
import time

DEFAULT_WINDOW_HZ = 99.0     # on-demand window rate when -profile.hz 0
MAX_HZ = 1000.0
MAX_WINDOW_S = 60.0
MAX_FOLDED = 4096            # distinct folded stacks per collector
MAX_STACK_DEPTH = 64
_OTHER = "(other)"

_lock = threading.Lock()
_hz = 0.0
_agg = {"folded": {}, "samples": 0}
_sinks: list[dict] = []      # transient ?seconds=N window collectors
_thread: "threading.Thread | None" = None
_stop = threading.Event()

# lazily-bound prometheus counter (same shape as tracing._observe)
_counter: object = None


def init(hz: float = 0.0) -> None:
    """Wire from the CLI flag: -profile.hz (0 disables the always-on
    sampler; /debug/profile?seconds=N still works on demand)."""
    global _hz
    _hz = max(0.0, min(float(hz), MAX_HZ))


def enabled() -> bool:
    return _hz > 0


def running() -> bool:
    return _thread is not None and _thread.is_alive()


def reset() -> None:
    """Drop the aggregate (tests)."""
    with _lock:
        _agg["folded"] = {}
        _agg["samples"] = 0


def start() -> "threading.Thread | None":
    """Start the always-on sampler thread (idempotent; no-op at
    -profile.hz 0). Called per process, so every -workers sibling
    samples itself."""
    global _thread
    if _hz <= 0 or running():
        return _thread
    from ..util import tracing
    tracing.track_thread_tiers(True)
    _stop.clear()
    _thread = threading.Thread(
        target=_run, args=(_hz, _stop, None),
        name="swtpu-profiler", daemon=True)
    _thread.start()
    return _thread


def stop() -> None:
    """Stop the always-on sampler (tests / shutdown)."""
    global _thread
    _stop.set()
    t = _thread
    if t is not None and t.is_alive():
        t.join(timeout=2.0)
    _thread = None
    from ..util import tracing
    tracing.track_thread_tiers(False)


# ---------------------------------------------------------------------------
# the sampler


def _run(hz: float, stop: threading.Event,
         sinks: "list[dict] | None") -> None:
    """Sampler loop on ABSOLUTE deadlines: an oversleeping tick is
    followed by an immediate one, so the total sample count tracks
    hz × elapsed (the deterministic-accounting contract) instead of
    accumulating per-tick drift."""
    period = 1.0 / hz
    next_t = time.perf_counter() + period
    while not stop.wait(max(0.0, next_t - time.perf_counter())):
        now = time.perf_counter()
        if now - next_t > 1.0:
            # a long stall (suspend, GC storm): re-anchor instead of
            # bursting hundreds of catch-up samples in one slice
            next_t = now
        next_t += period
        _sample_once(sinks)


def _sample_once(sinks: "list[dict] | None") -> None:
    from ..util import tracing
    me = threading.get_ident()
    frames = sys._current_frames()
    keys: list[str] = []
    for tid, frame in frames.items():
        if tid == me:
            continue
        stack: list[str] = []
        f = frame
        while f is not None and len(stack) < MAX_STACK_DEPTH:
            co = f.f_code
            stack.append(
                f"{co.co_filename.rsplit('/', 1)[-1]}:{co.co_name}")
            f = f.f_back
        if not stack:
            continue
        stack.reverse()
        tier = tracing.thread_tier(tid) or "-"
        keys.append(tier + ";" + ";".join(stack))
    del frames
    with _lock:
        targets = [_agg] + _sinks if sinks is None else sinks
        for sink in targets:
            sink["samples"] += 1
            folded = sink["folded"]
            for key in keys:
                if key in folded:
                    folded[key] += 1
                elif len(folded) < MAX_FOLDED:
                    folded[key] = 1
                else:
                    folded[_OTHER] = folded.get(_OTHER, 0) + 1
    _count_sample()


def _count_sample() -> None:
    global _counter
    if _counter is None:
        try:
            from . import metrics
            _counter = (metrics.PROFILE_SAMPLES
                        if metrics.HAVE_PROMETHEUS else False)
        except ImportError:
            _counter = False
    if _counter:
        _counter.inc()


# ---------------------------------------------------------------------------
# payloads


def profile_dict() -> dict:
    """The always-on aggregate: the /debug/profile body without
    ?seconds=."""
    with _lock:
        folded = dict(_agg["folded"])
        samples = _agg["samples"]
    return {"hz": _hz, "running": running(), "window_s": 0.0,
            "samples": samples, "folded": folded}


async def profile_window(seconds: float,
                         hz: "float | None" = None) -> dict:
    """Record a fresh folded window of `seconds`: piggybacks on the
    always-on sampler when it runs (a registered sink sees exactly the
    window's ticks), otherwise spins a temporary sampler at `hz`
    (default :data:`DEFAULT_WINDOW_HZ`)."""
    import asyncio
    seconds = max(0.05, min(float(seconds), MAX_WINDOW_S))
    sink = {"folded": {}, "samples": 0}
    if running():
        with _lock:
            _sinks.append(sink)
        try:
            await asyncio.sleep(seconds)
        finally:
            with _lock:
                _sinks.remove(sink)
        rate = _hz
    else:
        rate = max(1.0, min(float(hz or DEFAULT_WINDOW_HZ), MAX_HZ))
        from ..util import tracing
        tracing.track_thread_tiers(True)
        stop = threading.Event()
        t = threading.Thread(target=_run, args=(rate, stop, [sink]),
                             name="swtpu-profile-window", daemon=True)
        t.start()
        try:
            await asyncio.sleep(seconds)
        finally:
            stop.set()
            if not running():
                tracing.track_thread_tiers(False)
        t.join(timeout=2.0)
    with _lock:
        folded = dict(sink["folded"])
        samples = sink["samples"]
    return {"hz": rate, "running": running(),
            "window_s": round(seconds, 3), "samples": samples,
            "folded": folded}


def merge_payloads(payloads: "list[dict]") -> dict:
    """Fold several workers' /debug/profile bodies into one whole-host
    view: folded counts and sample counts SUM per stack (each worker
    sampled only itself), hz/window report the max."""
    folded: dict[str, int] = {}
    samples = 0
    hz = 0.0
    window = 0.0
    run = False
    for p in payloads:
        samples += int(p.get("samples", 0) or 0)
        hz = max(hz, float(p.get("hz", 0) or 0))
        window = max(window, float(p.get("window_s", 0) or 0))
        run = run or bool(p.get("running"))
        for k, v in (p.get("folded") or {}).items():
            folded[k] = folded.get(k, 0) + int(v)
    return {"hz": hz, "running": run, "window_s": window,
            "samples": samples, "folded": folded}


def folded_text(payload: dict) -> str:
    """Flamegraph-ready folded lines ("stack count"), deterministic
    order (count desc, then stack) — pipe straight into flamegraph.pl
    or speedscope."""
    rows = sorted((payload.get("folded") or {}).items(),
                  key=lambda kv: (-kv[1], kv[0]))
    return "\n".join(f"{k} {v}" for k, v in rows) + ("\n" if rows else "")


async def profile_query(query) -> dict:
    """The one /debug/profile parser shared by every server handler:
    ?seconds=N records an on-demand window (clamped to 60s), otherwise
    the always-on aggregate (raises ValueError on malformed values)."""
    seconds = float(query.get("seconds", 0) or 0)
    if seconds > 0:
        hz = query.get("hz")
        return await profile_window(seconds,
                                    hz=float(hz) if hz else None)
    return profile_dict()


def debug_handler():
    """One aiohttp /debug/profile handler — registered by every
    non-worker-aggregating server (master, filer, S3, WebDAV); the
    volume server has its own -workers-merging twin."""
    from aiohttp import web

    async def h_profile(req):
        try:
            payload = await profile_query(req.query)
        except ValueError:
            return web.json_response({"error": "bad seconds/hz"},
                                     status=400)
        if req.query.get("format") == "folded":
            return web.Response(text=folded_text(payload),
                                content_type="text/plain")
        return web.json_response(payload)

    return h_profile
