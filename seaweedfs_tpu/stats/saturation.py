"""Saturation probes: is "slow" a saturated resource?

USE-style (utilization/saturation) signals sampled into the metrics
timelines (stats/timeline.py) right before each snapshot, so a latency
regression in a window can be attributed to the resource that
saturated in the SAME window:

- **event-loop lag** — max asyncio scheduling delay since the last
  snapshot (a continuously-running probe task measures the drift of
  short sleeps; anything in the tens of milliseconds means a blocking
  call is squatting the loop);
- **executor queue wait/depth** — how long a just-submitted no-op sat
  in the default ThreadPoolExecutor queue, plus the queue depth when
  introspectable (store preads, EC decodes and vacuum all ride this
  pool: a deep queue is the disk-path saturation signal);
- **open fds** — descriptor count from /proc (volume handles, sockets,
  cache mmaps; a leak shows as a monotonic gauge long before EMFILE);
- **disk usage** — used/free bytes per data dir (summed across
  -workers like every other merged gauge);
- **cache occupancy vs budget** — `SeaweedFS_cache_used_bytes` already
  exists; `SeaweedFS_cache_budget_bytes` (set by util/chunk_cache at
  construction) completes the ratio.

Every probe is cheap, synchronous, and never raises into the recorder.
"""

from __future__ import annotations

import asyncio
import os
import shutil
import threading
import time

_lag_lock = threading.Lock()
_lag_max = 0.0
_lag_last = 0.0         # last flushed window max (peekable between snaps)

_exec_probe_running = False
_exec_wait_last = 0.0   # last measured executor queue wait (seconds)


def note_loop_lag(lag_s: float) -> None:
    """Fed by the timeline module's continuous lag-probe task."""
    global _lag_max
    with _lag_lock:
        if lag_s > _lag_max:
            _lag_max = lag_s


def sample_loop_lag() -> None:
    """Flush the max observed scheduling lag to the gauge (and reset
    the max, so each window reports its own worst case)."""
    global _lag_max, _lag_last
    from . import metrics
    if not metrics.HAVE_PROMETHEUS:
        return
    with _lag_lock:
        lag, _lag_max = _lag_max, 0.0
        _lag_last = lag
    metrics.EVENTLOOP_LAG.set(round(lag, 6))


def current_lag_s() -> float:
    """Live peek for the QoS shedder (seaweedfs_tpu/qos/): the worst
    scheduling lag seen this window or the last flushed one —
    whichever is worse — WITHOUT resetting the running max."""
    with _lag_lock:
        return max(_lag_max, _lag_last)


def current_exec_wait_s() -> float:
    """Last measured executor queue wait (the QoS shedder's
    disk-path saturation signal)."""
    return _exec_wait_last


def sample_process() -> None:
    """Open-fd count (linux /proc; no-op elsewhere)."""
    from . import metrics
    if not metrics.HAVE_PROMETHEUS:
        return
    try:
        metrics.OPEN_FDS.set(len(os.listdir("/proc/self/fd")))
    except OSError:
        pass


def disk_probe(paths: "list[str]"):
    """A probe closure sampling used/free bytes per data dir."""
    uniq = sorted(set(paths))

    def probe() -> None:
        from . import metrics
        if not metrics.HAVE_PROMETHEUS:
            return
        for p in uniq:
            try:
                u = shutil.disk_usage(p)
            except OSError:
                continue
            metrics.DISK_FREE_BYTES.labels(p).set(u.free)
            metrics.DISK_USED_BYTES.labels(p).set(u.used)

    probe.__name__ = "disk_probe"
    return probe


def start_executor_probe(loop, period_s: float = 10.0) -> None:
    """Periodically time a no-op through the default executor: the
    submit→run delay IS the queue wait a real pread would pay right
    now. Runs as a retained task on `loop`; idempotent per process."""
    global _exec_probe_running
    if _exec_probe_running:
        return
    _exec_probe_running = True

    async def probe_loop() -> None:
        from . import metrics
        while True:
            await asyncio.sleep(period_s)
            if not metrics.HAVE_PROMETHEUS:
                continue
            t0 = time.perf_counter()
            try:
                # cap the wait: a wedged pool must not wedge the probe —
                # the capped value still lands in the gauge as "at
                # least this saturated"
                await asyncio.wait_for(
                    asyncio.shield(
                        loop.run_in_executor(None, lambda: None)),  # weedlint: ignore[executor-ctx] probe measures RAW queue wait; a context copy would add the cost being measured and no span parenthood exists here
                    timeout=5.0)
            except asyncio.TimeoutError:
                pass
            global _exec_wait_last
            _exec_wait_last = time.perf_counter() - t0
            metrics.EXECUTOR_WAIT.set(round(_exec_wait_last, 6))
            pool = getattr(loop, "_default_executor", None)
            q = getattr(pool, "_work_queue", None)
            if q is not None:
                try:
                    metrics.EXECUTOR_QUEUE_DEPTH.set(q.qsize())
                except (AttributeError, NotImplementedError):
                    pass

    task = loop.create_task(probe_loop())
    # retained module-wide; dies with the loop at process exit

    def _done(_t) -> None:
        global _exec_probe_running
        _exec_probe_running = False

    task.add_done_callback(_done)
    global _exec_probe_task
    _exec_probe_task = task


def stop_executor_probe() -> None:
    """Cancel the probe task (daemon shutdown path)."""
    if _exec_probe_task is not None and not _exec_probe_task.done():
        _exec_probe_task.cancel()


_exec_probe_task = None
