"""SLO burn-rate engine: a machine-readable health verdict.

Declarative objectives (``-slo "volume.read:p99<50ms@99.9"``) are
evaluated over the metrics timelines (stats/timeline.py) with the
standard multi-window burn-rate method (the SRE-workbook shape):

- an objective ``tier.op:pQQ<THRESHms@OBJ`` says "the QQth percentile
  of ``tier.op`` latency must stay under THRESH ms, met OBJ percent of
  the time": ``p99<50ms`` by itself already PERMITS 1% of requests
  over 50ms, so only the fraction BEYOND that allowance spends budget
  — ``p50<10ms`` is meaningfully laxer than ``p99<10ms``;
- each timeline window carries the raw bucket deltas of
  ``SeaweedFS_request_duration_seconds{tier,op,status}``, so the
  fraction of requests over the threshold is computed EXACTLY from the
  histogram (linear interpolation inside the containing bucket, summed
  across status labels — an injected 500 that returned fast still
  counts against latency only if it WAS slow; error-rate objectives
  would be a second spec kind);
- burn rate = excess violating fraction / error budget:
  ``max(0, frac_over - (1 - QQ/100)) / (1 - OBJ/100)``.  A burn of
  1.0 spends exactly the budget; 14.4 pages because it would exhaust a
  30-day budget in 2 days;
- two windows guard against both blips and slow bleeds: PAGE when the
  fast (default 60s) AND slow (default 600s) windows both burn ≥ 14.4,
  WARN when both burn ≥ 6.0. Fewer than ``MIN_COUNT`` requests in the
  fast window never pages (one slow request on an idle daemon is not
  an incident).

The verdict is served at ``/debug/health`` with EVIDENCE: the
violating timeline slice (per-window violating fractions), the journal
events (util/events.py) that correlate with the violation window
(breaker trips, retry-budget exhaustion, holder refreshes, scrub
corruption), and the worst matching trace id from the span ring — the
"what was the cluster doing when it went bad" bundle.  Verdicts also
export ``SeaweedFS_slo_status{objective}`` /
``SeaweedFS_slo_burn_rate{objective,window}`` and a glog WARNING on
every ok→page transition carrying the worst trace id.
"""

from __future__ import annotations

import re
import time

from ..util import glog

PAGE_BURN = 14.4
WARN_BURN = 6.0
FAST_WINDOW_S = 60.0
SLOW_WINDOW_S = 600.0
MIN_COUNT = 10          # fast-window request floor before paging

# journal event types worth correlating into violation evidence
# (tenant_shed / arbiter_yield: a paying tenant's burn alongside the
# abuser being shed or background repair yielding IS the explanation)
EVIDENCE_TYPES = {"breaker_open", "breaker_close",
                  "retry_budget_exhausted", "holder_refresh",
                  "scrub_corruption", "worker_respawn",
                  "tenant_shed", "arbiter_yield"}

_HIST = "SeaweedFS_request_duration_seconds"
# per-tenant objectives (`tier.op/tenant:...`) evaluate against the
# tenant-attributed entry histogram instead (seaweedfs_tpu/qos/)
_TENANT_HIST = "SeaweedFS_qos_tenant_request_seconds"

_SPEC_RE = re.compile(
    r"^(?P<tier>[a-z0-9_]+)\.(?P<op>[a-z0-9_.]+)"
    r"(?:/(?P<tenant>[A-Za-z0-9._-]+))?:"
    r"p(?P<q>\d{1,2}(?:\.\d+)?)<(?P<thresh>\d+(?:\.\d+)?)"
    r"(?P<unit>ms|s)@(?P<obj>\d{1,2}(?:\.\d+)?)$")

STATUS_LEVELS = {"ok": 0, "warn": 1, "page": 2}


class SloSpec:
    """One parsed objective. `tier.op/tenant:pQQ<NNms@OBJ` scopes the
    objective to ONE tenant's entry-tier latency (the bounded tenant
    label from seaweedfs_tpu/qos/) — the paying tenant keeps an armed
    objective while the abuser is shed around it."""

    __slots__ = ("raw", "tier", "op", "tenant", "quantile",
                 "threshold_s", "objective")

    def __init__(self, raw: str):
        m = _SPEC_RE.match(raw.strip())
        if m is None:
            raise ValueError(
                f"bad -slo spec {raw!r}: want "
                f"tier.op[/tenant]:pQQ<NNms@OBJ "
                f"(e.g. volume.read:p99<50ms@99.9)")
        self.raw = raw.strip()
        self.tier = m.group("tier")
        self.op = m.group("op")
        self.tenant = m.group("tenant") or ""
        self.quantile = float(m.group("q")) / 100.0
        thresh = float(m.group("thresh"))
        self.threshold_s = thresh / 1000.0 if m.group("unit") == "ms" \
            else thresh
        self.objective = float(m.group("obj")) / 100.0
        if not 0.0 < self.objective < 1.0:
            raise ValueError(f"bad -slo spec {raw!r}: objective "
                             f"{m.group('obj')} must be in (0, 100)")

    @property
    def budget(self) -> float:
        return 1.0 - self.objective

    def to_dict(self) -> dict:
        d = {"spec": self.raw, "tier": self.tier, "op": self.op,
             "quantile": self.quantile,
             "threshold_ms": round(self.threshold_s * 1000.0, 3),
             "objective": self.objective}
        if self.tenant:
            d["tenant"] = self.tenant
        return d


def parse_specs(raws: "list[str]") -> "list[SloSpec]":
    return [SloSpec(r) for r in raws]


# ---------------------------------------------------------------------------
# histogram math


def _matches(spec: SloSpec, base_key: str) -> bool:
    from .timeline import split_key
    name, labels = split_key(base_key)
    if spec.tenant:
        return (name == _TENANT_HIST
                and labels.get("tier") == spec.tier
                and labels.get("op") == spec.op
                and labels.get("tenant") == spec.tenant)
    return (name == _HIST and labels.get("tier") == spec.tier
            and labels.get("op") == spec.op)


def _frac_over(buckets: "dict[str, float]", threshold_s: float,
               total: float) -> float:
    """Fraction of the window's requests SLOWER than threshold_s,
    interpolated inside the containing bucket (conservative: mass in
    the +Inf bucket is always counted as over)."""
    if total <= 0:
        return 0.0
    edges = []
    for le, c in buckets.items():
        try:
            edges.append((float("inf") if le in ("+Inf", "inf")
                          else float(le), c))
        except ValueError:
            continue
    edges.sort()
    lo_edge, lo_cum = 0.0, 0.0
    under = 0.0
    for edge, cum in edges:
        if edge >= threshold_s:
            if edge == float("inf") or edge == threshold_s:
                under = cum if edge == threshold_s else lo_cum
            else:
                under = lo_cum + (cum - lo_cum) * \
                    (threshold_s - lo_edge) / (edge - lo_edge)
            break
        lo_edge, lo_cum = edge, cum
    else:
        under = total
    return max(0.0, min(1.0, 1.0 - under / total))


def _span_stats(spec: SloSpec, windows: "list[dict]",
                horizon_s: float, now_ms: float) -> dict:
    """Sum the spec's histogram deltas over the windows inside the
    horizon and derive (count, violating fraction, burn, slice)."""
    floor = now_ms - horizon_s * 1000.0
    buckets: dict[str, float] = {}
    total = 0.0
    per_window: list[dict] = []
    for w in windows:
        if w["wall_ms"] < floor:
            continue
        wcount = 0.0
        wbuckets: dict[str, float] = {}
        for base, h in w.get("hist", {}).items():
            if not _matches(spec, base):
                continue
            wcount += h.get("count", 0.0)
            for le, c in h.get("buckets", {}).items():
                wbuckets[le] = wbuckets.get(le, 0.0) + c
                buckets[le] = buckets.get(le, 0.0) + c
        total += wcount
        if wcount:
            per_window.append({
                "wall_ms": w["wall_ms"],
                "count": wcount,
                "frac_over": round(
                    _frac_over(wbuckets, spec.threshold_s, wcount), 4),
            })
    frac = _frac_over(buckets, spec.threshold_s, total)
    # pQQ<THRESH permits (1 - QQ) of requests over THRESH for free;
    # only the excess spends the @OBJ error budget (this is what makes
    # p50 in a spec actually laxer than p99)
    excess = max(0.0, frac - (1.0 - spec.quantile))
    return {"count": total, "frac_over": round(frac, 4),
            "burn": round(excess / spec.budget, 2),
            "windows": per_window}


# ---------------------------------------------------------------------------
# the engine


class SloEngine:
    def __init__(self, specs: "list[SloSpec]",
                 fast_s: float = FAST_WINDOW_S,
                 slow_s: float = SLOW_WINDOW_S,
                 page_burn: float = PAGE_BURN,
                 warn_burn: float = WARN_BURN,
                 min_count: float = MIN_COUNT):
        self.specs = specs
        self.fast_s = fast_s
        self.slow_s = slow_s
        self.page_burn = page_burn
        self.warn_burn = warn_burn
        self.min_count = min_count
        self._last_status: dict[str, str] = {}

    def evaluate(self, windows: "list[dict]",
                 events: "list[dict] | None" = None,
                 now_ms: "float | None" = None,
                 update_metrics: bool = False) -> dict:
        """The /debug/health payload over the given timeline windows
        (local or whole-host-merged) and journal events."""
        now_ms = now_ms if now_ms is not None else time.time() * 1000.0
        objectives = []
        worst = "ok"
        for spec in self.specs:
            fast = _span_stats(spec, windows, self.fast_s, now_ms)
            slow = _span_stats(spec, windows, self.slow_s, now_ms)
            status = "ok"
            if fast["count"] >= self.min_count:
                if fast["burn"] >= self.page_burn and \
                        slow["burn"] >= self.page_burn:
                    status = "page"
                elif fast["burn"] >= self.warn_burn and \
                        slow["burn"] >= self.warn_burn:
                    status = "warn"
            row = {**spec.to_dict(), "status": status,
                   "fast": {"horizon_s": self.fast_s,
                            "count": fast["count"],
                            "frac_over": fast["frac_over"],
                            "burn": fast["burn"]},
                   "slow": {"horizon_s": self.slow_s,
                            "count": slow["count"],
                            "frac_over": slow["frac_over"],
                            "burn": slow["burn"]}}
            if status != "ok":
                row["evidence"] = self._evidence(spec, slow, events,
                                                 now_ms)
            objectives.append(row)
            if STATUS_LEVELS[status] > STATUS_LEVELS[worst]:
                worst = status
            if update_metrics:
                # only the canonical per-snapshot tick() path exports
                # gauges AND tracks transitions: a /debug/health poll
                # evaluates whole-host MERGED windows against the same
                # engine, and letting it touch _last_status would log
                # phantom ok->page->ok flaps whenever local and merged
                # verdicts disagree (e.g. only a sibling is slow)
                self._export(spec, status, fast, slow)
                self._log_transition(spec, status, row)
        return {"status": worst, "objectives": objectives,
                "now_ms": round(now_ms, 3)}

    def _evidence(self, spec: SloSpec, slow: dict,
                  events: "list[dict] | None", now_ms: float) -> dict:
        """The violating timeline slice + correlated journal events +
        the worst matching trace id from the span ring.

        Both span the whole burn episode (the SLOW horizon), not just
        the fast window: a slow-burn page can land minutes after the
        breaker trips that explain it, and evidence clipped to the
        last 60s would come up empty exactly when it matters."""
        violating = sorted(
            (w for w in slow["windows"]
             # a window violates when its own p-quantile is over the
             # threshold, i.e. more than the spec's allowance of its
             # requests were slow
             if w["frac_over"] > (1.0 - spec.quantile)),
            key=lambda w: w["wall_ms"])
        from_ms = now_ms - self.fast_s * 1000.0
        if violating:
            # correlate from the START of the damage, with one fast
            # horizon of margin for the events that caused it
            from_ms = min(from_ms,
                          violating[0]["wall_ms"] - self.fast_s * 1000.0)
        ev: dict = {
            "window": {"from_ms": round(from_ms, 3),
                       "to_ms": round(now_ms, 3)},
            "violating_total": len(violating),
            "violating_windows": violating[-200:],
        }
        if events is None:
            from ..util import events as journal
            correlated = journal.window(from_ms, now_ms,
                                        types=EVIDENCE_TYPES)
        else:
            correlated = [e for e in events
                          if e.get("type") in EVIDENCE_TYPES
                          and from_ms <= e.get("wall_ms", 0) <= now_ms]
        # journal.window is chronological but /debug/events payloads
        # arrive newest-first — normalize before truncating so every
        # path keeps the NEWEST 20 (the breaker that just fired), in
        # chronological order
        correlated.sort(key=lambda e: e.get("wall_ms", 0))
        ev["events"] = correlated[-20:]
        worst = self._worst_trace(spec, from_ms, now_ms)
        if worst:
            ev["worst_trace"] = worst
        return ev

    def _worst_trace(self, spec: SloSpec, from_ms: float,
                     to_ms: float) -> "dict | None":
        """Slowest span of the spec's (tier, op) that started inside
        the violation window — the direct pointer from a page to ONE
        reconstructable request."""
        from ..util import tracing
        payload = tracing.traces_dict(recent=0, slowest=50)
        best: dict | None = None
        for g in payload.get("slowest", ()):
            for s in g.get("spans", ()):
                if s.get("tier") != spec.tier or s.get("op") != spec.op:
                    continue
                if not from_ms <= s.get("start_ms", 0) <= to_ms:
                    continue
                if best is None or s["dur_ms"] > best["dur_ms"]:
                    best = {"trace": s["trace"],
                            "dur_ms": s["dur_ms"],
                            "status": s.get("status")}
        return best

    def _export(self, spec: SloSpec, status: str, fast: dict,
                slow: dict) -> None:
        from . import metrics
        if not metrics.HAVE_PROMETHEUS:
            return
        metrics.SLO_STATUS.labels(spec.raw).set(STATUS_LEVELS[status])
        metrics.SLO_BURN_RATE.labels(spec.raw, "fast").set(fast["burn"])
        metrics.SLO_BURN_RATE.labels(spec.raw, "slow").set(slow["burn"])

    def _log_transition(self, spec: SloSpec, status: str,
                        row: dict) -> None:
        prev = self._last_status.get(spec.raw, "ok")
        if status == prev:
            return
        self._last_status[spec.raw] = status
        if STATUS_LEVELS[status] > STATUS_LEVELS[prev]:
            trace = (row.get("evidence", {})
                     .get("worst_trace") or {}).get("trace", "")
            glog.warning(
                "SLO %s: %s -> %s (fast burn %.1f, slow burn %.1f)%s",
                spec.raw, prev, status, row["fast"]["burn"],
                row["slow"]["burn"],
                f" worst trace={trace}" if trace else "")
        else:
            glog.info("SLO %s: %s -> %s (recovered)", spec.raw, prev,
                      status)


# ---------------------------------------------------------------------------
# process-wide engine (wired from -slo flags)

_engine: "SloEngine | None" = None


def init(raw_specs: "list[str]") -> "SloEngine | None":
    """Build the process engine from -slo flags (ValueError on a bad
    spec — a daemon must refuse to start guarding nothing)."""
    global _engine
    _engine = SloEngine(parse_specs(raw_specs)) if raw_specs else None
    return _engine


def engine() -> "SloEngine | None":
    return _engine


def windows_needed(minimum: int = 200) -> int:
    """Timeline windows that cover the SLOW burn horizon at the wired
    snapshot cadence. A fixed fetch of 200 silently truncates the 600s
    slow window whenever -timeline.interval is under 3s — the slow
    burn then collapses toward the fast burn and a short blip pages
    where the 600s dilution is supposed to suppress it."""
    from . import timeline
    iv = timeline.interval_s()
    if iv <= 0:
        return minimum
    slow_s = _engine.slow_s if _engine is not None else SLOW_WINDOW_S
    return max(minimum, min(10_000, int(slow_s / iv) + 2))


def tick() -> None:
    """Per-snapshot evaluation over THIS process's local ring: keeps
    the SeaweedFS_slo_* gauges and the transition log live even when
    nobody polls /debug/health."""
    if _engine is None or not _engine.specs:
        return
    from . import timeline
    # render=False: evaluate() reads only the raw hist deltas, and
    # this runs on every snapshot
    payload = timeline.timeline_dict(n=windows_needed(), render=False)
    _engine.evaluate(payload["windows"], update_metrics=True)


def health_dict(windows: "list[dict]",
                events: "list[dict] | None" = None) -> dict:
    """The /debug/health payload (empty-engine daemons report ok with
    zero objectives, so the schema is stable for the CI smoke)."""
    if _engine is None or not _engine.specs:
        return {"status": "ok", "objectives": [],
                "now_ms": round(time.time() * 1000.0, 3)}
    return _engine.evaluate(windows, events=events)


def debug_handler():
    """One aiohttp /debug/health handler over THIS process's local
    timeline + journal — registered by every non-worker-aggregating
    server."""
    from aiohttp import web

    async def h_health(req):
        from ..util import events as journal
        from . import timeline
        payload = timeline.timeline_dict(n=windows_needed(),
                                         render=False)
        return web.json_response(health_dict(
            payload["windows"],
            events=journal.events_dict(n=500)["events"]))

    return h_health
