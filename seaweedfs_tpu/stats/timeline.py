"""Metrics timelines: a bounded in-process time-series ring.

``/metrics`` is a point-in-time scrape — by the time someone looks, the
bad minute is gone.  This module snapshots every counter, gauge and
histogram registered in the process's Prometheus registry on a fixed
cadence (``-timeline.interval``, default 10s) and keeps the last
``-timeline.ring`` WINDOWS, where a window is the delta between two
consecutive snapshots:

- **counters** become per-second rates over the window;
- **gauges** keep their value at the window's end;
- **histograms** keep their raw per-window BUCKET DELTAS (plus sum and
  count deltas) — quantiles are derived at render time by walking the
  cumulative deltas with linear interpolation, and because the raw
  buckets ride in the payload, a whole-host merge under ``-workers``
  can sum siblings' buckets and recompute honest host-level quantiles
  (the same discipline as ``merge_metrics_texts``: sum per key, never
  average derived values).

Saturation probes (stats/saturation.py) run right before each
snapshot, so event-loop lag, executor queue wait, open fds, disk usage
and cache occupancy land in the SAME windows as the request-rate and
latency series — "slow at 14:02:10" becomes attributable to the
resource that saturated at 14:02:10.

Exposed at ``/debug/timeline`` (``/__debug__/timeline`` on the
path-shadowing gateways).  ``POST /debug/timeline?snap=1`` forces a
snapshot NOW — how tests and the CI smoke get deterministic windows.
The SLO engine (stats/slo.py) evaluates its burn rates over these
windows after every snapshot.
"""

from __future__ import annotations

import asyncio
import threading
import time
from collections import deque

from ..util import glog

# default cadence/ring (wired from -timeline.interval / -timeline.ring)
DEFAULT_INTERVAL_S = 10.0
DEFAULT_RING = 360              # 1h of 10s windows

_QUANTILES = (0.5, 0.95, 0.99)

_lock = threading.Lock()
_interval_s = DEFAULT_INTERVAL_S
_ring: deque = deque(maxlen=DEFAULT_RING)
_last_snap: "dict | None" = None        # (wall, mono, flat samples)
_probes: list = []                      # sync callables run pre-snapshot
_task: "asyncio.Task | None" = None
_lag_task: "asyncio.Task | None" = None


def init(interval_s: float = DEFAULT_INTERVAL_S,
         ring: int = DEFAULT_RING) -> None:
    """Wire from CLI flags: -timeline.interval, -timeline.ring."""
    global _interval_s, _ring
    _interval_s = interval_s
    with _lock:
        if ring != _ring.maxlen:
            _ring = deque(_ring, maxlen=max(4, ring))


def reset() -> None:
    """Drop all windows and the snapshot baseline (tests)."""
    global _last_snap
    with _lock:
        _ring.clear()
        _last_snap = None


def enabled() -> bool:
    return _interval_s > 0


def interval_s() -> float:
    """The wired snapshot cadence (slo.windows_needed sizes its window
    fetch from this)."""
    return _interval_s


def register_probe(fn) -> None:
    """Register a synchronous saturation probe run before every
    snapshot (sets gauges; must be cheap and never raise)."""
    if fn not in _probes:
        _probes.append(fn)


# ---------------------------------------------------------------------------
# snapshotting


def _collect_flat() -> "tuple[dict, dict]":
    """(samples, kinds): samples maps ``name{label="v",...}`` -> value
    for every non-_created sample in the registry; kinds maps the same
    keys to "counter" | "gauge" | "hist_bucket" | "hist_sum" |
    "hist_count"."""
    from . import metrics
    samples: dict[str, float] = {}
    kinds: dict[str, str] = {}
    if not metrics.HAVE_PROMETHEUS:
        return samples, kinds
    for fam in metrics.REGISTRY.collect():
        ftype = fam.type
        for s in fam.samples:
            name = s.name
            if name.endswith("_created"):
                continue
            if ftype == "histogram":
                if name.endswith("_bucket"):
                    kind = "hist_bucket"
                elif name.endswith("_sum"):
                    kind = "hist_sum"
                elif name.endswith("_count"):
                    kind = "hist_count"
                else:
                    kind = "gauge"
            elif ftype == "counter":
                kind = "counter"
            else:
                kind = "gauge"
            if s.labels:
                lbl = ",".join(f'{k}="{v}"'
                               for k, v in sorted(s.labels.items()))
                key = f"{name}{{{lbl}}}"
            else:
                key = name
            samples[key] = float(s.value)
            kinds[key] = kind
    return samples, kinds


def split_key(key: str) -> "tuple[str, dict]":
    """``name{a="x",b="y"}`` -> ("name", {"a": "x", "b": "y"})."""
    name, brace, rest = key.partition("{")
    labels: dict[str, str] = {}
    if brace:
        for part in rest.rstrip("}").split('",'):
            if not part:
                continue
            k, _, v = part.partition('="')
            labels[k] = v.rstrip('"')
    return name, labels


def _hist_base(key: str) -> "tuple[str, str]":
    """bucket-sample key -> (base key without the le label, le value)."""
    name, labels = split_key(key)
    le = labels.pop("le", "+Inf")
    base = name[:-len("_bucket")]
    if labels:
        lbl = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
        return f"{base}{{{lbl}}}", le
    return base, le


def snap() -> "dict | None":
    """Take one snapshot NOW and, when a baseline exists, append the
    delta window to the ring. Returns the new window (or None for the
    very first snapshot, which only establishes the baseline)."""
    global _last_snap
    for probe in list(_probes):
        try:
            probe()
        except Exception as e:  # noqa: BLE001 — a broken probe must not
            # stop the recorder; it stays visible in the log
            glog.warning("timeline probe %s failed: %s",
                         getattr(probe, "__name__", probe), e)
    wall = time.time()
    mono = time.perf_counter()
    samples, kinds = _collect_flat()
    from ..util import tracing
    exemplars = tracing.drain_exemplars()
    with _lock:
        prev, _last_snap = _last_snap, (wall, mono, samples, kinds)
        if prev is None:
            return None
        pwall, pmono, psamples, _ = prev
        dt = max(1e-9, mono - pmono)
        win = _window(wall, dt, samples, psamples, kinds)
        if exemplars:
            # worst trace per (tier, op) observed during this window —
            # the link from a timeline row into /debug/cluster/trace/<id>
            win["exemplars"] = exemplars
        _ring.append(win)
        return win


def _window(wall: float, dt: float, cur: dict, prev: dict,
            kinds: dict) -> dict:
    rates: dict[str, float] = {}
    gauges: dict[str, float] = {}
    hist: dict[str, dict] = {}
    for key, val in cur.items():
        kind = kinds[key]
        if kind == "gauge":
            gauges[key] = val
            continue
        delta = val - prev.get(key, 0.0)
        if delta < 0:
            # counter reset (process restart mid-merge): start over
            delta = val
        if kind == "counter":
            rates[key] = round(delta / dt, 6)
        elif kind == "hist_bucket":
            base, le = _hist_base(key)
            hist.setdefault(base, {"buckets": {}, "sum": 0.0,
                                   "count": 0.0})["buckets"][le] = delta
        elif kind == "hist_sum":
            base = key[:-len("_sum")] if "{" not in key else \
                _strip_suffix(key, "_sum")
            hist.setdefault(base, {"buckets": {}, "sum": 0.0,
                                   "count": 0.0})["sum"] = round(delta, 9)
        elif kind == "hist_count":
            base = key[:-len("_count")] if "{" not in key else \
                _strip_suffix(key, "_count")
            hist.setdefault(base, {"buckets": {}, "sum": 0.0,
                                   "count": 0.0})["count"] = delta
    # drop all-zero histogram windows: they carry no information and
    # dominate payload size on an idle daemon
    hist = {k: v for k, v in hist.items() if v["count"]}
    return {"wall_ms": round(wall * 1000.0, 3), "dt_s": round(dt, 3),
            "rates": {k: v for k, v in rates.items() if v},
            "gauges": gauges, "hist": hist}


def _strip_suffix(key: str, suffix: str) -> str:
    name, brace, rest = key.partition("{")
    return name[:-len(suffix)] + (brace + rest if brace else "")


# ---------------------------------------------------------------------------
# quantiles from bucket deltas


def quantiles_from_buckets(buckets: "dict[str, float]",
                           qs=_QUANTILES) -> "dict[str, float]":
    """{le: delta-count} -> {"p50": s, ...} seconds, by walking the
    cumulative distribution with linear interpolation inside the
    containing bucket. The +Inf bucket has no finite upper edge, so a
    quantile landing there reports the largest finite bound (a FLOOR —
    honest "at least this slow")."""
    try:
        edges = sorted(((float("inf") if le in ("+Inf", "inf") else
                         float(le)), c) for le, c in buckets.items())
    except ValueError:
        return {}
    total = edges[-1][1] if edges else 0.0
    if total <= 0:
        return {}
    out: dict[str, float] = {}
    finite = [e for e, _ in edges if e != float("inf")]
    top = finite[-1] if finite else 0.0
    for q in qs:
        target = q * total
        lo_edge, lo_cum = 0.0, 0.0
        val = top
        for edge, cum in edges:
            if cum >= target:
                if edge == float("inf"):
                    val = top
                elif cum == lo_cum:
                    val = edge
                else:
                    val = lo_edge + (edge - lo_edge) * \
                        (target - lo_cum) / (cum - lo_cum)
                break
            lo_edge, lo_cum = edge, cum
        out[f"p{int(q * 100)}"] = round(val, 6)
    return out


def _render(win: dict) -> dict:
    """A ring window + derived per-histogram quantiles/rate/avg."""
    out = dict(win)
    q: dict[str, dict] = {}
    for base, h in win.get("hist", {}).items():
        count = h.get("count", 0.0)
        if not count:
            continue
        row = quantiles_from_buckets(h.get("buckets", {}))
        row["count"] = count
        row["rate"] = round(count / max(1e-9, win["dt_s"]), 3)
        if h.get("sum"):
            row["avg"] = round(h["sum"] / count, 6)
        q[base] = row
    out["quantiles"] = q
    return out


# ---------------------------------------------------------------------------
# debug surface (/debug/timeline)


def timeline_dict(n: int = 60, render: bool = True) -> dict:
    """The /debug/timeline JSON body for THIS process's ring: the last
    `n` windows, oldest first, each with derived quantiles.

    ``render=False`` skips the per-histogram quantile interpolation
    and hands out the raw ring windows — the SLO tick only reads the
    raw ``hist`` deltas, and rendering 200 windows' quantiles per
    snapshot just to discard them is measurable at
    ``-timeline.interval 1``. Raw windows are the live ring dicts:
    callers must not mutate them."""
    n = max(0, min(int(n), 10_000))
    with _lock:
        wins = list(_ring)[-n:] if n else []
    return {"interval_s": _interval_s, "ring": _ring.maxlen,
            "windows": [_render(w) for w in wins] if render
            else wins}


def _merge_gauge(key: str, old: float, new: float) -> float:
    # one non-additive policy for both whole-host merges: see
    # metrics.NON_ADDITIVE_GAUGE_PREFIXES for which gauges take max
    # (same-filesystem disk usage, per-loop latencies, build identity,
    # process start time) and why summing them fabricates a value
    from . import metrics
    if key.startswith(metrics.NON_ADDITIVE_GAUGE_PREFIXES):
        return max(old, new)
    return old + new


def _merge_exemplars(dst: dict, src: "dict | None") -> None:
    """Fold exemplar maps ({"tier.op": {"trace", "dur_ms"}}) across
    windows: the WORST (max dur_ms) trace per key wins — exemplars are
    pointers, not statistics, so there is nothing to sum."""
    for k, ex in (src or {}).items():
        cur = dst.get(k)
        if cur is None or float(ex.get("dur_ms", 0.0)) > \
                float(cur.get("dur_ms", 0.0)):
            dst[k] = ex


def _fold_same_process(windows, interval: float) -> "list[dict]":
    """Combine ONE payload's windows that land in the same wall bucket
    (a forced ``?snap=1`` a few hundred ms after the periodic snap):
    their dt_s are disjoint sub-intervals of the bucket, so summing
    their per-second rates would double-count — rates recombine as
    (count1+count2)/(dt1+dt2), gauges keep the newest sample (the SAME
    process observed both; adding them fabricates double the fds), and
    histogram deltas sum like any disjoint spans."""
    out: dict[int, dict] = {}
    for w in windows:
        bucket = int(w["wall_ms"] / 1000.0 / interval)
        m = out.get(bucket)
        if m is None:
            out[bucket] = {"wall_ms": w["wall_ms"], "dt_s": w["dt_s"],
                           "rates": dict(w.get("rates", {})),
                           "gauges": dict(w.get("gauges", {})),
                           "hist": {b: {"buckets": dict(h.get("buckets", {})),
                                        "sum": h.get("sum", 0.0),
                                        "count": h.get("count", 0.0)}
                                    for b, h in w.get("hist", {}).items()}}
            if w.get("exemplars"):
                out[bucket]["exemplars"] = dict(w["exemplars"])
            continue
        dt0, dt1 = m["dt_s"], w["dt_s"]
        span = max(1e-9, dt0 + dt1)
        for k in set(m["rates"]) | set(w.get("rates", {})):
            cnt = (m["rates"].get(k, 0.0) * dt0
                   + w.get("rates", {}).get(k, 0.0) * dt1)
            m["rates"][k] = round(cnt / span, 6)
        if w["wall_ms"] >= m["wall_ms"]:
            m["gauges"].update(w.get("gauges", {}))
        else:
            m["gauges"] = {**w.get("gauges", {}), **m["gauges"]}
        for base, h in w.get("hist", {}).items():
            mh = m["hist"].setdefault(
                base, {"buckets": {}, "sum": 0.0, "count": 0.0})
            for le, c in h.get("buckets", {}).items():
                mh["buckets"][le] = mh["buckets"].get(le, 0.0) + c
            mh["sum"] = round(mh["sum"] + h.get("sum", 0.0), 9)
            mh["count"] += h.get("count", 0.0)
        if w.get("exemplars"):
            _merge_exemplars(m.setdefault("exemplars", {}),
                             w["exemplars"])
        m["wall_ms"] = max(m["wall_ms"], w["wall_ms"])
        m["dt_s"] = round(span, 3)
    return [out[b] for b in sorted(out)]


def merge_payloads(payloads: "list[dict]", n: int = 60,
                   render: bool = True) -> dict:
    """Fold several workers' /debug/timeline bodies into one whole-host
    view: each payload's windows are first folded per wall bucket
    (_fold_same_process — a forced snap must not double-count its own
    process), then windows align on wall-clock buckets of the sampling
    interval and within a bucket rates/gauges/histogram buckets are
    SUMMED per key across processes (the /metrics merge discipline —
    except the non-additive gauges in
    metrics.NON_ADDITIVE_GAUGE_PREFIXES, which take the max), then
    quantiles recomputed from the summed buckets — the host p99 is
    derived from host-wide buckets, never averaged from per-worker
    quantiles."""
    n = max(0, min(int(n), 10_000))
    interval = max((float(p.get("interval_s") or 0) for p in payloads),
                   default=_interval_s) or DEFAULT_INTERVAL_S
    ring = max((int(p.get("ring") or 0) for p in payloads),
               default=_ring.maxlen)
    merged: dict[int, dict] = {}
    for p in payloads:
        for w in _fold_same_process(p.get("windows", ()), interval):
            bucket = int(w["wall_ms"] / 1000.0 / interval)
            m = merged.get(bucket)
            if m is None:
                m = merged[bucket] = {
                    "wall_ms": w["wall_ms"], "dt_s": w["dt_s"],
                    "rates": {}, "gauges": {}, "hist": {}}
            m["wall_ms"] = max(m["wall_ms"], w["wall_ms"])
            m["dt_s"] = max(m["dt_s"], w["dt_s"])
            for k, v in w.get("rates", {}).items():
                m["rates"][k] = round(m["rates"].get(k, 0.0) + v, 6)
            for k, v in w.get("gauges", {}).items():
                if k in m["gauges"]:
                    m["gauges"][k] = _merge_gauge(k, m["gauges"][k], v)
                else:
                    m["gauges"][k] = v
            for base, h in w.get("hist", {}).items():
                mh = m["hist"].setdefault(
                    base, {"buckets": {}, "sum": 0.0, "count": 0.0})
                for le, c in h.get("buckets", {}).items():
                    mh["buckets"][le] = mh["buckets"].get(le, 0.0) + c
                mh["sum"] = round(mh["sum"] + h.get("sum", 0.0), 9)
                mh["count"] += h.get("count", 0.0)
            if w.get("exemplars"):
                _merge_exemplars(m.setdefault("exemplars", {}),
                                 w["exemplars"])
    wins = [merged[b] for b in sorted(merged)][-n:]
    return {"interval_s": interval, "ring": ring,
            "windows": [_render(w) for w in wins] if render else wins}


def timeline_query(query) -> dict:
    """timeline_dict driven by a ?n= query mapping (raises ValueError
    on malformed counts) — shared by every server handler."""
    return timeline_dict(n=int(query.get("n", 60)))


# ---------------------------------------------------------------------------
# the recorder task


async def _run() -> None:
    while True:
        await asyncio.sleep(_interval_s)
        try:
            snap()
        except Exception as e:  # noqa: BLE001 — a collector raising
            # during the sweep must not silently kill the recorder for
            # the rest of the process lifetime (health would keep
            # serving a stale verdict off a frozen ring)
            glog.warning("timeline snapshot failed: %s", e)
        try:
            from . import slo
            slo.tick()
        except Exception as e:  # noqa: BLE001 — SLO evaluation must not
            # kill the recorder; the engine logs its own transitions
            glog.warning("slo tick failed: %s", e)


async def _lag_probe(period_s: float = 0.25) -> None:
    """Continuously measure event-loop scheduling delay; the max since
    the last snapshot is flushed to the gauge by sample_loop_lag()."""
    from . import saturation
    loop = asyncio.get_running_loop()
    while True:
        t0 = loop.time()
        await asyncio.sleep(period_s)
        saturation.note_loop_lag(max(0.0, loop.time() - t0 - period_s))


def start_recorder(disk_paths: "list[str] | None" = None):
    """Start the sampling loop (+ the loop-lag probe task) on the
    running event loop; idempotent per process. Returns a handle with
    ``cancel()`` for the daemon's shutdown path, or None when disabled
    (-timeline.interval 0)."""
    global _task, _lag_task
    if _interval_s <= 0:
        return None
    from . import saturation
    register_probe(saturation.sample_process)
    if disk_paths:
        register_probe(saturation.disk_probe(disk_paths))
    loop = asyncio.get_running_loop()
    if _task is None or _task.done():
        _task = loop.create_task(_run())
        snap()                       # establish the baseline NOW
    if _lag_task is None or _lag_task.done():
        _lag_task = loop.create_task(_lag_probe())
    register_probe(saturation.sample_loop_lag)
    saturation.start_executor_probe(loop)

    class _Handle:
        def cancel(self) -> None:
            global _task, _lag_task
            for t in (_task, _lag_task):
                if t is not None and not t.done():
                    t.cancel()
            _task = _lag_task = None
            saturation.stop_executor_probe()

    return _Handle()


def debug_handler():
    """One aiohttp /debug/timeline handler over THIS process's ring
    (GET ?n=; POST ?snap=1 forces a snapshot) — registered by every
    non-worker-aggregating server so the contract cannot drift."""
    from aiohttp import web

    async def h_timeline(req):
        if req.method == "POST":
            if req.query.get("snap", "") not in ("1", "true"):
                return web.json_response({"error": "POST wants ?snap=1"},
                                         status=400)
            snap()
        try:
            return web.json_response(timeline_query(req.query))
        except ValueError:
            return web.json_response({"error": "bad n"}, status=400)

    return h_timeline


def recorder_handlers():
    """(h_timeline, h_events, h_health): the flight-recorder trio over
    THIS process's rings — the one factory every non-worker-aggregating
    server (master, filer, S3, WebDAV) registers, so the recorder
    contract cannot drift between surfaces. (The volume server has its
    own -workers-merging twins.)"""
    from ..util import events
    from . import slo
    return debug_handler(), events.debug_handler(), slo.debug_handler()
