"""storage subpackage."""
