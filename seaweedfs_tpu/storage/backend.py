"""Tiered-storage backends: move sealed .dat files to remote object
storage while reads keep flowing through the volume transparently.

Reference: weed/storage/backend/backend.go:15-75 (`BackendStorageFile` /
`BackendStorage` / factory registry loaded from `[storage.backend.*]`
TOML) and weed/storage/backend/s3_backend/s3_backend.go:113-146
(`S3BackendStorage` serving ReadAt via ranged GETs). The volume info
sidecar (.vif, reference pb/volume_info.go) records which backend holds
the .dat and under what key.

The S3 backend speaks plain S3 REST (PUT/ranged GET/DELETE) against any
S3-compatible endpoint — including this package's own gateway — via
synchronous HTTP, because volume reads run in executor threads.
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.request
from typing import Callable, Protocol

from ..util import failpoints


class BackendError(Exception):
    pass


class BackendStorageFile(Protocol):
    """File-shaped handle the Volume reads through
    (backend.go:15-23: ReadAt/GetStat/Name)."""

    def read_at(self, offset: int, size: int) -> bytes: ...
    def size(self) -> int: ...
    def name(self) -> str: ...
    def close(self) -> None: ...


class BackendStorage(Protocol):
    """A configured remote tier (backend.go:25-39)."""

    def new_storage_file(self, key: str) -> BackendStorageFile: ...
    def copy_file(self, local_path: str, key: str) -> int: ...
    def download_file(self, key: str, local_path: str) -> int: ...
    def delete_file(self, key: str) -> None: ...


# ---- S3-compatible backend ----


class S3BackendStorageFile:
    def __init__(self, backend: "S3BackendStorage", key: str,
                 known_size: int = -1):
        self._b = backend
        self._key = key
        self._size = known_size

    def read_at(self, offset: int, size: int) -> bytes:
        if size <= 0:
            return b""
        # chaos site: a degraded remote tier (erroring or slow ranged
        # GETs) must surface as a bounded read error through the normal
        # OSError paths — never a wedged executor thread (sync: volume
        # reads run in executor threads)
        failpoints.sync_fail("tier.read")
        req = urllib.request.Request(
            self._b._url(self._key),
            headers={"Range": f"bytes={offset}-{offset + size - 1}"})
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                return r.read()
        except urllib.error.URLError as e:
            raise BackendError(f"s3 read {self._key}@{offset}: {e}") from e

    def size(self) -> int:
        if self._size >= 0:
            return self._size
        req = urllib.request.Request(self._b._url(self._key), method="HEAD")
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                self._size = int(r.headers.get("Content-Length", 0))
        except urllib.error.URLError as e:
            raise BackendError(f"s3 head {self._key}: {e}") from e
        return self._size

    def name(self) -> str:
        return f"s3://{self._b.bucket}/{self._key}"

    def close(self) -> None:
        pass


class S3BackendStorage:
    """Plain S3 REST client (unsigned; for gated/authenticated endpoints
    front it with a proxy or extend with SigV4 — the reference reads its
    credentials from the same backend config section)."""

    def __init__(self, backend_id: str, endpoint: str, bucket: str,
                 storage_class: str = ""):
        self.id = backend_id
        self.endpoint = endpoint.rstrip("/")
        if not self.endpoint.startswith("http"):
            self.endpoint = "http://" + self.endpoint
        self.bucket = bucket
        self.storage_class = storage_class

    def _url(self, key: str) -> str:
        return f"{self.endpoint}/{self.bucket}/{key}"

    def ensure_bucket(self) -> None:
        req = urllib.request.Request(
            f"{self.endpoint}/{self.bucket}", method="PUT")
        try:
            urllib.request.urlopen(req, timeout=30).read()
        except urllib.error.HTTPError as e:
            if e.code not in (200, 409):  # exists is fine
                raise BackendError(f"create bucket: http {e.code}") from e
        except urllib.error.URLError as e:
            raise BackendError(f"create bucket: {e}") from e

    def new_storage_file(self, key: str,
                         known_size: int = -1) -> S3BackendStorageFile:
        return S3BackendStorageFile(self, key, known_size)

    def copy_file(self, local_path: str, key: str) -> int:
        self.ensure_bucket()
        size = os.path.getsize(local_path)
        with open(local_path, "rb") as f:
            # stream the PUT: urllib sends file-like bodies in chunks when
            # Content-Length is set, so a 30GB .dat never sits in RAM
            req = urllib.request.Request(
                self._url(key), data=f, method="PUT",
                headers={"Content-Length": str(size)})
            try:
                urllib.request.urlopen(req, timeout=600).read()
            except urllib.error.URLError as e:
                raise BackendError(f"s3 upload {key}: {e}") from e
        return size

    def download_file(self, key: str, local_path: str) -> int:
        try:
            with urllib.request.urlopen(self._url(key), timeout=600) as r:
                with open(local_path, "wb") as f:
                    total = 0
                    while True:
                        chunk = r.read(1 << 20)
                        if not chunk:
                            break
                        f.write(chunk)
                        total += len(chunk)
                    return total
        except urllib.error.URLError as e:
            raise BackendError(f"s3 download {key}: {e}") from e

    def delete_file(self, key: str) -> None:
        req = urllib.request.Request(self._url(key), method="DELETE")
        try:
            urllib.request.urlopen(req, timeout=60).read()
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise BackendError(f"s3 delete {key}: http {e.code}") from e
        except urllib.error.URLError as e:
            raise BackendError(f"s3 delete {key}: {e}") from e


# ---- memory-mapped local backend ----


class MmapBackendStorageFile:
    """read_at served straight from a read-only memory map: the OS page
    cache holds hot volume pages, and a pread-style slice is a memcpy,
    no syscall-per-read (reference: weed/storage/backend/memory_map/
    memory_map_backend.go, re-expressed POSIX-first instead of the
    reference's Windows CreateFileMapping path)."""

    def __init__(self, path: str):
        import mmap
        self._path = path
        self._f = None
        try:
            self._f = open(path, "rb")
            self._size = os.fstat(self._f.fileno()).st_size
            self._mm = (mmap.mmap(self._f.fileno(), self._size,
                                  prot=mmap.PROT_READ)
                        if self._size else None)
        except OSError as e:
            if self._f is not None:
                self._f.close()
            raise BackendError(f"mmap open {path}: {e}") from e

    def read_at(self, offset: int, size: int) -> bytes:
        # same site as the S3 path: every tiered read is breakable,
        # whichever backend serves it
        failpoints.sync_fail("tier.read")
        if self._mm is None or offset >= self._size:
            return b""
        return self._mm[offset:offset + size]

    def size(self) -> int:
        return self._size

    def name(self) -> str:
        return f"mmap://{self._path}"

    def close(self) -> None:
        if self._mm is not None:
            self._mm.close()
        self._f.close()


class MmapBackendStorage:
    """Local-directory tier with memory-mapped reads — point it at a
    tmpfs/ramdisk for an in-memory tier, or a big slow disk for a cold
    tier. Second in-tree BackendStorage (backend.go factory plurality)."""

    def __init__(self, backend_id: str, dirname: str):
        self.id = backend_id
        self.dir = dirname
        os.makedirs(dirname, exist_ok=True)

    def _path(self, key: str) -> str:
        # keys look like "1.dat.<random>"; keep them flat
        return os.path.join(self.dir, key.replace("/", "_"))

    def new_storage_file(self, key: str,
                         known_size: int = -1) -> MmapBackendStorageFile:
        return MmapBackendStorageFile(self._path(key))

    def copy_file(self, local_path: str, key: str) -> int:
        dst = self._path(key)
        tmp = dst + ".tmp"
        # durable before rename: tier_upload deletes the only local copy
        # right after this returns, so the bytes must be ON the tier
        # medium, not just in page cache (the S3 backend gets the same
        # guarantee from the server ack)
        try:
            with open(local_path, "rb") as src, open(tmp, "wb") as out:
                while True:
                    chunk = src.read(1 << 20)
                    if not chunk:
                        break
                    out.write(chunk)
                out.flush()
                os.fsync(out.fileno())
            os.replace(tmp, dst)
            dfd = os.open(self.dir, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
            return os.path.getsize(dst)
        except OSError as e:
            # don't pin tier space: a failed upload must leave neither a
            # partial temp file nor (when the rename already happened and
            # a later fsync failed) an orphaned dst — the caller reports
            # failure and retries under a fresh key
            for leftover in (tmp, dst):
                try:
                    os.remove(leftover)
                except OSError:
                    pass
            raise BackendError(f"mmap upload {key}: {e}") from e

    def download_file(self, key: str, local_path: str) -> int:
        import shutil
        src = self._path(key)
        try:
            shutil.copyfile(src, local_path)
        except OSError as e:
            raise BackendError(f"mmap download {key}: {e}") from e
        return os.path.getsize(local_path)

    def delete_file(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass
        except OSError as e:
            raise BackendError(f"mmap delete {key}: {e}") from e


# ---- registry (backend.go:24-45 factory map + LoadConfiguration) ----

_FACTORIES: dict[str, Callable[..., BackendStorage]] = {}
_STORAGES: dict[str, BackendStorage] = {}


def register_backend_factory(type_name: str,
                             factory: Callable[..., BackendStorage]) -> None:
    _FACTORIES[type_name] = factory


register_backend_factory(
    "s3", lambda backend_id, conf: S3BackendStorage(
        backend_id, conf["endpoint"], conf["bucket"],
        conf.get("storage_class", "")))

register_backend_factory(
    "mmap", lambda backend_id, conf: MmapBackendStorage(
        backend_id, conf["dir"]))


def load_backends(config: dict) -> None:
    """Configure backends from {"s3": {"default": {endpoint, bucket}}}
    (the shape of the reference's [storage.backend.s3.default] TOML)."""
    for type_name, instances in config.items():
        factory = _FACTORIES.get(type_name)
        if factory is None:
            raise BackendError(f"unknown backend type {type_name!r}")
        for inst_name, conf in instances.items():
            if not conf.get("enabled", True):
                continue
            backend_id = f"{type_name}.{inst_name}"
            _STORAGES[backend_id] = factory(backend_id, conf)


def get_backend(backend_id: str) -> BackendStorage:
    try:
        return _STORAGES[backend_id]
    except KeyError:
        raise BackendError(f"backend {backend_id!r} not configured "
                           f"(have: {sorted(_STORAGES)})") from None


def clear_backends() -> None:
    _STORAGES.clear()


# ---- .vif sidecar (pb/volume_info.go analog, JSON instead of pb) ----


def vif_path(base: str) -> str:
    return base + ".vif"


def save_volume_info(base: str, backend_id: str, key: str,
                     size: int, version: int) -> None:
    info = {"version": version,
            "files": [{"backend_id": backend_id, "key": key,
                       "file_size": size}]}
    tmp = vif_path(base) + ".tmp"
    with open(tmp, "w") as f:
        json.dump(info, f)
    os.replace(tmp, vif_path(base))


def load_volume_info(base: str) -> dict | None:
    p = vif_path(base)
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return json.load(f)


class RemoteDatFile:
    """Adapter giving a BackendStorageFile the seek/read/tell surface
    Volume._dat expects, so tiered volumes read transparently
    (volume reads become ranged GETs, s3_backend.go:113-146)."""

    def __init__(self, bf: BackendStorageFile):
        self._bf = bf
        self._pos = 0

    def seek(self, offset: int, whence: int = os.SEEK_SET) -> int:
        if whence == os.SEEK_SET:
            self._pos = offset
        elif whence == os.SEEK_CUR:
            self._pos += offset
        elif whence == os.SEEK_END:
            self._pos = self._bf.size() + offset
        return self._pos

    def tell(self) -> int:
        return self._pos

    def read(self, size: int = -1) -> bytes:
        if size < 0:
            size = max(0, self._bf.size() - self._pos)
        data = self._bf.read_at(self._pos, size)
        self._pos += len(data)
        return data

    def pread(self, size: int, offset: int) -> bytes:
        # positioned read (os.pread argument order) — no shared seek state
        return self._bf.read_at(offset, size)

    def size(self) -> int:
        return self._bf.size()

    def write(self, data: bytes) -> int:
        raise BackendError("tiered volume is read-only")

    def flush(self) -> None:
        pass

    def truncate(self, size: int) -> None:
        raise BackendError("tiered volume is read-only")

    def close(self) -> None:
        self._bf.close()
