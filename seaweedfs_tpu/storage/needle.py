"""Needle: one stored blob record in a volume file.

Binary layout follows the reference byte-for-byte so .dat files are
interchangeable (weed/storage/needle/needle.go:26-46,
needle_read_write.go:31-151):

  header : cookie(4) id(8) size(4)                       -- big-endian
  body v2/v3 (present only when size > 0):
    dataSize(4) data flags(1)
    [nameSize(1) name]         if FlagHasName
    [mimeSize(1) mime]         if FlagHasMime
    [lastModified(5)]          if FlagHasLastModifiedDate
    [ttl(2)]                   if FlagHasTtl
    [pairsSize(2) pairs]       if FlagHasPairs
  footer : checksum(4 masked crc32c of data)
           appendAtNs(8)                                  -- v3 only
           zero padding to 8-byte alignment of the whole record
  size   = 4 + dataSize + 1 + optional-field bytes (0 when no data)
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..util import crc32c
from . import types as t

FLAG_GZIP = 0x01
FLAG_HAS_NAME = 0x02
FLAG_HAS_MIME = 0x04
FLAG_HAS_LAST_MODIFIED = 0x08
FLAG_HAS_TTL = 0x10
FLAG_HAS_PAIRS = 0x20
FLAG_IS_CHUNK_MANIFEST = 0x80

LAST_MODIFIED_BYTES = 5

MAX_POSSIBLE_VOLUME_SIZE = 4 * 1024 * 1024 * 1024 * 8  # 32GB w/ 4B offsets


class NeedleError(Exception):
    pass


class CrcMismatch(NeedleError):
    pass


@dataclass
class Needle:
    cookie: int = 0
    id: int = 0
    data: bytes = b""
    name: bytes = b""
    mime: bytes = b""
    pairs: bytes = b""
    last_modified: int = 0
    ttl: t.TTL = field(default_factory=t.TTL)
    flags: int = 0
    append_at_ns: int = 0
    # populated on read:
    size: int = 0
    checksum: int = 0

    # ---- flag helpers ----
    def has(self, flag: int) -> bool:
        return bool(self.flags & flag)

    def set_flag(self, flag: int, on: bool = True) -> None:
        if on:
            self.flags |= flag
        else:
            self.flags &= ~flag

    @property
    def is_gzipped(self) -> bool:
        return self.has(FLAG_GZIP)

    @property
    def is_chunked_manifest(self) -> bool:
        return self.has(FLAG_IS_CHUNK_MANIFEST)

    def etag(self) -> str:
        if not self.data and self.checksum:
            # meta-only read (zero-copy ref): derive the same CRC the
            # buffered path computes, from the stored footer checksum
            return "%08x" % crc32c.unmasked(self.checksum)
        return "%08x" % (crc32c.crc32c(self.data) & 0xFFFFFFFF)

    # ---- serialization ----

    def _body_size(self) -> int:
        if not self.data:
            return 0
        size = 4 + len(self.data) + 1
        if self.has(FLAG_HAS_NAME):
            size += 1 + min(len(self.name), 255)
        if self.has(FLAG_HAS_MIME):
            size += 1 + len(self.mime)
        if self.has(FLAG_HAS_LAST_MODIFIED):
            size += LAST_MODIFIED_BYTES
        if self.has(FLAG_HAS_TTL):
            size += 2
        if self.has(FLAG_HAS_PAIRS):
            size += 2 + len(self.pairs)
        return size

    def to_bytes(self, version: int = t.CURRENT_VERSION) -> bytes:
        """Serialized record incl. footer + padding (prepareWriteBuffer)."""
        if version == t.VERSION1:
            self.size = len(self.data)
            out = bytearray()
            out += self.cookie.to_bytes(4, "big")
            out += self.id.to_bytes(8, "big")
            out += self.size.to_bytes(4, "big")
            out += self.data
            out += crc32c.checksum_value(self.data).to_bytes(4, "big")
            out += b"\x00" * t.padding_length(self.size, version)
            return bytes(out)
        if version not in (t.VERSION2, t.VERSION3):
            raise NeedleError(f"unsupported needle version {version}")

        if len(self.mime) > 255:
            raise NeedleError(f"mime too long: {len(self.mime)} > 255")
        if len(self.pairs) > 65535:
            raise NeedleError(f"pairs too long: {len(self.pairs)} > 65535")
        # auto-set flags for populated optional fields
        if self.name:
            self.set_flag(FLAG_HAS_NAME)
        if self.mime:
            self.set_flag(FLAG_HAS_MIME)
        if self.last_modified:
            self.set_flag(FLAG_HAS_LAST_MODIFIED)
        if self.ttl.count:
            self.set_flag(FLAG_HAS_TTL)
        if self.pairs:
            self.set_flag(FLAG_HAS_PAIRS)

        self.size = self._body_size()
        out = bytearray()
        out += self.cookie.to_bytes(4, "big")
        out += self.id.to_bytes(8, "big")
        out += self.size.to_bytes(4, "big")
        if self.data:
            out += len(self.data).to_bytes(4, "big")
            out += self.data
            out += bytes([self.flags])
            if self.has(FLAG_HAS_NAME):
                name = self.name[:255]
                out += bytes([len(name)]) + name
            if self.has(FLAG_HAS_MIME):
                out += bytes([len(self.mime)]) + self.mime
            if self.has(FLAG_HAS_LAST_MODIFIED):
                out += self.last_modified.to_bytes(8, "big")[-LAST_MODIFIED_BYTES:]
            if self.has(FLAG_HAS_TTL):
                out += self.ttl.to_bytes()
            if self.has(FLAG_HAS_PAIRS):
                out += len(self.pairs).to_bytes(2, "big") + self.pairs
        out += crc32c.checksum_value(self.data).to_bytes(4, "big")
        if version == t.VERSION3:
            if not self.append_at_ns:
                self.append_at_ns = time.time_ns()
            out += self.append_at_ns.to_bytes(8, "big")
        out += b"\x00" * t.padding_length(self.size, version)
        return bytes(out)

    @classmethod
    def from_bytes(cls, blob: bytes, version: int = t.CURRENT_VERSION,
                   check_crc: bool = True) -> "Needle":
        """Parse a full record blob (header..padding) — ReadBytes/ReadData."""
        n = cls()
        n.cookie = int.from_bytes(blob[0:4], "big")
        n.id = int.from_bytes(blob[4:12], "big")
        n.size = int.from_bytes(blob[12:16], "big")
        body = blob[t.NEEDLE_HEADER_SIZE:t.NEEDLE_HEADER_SIZE + n.size]
        if version == t.VERSION1:
            n.data = bytes(body)
        elif n.size > 0:
            n._parse_body(body)
        footer_off = t.NEEDLE_HEADER_SIZE + n.size
        n.checksum = int.from_bytes(blob[footer_off:footer_off + 4], "big")
        if version == t.VERSION3:
            n.append_at_ns = int.from_bytes(
                blob[footer_off + 4:footer_off + 12], "big")
        if check_crc and n.size > 0:
            if crc32c.checksum_value(n.data) != n.checksum:
                raise CrcMismatch(
                    f"needle {n.id:x} crc mismatch: "
                    f"stored {n.checksum:08x}")
        return n

    def _parse_body(self, body: bytes) -> None:
        idx = 0
        data_size = int.from_bytes(body[idx:idx + 4], "big")
        idx += 4
        self.data = bytes(body[idx:idx + data_size])
        idx += data_size
        self.flags = body[idx]
        idx += 1
        if self.has(FLAG_HAS_NAME):
            ln = body[idx]
            idx += 1
            self.name = bytes(body[idx:idx + ln])
            idx += ln
        if self.has(FLAG_HAS_MIME):
            ln = body[idx]
            idx += 1
            self.mime = bytes(body[idx:idx + ln])
            idx += ln
        if self.has(FLAG_HAS_LAST_MODIFIED):
            self.last_modified = int.from_bytes(
                body[idx:idx + LAST_MODIFIED_BYTES], "big")
            idx += LAST_MODIFIED_BYTES
        if self.has(FLAG_HAS_TTL):
            self.ttl = t.TTL.from_bytes(body[idx:idx + 2])
            idx += 2
        if self.has(FLAG_HAS_PAIRS):
            ln = int.from_bytes(body[idx:idx + 2], "big")
            idx += 2
            self.pairs = bytes(body[idx:idx + ln])
            idx += ln

    def disk_size(self, version: int = t.CURRENT_VERSION) -> int:
        return t.actual_size(self._body_size() if not self.size else self.size,
                             version)

    def has_expired(self, now: float | None = None) -> bool:
        """TTL check against last_modified (volume_read_write.go:152-163)."""
        if not self.ttl.minutes:
            return False
        now = now if now is not None else time.time()
        return now >= self.last_modified + self.ttl.minutes * 60
