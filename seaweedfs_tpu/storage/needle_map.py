"""Needle maps: needleId -> (offset, size) within one volume.

Mirrors the reference's NeedleMapper contract (weed/storage/needle_map.go:
21-33) and its .idx append-log persistence (16-byte big-endian entries:
key(8) offset(4, units of 8B) size(4); tombstone size = 0xFFFFFFFF —
needle_map.go:50, idx/walk.go:44).

Kinds (needle_map.go:12-19): in-memory (the default; the reference's
CompactMap becomes a plain dict here with an optional C++ fast map in
native/), plus a read-only sorted-file map over .sdx used by tiered volumes
and the EC .ecx index (needle_map_sorted_file.go).
"""

from __future__ import annotations

import io
import os
import struct
from dataclasses import dataclass
from typing import Callable, Iterator

from . import types as t

_ENTRY4 = struct.Struct(">QII")  # key, offset/8, size (4-byte offsets)


def pack_entry(key: int, actual_offset: int, size: int) -> bytes:
    """One .idx/.ecx entry at the current offset width (idx/walk.go:44;
    5-byte layout per offset_5bytes.go)."""
    units = actual_offset // t.NEEDLE_PADDING_SIZE
    if units >= 1 << (8 * t.OFFSET_SIZE):
        raise OverflowError(
            f"offset {actual_offset} exceeds the {t.OFFSET_SIZE}-byte "
            f"index limit ({t.max_volume_size()} bytes); "
            f"use set_offset_size(5) / SWTPU_OFFSET_BYTES=5")
    if t.OFFSET_SIZE == 4:
        return _ENTRY4.pack(key, units, size)
    # reference 5BytesOffset layout (offset_5bytes.go:18-24): the LOW 32
    # bits big-endian in bytes[0..3], the high byte LAST in bytes[4] —
    # NOT a plain 5-byte big-endian integer
    return (key.to_bytes(t.NEEDLE_ID_SIZE, "big")
            + (units & 0xFFFFFFFF).to_bytes(4, "big")
            + bytes([units >> 32])
            + size.to_bytes(t.SIZE_SIZE, "big"))


def unpack_entry(blob: bytes, pos: int = 0) -> tuple[int, int, int]:
    """-> (key, actual_offset, size) from one entry at `pos`."""
    if t.OFFSET_SIZE == 4:
        key, units, size = _ENTRY4.unpack_from(blob, pos)
    else:
        key = int.from_bytes(blob[pos:pos + t.NEEDLE_ID_SIZE], "big")
        p = pos + t.NEEDLE_ID_SIZE
        units = (int.from_bytes(blob[p:p + 4], "big")
                 | (blob[p + 4] << 32))
        p += t.OFFSET_SIZE
        size = int.from_bytes(blob[p:p + t.SIZE_SIZE], "big")
    return key, units * t.NEEDLE_PADDING_SIZE, size


@dataclass
class NeedleValue:
    key: int
    offset: int  # actual byte offset
    size: int


def walk_index_blob(blob: bytes) -> Iterator[tuple[int, int, int]]:
    """Yield (key, actual_offset, size) per entry (idx/walk.go:12)."""
    n = len(blob) // t.NEEDLE_MAP_ENTRY_SIZE
    for i in range(n):
        yield unpack_entry(blob, i * t.NEEDLE_MAP_ENTRY_SIZE)


def walk_index_file(path: str,
                    fn: Callable[[int, int, int], None]) -> None:
    with open(path, "rb") as f:
        blob = f.read()
    for key, off, size in walk_index_blob(blob):
        fn(key, off, size)


class MemoryNeedleMap:
    """In-memory map + .idx append log (needle_map_memory.go)."""

    @staticmethod
    def _new_map():
        return {}

    def __init__(self, index_path: str | None = None):
        self.index_path = index_path
        self._m = self._new_map()
        self._idx: io.BufferedWriter | None = None
        self.deleted_count = 0
        self.deleted_bytes = 0
        self.file_count = 0
        self.content_bytes = 0
        self.max_file_key = 0
        # (key, actual_offset, size) of the highest-offset logged record,
        # tombstones included — the true .dat tail for integrity checking.
        self.last_entry: tuple[int, int, int] | None = None
        if index_path:
            if os.path.exists(index_path):
                self._load(index_path)
            self._idx = open(index_path, "ab")

    def _load(self, path: str) -> None:
        def visit(key: int, offset: int, size: int) -> None:
            self._apply(key, offset, size)
        walk_index_file(path, visit)

    def _apply(self, key: int, offset: int, size: int) -> None:
        """Replay one idx entry into the in-memory state.

        Deletes keep a tombstone NeedleValue (size = TOMBSTONE_FILE_SIZE) so
        reads can distinguish "already deleted" from "never existed"
        (volume_read_write.go:147-149). The logged offset of a delete is the
        position of the tombstone record appended to .dat."""
        self.max_file_key = max(self.max_file_key, key)
        if offset > 0 and (self.last_entry is None
                           or offset > self.last_entry[1]):
            self.last_entry = (key, offset, size)
        if offset > 0 and size != t.TOMBSTONE_FILE_SIZE:
            old = self._m.get(key)
            if old is not None and old.size != t.TOMBSTONE_FILE_SIZE:
                self.deleted_count += 1
                self.deleted_bytes += old.size
            elif old is None:
                self.file_count += 1
            self.content_bytes += size
            self._m[key] = NeedleValue(key, offset, size)
        else:
            old = self._m.get(key)
            if old is not None and old.size != t.TOMBSTONE_FILE_SIZE:
                self.deleted_count += 1
                self.deleted_bytes += old.size
            self._m[key] = NeedleValue(key, 0, t.TOMBSTONE_FILE_SIZE)

    def _log(self, key: int, offset: int, size: int) -> None:
        if self._idx is not None:
            self._idx.write(pack_entry(key, offset, size))
            self._idx.flush()

    # -- NeedleMapper API --

    def put(self, key: int, offset: int, size: int) -> None:
        self._apply(key, offset, size)
        self._log(key, offset, size)

    def get(self, key: int) -> NeedleValue | None:
        return self._m.get(key)

    def delete(self, key: int, offset: int) -> None:
        """offset = position of the tombstone record appended to .dat."""
        self._apply(key, offset, t.TOMBSTONE_FILE_SIZE)
        self._log(key, offset, t.TOMBSTONE_FILE_SIZE)

    def close(self) -> None:
        if self._idx is not None:
            self._idx.close()
            self._idx = None

    def destroy(self) -> None:
        self.close()
        if self.index_path and os.path.exists(self.index_path):
            os.remove(self.index_path)

    def __len__(self) -> int:
        return len(self._m)

    def keys(self):
        return self._m.keys()

    def index_file_size(self) -> int:
        if self.index_path and os.path.exists(self.index_path):
            return os.path.getsize(self.index_path)
        return 0

    @property
    def content_size(self) -> int:
        return self.content_bytes

    @property
    def deleted_size(self) -> int:
        return self.deleted_bytes


class _NativeMapAdapter:
    """dict-shaped facade over native.needle_map.NativeMap, storing
    NeedleValue payloads at 16 bytes/entry instead of ~200 for a dict.
    Key 0 (reserved as the native empty marker) gets a sideband slot."""

    def __init__(self):
        from ..native.needle_map import NativeMap
        self._nm = NativeMap()
        self._zero: NeedleValue | None = None

    def get(self, key: int) -> NeedleValue | None:
        if key == 0:
            return self._zero
        r = self._nm.get(key)
        if r is None:
            return None
        # offsets are stored /8 like the .idx format: a raw byte offset
        # would wrap the native uint32 field past 4 GiB (volumes default
        # to 30 GB)
        return NeedleValue(key, r[0] * t.NEEDLE_PADDING_SIZE, r[1])

    def __setitem__(self, key: int, val: "NeedleValue") -> None:
        if key == 0:
            self._zero = val
            return
        assert val.offset % t.NEEDLE_PADDING_SIZE == 0, val.offset
        units = val.offset // t.NEEDLE_PADDING_SIZE
        if units > 0xFFFFFFFF:
            # the native store's offset field is uint32; ctypes would
            # silently truncate and later reads would return the wrong
            # needle (silent corruption) — refuse loudly instead
            raise OverflowError(
                f"needle offset {val.offset} exceeds the native compact "
                f"map's 32 GiB range; use -index memory/disk for volumes "
                f"above 32 GiB")
        self._nm.set(key, units, val.size)

    def __len__(self) -> int:
        return len(self._nm) + (1 if self._zero is not None else 0)

    def keys(self):
        if self._zero is not None:
            yield 0
        for k, _, _ in self._nm.items():
            yield k

    def close(self) -> None:
        self._nm.close()


class CompactNeedleMap(MemoryNeedleMap):
    """MemoryNeedleMap on the native compact store (needle_map.c) — the
    CompactMap analog (compact_map.go:14-40; its perf test budgets 100M
    entries/volume, far beyond what a Python dict can hold)."""

    @staticmethod
    def _new_map():
        return _NativeMapAdapter()

    def close(self) -> None:
        super().close()
        self._m.close()


class _SqliteMapAdapter:
    """dict-shaped facade over a sqlite table, same contract as
    _NativeMapAdapter — lets DiskNeedleMap inherit every line of the
    counter/tombstone bookkeeping instead of forking it."""

    def __init__(self, path: str):
        import sqlite3
        import threading
        self.path = path
        # served from event-loop AND executor threads: one shared
        # connection guarded by a lock (sqlite objects are
        # thread-affine by default)
        self._lock = threading.Lock()
        # autocommit + WAL: each put is durable without explicit commits
        self._db = sqlite3.connect(path, isolation_level=None,
                                   check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._db.execute("CREATE TABLE IF NOT EXISTS needles("
                         "key INTEGER PRIMARY KEY, offset INTEGER, "
                         "size INTEGER)")
        # the .idx replay repopulates from scratch on every open (the
        # reference replays only the stale tail; full replay is simpler
        # and the .idx stays the source of truth)
        self._db.execute("DELETE FROM needles")

    def get(self, key: int) -> NeedleValue | None:
        with self._lock:
            row = self._db.execute(
                "SELECT offset, size FROM needles WHERE key=?",
                (key,)).fetchone()
        return NeedleValue(key, row[0], row[1]) if row else None

    def __setitem__(self, key: int, val: "NeedleValue") -> None:
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO needles VALUES (?,?,?)",
                (key, val.offset, val.size))

    def __len__(self) -> int:
        with self._lock:
            return self._db.execute(
                "SELECT COUNT(*) FROM needles").fetchone()[0]

    def keys(self):
        with self._lock:
            ks = [k for (k,) in self._db.execute(
                "SELECT key FROM needles ORDER BY key")]
        yield from ks

    def close(self) -> None:
        with self._lock:
            if self._db is not None:
                self._db.close()
                self._db = None

    def destroy_files(self) -> None:
        for suffix in ("", "-wal", "-shm"):
            if os.path.exists(self.path + suffix):
                os.remove(self.path + suffix)


class DiskNeedleMap(MemoryNeedleMap):
    """Disk-backed needle map for memory-constrained servers — the role
    of LevelDbNeedleMap (needle_map_leveldb.go: key index on disk, only
    counters in RAM), on sqlite instead of leveldb (no cgo-free leveldb
    in this image)."""

    def _new_map(self):
        import uuid
        path = ((self.index_path + ".sdb") if self.index_path
                else os.path.join("/tmp", f"swtpu-nm-{uuid.uuid4()}.sdb"))
        return _SqliteMapAdapter(path)

    def close(self) -> None:
        super().close()
        self._m.close()

    def destroy(self) -> None:
        super().destroy()
        self._m.destroy_files()


def best_needle_map(index_path: str | None = None,
                    kind: str = "auto") -> MemoryNeedleMap:
    """NeedleMapType selection (storage/needle_map.go:12-19, the
    -index=memory|leveldb flag):
    auto    — native CompactNeedleMap when built, else dict map
    memory  — dict map
    compact — native map (raises if the toolchain is unavailable)
    disk    — sqlite-backed DiskNeedleMap (LevelDbNeedleMap analog,
              near-zero RAM per entry)"""
    if kind == "memory":
        return MemoryNeedleMap(index_path)
    if kind == "disk":
        return DiskNeedleMap(index_path)
    if kind == "compact":
        if t.OFFSET_SIZE != 4:
            raise ValueError(
                "the native compact map stores 32-bit offsets and cannot "
                "index 5-byte-offset volumes; use -index memory/disk")
        return CompactNeedleMap(index_path)
    from ..native import needle_map as native_nm
    if native_nm.available() and t.OFFSET_SIZE == 4:
        return CompactNeedleMap(index_path)
    return MemoryNeedleMap(index_path)


class SortedFileNeedleMap:
    """Binary search over a sorted 16B-entry index (.sdx/.ecx).

    Reference: needle_map_sorted_file.go, ec_volume.go:203-228
    (SearchNeedleFromSortedIndex). Open writable for the EC delete path,
    which tombstones entries in place (MarkNeedleDeleted,
    ec_volume_delete.go:13-25).
    """

    def __init__(self, path: str, writable: bool = False):
        self.path = path
        self.writable = writable
        self._f = open(path, "r+b" if writable else "rb")
        self._size = os.path.getsize(path)
        assert self._size % t.NEEDLE_MAP_ENTRY_SIZE == 0, path
        self.count = self._size // t.NEEDLE_MAP_ENTRY_SIZE

    def _entry(self, i: int) -> tuple[int, int, int]:
        self._f.seek(i * t.NEEDLE_MAP_ENTRY_SIZE)
        return unpack_entry(self._f.read(t.NEEDLE_MAP_ENTRY_SIZE))

    def locate(self, key: int) -> int | None:
        """Entry index of key, or None."""
        lo, hi = 0, self.count - 1
        while lo <= hi:
            mid = (lo + hi) // 2
            k, _, _ = self._entry(mid)
            if k == key:
                return mid
            if k < key:
                lo = mid + 1
            else:
                hi = mid - 1
        return None

    def get_raw(self, key: int) -> tuple[int, int] | None:
        """(actual_offset, size) incl. tombstone sizes, or None if absent."""
        i = self.locate(key)
        if i is None:
            return None
        _, off, size = self._entry(i)
        return off, size

    def get(self, key: int) -> NeedleValue | None:
        raw = self.get_raw(key)
        if raw is None or raw[1] == t.TOMBSTONE_FILE_SIZE:
            return None
        return NeedleValue(key, raw[0], raw[1])

    def mark_deleted(self, key: int) -> bool:
        """Overwrite the entry's size with the tombstone marker in place."""
        assert self.writable, self.path
        i = self.locate(key)
        if i is None:
            return False
        self._f.seek(i * t.NEEDLE_MAP_ENTRY_SIZE + t.NEEDLE_ID_SIZE
                     + t.OFFSET_SIZE)
        self._f.write(t.TOMBSTONE_FILE_SIZE.to_bytes(4, "big"))
        self._f.flush()
        return True

    def close(self) -> None:
        self._f.close()


def write_sorted_index(entries: list[tuple[int, int, int]], path: str) -> None:
    """Write (key, actual_offset, size) entries as a sorted index file.

    Last entry per key wins (matching WriteSortedFileFromIdx semantics,
    ec_encoder.go:26-50: deleted needles keep their tombstone size).
    """
    latest: dict[int, tuple[int, int]] = {}
    for key, off, size in entries:
        latest[key] = (off, size)
    with open(path, "wb") as f:
        for key in sorted(latest):
            off, size = latest[key]
            f.write(pack_entry(key, off, size))
