"""Store: the per-volume-server manager of volumes and EC shards.

Reference: weed/storage/store.go:24-40 (DiskLocations, read/write/delete
routing, heartbeat building), disk_location.go (load volumes on start),
disk_location_ec.go (discover EC shards), store_ec.go (EC reads).
"""

from __future__ import annotations

import glob
import os
import re
import threading
import time

from ..ec import gf
from ..ec.ec_volume import EcVolume, NotFoundError as EcNotFound
from ..ec.locate import LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE
from ..pb import messages as pb
from ..util import events, failpoints, tracing
from . import types as t
from .needle import Needle
from .super_block import ReplicaPlacement
from .volume import (AlreadyDeleted, NotFound, Volume, VolumeError,
                     _Append)


_VOL_RE = re.compile(r"^(?:(?P<col>.+)_)?(?P<vid>\d+)\.dat$")
_EC_RE = re.compile(r"^(?:(?P<col>.+)_)?(?P<vid>\d+)\.ecx$")


class BatchBudgetExceeded(Exception):
    """Marker for batch-read rows beyond the response byte budget."""


class _VolumeAppender:
    """Group-commit coordinator for one volume: concurrent write_needle
    callers (executor threads) enqueue; whichever thread finds the
    commit slot free becomes the LEADER, drains the whole queue and
    lands it as one vectored append + one flush/fsync
    (Volume.append_needles). Natural batching: zero added latency for a
    lone writer, batches form exactly when writers contend. An optional
    window (-groupcommit.ms) makes the leader linger to deepen batches
    on bursty open-loop load."""

    __slots__ = ("cv", "queue", "busy", "window",
                 "batches", "appended", "max_batch")

    def __init__(self, window_s: float = 0.0) -> None:
        self.cv = threading.Condition()
        self.queue: list[_Append] = []
        self.busy = False
        self.window = window_s
        self.batches = 0
        self.appended = 0
        self.max_batch = 0

    def append(self, v: Volume, n: Needle) -> tuple[int, int, int]:
        item = _Append(n)
        with self.cv:
            self.queue.append(item)
            while self.busy and not item.done:
                self.cv.wait()
            if not item.done:
                self.busy = True      # this thread leads the next batch
        if not item.done:
            if self.window > 0:
                # deepen the batch: latecomers during the window join
                time.sleep(self.window)
            with self.cv:
                batch = self.queue
                self.queue = []
            try:
                for it in batch:
                    it.batch = len(batch)
                v.append_needles(batch)
                self.batches += 1
                self.appended += len(batch)
                if len(batch) > self.max_batch:
                    self.max_batch = len(batch)
                    if v.fsync and len(batch) > 1:
                        # deepest-yet batch sharing ONE durable fsync
                        # point: rate-bounded by construction (only
                        # new records journal), the flight recorder's
                        # view of group-commit behavior under load
                        events.record("fsync_upgrade", vid=v.vid,
                                      batch=len(batch))
            except BaseException as e:  # noqa: BLE001 — every waiter
                # must be released; append_needles only raises on bugs
                for it in batch:
                    if not it.done:
                        it.fail(e)
            finally:
                with self.cv:
                    self.busy = False
                    self.cv.notify_all()
        if item.exc is not None:
            raise item.exc
        assert item.result is not None
        # (offset, size, batch-size-this-write-rode-in)
        return item.result[0], item.result[1], item.batch

    def to_dict(self) -> dict:
        return {"batches": self.batches, "appended": self.appended,
                "max_batch": self.max_batch}


class Store:
    def __init__(self, dirs: list[str], ip: str = "localhost",
                 port: int = 0, public_url: str = "",
                 max_volume_counts: list[int] | None = None,
                 ec_large_block: int = LARGE_BLOCK_SIZE,
                 ec_small_block: int = SMALL_BLOCK_SIZE,
                 compaction_bytes_per_second: int = 0,
                 index_type: str = "auto",
                 partition: "tuple[int, int] | None" = None,
                 needle_cache_bytes: int = 0,
                 group_commit_window: float = 0.0,
                 fsync: bool = False,
                 ec_small_recover_bytes: int | None = None):
        # device-vs-host EC recover crossover (-ec.smallrecover flag);
        # None keeps EcVolume.SMALL_RECOVER_BYTES
        self.ec_small_recover_bytes = ec_small_recover_bytes
        # needle map kind for every owned volume (-index flag analog)
        self.index_type = index_type
        # hot-needle read cache (-cache.mem flag): parsed needles keyed
        # (vid, nid) under one byte budget, consulted by BOTH http paths
        # through read_needle/cached_needle; 0 disables every volume-side
        # read cache (needle + EC reconstruction)
        from ..util.chunk_cache import EcRecoverCache, NeedleCache
        # the configured budget is the TOTAL: hot needles get three
        # quarters, the EC reconstruction cache the remaining quarter —
        # an operator sizing -cache.mem must not find the process using
        # more than the flag says
        self.needle_cache = (NeedleCache(needle_cache_bytes * 3 // 4)
                             if needle_cache_bytes > 0 else None)
        # degraded-read reconstruction cache, shared across this store's
        # EC volumes (keys carry the vid): repeated reads of a lost
        # shard's hot intervals reuse the decoded bytes instead of
        # re-running the GF(256) transform
        self.ec_recover_cache = (EcRecoverCache(needle_cache_bytes // 4)
                                 if needle_cache_bytes > 0 else None)
        # (index, total) under -workers N: this store owns only volumes
        # with vid % total == index — workers sharing the data dirs open
        # disjoint volume sets, so needle maps and file handles stay
        # shared-nothing across processes (server/workers.py)
        if partition is not None and not 0 <= partition[0] < partition[1]:
            raise ValueError(f"bad store partition {partition}")
        self.partition = partition
        self.dirs = dirs
        # vacuum copy rate limit applied to every owned volume
        # (compactionBytePerSecond flag)
        self.compaction_bytes_per_second = compaction_bytes_per_second
        self.ip = ip
        self.port = port
        self.public_url = public_url or f"{ip}:{port}"
        self.max_volume_counts = max_volume_counts or [8] * len(dirs)
        from ..ec.locate import check_blocks
        check_blocks(ec_large_block, ec_small_block)
        self.ec_large_block = ec_large_block
        self.ec_small_block = ec_small_block
        self.volumes: dict[int, Volume] = {}
        self.ec_volumes: dict[int, EcVolume] = {}
        # note: _own() applies per-store volume policy on every Volume
        self._lock = threading.RLock()
        # deltas queued for the next heartbeat
        self.new_volumes: list[pb.VolumeInformationMessage] = []
        self.deleted_volumes: list[pb.VolumeInformationMessage] = []
        self.new_ec_shards: list[pb.VolumeEcShardInformationMessage] = []
        self.deleted_ec_shards: list[pb.VolumeEcShardInformationMessage] = []
        # group-commit append coordination: one appender per volume,
        # created lazily; window 0 = natural batching (-groupcommit.ms)
        self.group_commit_window = group_commit_window
        self.fsync = fsync
        self._appenders: dict[int, _VolumeAppender] = {}
        # remote shard readers injected by the volume server layer:
        # single-interval and batched (one request per holder) forms
        self.fetch_remote_shard = None
        self.fetch_remote_shard_batch = None
        # repair-planning hooks (volume server layer): peek the cached
        # holder map with no I/O (fn(vid) -> {sid: holder} | None) and
        # force one holder re-resolve after a failed batch gather
        # (fn(vid) -> None) — ec_volume._repair_plan / _recover_interval
        self.ec_holder_peek = None
        self.ec_refresh_holders = None
        for d in dirs:
            os.makedirs(d, exist_ok=True)
            self._load_existing(d)

    def owns(self, vid: int) -> bool:
        """True when this store's partition covers the volume id."""
        return self.partition is None or \
            vid % self.partition[1] == self.partition[0]

    # ---- loading (disk_location.go:79-113, disk_location_ec.go:115-161) ----

    def _load_existing(self, d: str) -> None:
        for path in glob.glob(os.path.join(d, "*.dat")):
            m = _VOL_RE.match(os.path.basename(path))
            if not m:
                continue
            vid = int(m.group("vid"))
            if not self.owns(vid):
                continue
            col = m.group("col") or ""
            try:
                self.volumes[vid] = self._own(Volume(
                    d, col, vid, create_if_missing=False,
                    needle_map_kind=self.index_type))
            except VolumeError:
                continue
        for path in glob.glob(os.path.join(d, "*.vif")):
            # tiered volume: .dat lives on a remote backend (.vif sidecar)
            m = _VOL_RE.match(os.path.basename(path)[:-4] + ".dat")
            if not m:
                continue
            vid = int(m.group("vid"))
            if vid in self.volumes or not self.owns(vid):
                continue
            col = m.group("col") or ""
            try:
                self.volumes[vid] = self._own(Volume(
                    d, col, vid, create_if_missing=False,
                    needle_map_kind=self.index_type))
            except Exception as e:  # noqa: BLE001 — any backend shape
                # backend unreachable or not configured yet: skip, but
                # an operator must be able to see WHY a tiered volume
                # did not come up
                from ..util import glog
                glog.warning("tiered volume %d (%s): not loaded: %s",
                             vid, path, e)
                continue
        for path in glob.glob(os.path.join(d, "*.ecx")):
            m = _EC_RE.match(os.path.basename(path))
            if not m:
                continue
            vid = int(m.group("vid"))
            if vid in self.volumes or not self.owns(vid):
                continue
            col = m.group("col") or ""
            try:
                self._mount_ec(d, col, vid)
            except OSError:
                continue

    def _mount_ec(self, d: str, collection: str, vid: int) -> EcVolume:
        ev = EcVolume(d, collection, vid,
                      large_block=self.ec_large_block,
                      small_block=self.ec_small_block,
                      fetch_remote=self._make_remote_fetcher(vid),
                      fetch_remote_batch=self._make_remote_batch_fetcher(
                          vid),
                      recover_cache=self.ec_recover_cache,
                      holder_peek=self._make_holder_peek(vid),
                      refresh_holders=self._make_holder_refresh(vid),
                      small_recover_bytes=self.ec_small_recover_bytes)
        self.ec_volumes[vid] = ev
        return ev

    def _make_holder_peek(self, vid: int):
        def peek():
            if self.ec_holder_peek is None:
                return None
            return self.ec_holder_peek(vid)
        return peek

    def _make_holder_refresh(self, vid: int):
        def refresh():
            if self.ec_refresh_holders is not None:
                self.ec_refresh_holders(vid)
        return refresh

    def _make_remote_fetcher(self, vid: int):
        def fetch(shard_id: int, offset: int, size: int):
            if self.fetch_remote_shard is None:
                return None
            return self.fetch_remote_shard(vid, shard_id, offset, size)
        return fetch

    def _make_remote_batch_fetcher(self, vid: int):
        def fetch_batch(reads: "list[tuple[int, int, int]]"):
            if self.fetch_remote_shard_batch is None:
                return None
            return self.fetch_remote_shard_batch(vid, reads)
        return fetch_batch

    # ---- volume lifecycle ----

    def _own(self, v: Volume) -> Volume:
        v.compaction_bytes_per_second = self.compaction_bytes_per_second
        v.fsync = self.fsync
        return v

    def add_volume(self, vid: int, collection: str = "",
                   replication: str = "", ttl: str = "",
                   preallocate: int = 0) -> Volume:
        with self._lock:
            if vid in self.volumes:
                raise VolumeError(f"volume {vid} already exists")
            if not self.owns(vid):
                raise VolumeError(
                    f"volume {vid} belongs to worker "
                    f"{vid % self.partition[1]}, not {self.partition[0]}")
            v = self._own(Volume(
                self.dirs[vid % len(self.dirs)], collection, vid,
                replica_placement=ReplicaPlacement.parse(replication),
                ttl=t.TTL.parse(ttl), preallocate=preallocate,
                needle_map_kind=self.index_type))
            self.volumes[vid] = v
            self.new_volumes.append(self._volume_message(v))
            events.record("volume_mount", vid=vid, kind="allocate",
                          collection=collection)
            return v

    def delete_volume(self, vid: int, collection: str = "") -> None:
        self.drop_cached_volume(vid)
        with self._lock:
            v = self.volumes.pop(vid, None)
            if v is not None:
                msg = self._volume_message(v)
                v.destroy()
                self.deleted_volumes.append(msg)
                events.record("volume_unmount", vid=vid, kind="delete")
                return
            # not mounted: still destroy the on-disk files — an unmount
            # followed by delete must not leave .dat/.idx behind to
            # resurrect the volume on the next mount or restart
            for d in self.dirs:
                base = os.path.join(
                    d, f"{collection}_{vid}" if collection else str(vid))
                # a tiered volume without keepLocal has no .dat — its
                # .vif/.idx still resurrect it on restart, so any sidecar
                # marks the volume as present for deletion
                if not any(os.path.exists(base + ext)
                           for ext in (".dat", ".vif", ".idx")):
                    continue
                try:
                    Volume(d, collection, vid, create_if_missing=False,
                           needle_map_kind=self.index_type).destroy()
                except Exception:  # noqa: BLE001 — damaged volume: the
                    # load path may refuse it, but delete must still win
                    for ext in (".dat", ".idx", ".vif", ".sdx",
                                ".cpd", ".cpx"):
                        p = base + ext
                        if os.path.exists(p):
                            os.remove(p)
                return
        raise VolumeError(f"volume {vid} not found")

    def mark_readonly(self, vid: int, read_only: bool = True) -> None:
        with self._lock:
            if vid in self.volumes:
                self.volumes[vid].read_only = read_only

    def mount_volume(self, collection: str, vid: int) -> None:
        """Load an on-disk volume (after a copy) — VolumeMount."""
        self.drop_cached_volume(vid)    # copied-in bytes may differ
        with self._lock:
            if vid in self.volumes:
                return
            if not self.owns(vid):
                raise VolumeError(
                    f"volume {vid} not in this worker's partition")
            for d in self.dirs:
                base = os.path.join(
                    d, f"{collection}_{vid}" if collection else str(vid))
                if os.path.exists(base + ".dat"):
                    v = self._own(Volume(d, collection, vid,
                                         create_if_missing=False,
                                         needle_map_kind=self.index_type))
                    self.volumes[vid] = v
                    self.new_volumes.append(self._volume_message(v))
                    events.record("volume_mount", vid=vid, kind="mount",
                                  collection=collection)
                    return
            raise VolumeError(f"volume {vid} not on disk")

    def unmount_volume(self, vid: int) -> None:
        self.drop_cached_volume(vid)
        with self._lock:
            v = self.volumes.pop(vid, None)
            if v is not None:
                self.deleted_volumes.append(self._volume_message(v))
                v.close()
                events.record("volume_unmount", vid=vid, kind="unmount")

    # ---- data plane ----

    def _appender_for(self, vid: int) -> _VolumeAppender:
        ap = self._appenders.get(vid)
        if ap is None:
            with self._lock:
                ap = self._appenders.setdefault(
                    vid, _VolumeAppender(self.group_commit_window))
        return ap

    def write_needle(self, vid: int, n: Needle) -> tuple[int, int]:
        # chaos site `store.write`: sits below BOTH wire shapes (the
        # unified wire layer feeds it from the aiohttp and raw
        # listeners alike), and fires per CALLER — an injected fault
        # fails only this writer, never the whole group-commit batch.
        # One dict-emptiness check when disarmed.
        failpoints.sync_fail("store.write")
        with tracing.start("store", "write", vid=vid) as sp:
            v = self.volumes.get(vid)
            if v is None:
                sp.status = "404"
                raise NotFound(f"volume {vid} not found")
            # group commit: concurrent writers to this volume coalesce
            # into one vectored append + one shared flush/fsync
            item = self._appender_for(vid).append(v, n)
            sp.nbytes = len(n.data)
            if item[2] > 1:
                sp.set("gc_batch", item[2])
            result = (item[0], item[1])
            # AFTER the durable append: dropping first would let a racing
            # reader re-populate the old bytes between drop and write
            if self.needle_cache is not None:
                self.needle_cache.invalidate(vid, n.id)
            return result

    def group_commit_stats(self) -> dict:
        """Aggregate group-commit counters across this store's volumes
        (whole-process view for /status and the wire smoke)."""
        out = {"batches": 0, "appended": 0, "max_batch": 0}
        for ap in list(self._appenders.values()):
            out["batches"] += ap.batches
            out["appended"] += ap.appended
            out["max_batch"] = max(out["max_batch"], ap.max_batch)
        return out

    def _cached(self, vid: int, needle_id: int, cookie: int | None,
                count_miss: bool = True,
                count_hit: bool = True) -> Needle | None:
        """Cache peek; None means the slow path must decide (miss,
        cookie mismatch, expiry — the disk read raises the right
        error for the last two). A hit is counted only AFTER the
        cookie/expiry checks pass, so unservable entries don't inflate
        the hit rate; the miss is counted once, by the slow path."""
        nc = self.needle_cache
        if nc is None:
            return None
        n = nc.peek(vid, needle_id)
        if n is None or (cookie is not None and n.cookie != cookie) \
                or n.has_expired():
            if count_miss:
                nc.miss()
            return None
        if count_hit:
            nc.hit(n)
        return n

    def cached_needle(self, vid: int, needle_id: int,
                      cookie: int | None = None,
                      count: bool = True) -> Needle | None:
        """Synchronous hot-path peek for the event-loop read handlers:
        a hit answers without the executor round-trip or any disk I/O.
        Declines (None) whenever the `store.read` chaos site is armed so
        injected read faults keep firing under cache-hot load.
        ``count=False`` defers all accounting to the caller — for the
        fasthttp path, whose replay-to-aiohttp branch would otherwise
        count the same client request twice."""
        if failpoints.pending("store.read"):
            return None
        # the slow path that follows a peek miss counts it; counting
        # here too would double every cold read's miss
        return self._cached(vid, needle_id, cookie, count_miss=False,
                            count_hit=count)

    def read_needle(self, vid: int, needle_id: int,
                    cookie: int | None = None) -> Needle:
        failpoints.sync_fail("store.read")  # chaos site (see store.write)
        # the store span records WHERE the bytes came from — cache,
        # pread, sendfile ref, or EC gather/reconstruct — the
        # per-request attribution the degraded-read literature says
        # dominates tail latency
        with tracing.start("store", "read", vid=vid) as sp:
            return self._read_inner(vid, needle_id, cookie, sp)

    def read_needle_ex(self, vid: int, needle_id: int,
                       cookie: int | None = None, ref_min: int = 0):
        """Read for the unified wire layer: when ``ref_min`` > 0 and
        the needle is a plain local-volume record at least that big,
        returns ``(meta_needle, NeedleRef)`` so the caller can
        zero-copy the body with sendfile; otherwise ``(needle, None)``
        via the exact buffered path. One failpoint fire, one span,
        one executor hop either way."""
        failpoints.sync_fail("store.read")
        with tracing.start("store", "read", vid=vid) as sp:
            if ref_min > 0:
                v = self.volumes.get(vid)
                if v is not None:
                    try:
                        got = v.read_needle_ref(needle_id, cookie,
                                                ref_min)
                    except (NotFound, AlreadyDeleted):
                        sp.status = "404"
                        raise
                    except OSError:
                        got = None   # racing unmount etc: buffered path
                    if got is not None:
                        n, ref = got
                        sp.set("source", "sendfile")
                        sp.nbytes = ref.length
                        return n, ref
            return self._read_inner(vid, needle_id, cookie, sp), None

    def read_needles(self, specs: "list[tuple[int, int, int | None]]",
                     byte_budget: "int | None" = None
                     ) -> "list[Needle | Exception]":
        """Batch read: resolve many (vid, needle_id, cookie) specs in
        ONE executor round trip — the per-request pread coalescing the
        batch GET endpoint rides. Per-needle failures come back as the
        exception instead of poisoning the whole batch; once the byte
        budget is spent, remaining rows come back BatchBudgetExceeded
        (the server must not buffer an unbounded response for one
        request — those rows fall back to streamed single GETs)."""
        out: "list[Needle | Exception]" = []
        used = 0
        for vid, needle_id, cookie in specs:
            if byte_budget is not None and used > byte_budget:
                out.append(BatchBudgetExceeded(
                    f"batch byte budget exhausted after {used} bytes"))
                continue
            try:
                n = self.read_needle(vid, needle_id, cookie)
                used += len(n.data)
                out.append(n)
            except Exception as e:  # noqa: BLE001 — per-row verdicts:
                # the caller maps each to its row's HTTP status
                out.append(e)
        return out

    def _read_inner(self, vid: int, needle_id: int,
                    cookie: int | None, sp) -> Needle:
        n = self._cached(vid, needle_id, cookie)
        if n is not None:
            sp.set("source", "cache")
            sp.nbytes = len(n.data)
            return n
        # snapshot the volume's mutation generation BEFORE the disk
        # read: a write/delete landing between our read and our put
        # bumps it, and put() then refuses the stale fill
        nc = self.needle_cache
        gen = nc.generation(vid) if nc is not None else 0
        v = self.volumes.get(vid)
        if v is not None:
            try:
                n = v.read_needle(needle_id, cookie)
            except OSError:
                if vid not in self.volumes:
                    # the volume was destroyed mid-read (TTL
                    # reclamation / admin delete): a clean 404, not
                    # a bad-file-descriptor 500
                    sp.status = "404"
                    raise NotFound(f"volume {vid} was removed")
                raise
            if nc is not None:
                nc.put(vid, needle_id, n, gen=gen)
            sp.set("source", "pread")
            sp.nbytes = len(n.data)
            return n
        ev = self.ec_volumes.get(vid)
        if ev is not None:
            try:
                n = ev.read_needle(needle_id, cookie)
            except EcNotFound as e:
                sp.status = "404"
                raise NotFound(str(e))
            if nc is not None:
                nc.put(vid, needle_id, n, gen=gen)
            sp.set("source", "ec")
            sp.nbytes = len(n.data)
            return n
        sp.status = "404"
        raise NotFound(f"volume {vid} not found")

    def delete_needle(self, vid: int, n: Needle) -> int:
        v = self.volumes.get(vid)
        if v is not None:
            size = v.delete_needle(n)
            if self.needle_cache is not None:
                self.needle_cache.invalidate(vid, n.id)
            return size
        ev = self.ec_volumes.get(vid)
        if ev is not None:
            ev.delete_needle(n.id)
            if self.needle_cache is not None:
                self.needle_cache.invalidate(vid, n.id)
            return 0
        raise NotFound(f"volume {vid} not found")

    def drop_cached_volume(self, vid: int) -> None:
        """Volume-wide cache invalidation: vacuum commit, tail-receive
        apply, unmount/delete — any event that may rewrite needles
        without going through write_needle/delete_needle."""
        if self.needle_cache is not None:
            self.needle_cache.drop_volume(vid)

    def commit_compaction(self, vid: int) -> None:
        """Vacuum commit + strict cache invalidation: the .dat/.idx
        swap moves every surviving needle, so all cached entries for
        the volume are dropped (a cached needle MUST miss after the
        volume is vacuumed)."""
        from . import vacuum
        v = self.volumes.get(vid)
        if v is None:
            raise NotFound(f"volume {vid} not found")
        vacuum.commit_compact(v)
        self.drop_cached_volume(vid)
        events.record("volume_vacuum", vid=vid)

    def has_volume(self, vid: int) -> bool:
        return vid in self.volumes or vid in self.ec_volumes

    # ---- EC shard lifecycle (server side of ec.encode/rebuild) ----

    def _drop_ec_recover(self, vid: int) -> None:
        """The reconstruction cache is store-wide, so entries outlive
        any one EcVolume object: a re-encoded volume remounted at the
        same vid must not serve the old generation's decoded bytes.
        drop_volume also bumps the vid's generation, which fences any
        reconstruction fill still in flight against the old shards."""
        if self.ec_recover_cache is not None:
            self.ec_recover_cache.drop_volume(vid)

    def mount_ec_shards(self, collection: str, vid: int) -> list[int]:
        self._drop_ec_recover(vid)
        self.drop_cached_volume(vid)
        with self._lock:
            if not self.owns(vid):
                raise VolumeError(
                    f"ec volume {vid} not in this worker's partition")
            ev = self.ec_volumes.get(vid)
            if ev is not None:
                ev.close()
            for d in self.dirs:
                base = os.path.join(
                    d, f"{collection}_{vid}" if collection else str(vid))
                if os.path.exists(base + ".ecx"):
                    ev = self._mount_ec(d, collection, vid)
                    bits = 0
                    for sid in ev.shards:
                        bits = pb.shard_bits_add(bits, sid)
                    self.new_ec_shards.append(
                        pb.VolumeEcShardInformationMessage(
                            id=vid, collection=collection,
                            ec_index_bits=bits))
                    events.record("ec_mount", vid=vid,
                                  shards=sorted(ev.shards))
                    return sorted(ev.shards)
            raise VolumeError(f"no .ecx found for ec volume {vid}")

    def unmount_ec_shards(self, vid: int, shard_ids: list[int] | None = None
                          ) -> None:
        self._drop_ec_recover(vid)
        self.drop_cached_volume(vid)
        with self._lock:
            ev = self.ec_volumes.get(vid)
            if ev is None:
                return
            bits = 0
            removed = shard_ids if shard_ids is not None else list(ev.shards)
            for sid in removed:
                f = ev.shards.pop(sid, None)
                if f is not None:
                    f.close()
                bits = pb.shard_bits_add(bits, sid)
            # the missing-set changed: repair plans keyed on it are
            # stale (a plan could otherwise route a recover at a
            # just-closed local fd)
            ev.invalidate_plans()
            self.deleted_ec_shards.append(
                pb.VolumeEcShardInformationMessage(
                    id=vid, collection=ev.collection, ec_index_bits=bits))
            events.record("ec_unmount", vid=vid, shards=removed)
            if not ev.shards:
                ev.close()
                del self.ec_volumes[vid]

    def read_ec_shard_interval(self, vid: int, shard_id: int,
                               offset: int, size: int) -> bytes | None:
        ev = self.ec_volumes.get(vid)
        if ev is None:
            return None
        f = ev.shards.get(shard_id)
        if f is None:
            return None
        failpoints.sync_fail("store.ec_read")
        data = os.pread(f.fileno(), size, offset)
        return data + b"\x00" * (size - len(data))

    def read_ec_shard_intervals(
            self, vid: int, reads: "list[tuple[int, int, int]]"
            ) -> "list[bytes | None]":
        """Batched shard-interval reads for the wire batch path: one
        request (and one executor hop) gathers many (shard, offset,
        size) intervals — the k-fetch fan-out of a degraded read costs
        one round trip per HOLDER instead of one per interval."""
        return [self.read_ec_shard_interval(vid, sid, off, size)
                for sid, off, size in reads]

    # ---- heartbeat (store.go:165-219 CollectHeartbeat) ----

    def _volume_message(self, v: Volume) -> pb.VolumeInformationMessage:
        st = v.stat()
        return pb.VolumeInformationMessage(
            id=v.vid, size=st.size, collection=v.collection,
            file_count=st.file_count, delete_count=st.deleted_count,
            deleted_byte_count=st.deleted_bytes, read_only=v.read_only,
            replica_placement=v.super_block.replica_placement.to_byte(),
            version=v.version, ttl=v.ttl.to_uint32(),
            compact_revision=v.super_block.compaction_revision,
            remote=v.is_remote)

    # minutes an expired TTL volume lingers before its files are
    # destroyed (store.go MAX_TTL_VOLUME_REMOVAL_DELAY); actual delay is
    # min(this, ttl/10) like the reference's expiredLongEnough
    MAX_TTL_REMOVAL_DELAY_M = 10.0

    def _ttl_lived_minutes(self, v) -> float | None:
        """Minutes past this TTL volume's expiry, or None when the
        volume has no TTL / no content yet (volume.go expired())."""
        ttl_m = v.ttl.minutes
        if not ttl_m or not v.last_modified_ts or v.data_size() <= 8:
            return None
        lived_m = (time.time() - v.last_modified_ts) / 60
        return lived_m - ttl_m if lived_m > ttl_m else None

    def collect_heartbeat(self, data_center: str = "",
                          rack: str = "") -> pb.Heartbeat:
        with self._lock:
            # TTL volume reclamation rides the heartbeat walk like the
            # reference (store.go:165-200): an expired volume stops
            # being advertised immediately and its files are destroyed
            # once it has lingered past the removal delay
            expired_now: list[int] = []
            active = {}
            for vid, v in self.volumes.items():
                over_m = self._ttl_lived_minutes(v)
                if over_m is None:
                    active[vid] = v
                elif over_m > min(self.MAX_TTL_REMOVAL_DELAY_M,
                                  v.ttl.minutes / 10):
                    expired_now.append(vid)
                # else: expired but within the grace window — drop from
                # the advertised set, keep the files for now
            for vid in expired_now:
                v = self.volumes.pop(vid)
                self.deleted_volumes.append(self._volume_message(v))
                v.destroy()
                self.drop_cached_volume(vid)
            volumes = [self._volume_message(v) for v in active.values()]
            ec_msgs = []
            for vid, ev in self.ec_volumes.items():
                bits = 0
                for sid in ev.shards:
                    bits = pb.shard_bits_add(bits, sid)
                ec_msgs.append(pb.VolumeEcShardInformationMessage(
                    id=vid, collection=ev.collection, ec_index_bits=bits))
            max_key = max((v.nm.max_file_key
                           for v in self.volumes.values()), default=0)
            # under -workers the slot budget is split across the worker
            # fleet, or the master would see N× the real disk capacity
            slots = sum(self.max_volume_counts)
            if self.partition is not None:
                idx, total = self.partition
                slots = slots // total + (1 if idx < slots % total else 0)
            hb = pb.Heartbeat(
                ip=self.ip, port=self.port, public_url=self.public_url,
                max_volume_count=slots,
                max_file_key=max_key,
                data_center=data_center, rack=rack,
                volumes=volumes,
                new_volumes=self.new_volumes[:],
                deleted_volumes=self.deleted_volumes[:],
                ec_shards=ec_msgs,
                has_no_volumes=not volumes,
                has_no_ec_shards=not ec_msgs,
            )
            self.new_volumes.clear()
            self.deleted_volumes.clear()
            hb.new_ec_shards = self.new_ec_shards[:]
            hb.deleted_ec_shards = self.deleted_ec_shards[:]
            self.new_ec_shards.clear()
            self.deleted_ec_shards.clear()
            return hb

    def close(self) -> None:
        with self._lock:
            for v in self.volumes.values():
                v.close()
            for ev in self.ec_volumes.values():
                ev.close()
