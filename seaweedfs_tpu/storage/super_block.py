"""Volume superblock + replica placement encoding.

Reference: weed/storage/super_block/super_block.go:16-39 (8-byte header:
version, replica-placement byte, ttl 2B, compaction revision 2B, 2B
reserved/extra-size) and replica_placement.go:8-31 ("xyz" digit policy:
x = copies on different DCs, y = different racks same DC, z = different
servers same rack).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from . import types as t

SUPER_BLOCK_SIZE = 8


@dataclass(frozen=True)
class ReplicaPlacement:
    same_rack: int = 0       # z
    diff_rack: int = 0       # y
    diff_dc: int = 0         # x

    @classmethod
    def parse(cls, s: str | int | None) -> "ReplicaPlacement":
        if s is None or s == "":
            return cls()
        if isinstance(s, int):
            s = f"{s:03d}"
        if len(s) != 3 or not s.isdigit():
            raise ValueError(f"invalid replication {s!r}")
        x, y, z = (int(c) for c in s)
        if x > 2 or y > 2 or z > 2:
            raise ValueError(f"replication counts must be <= 2: {s!r}")
        return cls(same_rack=z, diff_rack=y, diff_dc=x)

    def to_byte(self) -> int:
        return self.diff_dc * 100 + self.diff_rack * 10 + self.same_rack

    @classmethod
    def from_byte(cls, b: int) -> "ReplicaPlacement":
        return cls(same_rack=b % 10, diff_rack=(b // 10) % 10,
                   diff_dc=b // 100)

    @property
    def copy_count(self) -> int:
        return self.diff_dc + self.diff_rack + self.same_rack + 1

    def __str__(self) -> str:
        return f"{self.diff_dc}{self.diff_rack}{self.same_rack}"


@dataclass
class SuperBlock:
    version: int = t.CURRENT_VERSION
    replica_placement: ReplicaPlacement = field(default_factory=ReplicaPlacement)
    ttl: t.TTL = field(default_factory=t.TTL)
    compaction_revision: int = 0

    def to_bytes(self) -> bytes:
        out = bytearray(SUPER_BLOCK_SIZE)
        out[0] = self.version
        out[1] = self.replica_placement.to_byte()
        out[2:4] = self.ttl.to_bytes()
        out[4:6] = self.compaction_revision.to_bytes(2, "big")
        return bytes(out)

    @classmethod
    def from_bytes(cls, b: bytes) -> "SuperBlock":
        if len(b) < SUPER_BLOCK_SIZE:
            raise ValueError("superblock too short")
        return cls(
            version=b[0],
            replica_placement=ReplicaPlacement.from_byte(b[1]),
            ttl=t.TTL.from_bytes(b[2:4]),
            compaction_revision=int.from_bytes(b[4:6], "big"),
        )
