"""Core storage scalar types and binary constants.

Semantics follow the reference's weed/storage/types/ (needle_types.go,
needle_id_type.go, offset_4bytes.go) and weed/storage/needle/
(volume_ttl.go, volume_id.go, file_id.go): big-endian on-disk integers,
8-byte-aligned needle offsets stored as 4-byte multiples-of-8 (32GB max
volume; the 5-byte build is a config knob here, not a build tag).
"""

from __future__ import annotations

import re
import secrets
from dataclasses import dataclass

COOKIE_SIZE = 4
NEEDLE_ID_SIZE = 8
SIZE_SIZE = 4
# Needle-offset width in .idx/.ecx entries. 4 bytes (units of 8) caps a
# volume at 32 GiB; 5 bytes at 8 TiB. The reference switches this with the
# `5BytesOffset` build tag (offset_5bytes.go:14-16, Makefile:16) — i.e. a
# process-wide constant, because every index entry in the store shares one
# width. Here it is a runtime switch: set_offset_size(5), or
# SWTPU_OFFSET_BYTES=5 in the environment (read by the CLI at startup).
OFFSET_SIZE = 4
NEEDLE_HEADER_SIZE = COOKIE_SIZE + NEEDLE_ID_SIZE + SIZE_SIZE  # 16
NEEDLE_MAP_ENTRY_SIZE = NEEDLE_ID_SIZE + OFFSET_SIZE + SIZE_SIZE  # 16
TIMESTAMP_SIZE = 8
NEEDLE_PADDING_SIZE = 8
NEEDLE_CHECKSUM_SIZE = 4
TOMBSTONE_FILE_SIZE = 0xFFFFFFFF


def max_volume_size() -> int:
    """Largest addressable byte offset + 1 for the current offset width
    (offset_4bytes.go: 32GB; offset_5bytes.go:14-16: 8TB)."""
    return (1 << (8 * OFFSET_SIZE)) * NEEDLE_PADDING_SIZE


def set_offset_size(n: int) -> None:
    """Switch the process-wide index entry offset width (4 or 5 bytes).

    Must be called before any volume/index is opened: mixing widths in one
    process would mis-parse every existing entry, exactly like linking a
    5BytesOffset build against a 4-byte .idx in the reference.
    """
    if n not in (4, 5):
        raise ValueError(f"offset size must be 4 or 5, got {n}")
    global OFFSET_SIZE, NEEDLE_MAP_ENTRY_SIZE
    OFFSET_SIZE = n
    NEEDLE_MAP_ENTRY_SIZE = NEEDLE_ID_SIZE + n + SIZE_SIZE

# Needle format versions (weed/storage/needle/volume_version.go)
VERSION1 = 1
VERSION2 = 2
VERSION3 = 3
CURRENT_VERSION = VERSION3


def random_cookie() -> int:
    return secrets.randbits(32)


def offset_to_bytes(actual_offset: int) -> bytes:
    """actual byte offset -> stored offset (units of 8 bytes, current
    width). Raises instead of silently wrapping past the volume cap.

    5-byte width follows the reference layout (offset_5bytes.go:18-24):
    low 32 bits big-endian, then the high byte LAST."""
    assert actual_offset % NEEDLE_PADDING_SIZE == 0, actual_offset
    units = actual_offset // NEEDLE_PADDING_SIZE
    if units >= 1 << (8 * OFFSET_SIZE):
        raise OverflowError(
            f"offset {actual_offset} exceeds the {OFFSET_SIZE}-byte index "
            f"limit ({max_volume_size()} bytes); use set_offset_size(5)")
    if OFFSET_SIZE == 4:
        return units.to_bytes(4, "big")
    return (units & 0xFFFFFFFF).to_bytes(4, "big") + bytes([units >> 32])


def offset_from_bytes(b: bytes) -> int:
    """Stored offset (current width) -> actual byte offset."""
    units = int.from_bytes(b[:4], "big")
    if OFFSET_SIZE == 5:
        units |= b[4] << 32
    return units * NEEDLE_PADDING_SIZE


def padding_length(needle_size: int, version: int) -> int:
    if version == VERSION3:
        used = NEEDLE_HEADER_SIZE + needle_size + NEEDLE_CHECKSUM_SIZE + TIMESTAMP_SIZE
    else:
        used = NEEDLE_HEADER_SIZE + needle_size + NEEDLE_CHECKSUM_SIZE
    return (NEEDLE_PADDING_SIZE - used % NEEDLE_PADDING_SIZE) % NEEDLE_PADDING_SIZE


def actual_size(needle_size: int, version: int) -> int:
    """Total on-disk record length for a needle body size."""
    if version == VERSION3:
        base = NEEDLE_HEADER_SIZE + needle_size + NEEDLE_CHECKSUM_SIZE + TIMESTAMP_SIZE
    else:
        base = NEEDLE_HEADER_SIZE + needle_size + NEEDLE_CHECKSUM_SIZE
    return base + padding_length(needle_size, version)


# --------------------------------------------------------------------------
# TTL (volume_ttl.go): 2 bytes, count + unit.
# --------------------------------------------------------------------------

TTL_EMPTY = 0
TTL_MINUTE = 1
TTL_HOUR = 2
TTL_DAY = 3
TTL_WEEK = 4
TTL_MONTH = 5
TTL_YEAR = 6

_UNIT_CHARS = {"m": TTL_MINUTE, "h": TTL_HOUR, "d": TTL_DAY,
               "w": TTL_WEEK, "M": TTL_MONTH, "y": TTL_YEAR}
_CHAR_UNITS = {v: k for k, v in _UNIT_CHARS.items()}
_UNIT_MINUTES = {TTL_EMPTY: 0, TTL_MINUTE: 1, TTL_HOUR: 60, TTL_DAY: 1440,
                 TTL_WEEK: 10080, TTL_MONTH: 43200, TTL_YEAR: 525600}


@dataclass(frozen=True)
class TTL:
    count: int = 0
    unit: int = TTL_EMPTY

    @classmethod
    def parse(cls, s: str | None) -> "TTL":
        if not s:
            return cls()
        m = re.fullmatch(r"(\d+)([mhdwMy]?)", s)
        if not m:
            raise ValueError(f"invalid ttl: {s!r}")
        count = int(m.group(1))
        unit = _UNIT_CHARS.get(m.group(2) or "m", TTL_MINUTE)
        if not 0 <= count <= 255:
            raise ValueError(f"ttl count out of range: {s!r}")
        return cls(count, unit if count else TTL_EMPTY)

    @classmethod
    def from_bytes(cls, b: bytes) -> "TTL":
        if b[0] == 0 and b[1] == 0:
            return cls()
        return cls(b[0], b[1])

    @classmethod
    def from_uint32(cls, v: int) -> "TTL":
        return cls.from_bytes(bytes([(v >> 8) & 0xFF, v & 0xFF]))

    def to_bytes(self) -> bytes:
        return bytes([self.count & 0xFF, self.unit & 0xFF])

    def to_uint32(self) -> int:
        if self.count == 0:
            return 0
        return (self.count << 8) | self.unit

    @property
    def minutes(self) -> int:
        return self.count * _UNIT_MINUTES.get(self.unit, 0)

    def __str__(self) -> str:
        if self.count == 0:
            return ""
        return f"{self.count}{_CHAR_UNITS.get(self.unit, 'm')}"


# --------------------------------------------------------------------------
# FileId: "volumeId,needleKeyHex+cookieHex" (file_id.go:60-72)
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class FileId:
    volume_id: int
    key: int
    cookie: int

    def __str__(self) -> str:
        raw = self.key.to_bytes(NEEDLE_ID_SIZE, "big") + \
            self.cookie.to_bytes(COOKIE_SIZE, "big")
        stripped = raw.lstrip(b"\x00")
        if not stripped:
            stripped = b"\x00"
        return f"{self.volume_id},{stripped.hex()}"

    @classmethod
    def parse(cls, fid: str) -> "FileId":
        if "," not in fid:
            raise ValueError(f"wrong fid format: {fid!r}")
        vid_s, key_cookie = fid.split(",", 1)
        # needle deletion replication appends "_<count>" suffixes; strip.
        key_cookie = key_cookie.split("_")[0]
        if len(key_cookie) <= 8:
            raise ValueError(f"key-cookie too short: {fid!r}")
        if len(key_cookie) % 2 == 1:
            key_cookie = "0" + key_cookie
        raw = bytes.fromhex(key_cookie)
        return cls(volume_id=int(vid_s),
                   key=int.from_bytes(raw[:-COOKIE_SIZE], "big"),
                   cookie=int.from_bytes(raw[-COOKIE_SIZE:], "big"))
