"""Vacuum (GC/compaction): reclaim space from deleted/expired needles.

Reference: weed/storage/volume_vacuum.go — Compact/Compact2 copy live
needles to .cpd/.cpx while writes continue; CommitCompact replays writes
that raced the compaction (makeupDiff, :157-294) before atomically renaming
the copies over the originals. The superblock CompactionRevision increments
so stale replicas are detectable (super_block.go:28).
"""

from __future__ import annotations

import os
import struct
import time

from . import types as t
from .needle import Needle
from .needle_map import pack_entry, walk_index_blob
from .super_block import SuperBlock
from .volume import Volume

class VacuumError(Exception):
    pass


def compact(v: Volume) -> None:
    """Copy live needles to .cpd/.cpx based on the needle map (the
    Compact2 strategy, volume_vacuum.go:59-77). Leaves originals alive for
    concurrent traffic; remembers the watermark for makeup_diff."""
    base = v.file_name()
    from . import backend as _backend
    if v.is_remote or _backend.load_volume_info(base) is not None:
        raise VacuumError(
            f"volume {v.vid} is tiered; tier.download before compacting")
    v.last_compact_index_offset = v.nm.index_file_size()
    v.last_compact_revision = v.super_block.compaction_revision
    now = time.time()

    sb = SuperBlock(version=v.version,
                    replica_placement=v.super_block.replica_placement,
                    ttl=v.super_block.ttl,
                    compaction_revision=v.super_block.compaction_revision + 1)
    # separate read-only fd: never share seek state with live writers
    throttle = _Throttler(v.compaction_bytes_per_second)
    with open(base + ".dat", "rb") as src, \
            open(base + ".cpd", "wb") as dst, \
            open(base + ".cpx", "wb") as idx:
        dst.write(sb.to_bytes())
        new_offset = 8
        for key in sorted(v.nm.keys()):
            nv = v.nm.get(key)
            if nv is None or nv.offset == 0 or \
                    nv.size == t.TOMBSTONE_FILE_SIZE:
                continue
            blob_len = t.actual_size(nv.size, v.version)
            blob = os.pread(src.fileno(), blob_len, nv.offset)
            n = Needle.from_bytes(blob, v.version, check_crc=False)
            if n.has_expired(now):
                continue
            dst.write(blob)
            idx.write(pack_entry(key, new_offset, nv.size))
            new_offset += blob_len
            throttle.maybe_sleep(blob_len)


class _Throttler:
    """Compaction rate limiter (util/throttler.go): sleep whenever the
    copied-bytes budget for the elapsed wall time is exceeded, so vacuum
    doesn't starve live reads on the same spindle. 0 = unthrottled."""

    def __init__(self, bytes_per_second: int):
        self.bps = bytes_per_second
        self.start = time.monotonic()
        self.copied = 0

    def maybe_sleep(self, n: int) -> None:
        if self.bps <= 0:
            return
        self.copied += n
        # sleep the FULL deficit: capping per-call would let large
        # needles outrun the budget (the deficit is never drained)
        ahead = self.copied / self.bps - (time.monotonic() - self.start)
        if ahead > 0:
            time.sleep(ahead)


def commit_compact(v: Volume) -> None:
    """makeupDiff + rename + reload (CommitCompact, volume_vacuum.go:78-133).
    """
    base = v.file_name()
    if not os.path.exists(base + ".cpd"):
        raise VacuumError(f"no compaction in progress for volume {v.vid}")
    with v._lock:
        _makeup_diff(v, base + ".cpd", base + ".cpx",
                     base + ".dat", base + ".idx")
        v.nm.close()
        v._dat.close()
        os.rename(base + ".cpd", base + ".dat")
        os.rename(base + ".cpx", base + ".idx")
        v.reload()  # preserves v._lock (writers blocked on it stay safe)


def cleanup_compact(v: Volume) -> None:
    base = v.file_name()
    for ext in (".cpd", ".cpx"):
        if os.path.exists(base + ext):
            os.remove(base + ext)


def _makeup_diff(v: Volume, new_dat: str, new_idx: str,
                 old_dat: str, old_idx: str) -> None:
    """Replay idx entries appended after the compaction snapshot
    (makeupDiff, volume_vacuum.go:157-294)."""
    index_size = os.path.getsize(old_idx)
    watermark = getattr(v, "last_compact_index_offset", 0)
    if index_size == 0 or index_size <= watermark:
        return
    with open(old_dat, "rb") as f:
        f.seek(0)
        old_rev = SuperBlock.from_bytes(f.read(8)).compaction_revision
    if old_rev != getattr(v, "last_compact_revision", old_rev):
        raise VacuumError(
            f"old dat compact revision {old_rev} != expected "
            f"{v.last_compact_revision}")

    # newest entry per key among the racing appends (scan tail backwards)
    with open(old_idx, "rb") as f:
        f.seek(watermark)
        tail = f.read()
    updates: dict[int, tuple[int, int]] = {}
    for key, off, size in walk_index_blob(tail):
        updates[key] = (off, size)  # later entries win

    if not updates:
        return
    with open(new_dat, "rb+") as dst, open(new_idx, "ab") as idx, \
            open(old_dat, "rb") as src:
        dst.seek(0)
        new_rev = SuperBlock.from_bytes(dst.read(8)).compaction_revision
        if old_rev + 1 != new_rev:
            raise VacuumError(
                f"compacted dat revision {new_rev} != old {old_rev}+1")
        for key, (off, size) in updates.items():
            dst.seek(0, os.SEEK_END)
            pos = dst.tell()
            if pos % t.NEEDLE_PADDING_SIZE:
                pad = t.NEEDLE_PADDING_SIZE - pos % t.NEEDLE_PADDING_SIZE
                dst.write(b"\x00" * pad)
                pos += pad
            if off > 0 and size not in (0, t.TOMBSTONE_FILE_SIZE):
                src.seek(off)
                dst.write(src.read(t.actual_size(size, v.version)))
                idx.write(pack_entry(key, pos, size))
            else:
                tomb = Needle(cookie=0x12345678, id=key)
                dst.write(tomb.to_bytes(v.version))
                idx.write(pack_entry(key, 0, t.TOMBSTONE_FILE_SIZE))
