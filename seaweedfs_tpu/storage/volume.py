"""Volume: one append-only .dat blob file + .idx needle index.

Reference semantics: weed/storage/volume.go:21-45,
volume_read_write.go:66-172 (append-only writes, tombstone deletes, O(1)
reads via the needle map), volume_loading.go (load + integrity check),
volume_checking.go:14-78 (verify the last idx entry matches the data tail).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass

from . import types as t
from .needle import Needle, NeedleError
from .needle_map import best_needle_map
from .super_block import SuperBlock, ReplicaPlacement


class VolumeError(Exception):
    pass


class NotFound(VolumeError):
    pass


class AlreadyDeleted(VolumeError):
    pass


class NeedleRef:
    """Zero-copy handle to one needle's data region inside the .dat
    file: a private read-only file object (its own offset, its own
    lifetime — a vacuum swap renaming the .dat keeps the inode alive
    for in-flight sends) plus the byte window of the DATA field.
    The owner must close() it, normally after an os.sendfile-style
    kernel copy of exactly ``length`` bytes at ``offset``."""

    __slots__ = ("file", "offset", "length")

    def __init__(self, file, offset: int, length: int) -> None:
        self.file = file
        self.offset = offset
        self.length = length

    def slice(self, off: int, length: int) -> None:
        """Narrow to a sub-range of the data window (HTTP Range)."""
        self.offset += off
        self.length = length

    def close(self) -> None:
        try:
            self.file.close()
        except OSError:
            pass


class _Append:
    """One group-commit participant: the needle going in, and the
    result/exception coming back once the shared batch is durable."""

    __slots__ = ("needle", "result", "exc", "done", "batch")

    def __init__(self, needle: Needle) -> None:
        self.needle = needle
        self.result: tuple[int, int] | None = None
        self.exc: BaseException | None = None
        self.done = False
        self.batch = 1

    def finish(self, result: tuple[int, int]) -> None:
        self.result = result
        self.done = True

    def fail(self, exc: BaseException) -> None:
        self.exc = exc
        self.done = True


@dataclass
class VolumeStat:
    file_count: int
    deleted_count: int
    deleted_bytes: int
    size: int
    read_only: bool


class _ReadaheadCursor:
    """Window buffer over a positioned-read callable for sequential scans:
    each miss fetches one large chunk, so a remote .dat walk costs
    O(size/chunk) ranged GETs instead of two per record."""

    def __init__(self, pread, size: int, chunk: int = 4 << 20):
        self._pread = pread
        self._size = size
        self._chunk = chunk
        self._start = 0
        self._buf = b""

    def read(self, nbytes: int, offset: int) -> bytes:
        end = offset + nbytes
        if offset < self._start or end > self._start + len(self._buf):
            want = max(nbytes, self._chunk)
            self._buf = self._pread(min(want, self._size - offset), offset)
            self._start = offset
        lo = offset - self._start
        return self._buf[lo:lo + nbytes]


class Volume:
    """One volume on local disk: <dir>/<collection_><vid>.dat / .idx."""

    def __init__(self, dirname: str, collection: str, vid: int,
                 replica_placement: ReplicaPlacement | None = None,
                 ttl: t.TTL | None = None,
                 preallocate: int = 0,
                 create_if_missing: bool = True,
                 needle_map_kind: str = "auto"):
        self.needle_map_kind = needle_map_kind
        self.dir = dirname
        self.collection = collection
        self.vid = vid
        self.read_only = False
        self.last_append_at_ns = 0
        self.last_modified_ts = 0
        # vacuum copy rate limit, bytes/s; 0 = unthrottled
        # (compactionBytePerSecond flag + util/throttler.go)
        self.compaction_bytes_per_second = 0
        # fsync after every (group-committed) append before acking
        # writers (-fsync flag); off keeps the historical flush-only
        # durability point
        self.fsync = False
        self._lock = threading.RLock()

        base = self.file_name()
        dat_path = base + ".dat"
        exists = os.path.exists(dat_path)
        self.is_remote = False

        if not exists:
            # tiered volume? .vif sidecar says which backend holds .dat
            # (volume_tier.go LoadVolumeTierInfo)
            from . import backend as _backend
            vinfo = _backend.load_volume_info(base)
            if vinfo and vinfo.get("files"):
                fi = vinfo["files"][0]
                bs = _backend.get_backend(fi["backend_id"])
                self._dat = _backend.RemoteDatFile(
                    bs.new_storage_file(fi["key"],
                                        fi.get("file_size", -1)))
                self._dat.seek(0)
                self.super_block = SuperBlock.from_bytes(self._dat.read(8))
                self.is_remote = True
                self.read_only = True
                self.nm = best_needle_map(base + ".idx", self.needle_map_kind)
                last = self.nm.last_entry
                if last is not None and last[1] > 0:
                    try:
                        n = self._read_at(
                            last[1],
                            0 if last[2] == t.TOMBSTONE_FILE_SIZE
                            else last[2])
                        self.last_append_at_ns = n.append_at_ns
                    except (NeedleError, _backend.BackendError):
                        # a transient tier outage must not abort the load;
                        # the watermark is best-effort on tiered volumes
                        pass
                return
        if not exists and not create_if_missing:
            raise VolumeError(f"volume file missing: {dat_path}")

        if exists:
            self._dat = open(dat_path, "r+b")
            sb_raw = self._dat.read(8)
            if len(sb_raw) < 8:
                raise VolumeError(f"corrupt superblock in {dat_path}")
            self.super_block = SuperBlock.from_bytes(sb_raw)
            from . import backend as _backend
            if _backend.load_volume_info(base) is not None:
                # tiered with -keepLocal: serve reads from the local copy
                # but stay sealed — new writes would silently diverge
                # from the remote object recorded in the .vif
                self.read_only = True
        else:
            os.makedirs(dirname, exist_ok=True)
            self.super_block = SuperBlock(
                replica_placement=replica_placement or ReplicaPlacement(),
                ttl=ttl or t.TTL())
            self._dat = open(dat_path, "w+b")
            self._dat.write(self.super_block.to_bytes())
            self._dat.flush()
            if preallocate:
                # FALLOC_FL_KEEP_SIZE (mode 1, volume_create_linux.go:19):
                # reserve blocks WITHOUT extending st_size — appends
                # derive their offset from the file size, so a plain
                # posix_fallocate would push every write past the
                # preallocated region
                try:
                    import ctypes
                    libc = ctypes.CDLL(None, use_errno=True)
                    # argtypes matter: off_t is 64-bit — the ctypes
                    # default int conversion would truncate any
                    # preallocation >= 2GB (incl. the 30GB default)
                    libc.fallocate.argtypes = [
                        ctypes.c_int, ctypes.c_int,
                        ctypes.c_longlong, ctypes.c_longlong]
                    libc.fallocate.restype = ctypes.c_int
                    if libc.fallocate(self._dat.fileno(), 1, 0,
                                      preallocate) != 0:
                        raise OSError(ctypes.get_errno(), "fallocate")
                except (OSError, AttributeError):
                    pass  # unsupported fs: run unallocated, like the
                    #       reference's non-linux build
        self.nm = best_needle_map(base + ".idx", self.needle_map_kind)
        self._check_integrity()

    def reload(self) -> None:
        """Re-open .dat/.idx after an external swap (vacuum commit).
        Must run under self._lock; keeps the existing lock object so
        writers already blocked on it serialize correctly."""
        base = self.file_name()
        self._dat = open(base + ".dat", "r+b")
        self.super_block = SuperBlock.from_bytes(self._dat.read(8))
        self.nm = best_needle_map(base + ".idx", self.needle_map_kind)
        from . import backend as _backend
        # a .vif means the volume is tiered (keep_local): stay sealed so
        # local writes can't diverge from the remote object
        self.read_only = _backend.load_volume_info(base) is not None
        self._check_integrity()

    # ---- naming ----

    def file_name(self) -> str:
        name = f"{self.collection}_{self.vid}" if self.collection else str(self.vid)
        return os.path.join(self.dir, name)

    @property
    def version(self) -> int:
        return self.super_block.version

    @property
    def ttl(self) -> t.TTL:
        return self.super_block.ttl

    # ---- integrity (volume_checking.go:14-37) ----

    def _check_integrity(self) -> None:
        """Verify the last logged idx entry (tombstones included) points at
        a parseable needle at the data tail; truncate a torn tail write."""
        size = self.data_size()
        last = self.nm.last_entry
        if last is None:
            return
        key, offset, logged_size = last
        # tombstone records are empty-body needles on disk
        body_size = 0 if logged_size == t.TOMBSTONE_FILE_SIZE else logged_size
        expected_end = offset + t.actual_size(body_size, self.version)
        if expected_end > size:
            raise VolumeError(
                f"volume {self.vid}: index points past data end "
                f"({expected_end} > {size})")
        try:
            n = self._read_at(offset, body_size)
        except NeedleError as e:
            raise VolumeError(f"volume {self.vid}: tail needle corrupt: {e}")
        if n.id != key:
            raise VolumeError(
                f"volume {self.vid}: tail needle key mismatch "
                f"{n.id:x} != {key:x}")
        # restore the incremental-sync watermark (volume_backup.go relies
        # on lastAppendAtNs surviving restarts)
        self.last_append_at_ns = n.append_at_ns
        # ...and the modified watermark, or TTL volume reclamation
        # (store.go expired()) goes dead after a restart
        modified = n.last_modified or n.append_at_ns // 1_000_000_000
        if modified > self.last_modified_ts:
            self.last_modified_ts = modified
        if expected_end < size:
            # torn write past the last logged record: truncate it away
            self._dat.truncate(expected_end)

    # ---- I/O core ----

    def data_size(self) -> int:
        # fstat, NOT seek(END): this is called lock-free from the
        # heartbeat/stats paths, and moving the shared fd's position
        # would race a locked reader between its seek and read.
        # A tiered volume's _dat is a RemoteDatFile (no fileno); its
        # size() is a backend HEAD, equally position-free.
        fileno = getattr(self._dat, "fileno", None)
        if fileno is None:
            return self._dat.size()
        return os.fstat(fileno()).st_size

    def _pread(self, nbytes: int, offset: int) -> bytes:
        # positioned read: no shared seek state with writers or other
        # readers (the reference uses ReadAt for the same reason).
        # Tiered volumes route through RemoteDatFile.pread -> ranged GET
        # (s3_backend.go:113-146).
        fileno = getattr(self._dat, "fileno", None)
        if fileno is None:
            return self._dat.pread(nbytes, offset)
        return os.pread(fileno(), nbytes, offset)

    def _read_at(self, offset: int, size: int) -> Needle:
        blob = self._pread(t.actual_size(size, self.version), offset)
        return Needle.from_bytes(blob, self.version)

    def write_needle(self, n: Needle) -> tuple[int, int]:
        """Append a needle; returns (offset, size).

        volume_read_write.go:66-113: inherit volume TTL, verify existing
        cookie on overwrite, append, nm.Put.
        """
        with self._lock:
            if self.read_only:
                raise VolumeError(f"volume {self.vid} is read-only")
            if n.ttl.count == 0 and self.ttl.count != 0:
                n.ttl = self.ttl
            nv = self.nm.get(n.id)
            if (nv is not None and nv.offset > 0
                    and nv.size != t.TOMBSTONE_FILE_SIZE):
                existing = self._read_at(nv.offset, nv.size)
                if existing.cookie != n.cookie:
                    raise VolumeError(
                        f"mismatching cookie {n.cookie:x} for needle {n.id:x}")
            n.append_at_ns = time.time_ns()
            offset = self.data_size()
            blob = n.to_bytes(self.version)
            self._dat.seek(offset)
            self._dat.write(blob)
            self._dat.flush()
            self.last_append_at_ns = n.append_at_ns
            if nv is None or nv.offset < offset:
                self.nm.put(n.id, offset, n.size)
            modified = n.last_modified or n.append_at_ns // 1_000_000_000
            if modified > self.last_modified_ts:
                self.last_modified_ts = modified
            return offset, n.size

    def _validate_append(self, n: Needle):
        """Shared pre-append checks (under self._lock): TTL inherit +
        overwrite cookie verification. Returns the existing needle-map
        entry (or None)."""
        if n.ttl.count == 0 and self.ttl.count != 0:
            n.ttl = self.ttl
        nv = self.nm.get(n.id)
        if (nv is not None and nv.offset > 0
                and nv.size != t.TOMBSTONE_FILE_SIZE):
            existing = self._read_at(nv.offset, nv.size)
            if existing.cookie != n.cookie:
                raise VolumeError(
                    f"mismatching cookie {n.cookie:x} for needle {n.id:x}")
        return nv

    def append_needles(self, items: "list[_Append]") -> None:
        """Group-commit append: serialize every queued needle, land the
        whole batch with ONE vectored pwritev (single pwrite fallback)
        and ONE flush(+fsync when enabled), then publish the index
        entries — writers are acked only after the shared durable
        point. Per-needle validation errors fail only their own slot;
        an I/O error fails the batch and truncates the torn tail so the
        on-disk state never acknowledges bytes that didn't land."""
        with self._lock:
            if self.read_only:
                err = VolumeError(f"volume {self.vid} is read-only")
                for it in items:
                    it.fail(err)
                return
            offset = self.data_size()
            pos = offset
            blobs: list[bytes] = []
            metas: list[tuple[_Append, Needle, int, object]] = []
            for it in items:
                n = it.needle
                try:
                    nv = self._validate_append(n)
                    n.append_at_ns = time.time_ns()
                    blob = n.to_bytes(self.version)
                except (NeedleError, VolumeError, ValueError) as e:
                    it.fail(e)
                    continue
                metas.append((it, n, pos, nv))
                blobs.append(blob)
                pos += len(blob)
            if not blobs:
                return
            try:
                self._dat.flush()
                fileno = getattr(self._dat, "fileno", None)
                if fileno is None:
                    raise VolumeError(
                        f"volume {self.vid}: remote .dat is append-less")
                fd = fileno()
                self._pwrite_all(fd, blobs, offset)
                if self.fsync:
                    os.fsync(fd)
            except OSError as e:
                # torn batch: cut the tail back so a crashed/partial
                # vectored write can never be read as committed records
                try:
                    self._dat.truncate(offset)
                except OSError:
                    pass
                for it, _, _, _ in metas:
                    it.fail(e)
                return
            for it, n, at, nv in metas:
                self.last_append_at_ns = n.append_at_ns
                if nv is None or nv.offset < at:
                    self.nm.put(n.id, at, n.size)
                modified = n.last_modified or n.append_at_ns // 1_000_000_000
                if modified > self.last_modified_ts:
                    self.last_modified_ts = modified
                it.finish((at, n.size))

    @staticmethod
    def _pwrite_all(fd: int, blobs: list[bytes], offset: int) -> None:
        """Positioned vectored write of every blob, resilient to short
        writes and platforms without pwritev."""
        total = sum(len(b) for b in blobs)
        written = 0
        if hasattr(os, "pwritev"):
            # IOV_MAX-bounded slices; retry the remainder on any short
            # write by flattening what's left
            view = memoryview(b"")  # placeholder for the tail path
            idx = 0
            while idx < len(blobs) and written < total:
                group = blobs[idx:idx + 512]
                want = sum(len(b) for b in group)
                done = os.pwritev(fd, group, offset + written)
                written += done
                if done != want:
                    break
                idx += 512
            if written >= total:
                return
            view = memoryview(b"".join(blobs))[written:]
        else:
            view = memoryview(b"".join(blobs))
        while view:
            done = os.pwrite(fd, view, offset + written)
            written += done
            view = view[done:]

    # sendfile eligibility floor is the caller's business; this just
    # refuses refs when the map entry is too small to be worth one
    def read_needle_ref(self, needle_id: int, cookie: int | None = None,
                        min_bytes: int = 0
                        ) -> "tuple[Needle, NeedleRef] | None":
        """Zero-copy read: parse header + trailing metadata with two
        small preads and return the needle (``data`` EMPTY) plus a
        NeedleRef naming the data region in a PRIVATE file handle, so
        the body can go disk->socket via os.sendfile without entering
        Python. Returns None when a ref is not worth it / not possible
        (remote tier, v1-with-no-meta is fine, too small, torn record)
        — the caller then takes the buffered path. Raises exactly what
        read_needle raises for missing/deleted/expired/cookie-mismatch.

        The body CRC is NOT verified here (the bytes never enter
        userspace); the buffered path keeps CRC-on-read, and scrub
        (ec.verify) covers cold integrity."""
        with self._lock:
            nv = self.nm.get(needle_id)
            if nv is not None and nv.size == t.TOMBSTONE_FILE_SIZE:
                raise AlreadyDeleted(f"needle {needle_id:x} deleted")
            if nv is None or nv.offset == 0:
                raise NotFound(f"needle {needle_id:x} not found")
            if self.is_remote or nv.size < max(min_bytes, 32):
                return None
            fileno = getattr(self._dat, "fileno", None)
            if fileno is None:
                return None
            fd = fileno()
            head = os.pread(fd, t.NEEDLE_HEADER_SIZE + 4, nv.offset)
            if len(head) < t.NEEDLE_HEADER_SIZE + 4:
                return None
            n = Needle()
            n.cookie = int.from_bytes(head[0:4], "big")
            n.id = int.from_bytes(head[4:12], "big")
            n.size = int.from_bytes(head[12:16], "big")
            if n.id != needle_id or n.size != nv.size:
                return None          # map/record disagree: buffered path
            if self.version == t.VERSION1:
                data_len = n.size
                data_off = nv.offset + t.NEEDLE_HEADER_SIZE
                meta = b""
                footer_off = data_off + data_len
            else:
                data_len = int.from_bytes(head[16:20], "big")
                data_off = nv.offset + t.NEEDLE_HEADER_SIZE + 4
                meta_len = n.size - 4 - data_len
                if meta_len < 1:
                    return None      # corrupt body framing
                meta = os.pread(fd, meta_len, data_off + data_len)
                if len(meta) < meta_len:
                    return None
                footer_off = data_off + data_len + meta_len
            footer = os.pread(
                fd, 12 if self.version == t.VERSION3 else 4, footer_off)
            if len(footer) >= 4:
                n.checksum = int.from_bytes(footer[0:4], "big")
            if self.version == t.VERSION3 and len(footer) >= 12:
                n.append_at_ns = int.from_bytes(footer[4:12], "big")
            if meta:
                try:
                    self._parse_meta(n, meta)
                except (IndexError, ValueError):
                    return None
            # a PRIVATE handle: independent file offset (a dup'd fd
            # would share the append position with the writer) and an
            # inode pin across vacuum's .dat swap; opened under the
            # volume lock so the offsets and the file can't diverge
            try:
                f = open(self.file_name() + ".dat", "rb")
            except OSError:
                return None
        if cookie is not None and n.cookie != cookie:
            f.close()
            raise NotFound(f"cookie mismatch for needle {needle_id:x}")
        if n.has_expired():
            f.close()
            raise NotFound(f"needle {needle_id:x} expired")
        return n, NeedleRef(f, data_off, data_len)

    @staticmethod
    def _parse_meta(n: Needle, meta: bytes) -> None:
        """Post-data optional fields (flags name mime lm ttl pairs) —
        the tail of Needle._parse_body, for meta read without data."""
        from .needle import (FLAG_HAS_MIME, FLAG_HAS_NAME, FLAG_HAS_PAIRS,
                             FLAG_HAS_TTL, LAST_MODIFIED_BYTES)
        from .needle import FLAG_HAS_LAST_MODIFIED as _FLM
        idx = 0
        n.flags = meta[idx]
        idx += 1
        if n.has(FLAG_HAS_NAME):
            ln = meta[idx]
            idx += 1
            n.name = bytes(meta[idx:idx + ln])
            idx += ln
        if n.has(FLAG_HAS_MIME):
            ln = meta[idx]
            idx += 1
            n.mime = bytes(meta[idx:idx + ln])
            idx += ln
        if n.has(_FLM):
            n.last_modified = int.from_bytes(
                meta[idx:idx + LAST_MODIFIED_BYTES], "big")
            idx += LAST_MODIFIED_BYTES
        if n.has(FLAG_HAS_TTL):
            n.ttl = t.TTL.from_bytes(meta[idx:idx + 2])
            idx += 2
        if n.has(FLAG_HAS_PAIRS):
            ln = int.from_bytes(meta[idx:idx + 2], "big")
            idx += 2
            n.pairs = bytes(meta[idx:idx + ln])

    def delete_needle(self, n: Needle) -> int:
        """Tombstone delete; returns reclaimed byte count
        (volume_read_write.go:115-136)."""
        with self._lock:
            if self.read_only:
                raise VolumeError(f"volume {self.vid} is read-only")
            nv = self.nm.get(n.id)
            if nv is None or nv.size == t.TOMBSTONE_FILE_SIZE:
                return 0
            size = nv.size
            n.data = b""
            n.append_at_ns = time.time_ns()
            offset = self.data_size()
            self._dat.seek(offset)
            self._dat.write(n.to_bytes(self.version))
            self._dat.flush()
            if self.fsync:
                # -fsync must cover tombstones too, or an acked DELETE
                # could be lost on power failure while a concurrently
                # acked write in the same window is durable
                fileno = getattr(self._dat, "fileno", None)
                if fileno is not None:
                    os.fsync(fileno())
            self.last_append_at_ns = n.append_at_ns
            self.nm.delete(n.id, offset)
            return size

    def read_needle(self, needle_id: int, cookie: int | None = None) -> Needle:
        """O(1) read: nm.Get + one ReadAt (volume_read_write.go:139-172)."""
        with self._lock:
            nv = self.nm.get(needle_id)
            if nv is not None and nv.size == t.TOMBSTONE_FILE_SIZE:
                raise AlreadyDeleted(f"needle {needle_id:x} deleted")
            if nv is None or nv.offset == 0:
                raise NotFound(f"needle {needle_id:x} not found")
            n = self._read_at(nv.offset, nv.size)
        if cookie is not None and n.cookie != cookie:
            raise NotFound(f"cookie mismatch for needle {needle_id:x}")
        if n.has_expired():
            raise NotFound(f"needle {needle_id:x} expired")
        return n

    # ---- scanning (volume_read_write.go:174-230 ScanVolumeFile) ----

    def scan(self, visit) -> None:
        """visit(needle, offset) over every record incl. tombstones."""
        size = self.data_size()
        offset = 8  # past the superblock
        # sequential walk: on a tiered volume, coalesce the per-record
        # preads into few large ranged GETs instead of 2 round trips
        # per needle
        pread = (_ReadaheadCursor(self._pread, size).read
                 if self.is_remote else self._pread)
        while offset + t.NEEDLE_HEADER_SIZE <= size:
            header = pread(t.NEEDLE_HEADER_SIZE, offset)
            if len(header) < t.NEEDLE_HEADER_SIZE:
                break
            body_size = int.from_bytes(header[12:16], "big")
            rec_len = t.actual_size(body_size, self.version)
            blob = pread(rec_len, offset)
            if len(blob) < rec_len:
                break
            n = Needle.from_bytes(blob, self.version, check_crc=False)
            visit(n, offset)
            offset += rec_len

    # ---- stats / lifecycle ----

    def stat(self) -> VolumeStat:
        return VolumeStat(
            file_count=self.nm.file_count,
            deleted_count=self.nm.deleted_count,
            deleted_bytes=self.nm.deleted_size,
            size=self.data_size(),
            read_only=self.read_only,
        )

    def garbage_level(self) -> float:
        size = self.data_size()
        if size <= 8:
            return 0.0
        return self.nm.deleted_size / size

    def is_full(self, volume_size_limit: int) -> bool:
        return self.data_size() >= volume_size_limit

    def close(self) -> None:
        with self._lock:
            self.nm.close()
            self._dat.close()

    def destroy(self) -> None:
        with self._lock:
            self.nm.destroy()
            self._dat.close()
            base = self.file_name()
            # drop the remote object too (guarded on .vif presence, not
            # is_remote — a keep_local tiered volume reopened from its
            # local .dat has is_remote=False but still owns the object);
            # leftovers would otherwise orphan it, and the .vif would
            # resurrect an empty volume on restart
            from . import backend as _backend
            vinfo = _backend.load_volume_info(base)
            if vinfo and vinfo.get("files"):
                fi = vinfo["files"][0]
                try:
                    _backend.get_backend(fi["backend_id"]).delete_file(
                        fi["key"])
                except _backend.BackendError:
                    pass
            for ext in (".dat", ".vif"):
                p = base + ext
                if os.path.exists(p):
                    os.remove(p)
