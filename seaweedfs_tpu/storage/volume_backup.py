"""Incremental volume backup/sync by AppendAtNs watermark.

Reference: weed/storage/volume_backup.go — `IncrementalBackup` pulls the
tail of a remote volume newer than the local volume's last append
timestamp; `BinarySearchByAppendAtNs` (volume_backup.go:172-234) finds
the first .idx entry whose needle was appended after `since_ns`
(append-only volumes make the .idx time-ordered). Tombstone deletes are
replayed as deletes. `weed backup` (weed/command/backup.go) wraps this
with a full-copy fallback when compaction revisions diverge.
"""

from __future__ import annotations

from typing import Iterator

from . import types as t
from .needle import Needle
from .needle_map import walk_index_file
from .volume import Volume


def read_append_at_ns(v: Volume, offset: int) -> int:
    """append_at_ns of the needle record at a .dat offset
    (volume_backup.go:236-247)."""
    with v._lock:
        v._dat.seek(offset)
        header = v._dat.read(t.NEEDLE_HEADER_SIZE)
        if len(header) < t.NEEDLE_HEADER_SIZE:
            return 0
        body_size = int.from_bytes(header[12:16], "big")
        v._dat.seek(offset)
        blob = v._dat.read(t.actual_size(body_size, v.version))
    n = Needle.from_bytes(blob, v.version, check_crc=False)
    return n.append_at_ns


def _idx_entries(v: Volume) -> list[tuple[int, int, int]]:
    entries: list[tuple[int, int, int]] = []
    path = v.file_name() + ".idx"
    walk_index_file(path, lambda k, o, s: entries.append((k, o, s)))
    return entries


def _first_entry_after(v: Volume, since_ns: int,
                       entries: list[tuple[int, int, int]]) -> int:
    """Index of the first .idx entry appended strictly after since_ns.

    Matches volume_backup.go:172-234: binary search over the time-ordered
    .idx entries, reading each probed needle's AppendAtNs from .dat.
    Tombstone entries carry the tombstone record's own offset, so they
    participate like any other append.
    """
    lo, hi = 0, len(entries)
    while lo < hi:
        mid = (lo + hi) // 2
        ts = read_append_at_ns(v, entries[mid][1])
        if ts > since_ns:
            hi = mid
        else:
            lo = mid + 1
    return lo


def binary_search_by_append_at_ns(v: Volume, since_ns: int) -> int | None:
    """.dat offset of the first record appended strictly after since_ns,
    or None when the volume has nothing newer."""
    entries = [(k, o, s) for (k, o, s) in _idx_entries(v) if o > 0]
    i = _first_entry_after(v, since_ns, entries)
    return entries[i][1] if i < len(entries) else None


def tail_records(v: Volume, since_ns: int) -> Iterator[tuple[Needle, bool]]:
    """Yield (record, is_delete) for every append after since_ns, in
    append order — the VolumeTailSender stream (volume_server.proto:47-50).

    Driven by the .idx (one locked read per record, no full .dat scan):
    delete markers are idx entries with size == TOMBSTONE_FILE_SIZE, which
    disambiguates tombstones from legitimate zero-byte file writes.
    """
    with v._lock:
        revision = v.super_block.compaction_revision
        entries = [(k, o, s) for (k, o, s) in _idx_entries(v) if o > 0]
        start = _first_entry_after(v, since_ns, entries)
    for key, offset, size in entries[start:]:
        is_delete = size == t.TOMBSTONE_FILE_SIZE
        body_size = 0 if is_delete else size
        with v._lock:
            if v.super_block.compaction_revision != revision:
                # vacuum commit swapped .dat under us: the snapshot
                # offsets are stale — abort; the receiver retries from
                # its watermark against the compacted file
                return
            v._dat.seek(offset)
            blob = v._dat.read(t.actual_size(body_size, v.version))
        n = Needle.from_bytes(blob, v.version, check_crc=False)
        if n.append_at_ns > since_ns:
            yield n, is_delete


def tail_needles(v: Volume, since_ns: int) -> Iterator[Needle]:
    for n, _ in tail_records(v, since_ns):
        yield n


def apply_needle(v: Volume, n: Needle, is_delete: bool = False) -> None:
    """Replay a tailed record into a local volume, preserving its original
    append_at_ns (VolumeTailReceiver -> replica write path)."""
    with v._lock:
        offset = v.data_size()
        blob = n.to_bytes(t.CURRENT_VERSION)
        v._dat.seek(offset)
        v._dat.write(blob)
        v._dat.flush()
        v.last_append_at_ns = max(v.last_append_at_ns, n.append_at_ns)
        if is_delete:
            v.nm.delete(n.id, offset)
        else:
            v.nm.put(n.id, offset, n.size)


def frame_needle(n: Needle, is_delete: bool = False) -> bytes:
    """Wire frame for the tail stream: [1B flags][4B len][v3 needle blob].
    The explicit delete flag disambiguates tombstones from zero-byte
    writes; the blob is always re-serialized as v3 so append_at_ns rides
    along regardless of the source volume's on-disk version."""
    blob = n.to_bytes(t.VERSION3)
    return bytes([1 if is_delete else 0]) + \
        len(blob).to_bytes(4, "big") + blob


class FrameDecoder:
    """Incremental decoder for frame_needle() streams; feed() chunks of
    arbitrary size, get back completed records. Lets async receivers
    apply records as they arrive instead of buffering whole tails."""

    def __init__(self) -> None:
        self._buf = bytearray()

    def feed(self, chunk: bytes) -> list[tuple[Needle, bool]]:
        self._buf += chunk
        out: list[tuple[Needle, bool]] = []
        while True:
            if len(self._buf) < 5:
                break
            is_delete = self._buf[0] != 0
            ln = int.from_bytes(self._buf[1:5], "big")
            if len(self._buf) < 5 + ln:
                break
            blob = bytes(self._buf[5:5 + ln])
            del self._buf[:5 + ln]
            out.append((Needle.from_bytes(blob, t.VERSION3,
                                          check_crc=False), is_delete))
        return out


def iter_frames(data_iter) -> Iterator[tuple[Needle, bool]]:
    """Decode a stream of frame_needle()-framed records from a byte
    iterator (chunks of arbitrary size)."""
    dec = FrameDecoder()
    for chunk in data_iter:
        yield from dec.feed(chunk)
