"""Move sealed volumes between the local disk tier and remote object
storage.

Reference: weed/storage/volume_tier.go + weed/server/
volume_grpc_tier_upload.go:14 (`VolumeTierMoveDatToRemote`) and
_download.go:13 (`VolumeTierMoveDatFromRemote`), orchestrated by
weed/shell/command_volume_tier_upload.go/_download.go. The .dat moves;
the .idx stays local so needle lookups remain O(1) in memory, and every
data read becomes a ranged GET through the backend abstraction.
"""

from __future__ import annotations

import os
import uuid

from . import backend as _backend
from .volume import Volume, VolumeError


def tier_upload(v: Volume, backend_id: str,
                keep_local: bool = False) -> int:
    """Upload a volume's .dat to a remote backend and switch the live
    volume to remote reads. Returns uploaded byte count."""
    if v.is_remote:
        raise VolumeError(f"volume {v.vid} is already remote")
    bs = _backend.get_backend(backend_id)
    with v._lock:
        was_read_only = v.read_only
        v.read_only = True  # seal: tiered volumes take no more writes
        base = v.file_name()
        # unique key per upload: replicas of the same volume must not
        # share (and so overwrite/delete) one bucket object
        key = (f"{os.path.basename(base)}.dat."
               f"{uuid.uuid4().hex[:12]}")
    try:
        # upload OUTSIDE the lock: the sealed .dat is immutable, and a
        # multi-GB transfer must not stall concurrent reads
        size = bs.copy_file(base + ".dat", key)
    except Exception:
        with v._lock:
            v.read_only = was_read_only  # un-seal on failure
        raise
    with v._lock:
        _backend.save_volume_info(base, backend_id, key, size, v.version)
        v._dat.close()
        v._dat = _backend.RemoteDatFile(bs.new_storage_file(key, size))
        v.is_remote = True
        if not keep_local:
            os.remove(base + ".dat")
    return size


def tier_download(v: Volume) -> int:
    """Fetch a tiered volume's .dat back to local disk and drop the .vif.
    Returns downloaded byte count."""
    base = v.file_name()
    vinfo = _backend.load_volume_info(base)
    if not vinfo or not vinfo.get("files"):
        raise VolumeError(f"volume {v.vid} is not tiered (no .vif)")
    fi = vinfo["files"][0]
    bs = _backend.get_backend(fi["backend_id"])
    # download OUTSIDE the lock (multi-GB transfer must not stall reads);
    # the remote object is immutable, so no consistency risk
    tmp = base + ".dat.tmp"
    size = bs.download_file(fi["key"], tmp)
    with v._lock:
        os.replace(tmp, base + ".dat")
        os.remove(_backend.vif_path(base))
        v._dat.close()
        v._dat = open(base + ".dat", "r+b")
        v.is_remote = False
        v.read_only = False
    return size
