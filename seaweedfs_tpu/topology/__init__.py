"""topology subpackage."""
