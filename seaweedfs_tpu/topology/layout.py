"""Volume layouts and rack-aware replica placement.

Reference: weed/topology/volume_layout.go (per-(collection, replication,
ttl) writable sets), volume_growth.go:106-202 (findEmptySlotsForOneVolume:
3-level constrained random placement over DC -> rack -> server).
"""

from __future__ import annotations

import random
from dataclasses import dataclass

from ..storage.super_block import ReplicaPlacement
from .tree import DataCenter, DataNode, Rack, Topology


class PlacementError(Exception):
    pass


@dataclass(frozen=True)
class LayoutKey:
    collection: str
    replication: str
    ttl: str


class VolumeLayout:
    """Tracks writable volume ids for one layout key
    (volume_layout.go:17-32)."""

    def __init__(self, key: LayoutKey, volume_size_limit: int):
        self.key = key
        self.volume_size_limit = volume_size_limit
        self.writable: set[int] = set()

    def set_writable(self, vid: int, writable: bool) -> None:
        if writable:
            self.writable.add(vid)
        else:
            self.writable.discard(vid)

    def pick_for_write(self, topo: Topology,
                       replica_count: int) -> int | None:
        """Random writable vid that still has enough replicas registered
        (volume_layout.go:165 PickForWrite)."""
        candidates = [vid for vid in self.writable
                      if len(topo.volume_locations.get(vid, {}))
                      >= replica_count]
        if not candidates:
            return None
        return random.choice(candidates)


def rank_repair_targets(nodes, holder_urls) -> "list[str]":
    """Deterministic rack-aware candidate ranking for placing a
    repaired replica or rebuilt EC shard (the autopilot planner's
    placement primitive — the pure, seedless sibling of
    find_empty_slots' randomized growth placement).

    `nodes` is any iterable of objects with ``url``, ``data_center``,
    ``rack`` and ``free_slots`` attributes (autopilot/plan.NodeState);
    `holder_urls` the urls already holding a copy/shard of the volume.
    Candidates exclude current holders and full nodes, and are ordered:

      1. racks holding the FEWEST existing copies first (a repair must
         widen failure domains, not deepen one — the reference's
         command_volume_fix_replication.go preference);
      2. more free slots first (capacity-weighted like pick_weighted,
         but deterministically);
      3. url ascending (the total-order tiebreak that makes identical
         snapshots produce identical plans).
    """
    by_url = {n.url: n for n in nodes}
    rack_load: dict[tuple, int] = {}
    for u in holder_urls:
        n = by_url.get(u)
        if n is not None:
            key = (n.data_center, n.rack)
            rack_load[key] = rack_load.get(key, 0) + 1
    candidates = [n for n in by_url.values()
                  if n.url not in holder_urls and n.free_slots > 0]
    candidates.sort(key=lambda n: (
        rack_load.get((n.data_center, n.rack), 0),
        -n.free_slots, n.url))
    return [n.url for n in candidates]


def find_empty_slots(topo: Topology, rp: ReplicaPlacement,
                     preferred_dc: str | None = None
                     ) -> list[DataNode]:
    """Pick rp.copy_count() servers satisfying the xyz constraints:
    1 main server; rp.same_rack more on other servers of the same rack;
    rp.diff_rack more on other racks of the same DC; rp.diff_dc more on
    other DCs (volume_growth.go:106-202).
    """
    dcs = [dc for dc in topo.data_centers.values()
           if preferred_dc in (None, "", dc.id)]
    random.shuffle(dcs)
    last_err = "no data centers with capacity"
    for dc in dcs:
        try:
            return _place_in_dc(topo, dc, rp)
        except PlacementError as e:
            last_err = str(e)
    raise PlacementError(last_err)


def _place_in_dc(topo: Topology, main_dc: DataCenter,
                 rp: ReplicaPlacement) -> list[DataNode]:
    # main rack must supply 1 + same_rack servers; main DC must supply
    # 1 + diff_rack racks; cluster must supply 1 + diff_dc DCs.
    other_dcs = [d for d in topo.data_centers.values()
                 if d is not main_dc and d.free_space() > 0]
    if len(other_dcs) < rp.diff_dc:
        raise PlacementError(
            f"need {rp.diff_dc} other DCs with capacity, "
            f"have {len(other_dcs)}")

    racks = [r for r in main_dc.racks.values() if r.free_space() > 0]
    random.shuffle(racks)
    for main_rack in racks:
        other_racks = [r for r in main_dc.racks.values()
                       if r is not main_rack and r.free_space() > 0]
        if len(other_racks) < rp.diff_rack:
            continue
        nodes = [n for n in main_rack.nodes.values() if n.free_space() > 0]
        if len(nodes) < 1 + rp.same_rack:
            continue
        picked = topo.pick_weighted(nodes, 1 + rp.same_rack)
        if len(picked) < 1 + rp.same_rack:
            continue
        # one server from each of rp.diff_rack other racks
        for r in topo.pick_weighted(other_racks, rp.diff_rack):
            n = topo.pick_weighted(list(r.nodes.values()), 1)
            if not n:
                raise PlacementError(f"rack {r.id} has no free server")
            picked += n
        # one server from each of rp.diff_dc other DCs
        for d in topo.pick_weighted(other_dcs, rp.diff_dc):
            all_nodes = [n for r in d.racks.values()
                         for n in r.nodes.values()]
            n = topo.pick_weighted(all_nodes, 1)
            if not n:
                raise PlacementError(f"dc {d.id} has no free server")
            picked += n
        if len(picked) == rp.copy_count:
            return picked
    raise PlacementError(
        f"dc {main_dc.id}: no rack satisfies replication "
        f"{rp} (need 1+{rp.same_rack} servers in one rack, "
        f"{rp.diff_rack} other racks)")
