"""Cluster topology tree: Topology -> DataCenter -> Rack -> DataNode.

Reference: weed/topology/node.go:16-63 (Node hierarchy with up-propagated
counters), data_node.go, rack.go, data_center.go, topology.go:20-39,
topology_ec.go (EC shard registry), node.go:275-291 (dead node / full
volume detection).
"""

from __future__ import annotations

import random
import time

from ..pb import messages as pb


class DataNode:
    def __init__(self, node_id: str, ip: str, port: int, public_url: str,
                 max_volume_count: int):
        self.id = node_id
        self.ip = ip
        self.port = port
        self.public_url = public_url
        self.max_volume_count = max_volume_count
        self.volumes: dict[int, pb.VolumeInformationMessage] = {}
        self.ec_shards: dict[int, pb.VolumeEcShardInformationMessage] = {}
        self.last_seen = time.time()
        self.rack: "Rack | None" = None

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    def volume_count(self) -> int:
        return len(self.volumes)

    def ec_shard_count(self) -> int:
        return sum(pb.shard_bits_count(m.ec_index_bits)
                   for m in self.ec_shards.values())

    def free_space(self) -> int:
        # EC shards consume slots at shard granularity (10 shards ~ 1 volume)
        from ..ec import gf
        ec_slots = -(-self.ec_shard_count() // gf.DATA_SHARDS)
        return self.max_volume_count - len(self.volumes) - ec_slots

    def update_volumes(self, msgs: list[pb.VolumeInformationMessage]
                       ) -> tuple[list, list]:
        """Full-state sync; returns (new, deleted) volume messages."""
        incoming = {m.id: m for m in msgs}
        new = [m for vid, m in incoming.items() if vid not in self.volumes]
        deleted = [m for vid, m in self.volumes.items()
                   if vid not in incoming]
        self.volumes = incoming
        return new, deleted

    def update_ec_shards(self, msgs: list[pb.VolumeEcShardInformationMessage]
                         ) -> tuple[list, list]:
        """Full-state sync; returns (changed, deleted). For each changed vid
        the OLD message (whose bits must be unregistered first) is paired in
        deleted so shrunken shard masks heal (topology_ec.go:15-34)."""
        incoming = {m.id: m for m in msgs}
        changed, deleted = [], []
        for vid, m in incoming.items():
            old = self.ec_shards.get(vid)
            if old is None or old.ec_index_bits != m.ec_index_bits:
                if old is not None and old.ec_index_bits != m.ec_index_bits:
                    deleted.append(old)
                changed.append(m)
        for vid, m in self.ec_shards.items():
            if vid not in incoming:
                deleted.append(m)
        self.ec_shards = incoming
        return changed, deleted


class Rack:
    def __init__(self, rack_id: str):
        self.id = rack_id
        self.nodes: dict[str, DataNode] = {}
        self.data_center: "DataCenter | None" = None

    def get_or_create_node(self, node_id: str, ip: str, port: int,
                           public_url: str, max_volumes: int) -> DataNode:
        n = self.nodes.get(node_id)
        if n is None:
            n = DataNode(node_id, ip, port, public_url, max_volumes)
            n.rack = self
            self.nodes[node_id] = n
        n.max_volume_count = max_volumes
        # refresh on every pulse: a server that learns/changes its
        # -publicUrl after registration (workers bind ephemeral ports
        # at start) must not stay pinned to the stale advertisement
        n.public_url = public_url
        n.last_seen = time.time()
        return n

    def free_space(self) -> int:
        return sum(n.free_space() for n in self.nodes.values())


class DataCenter:
    def __init__(self, dc_id: str):
        self.id = dc_id
        self.racks: dict[str, Rack] = {}

    def get_or_create_rack(self, rack_id: str) -> Rack:
        r = self.racks.get(rack_id)
        if r is None:
            r = Rack(rack_id)
            r.data_center = self
            self.racks[rack_id] = r
        return r

    def free_space(self) -> int:
        return sum(r.free_space() for r in self.racks.values())


class Topology:
    def __init__(self, pulse_seconds: float = 5.0):
        self.data_centers: dict[str, DataCenter] = {}
        self.pulse_seconds = pulse_seconds
        # vid -> {node_id -> DataNode} for normal volumes
        self.volume_locations: dict[int, dict[str, DataNode]] = {}
        # vid -> {shard_id -> [DataNode]} for EC (topology_ec.go:15-63)
        self.ec_shard_locations: dict[int, dict[int, list[DataNode]]] = {}
        self.collections: dict[int, str] = {}
        self.max_volume_id = 0

    # ---- membership ----

    def get_or_create_data_center(self, dc_id: str) -> DataCenter:
        dc = self.data_centers.get(dc_id)
        if dc is None:
            dc = DataCenter(dc_id)
            self.data_centers[dc_id] = dc
        return dc

    def all_nodes(self) -> list[DataNode]:
        return [n for dc in self.data_centers.values()
                for r in dc.racks.values() for n in r.nodes.values()]

    def find_node(self, node_id: str) -> DataNode | None:
        for n in self.all_nodes():
            if n.id == node_id:
                return n
        return None

    def register_heartbeat(self, hb: pb.Heartbeat) -> DataNode:
        dc = self.get_or_create_data_center(hb.data_center or "DefaultDataCenter")
        rack = dc.get_or_create_rack(hb.rack or "DefaultRack")
        node = rack.get_or_create_node(
            f"{hb.ip}:{hb.port}", hb.ip, hb.port, hb.public_url,
            hb.max_volume_count)
        if hb.volumes or hb.has_no_volumes:
            new, deleted = node.update_volumes(hb.volumes)
            for m in new:
                self.register_volume(m, node)
            for m in deleted:
                self.unregister_volume(m, node)
        for m in hb.new_volumes:
            node.volumes[m.id] = m
            self.register_volume(m, node)
        for m in hb.deleted_volumes:
            node.volumes.pop(m.id, None)
            self.unregister_volume(m, node)
        if hb.ec_shards or hb.has_no_ec_shards:
            changed, deleted = node.update_ec_shards(hb.ec_shards)
            for m in deleted:
                self.unregister_ec_shards(m, node)
            for m in hb.ec_shards:
                self.register_ec_shards(m, node)
        for m in hb.new_ec_shards:
            node.ec_shards[m.id] = m
            self.register_ec_shards(m, node)
        for m in hb.deleted_ec_shards:
            self.unregister_ec_shards(m, node)
        return node

    def unregister_node(self, node: DataNode) -> list[int]:
        """Node loss: drop all its volume/shard locations
        (master_grpc_server.go:22-48). Returns affected vids."""
        affected = []
        for vid, m in list(node.volumes.items()):
            self.unregister_volume(m, node)
            affected.append(vid)
        for m in list(node.ec_shards.values()):
            self.unregister_ec_shards(m, node)
            affected.append(m.id)
        if node.rack:
            node.rack.nodes.pop(node.id, None)
        return affected

    # ---- volume location registry ----

    def register_volume(self, m: pb.VolumeInformationMessage,
                        node: DataNode) -> None:
        self.volume_locations.setdefault(m.id, {})[node.id] = node
        self.collections[m.id] = m.collection
        self.max_volume_id = max(self.max_volume_id, m.id)

    def unregister_volume(self, m: pb.VolumeInformationMessage,
                          node: DataNode) -> None:
        locs = self.volume_locations.get(m.id)
        if locs:
            locs.pop(node.id, None)
            if not locs:
                del self.volume_locations[m.id]

    def register_ec_shards(self, m: pb.VolumeEcShardInformationMessage,
                           node: DataNode) -> None:
        by_shard = self.ec_shard_locations.setdefault(m.id, {})
        for sid in pb.shard_bits_list(m.ec_index_bits):
            nodes = by_shard.setdefault(sid, [])
            if node not in nodes:
                nodes.append(node)
        self.collections[m.id] = m.collection
        self.max_volume_id = max(self.max_volume_id, m.id)

    def unregister_ec_shards(self, m: pb.VolumeEcShardInformationMessage,
                             node: DataNode) -> None:
        by_shard = self.ec_shard_locations.get(m.id)
        if not by_shard:
            return
        for sid in pb.shard_bits_list(m.ec_index_bits):
            nodes = by_shard.get(sid, [])
            if node in nodes:
                nodes.remove(node)
            if not nodes:
                by_shard.pop(sid, None)
        if not by_shard:
            self.ec_shard_locations.pop(m.id, None)

    def lookup(self, vid: int) -> list[DataNode]:
        """volumeId -> servers (normal or EC) — topology.go:89."""
        locs = self.volume_locations.get(vid)
        if locs:
            return list(locs.values())
        by_shard = self.ec_shard_locations.get(vid)
        if by_shard:
            seen: dict[str, DataNode] = {}
            for nodes in by_shard.values():
                for n in nodes:
                    seen[n.id] = n
            return list(seen.values())
        return []

    def next_volume_id(self) -> int:
        self.max_volume_id += 1
        return self.max_volume_id

    # ---- liveness (node.go:275-291) ----

    def dead_nodes(self, now: float | None = None) -> list[DataNode]:
        now = now or time.time()
        limit = 3 * self.pulse_seconds
        return [n for n in self.all_nodes() if now - n.last_seen > limit]

    # ---- placement-support queries ----

    def pick_weighted(self, candidates: list, k: int = 1) -> list:
        """Randomly pick k candidates weighted by free_space
        (node.go:65-117 RandomlyPickNodes analog)."""
        pool = [c for c in candidates if c.free_space() > 0]
        picked = []
        for _ in range(min(k, len(pool))):
            total = sum(c.free_space() for c in pool)
            if total <= 0:
                break
            r = random.randint(1, total)
            for c in pool:
                r -= c.free_space()
                if r <= 0:
                    picked.append(c)
                    pool.remove(c)
                    break
        return picked
