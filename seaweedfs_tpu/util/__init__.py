"""util subpackage."""
