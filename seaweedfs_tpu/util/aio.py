"""Sanctioned task-detachment helper.

A task that must SURVIVE its caller's cancellation (a singleflight
leader's shared work, a channel close kicked off from a sync
destructor) has three obligations a bare ``create_task`` silently
drops:

1. the handle must be retained somewhere until the task settles — an
   unreferenced asyncio task may be garbage-collected mid-flight;
2. its terminal exception must be consumed even when every awaiter was
   cancelled first, or asyncio logs "exception was never retrieved"
   at interpreter exit;
3. the detachment must be VISIBLE: reviewers (and weedlint's
   ``detach-discipline`` pass) treat ``detach(...)`` as the one
   spelling of "this outlives you by design" — a bare
   ``create_task`` next to a "survives cancellation" comment is a
   lint finding, not a convention.

``detach`` is that one spelling. It is NOT for loops whose handle the
owner retains and cancels on shutdown (heartbeats, GC loops) — those
want a plain ``create_task`` stored on the owner so ``stop()`` can
cancel them.
"""

from __future__ import annotations

import asyncio
from typing import Coroutine

# strong refs until each task settles (obligation 1); bounded by the
# number of genuinely in-flight detached tasks
_DETACHED: set[asyncio.Task] = set()


def _settled(task: asyncio.Task) -> None:
    _DETACHED.discard(task)
    if not task.cancelled():
        task.exception()        # consume (obligation 2)


def detach(coro: Coroutine, *, name: str | None = None) -> asyncio.Task:
    """Start ``coro`` as a task that deliberately outlives its caller.

    Cancelling the caller does not cancel the task; the returned
    handle lets interested callers ``await asyncio.shield(task)`` so a
    cancelled awaiter stops waiting while the work runs on.
    """
    task = asyncio.get_running_loop().create_task(coro, name=name)
    _DETACHED.add(task)
    task.add_done_callback(_settled)
    return task


def detached_count() -> int:
    """In-flight detached tasks (test/debug introspection)."""
    return len(_DETACHED)
