"""Framing for multi-needle batch responses on the volume data plane.

One batch response carries many needle bodies. Each record is a compact
JSON meta line terminated by ``\n`` followed by exactly ``meta["size"]``
raw body bytes:

    {"fid":"3,0101f1...","status":200,"size":17,"etag":"deadbeef"}\n
    <17 raw bytes>
    {"fid":"3,0202ab...","status":404,"size":0,"error":"not found"}\n

The format streams: a reader never needs more lookahead than one meta
line plus the declared body, and bodies are never escaped or base64'd.
Shared by the volume server (encode), the worker sibling fan-out and
the client SDK / benchmark (decode), and the EC batched shard reads.
"""

from __future__ import annotations

import json

CONTENT_TYPE = "application/x-seaweedfs-batch"

# a meta line is small; anything larger is a corrupt/hostile stream
MAX_META_LINE = 64 * 1024


def encode_record(meta: dict, body: bytes = b"") -> bytes:
    """One framed record; ``size`` is always derived from the body."""
    m = dict(meta)
    m["size"] = len(body)
    return json.dumps(m, separators=(",", ":")).encode() + b"\n" + body


class FrameParser:
    """Incremental decoder: feed() arbitrary chunks, get complete
    ``(meta, body)`` records back as they close."""

    __slots__ = ("_buf", "_meta", "_need")

    def __init__(self) -> None:
        self._buf = bytearray()
        self._meta: dict | None = None
        self._need = 0

    def feed(self, data: bytes) -> list[tuple[dict, bytes]]:
        self._buf += data
        out: list[tuple[dict, bytes]] = []
        while True:
            if self._meta is None:
                nl = self._buf.find(b"\n")
                if nl < 0:
                    if len(self._buf) > MAX_META_LINE:
                        raise ValueError("batch meta line too long")
                    return out
                line = bytes(self._buf[:nl])
                del self._buf[:nl + 1]
                meta = json.loads(line)
                if not isinstance(meta, dict):
                    raise ValueError("batch meta is not an object")
                self._meta = meta
                self._need = int(meta.get("size", 0))
                if self._need < 0:
                    raise ValueError("negative batch body size")
            if len(self._buf) < self._need:
                return out
            body = bytes(self._buf[:self._need])
            del self._buf[:self._need]
            out.append((self._meta, body))
            self._meta = None
            self._need = 0

    @property
    def pending(self) -> bool:
        """True when a partial record is still buffered."""
        return bool(self._buf) or self._meta is not None


def parse_all(blob: bytes) -> list[tuple[dict, bytes]]:
    """Decode a complete batch payload; raises on trailing garbage."""
    p = FrameParser()
    out = p.feed(blob)
    if p.pending:
        raise ValueError("truncated batch payload")
    return out


def parse_reads_spec(spec: str) -> "list[tuple[int, int, int]]":
    """Parse the EC gather's ``sid:off:size,...`` spec — shared by the
    HTTP and frame transports of /admin/ec/shard_read so the grammar
    cannot drift between them. Raises ValueError on anything else."""
    reads = [tuple(int(x) for x in part.split(":"))
             for part in spec.split(",") if part]
    if not reads or any(len(r) != 3 for r in reads):
        raise ValueError("bad reads spec")
    return reads


def encode_shard_rows(reads, datas) -> bytes:
    """Render the batched shard-read response rows ({shard, status}
    meta + raw interval payload) — the one encoding both transports
    serve."""
    out = bytearray()
    for (sid, _off, _size), data in zip(reads, datas):
        if data is None:
            out += encode_record({"shard": sid, "status": 404,
                                  "error": "shard not found"})
        else:
            out += encode_record({"shard": sid, "status": 200}, data)
    return bytes(out)
