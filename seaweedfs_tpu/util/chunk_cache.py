"""Tiered byte-budgeted read caches for the hot data path.

Reference: weed/util/chunk_cache/ — `chunk_cache.go` fronts a small
in-memory tier (chunk_cache_in_memory.go) over size-classed mmap-backed
on-disk cache volumes (chunk_cache_on_disk.go, on_disk_cache_layer.go);
readers consult memory first, then the disk classes smallest-first.

This module provides the same shape as composable primitives:

  * ``CacheCounters``  — hit/miss/byte/eviction counters per named cache,
    mirrored into Prometheus (stats/metrics.py) when available so every
    cache shows up on ``/metrics`` (and sums across ``-workers`` siblings
    through the existing exposition merge).
  * ``LruByteCache``   — thread-safe LRU over arbitrary values with a
    byte budget (the in-memory tier, and the EC reconstruction cache).
  * ``DiskCacheLayer`` — size-classed ring of slots inside one
    preallocated mmap file per class (the disk tier).
  * ``TieredChunkCache`` — memory tier + optional disk tier keyed by
    file id, used by WeedClient/filer for whole-chunk caching.
  * ``NeedleCache``    — LRU of parsed needles keyed ``(vid, nid)`` for
    the volume server's hot-needle path, with volume-wide drops for
    vacuum/unmount invalidation.

Every cache here is an *optimisation overlay*: a ``None`` cache (or a
zero budget) must behave exactly like the code before this layer
existed, and correctness never depends on an entry being present.
"""

from __future__ import annotations

import mmap
import os
import threading
from collections import OrderedDict


class CacheCounters:
    """Plain-int hit/miss counters, mirrored to Prometheus when present.

    The ints are authoritative for tests and ``to_dict()``; the
    Prometheus side is best-effort and lazily bound so importing this
    module never forces prometheus_client to load.
    """

    __slots__ = ("name", "hits", "misses", "hit_bytes", "evictions",
                 "used_bytes", "_prom")

    def __init__(self, name: str):
        self.name = name
        self.hits = 0
        self.misses = 0
        self.hit_bytes = 0
        self.evictions = 0
        self.used_bytes = 0
        self._prom = None

    def _labels(self):
        if self._prom is None:
            from ..stats import metrics
            if not metrics.HAVE_PROMETHEUS:
                self._prom = ()
            else:
                self._prom = (
                    metrics.CACHE_HITS.labels(self.name),
                    metrics.CACHE_MISSES.labels(self.name),
                    metrics.CACHE_HIT_BYTES.labels(self.name),
                    metrics.CACHE_EVICTIONS.labels(self.name),
                    metrics.CACHE_USED_BYTES.labels(self.name),
                )
        return self._prom

    def hit(self, nbytes: int) -> None:
        self.hits += 1
        self.hit_bytes += nbytes
        p = self._labels()
        if p:
            p[0].inc()
            p[2].inc(nbytes)

    def miss(self) -> None:
        self.misses += 1
        p = self._labels()
        if p:
            p[1].inc()

    def evicted(self, n: int = 1) -> None:
        self.evictions += n
        p = self._labels()
        if p:
            p[3].inc(n)

    def set_used(self, nbytes: int) -> None:
        self.used_bytes = nbytes
        p = self._labels()
        if p:
            p[4].set(nbytes)

    def set_budget(self, nbytes: int) -> None:
        """Export the configured byte budget so occupancy-vs-budget is
        one division on any scrape/timeline window (saturation.py)."""
        from ..stats import metrics
        if metrics.HAVE_PROMETHEUS:
            metrics.CACHE_BUDGET_BYTES.labels(self.name).set(nbytes)

    def to_dict(self) -> dict:
        total = self.hits + self.misses
        return {"hits": self.hits, "misses": self.misses,
                "hit_bytes": self.hit_bytes, "evictions": self.evictions,
                "used_bytes": self.used_bytes,
                "hit_rate": (self.hits / total) if total else 0.0}


class LruByteCache:
    """Thread-safe LRU with a byte budget over opaque values.

    ``put`` evicts least-recently-used entries until the new entry fits;
    an entry larger than the whole budget is simply not cached (the
    caller's read path must not depend on residency).
    """

    def __init__(self, budget: int, name: str = "lru",
                 counters: CacheCounters | None = None):
        self.budget = max(0, int(budget))
        self.counters = counters or CacheCounters(name)
        self.counters.set_budget(self.budget)
        self._lock = threading.Lock()
        self._map: "OrderedDict[object, tuple[object, int]]" = OrderedDict()
        self._used = 0

    def __len__(self) -> int:
        return len(self._map)

    @property
    def used(self) -> int:
        return self._used

    def get(self, key, count: bool = True):
        """``count=False`` skips hit/miss accounting — for a fronting
        tier that counts once after consulting every layer."""
        with self._lock:
            item = self._map.get(key)
            if item is None:
                if count:
                    self.counters.miss()
                return None
            self._map.move_to_end(key)
            if count:
                self.counters.hit(item[1])
            return item[0]

    def peek_contains(self, key) -> bool:
        """Membership check with no counter/recency side effects."""
        with self._lock:
            return key in self._map

    def put(self, key, value, size: int | None = None,
            guard=None) -> None:
        """``guard`` (if given) is evaluated UNDER the cache lock and
        the insert is skipped when it returns False — callers use it to
        make a freshness check atomic with the insert (a check done
        outside the lock could pass, then an invalidation could run to
        completion before the insert re-pins the stale value)."""
        if size is None:
            size = len(value)
        if size > self.budget:
            return
        with self._lock:
            if guard is not None and not guard():
                return
            old = self._map.pop(key, None)
            if old is not None:
                self._used -= old[1]
            evicted = 0
            while self._used + size > self.budget and self._map:
                _, (_, esz) = self._map.popitem(last=False)
                self._used -= esz
                evicted += 1
            self._map[key] = (value, size)
            self._used += size
            if evicted:
                self.counters.evicted(evicted)
            self.counters.set_used(self._used)

    def delete(self, key) -> None:
        with self._lock:
            item = self._map.pop(key, None)
            if item is not None:
                self._used -= item[1]
                self.counters.set_used(self._used)

    def drop_where(self, pred) -> int:
        """Delete every entry whose key matches ``pred`` (vacuum /
        volume-unmount invalidation)."""
        with self._lock:
            dead = [k for k in self._map if pred(k)]
            for k in dead:
                self._used -= self._map.pop(k)[1]
            if dead:
                self.counters.set_used(self._used)
            return len(dead)

    def clear(self) -> None:
        with self._lock:
            self._map.clear()
            self._used = 0
            self.counters.set_used(0)


class DiskCacheLayer:
    """One size class of the disk tier: a ring of fixed-size slots in a
    single preallocated file, accessed through mmap.

    Mirrors the reference's on-disk cache volumes
    (chunk_cache_on_disk.go): inserting wraps around the ring, evicting
    whatever previously occupied the slot; lookups are an offset table
    plus one mmap slice. The file is a *cache* — it is recreated empty
    on every start and never fsynced.
    """

    def __init__(self, path: str, slot_size: int, slots: int):
        self.slot_size = slot_size
        self.slots = max(1, slots)
        self.path = path
        size = self.slot_size * self.slots
        with open(path, "wb") as f:
            f.truncate(size)
        self._f = open(path, "r+b")
        self._mm = mmap.mmap(self._f.fileno(), size)
        self._index: dict[object, tuple[int, int]] = {}  # key -> (slot, len)
        self._owner: list[object | None] = [None] * self.slots
        self._cursor = 0

    def get(self, key) -> bytes | None:
        loc = self._index.get(key)
        if loc is None:
            return None
        slot, length = loc
        off = slot * self.slot_size
        return self._mm[off:off + length]

    def put(self, key, data: bytes) -> bool:
        if len(data) > self.slot_size:
            return False
        slot = self._cursor
        self._cursor = (self._cursor + 1) % self.slots
        old = self._owner[slot]
        if old is not None:
            self._index.pop(old, None)
        prev = self._index.pop(key, None)
        if prev is not None:
            self._owner[prev[0]] = None
        off = slot * self.slot_size
        self._mm[off:off + len(data)] = data
        self._owner[slot] = key
        self._index[key] = (slot, len(data))
        return old is not None

    def delete(self, key) -> None:
        loc = self._index.pop(key, None)
        if loc is not None:
            self._owner[loc[0]] = None

    @property
    def used(self) -> int:
        return sum(length for _, length in self._index.values())

    def close(self) -> None:
        self._mm.close()
        self._f.close()
        try:
            os.remove(self.path)
        except OSError:
            pass


# disk tier size classes (slot byte sizes); an item routes to the
# smallest class whose slot holds it — same ladder shape as the
# reference's 1MB/4MB on-disk layers, extended down to 256KB so the
# memory tier stays reserved for truly small chunks
DISK_SLOT_SIZES = (256 << 10, 1 << 20, 4 << 20)


class TieredChunkCache:
    """Whole-chunk cache keyed by file id: memory LRU for small chunks,
    size-classed disk tier for larger ones (weed/util/chunk_cache).

    Entries are immutable chunk bodies; ``delete`` exists for the rare
    same-fid overwrite/delete paths (read-your-writes through one
    client), mirroring the reference's assumption that chunk fids are
    content-stable.
    """

    def __init__(self, mem_bytes: int, disk_dir: str | None = None,
                 disk_bytes: int = 256 << 20,
                 mem_item_max: int | None = None,
                 name: str = "chunk"):
        self.counters = CacheCounters(name)
        if mem_item_max is None:
            # with a disk tier, memory stays reserved for small chunks
            # and the size classes catch the rest (reference layering);
            # memory-only must take larger chunks itself or a plain
            # object re-read caches nothing
            mem_item_max = (256 << 10) if disk_dir else (4 << 20)
        self.mem_item_max = min(mem_item_max, max(1, mem_bytes))
        # PER-FID mutation generations (a single global counter would
        # let every unrelated upload in flight suppress every fill —
        # near-zero hit rate under mixed load): fetchers snapshot
        # fill_token(fid) before the network read and set_if refuses
        # when it moved. The dict is bounded by an epoch sweep: clearing
        # it bumps the epoch, which conservatively invalidates every
        # outstanding token (a refused fill is always safe).
        self._gens: dict[str, int] = {}
        self._epoch = 0
        self._mem = LruByteCache(mem_bytes, counters=self.counters)
        self._lock = threading.Lock()
        self._disk: list[DiskCacheLayer] = []
        self._lock_f = None
        if disk_dir:
            os.makedirs(disk_dir, exist_ok=True)
            # exclusive per-directory flock: a second daemon pointed at
            # the same -cache.dir would truncate files this process has
            # mmapped and every hit would silently serve zeros — fail
            # loudly at startup instead. flock on a held-open fd is
            # kernel-accurate liveness: released on any process death,
            # immune to recycled pids and torn lockfiles.
            import fcntl
            self._lock_f = open(os.path.join(disk_dir, ".cache_lock"),
                                "a+")
            try:
                fcntl.flock(self._lock_f, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                self._lock_f.close()
                self._lock_f = None
                raise RuntimeError(
                    f"cache dir {disk_dir!r} already in use by another "
                    f"process; every daemon needs its own -cache.dir")
            per_class = max(disk_bytes // len(DISK_SLOT_SIZES),
                            max(DISK_SLOT_SIZES))
            for slot in DISK_SLOT_SIZES:
                self._disk.append(DiskCacheLayer(
                    os.path.join(disk_dir, f"cache_{slot}.dat"),
                    slot, per_class // slot))

    @property
    def has_disk(self) -> bool:
        """True when gets/sets may touch the mmap tier — callers on an
        event loop should then run them in an executor (a cold-page
        slice blocks on major page faults for up to a slot size)."""
        return bool(self._disk)

    @property
    def max_item_size(self) -> int:
        return self._disk[-1].slot_size if self._disk else self.mem_item_max

    def get(self, fid: str) -> bytes | None:
        data = self._mem.get(fid, count=False)
        if data is not None:
            self.counters.hit(len(data))
            return data
        if self._disk:
            with self._lock:
                for layer in self._disk:
                    data = layer.get(fid)
                    if data is not None:
                        self.counters.hit(len(data))
                        return data
        self.counters.miss()
        return None

    def set(self, fid: str, data: bytes) -> None:
        if len(data) <= self.mem_item_max:
            self._mem.put(fid, data)
            if self._disk:
                # the inner LRU published its memory-only total to the
                # shared used-bytes gauge; re-publish mem+disk so the
                # gauge never flaps by the disk tier's size
                with self._lock:
                    self._set_used_locked()
            return
        with self._lock:
            for layer in self._disk:
                if len(data) <= layer.slot_size:
                    if layer.put(fid, data):
                        self.counters.evicted()
                    self._set_used_locked()
                    return
        # larger than every class: not cacheable

    def fill_token(self, fid: str) -> tuple[int, int]:
        """Snapshot taken BEFORE a fetch; set_if refuses the fill when
        the fid was invalidated (or the gen table swept) in between."""
        return (self._epoch, self._gens.get(fid, 0))

    def set_if(self, fid: str, data: bytes,
               token: tuple[int, int]) -> bool:
        if token != (self._epoch, self._gens.get(fid, 0)):
            return False        # an overwrite/delete raced this fetch
        self.set(fid, data)
        return True

    def delete(self, fid: str) -> None:
        self._gens[fid] = self._gens.get(fid, 0) + 1
        if len(self._gens) > 4096:
            # bounded: the sweep moves the epoch so every outstanding
            # token — including ones whose per-fid counter we just
            # forgot — fails its set_if check
            self._gens.clear()
            self._epoch += 1
        self._mem.delete(fid)
        if self._disk:
            with self._lock:
                for layer in self._disk:
                    layer.delete(fid)
                self._set_used_locked()

    def _set_used_locked(self) -> None:
        self.counters.set_used(
            self._mem.used + sum(layer.used for layer in self._disk))

    def contains(self, fid: str) -> bool:
        """Residency peek with no counter or recency side effects."""
        if self._mem.peek_contains(fid):
            return True
        if self._disk:
            with self._lock:
                return any(fid in layer._index for layer in self._disk)
        return False

    def close(self) -> None:
        self._mem.clear()
        for layer in self._disk:
            layer.close()
        self._disk = []
        if self._lock_f is not None:
            # closing the fd releases the flock; the lockfile itself
            # stays (removing it would let two successors each lock a
            # different inode of the same path)
            self._lock_f.close()
            self._lock_f = None

    def to_dict(self) -> dict:
        return self.counters.to_dict()


class EcRecoverCache(LruByteCache):
    """LruByteCache with per-volume generations for keys shaped
    ``(vid, ...)``: drop_volume bumps the gen so a reconstruction fill
    racing an EC re-encode/remount is refused — the same fencing
    NeedleCache and TieredChunkCache use for their fill races."""

    def __init__(self, budget: int, name: str = "ec_recover"):
        super().__init__(budget, name=name)
        self._vid_gen: dict[int, int] = {}

    def generation(self, vid: int) -> int:
        return self._vid_gen.get(vid, 0)

    def put_fenced(self, key, value, gen: int) -> None:
        self.put(key, value,
                 guard=lambda: gen == self._vid_gen.get(key[0], 0))

    def drop_volume(self, vid: int) -> int:
        self._vid_gen[vid] = self._vid_gen.get(vid, 0) + 1
        return self.drop_where(lambda k: k[0] == vid)


# bookkeeping overhead charged per cached needle beyond its data bytes
# (parsed-object fields, dict slot) so the byte budget stays honest for
# many tiny needles
_NEEDLE_OVERHEAD = 256


class NeedleCache:
    """Hot-needle cache for the volume data plane: parsed ``Needle``
    objects keyed ``(vid, nid)`` under one byte budget.

    Serving a hit skips the disk pread, the record parse AND the CRC
    re-check — and, through ``Store.cached_needle``, the executor
    round-trip the read handlers otherwise pay. Strict invalidation
    (write/delete per needle, volume-wide on vacuum/unmount/tail-apply)
    keeps read-your-writes exact; the cookie stored in the needle is
    re-checked by the caller on every hit.
    """

    def __init__(self, budget: int, item_max: int | None = None,
                 name: str = "needle"):
        self.counters = CacheCounters(name)
        self._lru = LruByteCache(budget, counters=self.counters)
        self.item_max = item_max if item_max is not None \
            else max(64 << 10, budget // 64)
        # per-volume mutation generation: a fill racing an invalidation
        # must lose. Readers snapshot generation(vid) BEFORE the disk
        # read and put() refuses when it moved — otherwise a reader
        # that fetched old bytes could re-populate the cache AFTER the
        # writer's invalidate, leaving the stale entry pinned until the
        # next write. (GIL-atomic dict ops suffice: a lost concurrent
        # increment still leaves the value changed from any snapshot
        # taken before either bump; it can never move backwards.)
        self._gen: dict[int, int] = {}

    def peek(self, vid: int, nid: int):
        """Raw entry with NO counter updates — the caller validates
        cookie/expiry first and then reports hit()/miss(), so a
        present-but-unservable entry (wrong cookie, expired TTL) is
        never inflated into a hit."""
        return self._lru.get((vid, nid), count=False)

    def hit(self, needle) -> None:
        self.counters.hit(len(needle.data))

    def miss(self) -> None:
        self.counters.miss()

    def generation(self, vid: int) -> int:
        return self._gen.get(vid, 0)

    def put(self, vid: int, nid: int, needle,
            gen: int | None = None) -> None:
        size = len(needle.data) + _NEEDLE_OVERHEAD
        if size - _NEEDLE_OVERHEAD > self.item_max:
            return
        # the gen comparison runs UNDER the LRU lock, atomic with the
        # insert: checked outside, an invalidate() in another executor
        # thread could bump-and-delete entirely between the check and
        # the insert and the stale fill would land anyway. (If the bump
        # happens while we hold the lock, the invalidator's delete is
        # queued on the same lock and removes our entry right after.)
        guard = (None if gen is None
                 else lambda: gen == self._gen.get(vid, 0))
        self._lru.put((vid, nid), needle, size, guard=guard)

    def invalidate(self, vid: int, nid: int) -> None:
        self._gen[vid] = self._gen.get(vid, 0) + 1
        self._lru.delete((vid, nid))

    def drop_volume(self, vid: int) -> int:
        self._gen[vid] = self._gen.get(vid, 0) + 1
        return self._lru.drop_where(lambda k: k[0] == vid)

    def clear(self) -> None:
        self._lru.clear()

    def to_dict(self) -> dict:
        return self.counters.to_dict()
