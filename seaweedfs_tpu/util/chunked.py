"""Chunk-manifest files on raw volumes (no filer required).

Reference: weed/operation/chunked_file.go (ChunkManifest json model,
LoadChunkManifest, DeleteChunks) + submit.go:112-199 (client-side
auto-split of uploads larger than maxMB into per-chunk fids plus one
manifest needle flagged FLAG_IS_CHUNK_MANIFEST, stored with ?cm=true).
The volume server resolves the manifest on GET
(volume_server_handlers_read.go:170-199 tryHandleChunkedFile) and
deletes the chunks with the manifest needle on DELETE.
"""

from __future__ import annotations

import gzip
import json
from dataclasses import dataclass, field


@dataclass
class ChunkInfo:
    fid: str
    offset: int
    size: int

    def to_dict(self) -> dict:
        return {"fid": self.fid, "offset": self.offset, "size": self.size}


@dataclass
class ChunkManifest:
    name: str = ""
    mime: str = ""
    size: int = 0
    chunks: list[ChunkInfo] = field(default_factory=list)

    def marshal(self) -> bytes:
        return json.dumps({
            "name": self.name, "mime": self.mime, "size": self.size,
            "chunks": [c.to_dict() for c in self.chunks],
        }).encode()

    @classmethod
    def load(cls, buffer: bytes, is_gzipped: bool = False
             ) -> "ChunkManifest":
        if is_gzipped:
            buffer = gzip.decompress(buffer)
        d = json.loads(buffer)
        cm = cls(name=d.get("name", ""), mime=d.get("mime", ""),
                 size=int(d.get("size", 0)))
        cm.chunks = sorted(
            (ChunkInfo(c["fid"], int(c["offset"]), int(c["size"]))
             for c in d.get("chunks", [])),
            key=lambda c: c.offset)
        return cm

    def resolve(self, offset: int, size: int
                ) -> list[tuple[str, int, int, int]]:
        """Map a logical [offset, offset+size) range to
        (fid, chunk-local offset, length, logical offset) pieces."""
        out = []
        end = offset + size
        for c in self.chunks:
            lo = max(offset, c.offset)
            hi = min(end, c.offset + c.size)
            if lo < hi:
                out.append((c.fid, lo - c.offset, hi - lo, lo))
        return out

    async def delete_chunks(self, client) -> int:
        """DeleteChunks (chunked_file.go:76-89)."""
        return await client.delete_fids([c.fid for c in self.chunks])


async def upload_in_chunks(client, data: bytes, max_mb: int,
                           name: str = "", mime: str = "",
                           collection: str = "", replication: str = "",
                           ttl: str = "", data_center: str = ""
                           ) -> tuple[str, "ChunkManifest"]:
    """Client-side auto-split (submit.go:112-199): upload ceil(n/maxMB)
    chunk needles, then the manifest needle with ?cm=true. On any chunk
    failure the already-uploaded chunks are deleted. Returns
    (manifest fid, manifest)."""
    chunk_size = max_mb * 1024 * 1024
    cm = ChunkManifest(name=name, mime=mime, size=len(data))
    try:
        for i in range(0, len(data), chunk_size):
            piece = data[i:i + chunk_size]
            fid = await client.upload_data(
                piece, collection=collection, replication=replication,
                ttl=ttl, data_center=data_center)
            cm.chunks.append(ChunkInfo(fid, i, len(piece)))
        a = await client.assign(collection=collection,
                                replication=replication, ttl=ttl,
                                data_center=data_center)
        await client.upload_manifest(a["fid"], a["url"], cm, ttl=ttl,
                                     auth=a.get("auth", ""))
        return a["fid"], cm
    except Exception:
        # ANY mid-upload failure (network drop, timeout, bad assign
        # body — not just OperationError) must not orphan the
        # already-uploaded chunk needles
        await cm.delete_chunks(client)
        raise
