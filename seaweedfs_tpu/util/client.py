"""Async client SDK for the master/volume tier.

Reference: weed/operation/ (assign_file_id.go, upload_content.go,
lookup.go w/ 10-min vid cache, delete_content.go batch deletes) and
weed/wdclient/ (cached master client).
"""

from __future__ import annotations

from ..security import tls

import asyncio
import time

import aiohttp


class OperationError(Exception):
    pass


def parse_master_seeds(master_url: str) -> list[str]:
    """Comma-separated master seed list (shared with the volume
    server's heartbeat client)."""
    return [m.strip() for m in master_url.split(",") if m.strip()]


class WeedClient:
    def __init__(self, master_url: str,
                 session: aiohttp.ClientSession | None = None,
                 lookup_cache_ttl: float = 600.0,
                 jwt_key: str = ""):
        # comma-separated seed list: like the reference's wdclient, a
        # dead master must not strand the client — master requests
        # rotate through the surviving seeds (masterclient.go:45-119)
        self.master_seeds = parse_master_seeds(master_url)
        # empty input keeps the raw string and fails on first use, like
        # the pre-seed-list behavior
        self.master_url = (self.master_seeds[0] if self.master_seeds
                           else master_url)
        self._session = session
        self._own = session is None
        self._vid_cache: dict[str, tuple[float, list[dict]]] = {}
        self._cache_ttl = lookup_cache_ttl
        # when the cluster enforces write JWTs, co-deployed components
        # (filer, chunk GC) mint their own tokens with the shared key
        self.jwt_key = jwt_key
        self._master_client = None  # optional wdclient (attach_master_client)

    async def __aenter__(self) -> "WeedClient":
        if self._session is None:
            self._session = tls.make_session(
                timeout=aiohttp.ClientTimeout(total=120))
        return self

    async def __aexit__(self, *exc) -> None:
        if self._own and self._session:
            await self._session.close()

    @property
    def http(self) -> aiohttp.ClientSession:
        assert self._session is not None
        return self._session

    # ---- assign / lookup ----

    async def assign(self, count: int = 1, collection: str = "",
                     replication: str = "", ttl: str = "",
                     data_center: str = "") -> dict:
        params = {"count": str(count)}
        if collection:
            params["collection"] = collection
        if replication:
            params["replication"] = replication
        if ttl:
            params["ttl"] = ttl
        if data_center:
            params["dataCenter"] = data_center
        body = await self._master_get("/dir/assign", params)
        if "error" in body:
            raise OperationError(f"assign: {body['error']}")
        return body

    async def _master_get(self, path: str, params: dict) -> dict:
        """GET against the current master, rotating through the seed
        list when the master is unreachable (a killed leader must not
        strand single-seed-configured clients mid-failover)."""
        last: object = None
        for _ in range(max(1, len(self.master_seeds))):
            try:
                async with self.http.get(
                        tls.url(self.master_url, path),
                        params=params) as resp:
                    body = await resp.json()
                    if resp.status in (502, 503):
                        # reachable follower proxying a dead leader /
                        # no leader yet: the NEXT seed may already be
                        # the new leader
                        last = body.get("error", f"http {resp.status}")
                        self._rotate_seed()
                        continue
                    return body
            except (aiohttp.ClientError, asyncio.TimeoutError,
                    OSError) as e:
                last = e
                self._rotate_seed()
        raise OperationError(f"master unreachable: {last}")

    def _rotate_seed(self) -> None:
        if len(self.master_seeds) > 1:
            i = (self.master_seeds.index(self.master_url)
                 if self.master_url in self.master_seeds else -1)
            self.master_url = self.master_seeds[
                (i + 1) % len(self.master_seeds)]

    def attach_master_client(self, mc) -> None:
        """Route lookups through a watch-fed MasterClient
        (wdclient/masterclient.go) instead of per-vid HTTP requests."""
        self._master_client = mc

    async def lookup(self, vid: str) -> list[dict]:
        """Volume locations with a TTL cache (lookup.go:10min)."""
        mc = getattr(self, "_master_client", None)
        if mc is not None:
            try:
                vid_num = int(vid)
            except ValueError as e:
                raise OperationError(f"lookup: bad volume id {vid!r}") from e
            locs = mc.lookup(vid_num)
            if locs:
                return [{"url": loc.url, "publicUrl": loc.public_url}
                        for loc in locs]
        hit = self._vid_cache.get(vid)
        now = time.time()
        if hit and now - hit[0] < self._cache_ttl:
            return hit[1]
        body = await self._master_get("/dir/lookup", {"volumeId": vid})
        if "locations" not in body:
            raise OperationError(f"lookup {vid}: {body.get('error')}")
        self._vid_cache[vid] = (now, body["locations"])
        return body["locations"]

    def invalidate(self, vid: str) -> None:
        self._vid_cache.pop(vid, None)

    async def lookup_file_id(self, fid: str) -> str:
        vid = fid.split(",")[0]
        locs = await self.lookup(vid)
        return tls.url(locs[0]['publicUrl'], f"/{fid}")

    # ---- data ops ----

    def _mint_jwt(self, fid: str) -> str:
        if not self.jwt_key:
            return ""
        from ..security.jwt import gen_jwt
        return gen_jwt(self.jwt_key, fid)

    async def upload(self, fid: str, url: str, data: bytes,
                     mime: str = "", ttl: str = "",
                     auth: str = "") -> dict:
        params = {"ttl": ttl} if ttl else {}
        headers = {"Content-Type": mime} if mime else {}
        token = auth or self._mint_jwt(fid)
        if token:
            headers["Authorization"] = f"Bearer {token}"
        async with self.http.post(tls.url(url, f"/{fid}"), data=data,
                                  params=params, headers=headers) as resp:
            body = await resp.json()
            if resp.status not in (200, 201):
                raise OperationError(f"upload {fid}: {body}")
            return body

    async def upload_manifest(self, fid: str, url: str, manifest,
                              ttl: str = "", auth: str = "") -> dict:
        """Store a ChunkManifest needle (?cm=true marks the flag;
        operation/submit.go:222, needle_parse_multipart.go:86)."""
        params = {"cm": "true"}
        if ttl:
            params["ttl"] = ttl
        headers = {"Content-Type": "application/json"}
        token = auth or self._mint_jwt(fid)
        if token:
            headers["Authorization"] = f"Bearer {token}"
        async with self.http.post(tls.url(url, f"/{fid}"),
                                  data=manifest.marshal(),
                                  params=params, headers=headers) as resp:
            body = await resp.json()
            if resp.status not in (200, 201):
                raise OperationError(f"upload manifest {fid}: {body}")
            return body

    async def upload_data(self, data: bytes, collection: str = "",
                          replication: str = "", ttl: str = "",
                          mime: str = "", data_center: str = "") -> str:
        """assign + upload (forwarding the assign's write token); returns
        the fid."""
        a = await self.assign(collection=collection,
                              replication=replication, ttl=ttl,
                              data_center=data_center)
        await self.upload(a["fid"], a["url"], data, mime=mime, ttl=ttl,
                          auth=a.get("auth", ""))
        return a["fid"]

    async def read(self, fid: str, offset: int = 0,
                   size: int = -1) -> bytes:
        """Read with location failover: every holder from the lookup is
        tried (the reference's readUrl does the same across replicas /
        EC shard holders); a dead first holder must not fail the read.
        On a full miss the cached locations are invalidated and one
        fresh lookup retries — a killed server stays in the 10-min vid
        cache otherwise."""
        vid = fid.split(",")[0]
        headers = {}
        if offset or size >= 0:
            end = "" if size < 0 else str(offset + size - 1)
            headers["Range"] = f"bytes={offset}-{end}"
        last: str = "no locations"
        for attempt in range(2):
            try:
                locs = await self.lookup(vid)
            except OperationError as e:
                last = str(e)
                break
            for loc in locs:
                url = tls.url(loc["publicUrl"], f"/{fid}")
                try:
                    async with self.http.get(url, headers=headers) as resp:
                        if resp.status in (404, 410):
                            # authoritative: the holder says it is gone
                            raise OperationError(f"read {fid}: not found")
                        data = await resp.read()
                        if resp.status >= 400:
                            # an error body must never masquerade as
                            # file content; 5xx => try the next holder
                            last = (f"http {resp.status} "
                                    f"{data[:200].decode(errors='replace')}")
                            continue
                except (aiohttp.ClientError, asyncio.TimeoutError,
                        OSError) as e:
                    last = f"{type(e).__name__} {e}"
                    continue
                if resp.status == 200 and (offset or size >= 0):
                    # server ignored Range; slice locally
                    data = data[offset:offset + size if size >= 0
                                else None]
                return data
            if attempt == 0:
                self.invalidate(vid)  # stale holders: refresh + retry
        raise OperationError(f"read {fid}: {last}")

    async def delete_fids(self, fids: list[str]) -> int:
        """Batch delete grouped per volume server
        (delete_content.go DeleteFilesAtOneVolumeServer)."""
        by_server: dict[str, list[str]] = {}
        for fid in fids:
            try:
                locs = await self.lookup(fid.split(",")[0])
            except OperationError:
                continue
            for loc in locs:
                by_server.setdefault(loc["url"], []).append(fid)

        async def drop_one_by_one(server: str, batch: list[str]) -> int:
            n = 0
            for fid in batch:
                headers = {}
                token = self._mint_jwt(fid)
                if token:
                    headers["Authorization"] = f"Bearer {token}"
                try:
                    async with self.http.delete(
                            tls.url(server, f"/{fid}"),
                            params={"type": "replicate"},
                            headers=headers) as resp:
                        n += resp.status == 200
                except aiohttp.ClientError:
                    pass
            return n

        async def drop(server: str, batch: list[str]) -> int:
            # one round trip per holding server via the batch endpoint
            # (volume_grpc_batch_delete.go analog), with per-fid write
            # tokens when the cluster enforces them
            payload: dict = {"fileIds": batch}
            if self.jwt_key:
                payload["tokens"] = {f: self._mint_jwt(f) for f in batch}
            try:
                async with self.http.post(
                        tls.url(server, "/admin/batch_delete"),
                        json=payload) as resp:
                    if resp.status == 200:
                        res = (await resp.json()).get("results", [])
                        ok = sum(r.get("status") in (200, 202)
                                 for r in res)
                        # rows the batch mode cannot handle (406 chunk
                        # manifests, transient 5xx) still get the
                        # per-fid tombstone the old path gave them
                        retry = [r.get("fileId") for r in res
                                 if r.get("status") in (406, 500, 503)]
                        if retry:
                            ok += await drop_one_by_one(server, retry)
                        return ok
            except (aiohttp.ClientError, ValueError):
                pass
            # endpoint unavailable: per-fid tombstones
            return await drop_one_by_one(server, batch)

        counts = await asyncio.gather(
            *(drop(s, b) for s, b in by_server.items()))
        return sum(counts)
