"""Async client SDK for the master/volume tier.

Reference: weed/operation/ (assign_file_id.go, upload_content.go,
lookup.go w/ 10-min vid cache, delete_content.go batch deletes) and
weed/wdclient/ (cached master client).

Resilience (beyond the reference): every hop runs under a RetryPolicy
(exponential backoff, full jitter, deadlines, shared retry budget) and
a per-upstream CircuitBreaker (util/resilience.py), and reads stream
with mid-flight replica failover — a volume server dying mid-body
rotates to the next location and resumes via a Range request instead
of failing the read.
"""

from __future__ import annotations

from ..security import tls
from . import failpoints, tracing
from .resilience import BreakerRegistry, RetryBudget, RetryPolicy
from .singleflight import SingleFlight

import asyncio
import json
import time

import aiohttp


class OperationError(Exception):
    """`retryable=True` marks failures a caller can sensibly retry at
    a HIGHER level (fresh assign, different upstream): transport
    errors, 5xx exhaustion, open circuits — never 4xx."""

    def __init__(self, msg: object, retryable: bool = False):
        super().__init__(msg)
        self.retryable = retryable


# Per-request timeouts: connect must fail fast (a dead peer's SYN
# blackhole), sock_read guards mid-transfer stalls, and total stays
# unbounded for genuinely large streaming bodies — the old single
# total=120 session timeout let one stalled peer wedge an upload for
# two minutes.
MASTER_TIMEOUT = aiohttp.ClientTimeout(total=30, connect=5, sock_read=15)
DATA_TIMEOUT = aiohttp.ClientTimeout(total=None, connect=10, sock_read=60)


def parse_master_seeds(master_url: str) -> list[str]:
    """Comma-separated master seed list (shared with the volume
    server's heartbeat client)."""
    return [m.strip() for m in master_url.split(",") if m.strip()]


class WeedClient:
    def __init__(self, master_url: str,
                 session: aiohttp.ClientSession | None = None,
                 lookup_cache_ttl: float = 600.0,
                 jwt_key: str = "",
                 retry: RetryPolicy | None = None,
                 breakers: BreakerRegistry | None = None,
                 chunk_cache=None,
                 negative_lookup_ttl: float = 1.0):
        # comma-separated seed list: like the reference's wdclient, a
        # dead master must not strand the client — master requests
        # rotate through the surviving seeds (masterclient.go:45-119)
        self.master_seeds = parse_master_seeds(master_url)
        # empty input keeps the raw string and fails on first use, like
        # the pre-seed-list behavior
        self.master_url = (self.master_seeds[0] if self.master_seeds
                           else master_url)
        self._session = session
        self._own = session is None
        self._vid_cache: dict[str, tuple[float, list[dict]]] = {}
        self._cache_ttl = lookup_cache_ttl
        # when the cluster enforces write JWTs, co-deployed components
        # (filer, chunk GC) mint their own tokens with the shared key
        self.jwt_key = jwt_key
        self._master_client = None  # optional wdclient (attach_master_client)
        self.budget = RetryBudget()
        self.retry = retry or RetryPolicy(max_attempts=4, base_delay=0.05,
                                          max_delay=2.0, total_timeout=30.0,
                                          budget=self.budget)
        self.breakers = breakers or BreakerRegistry(
            threshold=5, reset_timeout=5.0)
        # optional whole-chunk read cache (util/chunk_cache
        # TieredChunkCache): hot re-reads skip the volume-server hop;
        # upload/delete of a fid drop its entry so read-your-writes
        # holds through this client
        self.chunk_cache = chunk_cache
        # singleflight collapses concurrent duplicate work: one master
        # lookup per vid round, one chunk fetch per fid round
        self._lookup_sf = SingleFlight()
        self._chunk_sf = SingleFlight()
        # short-TTL negative lookup cache: a deleted/unknown volume
        # answers from memory instead of hammering the master on every
        # read; invalidated by assign (the vid may have just been grown)
        self.negative_lookup_ttl = negative_lookup_ttl
        self._neg_vids: dict[str, float] = {}
        from .chunk_cache import CacheCounters
        self._neg_counters = CacheCounters("lookup_neg")
        # persistent multiplexed frame channels to volume servers
        # (util/frame.py), lazily created by pipelined_read — reads
        # are open over frames exactly like the HTTP listeners
        self._frame_hub = None

    @staticmethod
    def _budget_key(upstream: str) -> str:
        """Retry-budget pool key: upstream + the requesting tenant's
        QoS class (set by the entry-tier admission middleware), so an
        abusive tenant hammering a flapping volume drains only its
        own pool — not the paying tenant's."""
        from .. import qos
        cls = qos.current_class()
        return f"{upstream}|{cls}" if cls else upstream

    async def __aenter__(self) -> "WeedClient":
        if self._session is None:
            self._session = tls.make_session(timeout=DATA_TIMEOUT)
        return self

    async def __aexit__(self, *exc) -> None:
        if self._frame_hub is not None:
            await self._frame_hub.close()
            self._frame_hub = None
        if self._own and self._session:
            await self._session.close()

    @property
    def http(self) -> aiohttp.ClientSession:
        assert self._session is not None
        return self._session

    # ---- assign / lookup ----

    async def assign(self, count: int = 1, collection: str = "",
                     replication: str = "", ttl: str = "",
                     data_center: str = "") -> dict:
        params = {"count": str(count)}
        if collection:
            params["collection"] = collection
        if replication:
            params["replication"] = replication
        if ttl:
            params["ttl"] = ttl
        if data_center:
            params["dataCenter"] = data_center
        body = await self._master_get("/dir/assign", params)
        if "error" in body:
            raise OperationError(f"assign: {body['error']}")
        fid = body.get("fid", "")
        if fid:
            # the assign may have just grown this volume: a lingering
            # negative lookup entry would 404 the immediate read-back
            self._neg_vids.pop(fid.split(",")[0], None)
        return body

    async def _master_get(self, path: str, params: dict) -> dict:
        """GET against the current master, rotating through the seed
        list when the master is unreachable (a killed leader must not
        strand single-seed-configured clients mid-failover); unreachable
        rounds retry with backoff under the policy, and each seed sits
        behind its own circuit breaker so a long-dead master costs
        microseconds, not connect timeouts."""
        last: object = None
        sp = tracing.start("client", path.rsplit("/", 1)[-1] or "master")
        headers: dict = {}
        if sp:
            tracing.inject(headers, sp)
        attempt = 0
        try:
            async for _ in self.retry.attempts(self._budget_key("master")):
                attempt += 1
                if attempt > 1:
                    sp.event("retry", attempt=attempt)
                for _ in range(max(1, len(self.master_seeds))):
                    br = self.breakers.get(f"master:{self.master_url}")
                    if not br.allow():
                        last = last or \
                            f"master {self.master_url} circuit open"
                        sp.event("breaker_open", upstream=self.master_url)
                        self._rotate_seed()
                        continue
                    try:
                        await failpoints.fail("client.master_get")
                        framed = await self._frame_json(
                            self.master_url, "GET", path,
                            params=params, headers=headers,
                            timeout=30.0)
                        if framed is not None:
                            status, rh, body = framed
                            if status in (307, 502, 503):
                                # follower/no-leader answer: frames
                                # carry the redirect as data (no
                                # aiohttp auto-follow), so chase the
                                # explicit leader hint ourselves
                                last = body.get(
                                    "error", f"frame {status}") \
                                    if isinstance(body, dict) \
                                    else f"frame {status}"
                                br.record_success()
                                hb = body if isinstance(body, dict) \
                                    else {}
                                hint = (hb.get("leader", "")
                                        or rh.get("X-Raft-Leader", "")
                                        or rh.get("x-raft-leader", ""))
                                if hint and hint != self.master_url:
                                    sp.event("leader_hint",
                                             leader=hint)
                                    self.master_url = hint
                                else:
                                    sp.event("seed_rotate",
                                             status=status)
                                    self._rotate_seed()
                                continue
                            br.record_success()
                            sp.status = "ok"
                            return body
                        async with self.http.get(
                                tls.url(self.master_url, path),
                                params=params, headers=headers,
                                timeout=MASTER_TIMEOUT) as resp:
                            body = await resp.json()
                            if resp.status in (502, 503):
                                # no leader yet / quorum lost: chase an
                                # explicit leader hint when the reply
                                # carries one (X-Raft-Leader rides every
                                # follower answer), else the NEXT seed
                                # may already be the new leader
                                last = body.get("error",
                                                f"http {resp.status}")
                                br.record_success()  # reachable, not broken
                                hint = (body.get("leader", "")
                                        or resp.headers.get(
                                            "X-Raft-Leader", ""))
                                if hint and hint != self.master_url:
                                    sp.event("leader_hint", leader=hint)
                                    self.master_url = hint
                                else:
                                    sp.event("seed_rotate",
                                             status=resp.status)
                                    self._rotate_seed()
                                continue
                            if resp.history and resp.url.port:
                                # a follower 307'd us to the leader:
                                # remember it so the next request goes
                                # straight there (no redirect hop)
                                self._learn_master(
                                    f"{resp.url.host}:{resp.url.port}")
                            br.record_success()
                            sp.status = "ok"
                            return body
                    except (aiohttp.ClientError, asyncio.TimeoutError,
                            OSError) as e:
                        last = e
                        br.record_failure()
                        sp.event("seed_rotate",
                                 error=f"{type(e).__name__} {e}"[:120])
                        self._rotate_seed()
            sp.status = "error"
            raise OperationError(f"master unreachable: {last}")
        finally:
            sp.finish()

    def _rotate_seed(self) -> None:
        if len(self.master_seeds) > 1:
            i = (self.master_seeds.index(self.master_url)
                 if self.master_url in self.master_seeds else -1)
            self.master_url = self.master_seeds[
                (i + 1) % len(self.master_seeds)]

    def _learn_master(self, leader: str) -> None:
        """Adopt a leader learned from a 307/hint; fold it into the
        seed rotation so a later death of THIS leader still rotates
        through every master we ever met."""
        if not leader:
            return
        if leader not in self.master_seeds:
            self.master_seeds.append(leader)
        self.master_url = leader

    def attach_master_client(self, mc) -> None:
        """Route lookups through a watch-fed MasterClient
        (wdclient/masterclient.go) instead of per-vid HTTP requests."""
        self._master_client = mc

    async def lookup(self, vid: str) -> list[dict]:
        """Volume locations with a TTL cache (lookup.go:10min), a
        short-TTL negative cache, and singleflight: N concurrent misses
        for one vid cost one master round trip, and reads of a
        deleted/unknown volume stop hammering the master for the
        negative TTL."""
        mc = getattr(self, "_master_client", None)
        if mc is not None:
            try:
                vid_num = int(vid)
            except ValueError as e:
                raise OperationError(f"lookup: bad volume id {vid!r}") from e
            locs = mc.lookup(vid_num)
            if locs:
                return [{"url": loc.url, "publicUrl": loc.public_url}
                        for loc in locs]
        hit = self._vid_cache.get(vid)
        now = time.time()
        if hit and now - hit[0] < self._cache_ttl:
            return hit[1]
        neg_until = self._neg_vids.get(vid)
        if neg_until is not None:
            if now < neg_until:
                self._neg_counters.hit(0)
                raise OperationError(
                    f"lookup {vid}: volume not found (negative-cached)")
            self._neg_vids.pop(vid, None)
        return await self._lookup_sf.do(vid,
                                        lambda: self._lookup_master(vid))

    async def _lookup_master(self, vid: str) -> list[dict]:
        self._neg_counters.miss()
        body = await self._master_get("/dir/lookup", {"volumeId": vid})
        if "locations" not in body:
            # authoritative miss from a reachable master: negative-cache
            # it (transport failures raise in _master_get and are NOT
            # cached — the volume may be perfectly fine). Bounded: a
            # client probing many distinct dead vids must not grow the
            # dict forever — sweep expired entries, then oldest.
            if len(self._neg_vids) >= 1024:
                now = time.time()
                self._neg_vids = {k: t for k, t in self._neg_vids.items()
                                  if t > now}
                while len(self._neg_vids) >= 1024:
                    self._neg_vids.pop(next(iter(self._neg_vids)))
            self._neg_vids[vid] = time.time() + self.negative_lookup_ttl
            raise OperationError(f"lookup {vid}: {body.get('error')}")
        self._vid_cache[vid] = (time.time(), body["locations"])
        self._neg_vids.pop(vid, None)
        return body["locations"]

    def invalidate(self, vid: str) -> None:
        self._vid_cache.pop(vid, None)
        self._neg_vids.pop(vid, None)

    async def lookup_file_id(self, fid: str) -> str:
        vid = fid.split(",")[0]
        locs = await self.lookup(vid)
        return tls.url(locs[0]['publicUrl'], f"/{fid}")

    # ---- data ops ----

    def _mint_jwt(self, fid: str) -> str:
        if not self.jwt_key:
            return ""
        from ..security.jwt import gen_jwt
        return gen_jwt(self.jwt_key, fid)

    async def upload(self, fid: str, url: str, data: bytes,
                     mime: str = "", ttl: str = "",
                     auth: str = "") -> dict:
        """Upload with bounded retries: 5xx and transport errors back
        off and retry (the write is idempotent — same fid, same bytes);
        4xx fail immediately. The volume upstream sits behind a
        breaker so a dead server sheds load fast."""
        params = {"ttl": ttl} if ttl else {}
        headers = {"Content-Type": mime} if mime else {}
        token = auth or self._mint_jwt(fid)
        if token:
            headers["Authorization"] = f"Bearer {token}"
        if self.chunk_cache is not None:
            # same-fid overwrite: drop BEFORE the write so reads issued
            # from now on can't hit the old bytes. A second drop AFTER
            # the write succeeds (below) closes the other window — a
            # fetch that started during the POST's round trip read the
            # old body from the server and would otherwise re-pin it.
            self.chunk_cache.delete(fid)
        sp = tracing.start("client", "upload", fid=fid, upstream=url)
        if sp:
            tracing.inject(headers, sp)
        br = self.breakers.get(url)
        last: object = None
        attempt = 0
        try:
            async for _ in self.retry.attempts(self._budget_key(url)):
                attempt += 1
                if attempt > 1:
                    sp.event("retry", attempt=attempt)
                if not br.allow():
                    last = last or f"upload {fid}: {url} circuit open"
                    sp.event("breaker_open", upstream=url)
                    break
                try:
                    await failpoints.fail("client.upload")
                    framed = await self._frame_json(
                        url, "POST", f"/{fid}", params=params,
                        headers=headers, body=data, timeout=60.0)
                    if framed is not None:
                        status, _, body = framed
                    else:
                        async with self.http.post(
                                tls.url(url, f"/{fid}"), data=data,
                                params=params, headers=headers,
                                timeout=DATA_TIMEOUT) as resp:
                            body = await resp.json()
                            status = resp.status
                    if status in (200, 201):
                        br.record_success()
                        if self.chunk_cache is not None:
                            self.chunk_cache.delete(fid)
                        sp.status = "ok"
                        sp.nbytes = len(data)
                        return body
                    if status < 500:
                        br.record_success()  # server healthy, we erred
                        sp.status = str(status)
                        raise OperationError(f"upload {fid}: {body}")
                    last = f"upload {fid}: {body}"
                    br.record_failure()
                except (aiohttp.ClientError, asyncio.TimeoutError,
                        OSError, ValueError) as e:
                    last = f"upload {fid}: {type(e).__name__} {e}"
                    br.record_failure()
            sp.status = "error"
            raise OperationError(str(last), retryable=True)
        finally:
            sp.finish()

    async def upload_manifest(self, fid: str, url: str, manifest,
                              ttl: str = "", auth: str = "") -> dict:
        """Store a ChunkManifest needle (?cm=true marks the flag;
        operation/submit.go:222, needle_parse_multipart.go:86)."""
        params = {"cm": "true"}
        if ttl:
            params["ttl"] = ttl
        headers = {"Content-Type": "application/json"}
        token = auth or self._mint_jwt(fid)
        if token:
            headers["Authorization"] = f"Bearer {token}"
        if self.chunk_cache is not None:
            # same drop-before/drop-after discipline as upload(): a
            # manifest overwrite of a cached fid must not serve the
            # pre-overwrite bytes, and a fetch racing the POST's round
            # trip must not re-pin them afterwards
            self.chunk_cache.delete(fid)
        await failpoints.fail("client.upload_manifest")
        async with self.http.post(tls.url(url, f"/{fid}"),
                                  data=manifest.marshal(),
                                  params=params, headers=headers,
                                  timeout=DATA_TIMEOUT) as resp:
            body = await resp.json()
            if resp.status not in (200, 201):
                raise OperationError(f"upload manifest {fid}: {body}")
            if self.chunk_cache is not None:
                self.chunk_cache.delete(fid)
            return body

    async def upload_data(self, data: bytes, collection: str = "",
                          replication: str = "", ttl: str = "",
                          mime: str = "", data_center: str = "") -> str:
        """assign + upload (forwarding the assign's write token); returns
        the fid. A retryable upload failure (dead/open-circuit target)
        gets a FRESH assign — the master routes around the dead server
        within a pulse or two, so re-assigning is what keeps writes
        available through a node death instead of failing them fast."""
        last: OperationError | None = None
        for _ in range(3):
            a = await self.assign(collection=collection,
                                  replication=replication, ttl=ttl,
                                  data_center=data_center)
            try:
                await self.upload(a["fid"], a["url"], data, mime=mime,
                                  ttl=ttl, auth=a.get("auth", ""))
                return a["fid"]
            except OperationError as e:
                if not e.retryable:
                    raise
                last = e
        raise last

    async def read_stream(self, fid: str, offset: int = 0,
                          size: int = -1):
        """Cached-or-network chunk stream: when a chunk cache is
        attached and holds this fid's whole body, the requested range
        is sliced from memory and the volume-server hop is skipped
        entirely; otherwise the degraded-read network path below runs
        unchanged."""
        cc = self.chunk_cache
        if cc is not None:
            data = await self._cc_get(fid)
            if data is not None:
                end = len(data) if size < 0 else min(len(data),
                                                     offset + size)
                for pos in range(offset, end, 1 << 16):
                    yield data[pos:min(pos + (1 << 16), end)]
                return
        async for chunk in self._read_stream_net(fid, offset, size):
            yield chunk

    async def _cc_get(self, fid: str) -> bytes | None:
        """Chunk-cache lookup that keeps mmap disk-tier I/O off the
        event loop — a cold-page 4MB slice would otherwise block every
        request on the daemon behind its page faults."""
        cc = self.chunk_cache
        if cc.has_disk:
            return await tracing.run_in_executor(cc.get, fid)
        return cc.get(fid)

    async def chunk_bytes(self, fid: str, size: int = -1) -> bytes:
        """Whole-chunk read through the cache, with singleflight: N
        concurrent readers of one cold chunk trigger ONE volume-server
        fetch; everyone shares the bytes (filer/S3/WebDAV hot path)."""
        cc = self.chunk_cache
        if cc is None:
            return await self.read(fid, 0, size)
        data = await self._cc_get(fid)
        if data is not None:
            return data
        # token BEFORE the fetch, and IN the singleflight key: a fill
        # that raced an overwrite/delete is refused by set_if, and a
        # reader arriving AFTER an acknowledged write starts a fresh
        # round instead of joining the stale in-flight one (the old
        # round only serves callers that began before the write
        # completed — a legal serialization)
        token = cc.fill_token(fid)

        async def fetch() -> bytes:
            parts = []
            async for chunk in self._read_stream_net(fid, 0, size):
                parts.append(chunk)
            blob = b"".join(parts)
            if cc.has_disk:
                # mmap writes for disk-routed sizes: executor, not loop
                await tracing.run_in_executor(cc.set_if, fid, blob,
                                              token)
            else:
                cc.set_if(fid, blob, token)
            return blob

        return await self._chunk_sf.do((fid, token), fetch)

    async def _read_stream_net(self, fid: str, offset: int = 0,
                               size: int = -1):
        """Async-generate the bytes of a needle with DEGRADED-READ
        FAILOVER: every holder from the lookup is tried; a holder that
        dies MID-BODY does not fail the read — the stream rotates to
        the next location and resumes from the exact byte reached, via
        a Range request. On a full miss the cached locations are
        invalidated and one fresh lookup retries (a killed server stays
        in the 10-min vid cache otherwise). Open breakers demote a
        location to last place rather than skipping it outright — shed
        load first, but never turn a readable file into an error.

        A clean short body (server's Content-Length honored) ends the
        stream normally — sparse/short chunks stay the caller's
        zero-fill business, exactly as before.

        The whole read is one client-tier span; every replica rotation,
        mid-body Range resume, breaker demotion and lookup refresh is a
        span event, so a degraded read's recovery dance is visible in
        the trace instead of only in aggregate counters. The span is
        finished in the generator's finally (an abandoned stream still
        records what it did). NOT entered as a context manager: an
        async generator body runs in its consumer's context, so a
        contextvar set here could leak into (or fail to reset from) a
        different task — the volume hop is parented via the explicit
        traceparent header instead."""
        sp = tracing.start("client", "read", fid=fid)
        vid = fid.split(",")[0]
        sent = 0                    # bytes already yielded
        last: str = "no locations"
        stalled = 0
        tries = 0
        try:
            while stalled < 2:
                # keep rotating while bytes ADVANCE (every replica may be
                # flaky under injected faults); give up only after two
                # consecutive lookup rounds with zero forward progress
                round_start = sent
                try:
                    locs = await self.lookup(vid)
                except OperationError as e:
                    last = str(e)
                    break
                # blocking() is a side-effect-free peek — allow() here
                # would consume half-open probes for locations the read
                # may never touch, wedging recovered upstreams half-open
                locs = sorted(locs, key=lambda l: self.breakers.get(
                    l.get("publicUrl", l.get("url", ""))).blocking())
                for loc in locs:
                    upstream = loc.get("publicUrl", loc.get("url", ""))
                    url = tls.url(upstream, f"/{fid}")
                    br = self.breakers.get(upstream)
                    cur = offset + sent
                    headers = {}
                    if sp:
                        tracing.inject(headers, sp)
                    tries += 1
                    if tries > 1:
                        # a second holder is only tried after the first
                        # failed: this IS the replica failover, resuming
                        # from the exact byte reached when mid-body
                        sp.event("replica_rotate", upstream=upstream,
                                 resume_at=cur, last=str(last)[:120])
                    if cur or size >= 0:
                        end = "" if size < 0 else str(offset + size - 1)
                        headers["Range"] = f"bytes={cur}-{end}"
                        if sent and tries > 1:
                            sp.event("range_resume", at=cur)
                    else:
                        # whole-needle fast path: one round trip on the
                        # persistent frame channel to this holder.
                        # Ranged and mid-body-resumed reads always ride
                        # HTTP (Range is an HTTP-leg contract); any
                        # frame failure or non-authoritative status
                        # drops to the HTTP leg below, which keeps
                        # owning breakers, rotation and retries
                        from .frame import FrameChannelError
                        status = None
                        try:
                            # chaos site: worker.frame severs this leg
                            await failpoints.fail("worker.frame")
                            chan = self.frame_hub.get(target=upstream)
                            status, _, fbody = await chan.request(
                                "GET", f"/{fid}", headers=headers,
                                timeout=30.0)
                        except (FrameChannelError,
                                asyncio.TimeoutError, OSError):
                            status = None
                        if status in (404, 410):
                            br.record_success()
                            sp.status = "404"
                            raise OperationError(
                                f"read {fid}: not found")
                        if status == 200:
                            for pos in range(0, len(fbody), 1 << 16):
                                chunk = fbody[pos:pos + (1 << 16)]
                                sent += len(chunk)
                                yield chunk
                            br.record_success()
                            sp.status = "ok"
                            return
                    try:
                        await failpoints.fail("client.read")
                        async with self.http.get(
                                url, headers=headers,
                                timeout=DATA_TIMEOUT) as resp:
                            if resp.status in (404, 410):
                                # authoritative: the holder says gone
                                br.record_success()
                                sp.status = "404"
                                raise OperationError(
                                    f"read {fid}: not found")
                            if resp.status >= 400:
                                # an error body must never masquerade as
                                # file content; 5xx => try the next holder
                                body = await resp.read()
                                last = (f"http {resp.status} "
                                        f"{body[:200].decode(errors='replace')}")
                                if resp.status >= 500:
                                    br.record_failure()
                                else:
                                    br.record_success()
                                continue
                            # server ignored Range (200 to a mid-file
                            # resume): skip the delivered prefix
                            skip = cur if resp.status == 200 else 0
                            async for chunk in resp.content.iter_chunked(
                                    1 << 16):
                                if skip:
                                    if len(chunk) <= skip:
                                        skip -= len(chunk)
                                        continue
                                    chunk = chunk[skip:]
                                    skip = 0
                                if size >= 0:
                                    remain = size - sent
                                    if len(chunk) > remain:
                                        chunk = chunk[:remain]
                                if chunk:
                                    sent += len(chunk)
                                    yield chunk
                                if size >= 0 and sent >= size:
                                    break
                            br.record_success()
                            sp.status = "ok"
                            return
                    except (aiohttp.ClientError, asyncio.TimeoutError,
                            OSError) as e:
                        # mid-body deaths land here (aiohttp raises
                        # ClientPayloadError when the peer dies before
                        # Content-Length is satisfied): rotate + resume
                        last = f"{type(e).__name__} {e}"
                        br.record_failure()
                        continue
                stalled = stalled + 1 if sent == round_start else 0
                self.invalidate(vid)    # stale holders: refresh + retry
                sp.event("lookup_refresh", stalled=stalled)
            sp.status = sp.status or "error"
            raise OperationError(f"read {fid}: {last}")
        finally:
            sp.finish(nbytes=sent)

    async def read(self, fid: str, offset: int = 0,
                   size: int = -1) -> bytes:
        """Read with location failover (buffered form of read_stream).
        Whole-needle reads route through the chunk cache + singleflight
        when one is attached."""
        if self.chunk_cache is not None and offset == 0 and size < 0:
            return await self.chunk_bytes(fid)
        parts = []
        async for chunk in self.read_stream(fid, offset, size):
            parts.append(chunk)
        return b"".join(parts)

    async def batch_read(self, fids: list[str], batch_max: int = 64
                         ) -> dict[str, bytes | None]:
        """Multi-needle GET: group fids by holding server and resolve
        each group with `/batch` round trips (util/batchframe framing)
        instead of one request per needle — the per-request overhead
        amortization the volume tier's unified wire provides. Rows the
        batch endpoint can't serve (chunked manifests, transient
        errors) and servers without the endpoint fall back to the
        resilient single-GET path; a fid that ultimately can't be read
        maps to None (callers decide whether that's fatal).

        Cache-aware: attached chunk-cache hits skip the network, and
        fetched whole bodies fill the cache under the same fill-token
        fencing chunk_bytes uses."""
        result: dict[str, bytes | None] = {}
        cc = self.chunk_cache
        by_server: dict[str, list[str]] = {}
        sp = tracing.start("client", "batch_read", n=len(fids))
        try:
            for fid in dict.fromkeys(fids):   # dedup, order-stable
                if cc is not None:
                    data = await self._cc_get(fid)
                    if data is not None:
                        result[fid] = data
                        continue
                try:
                    locs = await self.lookup(fid.split(",")[0])
                except OperationError:
                    result[fid] = None
                    continue
                url = locs[0].get("publicUrl", locs[0].get("url", ""))
                by_server.setdefault(url, []).append(fid)

            async def fallback(fid: str) -> None:
                try:
                    result[fid] = await self.read(fid)
                except OperationError:
                    result[fid] = None

            async def one_server(server: str, group: list[str]) -> None:
                for lo in range(0, len(group), batch_max):
                    chunk = group[lo:lo + batch_max]
                    # fill tokens snapshotted BEFORE the fetch, like
                    # chunk_bytes: a fid overwritten/deleted while the
                    # /batch response is in flight bumps its gen and
                    # set_if refuses the stale fill
                    tokens = ({f: cc.fill_token(f) for f in chunk}
                              if cc is not None else {})
                    rows: list | None = None
                    try:
                        await failpoints.fail("client.batch_read")
                        async with self.http.get(
                                tls.url(server, "/batch"),
                                params={"fids": ",".join(chunk)},
                                timeout=DATA_TIMEOUT) as resp:
                            if resp.status == 200:
                                from .batchframe import parse_all
                                rows = parse_all(await resp.read())
                    except (aiohttp.ClientError, asyncio.TimeoutError,
                            OSError, ValueError):
                        rows = None
                    if rows is None or len(rows) != len(chunk):
                        # endpoint unavailable / torn payload: the
                        # whole chunk takes the single-GET path
                        sp.event("batch_fallback", server=server,
                                 n=len(chunk))
                        for fid in chunk:
                            await fallback(fid)
                        continue
                    for fid, (meta, body) in zip(chunk, rows):
                        if meta.get("status") == 200:
                            if meta.get("gzip"):
                                import gzip as _gzip
                                body = _gzip.decompress(body)
                            if cc is not None:
                                if cc.has_disk:
                                    await tracing.run_in_executor(
                                        cc.set_if, fid, body,
                                        tokens[fid])
                                else:
                                    cc.set_if(fid, body, tokens[fid])
                            result[fid] = body
                        elif meta.get("status") == 404:
                            result[fid] = None
                        else:
                            # 406 manifest / transient 5xx: single GET
                            await fallback(fid)

            await asyncio.gather(*(one_server(s, g)
                                   for s, g in by_server.items()))
            sp.status = "ok"
            return {fid: result.get(fid) for fid in fids}
        finally:
            sp.finish()

    @property
    def frame_hub(self):
        """Lazily-built cache of persistent multiplexed frame channels
        (util/frame.FrameHub) — one per volume server this client has
        pipelined against; closed with the session in __aexit__."""
        if self._frame_hub is None:
            from .frame import FrameHub
            self._frame_hub = FrameHub(ssl=tls.client_ctx(),
                                       jwt_key=self.jwt_key)
        return self._frame_hub

    async def _frame_json(self, server: str, method: str, path: str,
                          params: dict | None = None,
                          headers: dict | None = None,
                          body: bytes = b"",
                          timeout: float = 30.0):
        """One request over the persistent frame channel to `server`,
        answer parsed as JSON: (status, headers, body-dict), or None
        when the frame leg is unavailable (peer predates frames,
        severed channel, open breaker, FLAG_FALLBACK) and the caller
        should ride the resilient HTTP path. Never raises — HTTP is
        the leg whose failures drive retry/breaker bookkeeping."""
        from .frame import FrameChannelError
        try:
            # chaos site: worker.frame (also armed inside the channel
            # send itself) severs this frame leg so every caller's
            # HTTP fallback is exercised
            await failpoints.fail("worker.frame")
            chan = self.frame_hub.get(target=server)
            status, rheaders, raw = await chan.request(
                method, path, query=params, headers=headers,
                body=body, timeout=timeout)
            return status, rheaders, json.loads(raw or b"{}")
        except (FrameChannelError, asyncio.TimeoutError, OSError,
                ValueError):
            return None

    async def pipelined_read(self, fids: list[str], depth: int = 8
                             ) -> dict[str, bytes | None]:
        """Pipelined multi-needle read: up to `depth` requests in
        flight per keep-alive frame connection (util/frame.py), so a
        needle costs tens of bytes of protocol overhead and no
        per-request round-trip wait — responses complete out of order
        and the socket stays full. Complements batch_read: /batch
        amortizes one response over many needles, pipelining overlaps
        many independent responses (and never waits for the slowest
        row in a batch).

        Any channel-level failure (peer predates the frame protocol,
        severed connection, FLAG_FALLBACK row) silently downgrades
        that fid to the resilient single-GET HTTP path. Cache-aware
        exactly like batch_read: hits skip the network, fills are
        fence-tokened. A fid that ultimately can't be read maps to
        None."""
        from .frame import FrameChannelError
        result: dict[str, bytes | None] = {}
        cc = self.chunk_cache
        by_server: dict[str, list[str]] = {}
        sp = tracing.start("client", "pipelined_read", n=len(fids),
                           depth=depth)
        try:
            for fid in dict.fromkeys(fids):   # dedup, order-stable
                if cc is not None:
                    data = await self._cc_get(fid)
                    if data is not None:
                        result[fid] = data
                        continue
                try:
                    locs = await self.lookup(fid.split(",")[0])
                except OperationError:
                    result[fid] = None
                    continue
                url = locs[0].get("publicUrl", locs[0].get("url", ""))
                by_server.setdefault(url, []).append(fid)

            async def fallback(fid: str) -> None:
                try:
                    result[fid] = await self.read(fid)
                except OperationError:
                    result[fid] = None

            async def one_server(server: str, group: list[str]) -> None:
                chan = self.frame_hub.get(target=server)
                sem = asyncio.Semaphore(max(1, depth))
                fell_back = 0

                async def one(fid: str) -> None:
                    nonlocal fell_back
                    token = cc.fill_token(fid) if cc is not None \
                        else None
                    async with sem:
                        try:
                            await failpoints.fail("client.pipeline")
                            status, _, body = await chan.request(
                                "GET", "/" + fid, timeout=30.0)
                        except (FrameChannelError, OSError):
                            # dead channel / FLAG_FALLBACK / injected
                            # fault: this fid rides the HTTP path
                            fell_back += 1
                            await fallback(fid)
                            return
                    if status == 200:
                        if cc is not None:
                            if cc.has_disk:
                                await tracing.run_in_executor(
                                    cc.set_if, fid, body, token)
                            else:
                                cc.set_if(fid, body, token)
                        result[fid] = body
                    elif status == 404:
                        result[fid] = None
                    else:
                        # 406 manifest / transient 5xx: single GET
                        await fallback(fid)

                await asyncio.gather(*(one(f) for f in group))
                if fell_back:
                    sp.event("pipeline_fallback", server=server,
                             n=fell_back)

            await asyncio.gather(*(one_server(s, g)
                                   for s, g in by_server.items()))
            sp.status = "ok"
            return {fid: result.get(fid) for fid in fids}
        finally:
            sp.finish()

    async def delete_fids(self, fids: list[str]) -> int:
        """Batch delete grouped per volume server
        (delete_content.go DeleteFilesAtOneVolumeServer)."""
        by_server: dict[str, list[str]] = {}
        for fid in fids:
            if self.chunk_cache is not None:
                self.chunk_cache.delete(fid)
            try:
                locs = await self.lookup(fid.split(",")[0])
            except OperationError:
                continue
            for loc in locs:
                by_server.setdefault(loc["url"], []).append(fid)

        async def drop_one_by_one(server: str, batch: list[str]) -> int:
            n = 0
            for fid in batch:
                headers = {}
                token = self._mint_jwt(fid)
                if token:
                    headers["Authorization"] = f"Bearer {token}"
                try:
                    await failpoints.fail("client.delete")
                    async with self.http.delete(
                            tls.url(server, f"/{fid}"),
                            params={"type": "replicate"},
                            headers=headers,
                            timeout=DATA_TIMEOUT) as resp:
                        n += resp.status == 200
                except (aiohttp.ClientError, asyncio.TimeoutError,
                        OSError):
                    pass
            return n

        async def drop(server: str, batch: list[str]) -> int:
            # one round trip per holding server via the batch endpoint
            # (volume_grpc_batch_delete.go analog), with per-fid write
            # tokens when the cluster enforces them
            br = self.breakers.get(server)
            if not br.allow():
                return 0            # dead server: fail fast, not timeout
            payload: dict = {"fileIds": batch}
            if self.jwt_key:
                payload["tokens"] = {f: self._mint_jwt(f) for f in batch}
            try:
                await failpoints.fail("client.delete")
                framed = await self._frame_json(
                    server, "POST", "/admin/batch_delete",
                    headers={"content-type": "application/json"},
                    body=json.dumps(payload).encode(), timeout=30.0)
                if framed is not None:
                    br.record_success()
                    status, _, jbody = framed
                else:
                    async with self.http.post(
                            tls.url(server, "/admin/batch_delete"),
                            json=payload, timeout=DATA_TIMEOUT) as resp:
                        # the probe consumed by allow() MUST be
                        # resolved on every path — an unrecorded
                        # outcome wedges the breaker half-open forever
                        br.record_success()   # reachable (any status)
                        status = resp.status
                        jbody = (await resp.json()
                                 if status == 200 else {})
                if status == 200:
                    res = jbody.get("results", [])
                    ok = sum(r.get("status") in (200, 202)
                             for r in res)
                    # rows the batch mode cannot handle (406 chunk
                    # manifests, transient 5xx) still get the
                    # per-fid tombstone the old path gave them
                    retry = [r.get("fileId") for r in res
                             if r.get("status") in (406, 500, 503)]
                    if retry:
                        ok += await drop_one_by_one(server, retry)
                    return ok
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                    ValueError):
                br.record_failure()
            # endpoint unavailable: per-fid tombstones
            return await drop_one_by_one(server, batch)

        counts = await asyncio.gather(
            *(drop(s, b) for s, b in by_server.items()))
        if self.chunk_cache is not None:
            # second drop AFTER the tombstones landed: a fetch that
            # raced the deletes read the still-live body and would
            # otherwise re-pin a "deleted" chunk (see upload())
            for fid in fids:
                self.chunk_cache.delete(fid)
        return sum(counts)


class FilerHttpClient:
    """Shard-routing client for the filer metadata surface.

    Routes each path by longest-prefix match against the cached shard
    map, chases ``307 + X-Shard-Owner`` answers (bounded hops) and
    folds the learned owner into the route cache — the same learned-
    leader rotation discipline ``WeedClient._master_get`` applies to
    raft leadership. Used by tools/bench_meta.py and the meta soak;
    collapses to a plain filer client at 1 shard.
    """

    MAX_HOPS = 4

    def __init__(self, filers: list[str] | str, master_url: str = "",
                 timeout_s: float = 30.0):
        from ..filer.shard import RouteCache
        if isinstance(filers, str):
            filers = [f.strip() for f in filers.split(",") if f.strip()]
        if not filers:
            raise ValueError("FilerHttpClient needs at least one filer")
        self.filers = filers
        self.routes = RouteCache(master_url)
        self.timeout_s = timeout_s
        self.redirects_chased = 0
        self.session: aiohttp.ClientSession | None = None

    async def __aenter__(self) -> "FilerHttpClient":
        self.session = tls.make_session(
            timeout=aiohttp.ClientTimeout(total=self.timeout_s))
        if self.routes.master_seeds:
            await self.routes.refresh(self.session, force=True)
        return self

    async def __aexit__(self, *exc) -> None:
        if self.session is not None:
            await self.session.close()

    def _first_base(self, route_path: str) -> str:
        return self.routes.owner_for(route_path) or self.filers[0]

    async def request(self, method: str, path: str,
                      route_path: str | None = None,
                      params: dict | None = None,
                      data: bytes | None = None,
                      expect: tuple = (200, 201, 204)) -> dict:
        """One routed filer call. `route_path` is the namespace path
        the shard map routes on (defaults to `path` — pass it
        explicitly for /__api__/ calls whose URL is not the entry
        path). Raises OperationError on a non-`expect` terminal
        answer."""
        rp = route_path if route_path is not None else path
        base = self._first_base(rp)
        body: dict = {}
        for _ in range(self.MAX_HOPS):
            # chaos site: every routed metadata hop
            await failpoints.fail("filer.shard.route")
            async with self.session.request(
                    method, tls.url(base, path), params=params,
                    data=data, allow_redirects=False) as resp:
                if resp.status in (307, 302):
                    owner = resp.headers.get("X-Shard-Owner", "")
                    if not owner:
                        raise OperationError(
                            f"{method} {path}: redirect without "
                            f"X-Shard-Owner from {base}")
                    self.routes.learn(
                        resp.headers.get("X-Shard-Prefix", rp), owner,
                        int(resp.headers.get("X-Shard-Epoch", 0) or 0))
                    self.redirects_chased += 1
                    base = owner
                    continue
                if resp.content_type == "application/json":
                    body = await resp.json()
                else:
                    body = {"raw": await resp.read()}
                if resp.status == 503 and self.routes.master_seeds:
                    # owner unknown on that shard: refetch the map
                    # and retry (split window, registration race)
                    await asyncio.sleep(0.1)
                    await self.routes.refresh(self.session, force=True)
                    base = self._first_base(rp)
                    continue
                if resp.status not in expect:
                    raise OperationError(
                        f"{method} {path} -> {resp.status}: "
                        f"{body.get('error', '')}")
                return body
        raise OperationError(f"{method} {path}: shard redirect loop "
                             f"(> {self.MAX_HOPS} hops)")

    # -- the metadata ops the benchmarks drive -------------------------

    async def create(self, path: str, payload: bytes = b"x") -> dict:
        return await self.request("PUT", path, data=payload,
                                  expect=(201,))

    async def mkdir(self, path: str) -> dict:
        return await self.request("POST", path, params={"mkdir": "true"},
                                  expect=(201,))

    async def stat(self, path: str) -> dict:
        return await self.request("GET", "/__api__/lookup",
                                  route_path=path,
                                  params={"path": path})

    async def list_dir(self, path: str, start_file: str = "",
                       limit: int = 1024,
                       inclusive: bool = False) -> list[dict]:
        body = await self.request(
            "GET", "/__api__/list", route_path=path,
            params={"path": path, "startFile": start_file,
                    "inclusive": "true" if inclusive else "false",
                    "limit": str(limit)})
        return body.get("entries", [])

    async def rename(self, src: str, dst: str) -> dict:
        return await self.request("POST", "/__api__/rename",
                                  route_path=src,
                                  params={"from": src, "to": dst})

    async def delete(self, path: str, recursive: bool = False) -> dict:
        return await self.request(
            "DELETE", path,
            params={"recursive": "true"} if recursive else None)
