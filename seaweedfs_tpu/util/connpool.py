"""Keep-alive connection pools for SYNC (executor-thread) fetches.

The EC degraded-read path runs inside executor threads and cannot use
the server's aiohttp session; it used to open a fresh
urllib/TCP(+TLS) connection PER shard interval — exactly the k-fetch
fan-out cost the repair-bandwidth literature (arxiv 1309.0186) says
dominates recovery. These pools keep idle connections per target so a
degraded-read burst pays one handshake per holder, not one per
interval.

Two pools share the same discipline (thread-safe take/give, max-idle
age eviction, retry-once-on-stale so a respawned peer's poisoned
sockets never surface to the caller):

* :class:`SyncHttpPool` — `http.client` keep-alive HTTP.
* :class:`SyncFramePool` — the binary frame protocol (util/frame.py)
  over raw sockets: the same shard gather with per-request overhead
  measured in tens of bytes instead of HTTP headers. A peer that does
  not speak frames raises :class:`FrameUnsupported` and the caller
  falls back to the HTTP pool.
"""

from __future__ import annotations

import http.client
import random
import socket
import threading
import time

from ..security import tls
from . import events, glog
from .frame import (FrameDecoder, FrameError, HELLO, HELLO_IDENTITY_FID,
                    HELLO_IDENTITY_TTL_S, HELLO_OK, MAGIC, REQ, RESP,
                    VERSION, encode_frame)


class PoolError(OSError):
    pass


class FrameUnsupported(PoolError):
    """The target refused the frame handshake (predates the protocol
    or chaos severed it): retry this request over HTTP."""


class FrameProbeGate:
    """Per-target frame-downgrade bookkeeping with jittered
    exponential backoff — the fix for the old sticky 60s HTTP
    downgrade, where one transient peer restart silenced frames for a
    full minute with no signal. Each refusal doubles the re-probe
    delay (jittered +/-50% so a fleet of fetchers doesn't re-probe in
    lockstep) up to ``cap_s``; a frame success resets the target.
    Every downgrade is journaled as a ``frame_downgrade`` event.
    Thread-safe: the EC gather calls this from executor threads."""

    def __init__(self, base_s: float = 1.0, cap_s: float = 60.0,
                 max_targets: int = 256, rng=None, clock=time.monotonic):
        self.base_s = base_s
        self.cap_s = cap_s
        self.max_targets = max_targets
        self._rng = rng if rng is not None else random.Random()
        self._clock = clock
        # target -> (monotonic re-probe time, consecutive refusals)
        self._state: dict[str, tuple[float, int]] = {}
        self._lock = threading.Lock()

    def allow(self, target: str) -> bool:
        """True when frames should be tried for this target (never
        refused, or its backoff window has expired)."""
        with self._lock:
            st = self._state.get(target)
            return st is None or self._clock() >= st[0]

    def refused(self, target: str, reason: str = "") -> float:
        """Record a frame refusal; returns the chosen re-probe delay
        and journals the downgrade so it is never silent."""
        with self._lock:
            if target not in self._state \
                    and len(self._state) >= self.max_targets:
                self._state.clear()
            strikes = self._state.get(target, (0.0, 0))[1] + 1
            delay = min(self.cap_s,
                        self.base_s * (2 ** min(strikes - 1, 16)))
            delay = min(self.cap_s,
                        delay * (0.5 + self._rng.random()))
            self._state[target] = (self._clock() + delay, strikes)
        events.record("frame_downgrade", target=target,
                      retry_in_s=round(delay, 3), strikes=strikes,
                      reason=reason[:160])
        return delay

    def ok(self, target: str) -> None:
        """A frame request succeeded: clear the target's downgrade."""
        with self._lock:
            self._state.pop(target, None)


class _IdlePool:
    """Shared idle-connection store: per-target LIFO stacks with a
    max-idle age. A connection parked longer than ``max_idle_s`` is
    closed instead of reused — a sibling worker respawn (new process,
    same address) otherwise leaves every pooled socket pointing at a
    dead peer until each one surfaces an error to a caller."""

    def __init__(self, per_target: int, max_idle_s: float):
        self._idle: dict[str, list[tuple[float, object]]] = {}
        self._lock = threading.Lock()
        self.per_target = per_target
        self.max_idle_s = max_idle_s

    def take(self, target: str):
        now = time.monotonic()
        stale: list = []
        conn = None
        with self._lock:
            conns = self._idle.get(target)
            while conns:
                parked_at, c = conns.pop()
                if now - parked_at <= self.max_idle_s:
                    conn = c
                    break
                stale.append(c)
        for c in stale:
            _quiet_close(c)
        return conn

    def give(self, target: str, conn) -> None:
        with self._lock:
            conns = self._idle.setdefault(target, [])
            if len(conns) < self.per_target:
                conns.append((time.monotonic(), conn))
                return
        _quiet_close(conn)

    def drop_target(self, target: str) -> None:
        """Drain every idle connection for one target — called when a
        pooled connection turns out stale, because its siblings from
        the same dead peer are stale too."""
        with self._lock:
            conns = self._idle.pop(target, [])
        for _, c in conns:
            _quiet_close(c)

    def close(self) -> None:
        with self._lock:
            all_conns = [c for conns in self._idle.values()
                         for _, c in conns]
            self._idle.clear()
        for c in all_conns:
            _quiet_close(c)


def _quiet_close(conn) -> None:
    try:
        conn.close()
    except OSError:
        pass


class SyncHttpPool:
    def __init__(self, timeout: float = 30.0, per_target: int = 4,
                 max_idle_s: float = 30.0):
        self._pool = _IdlePool(per_target, max_idle_s)
        self.timeout = timeout

    def _connect(self, target: str) -> http.client.HTTPConnection:
        host, _, port = target.rpartition(":")
        ctx = tls.client_ctx()
        if ctx is not None:
            return http.client.HTTPSConnection(
                host, int(port), timeout=self.timeout, context=ctx)
        return http.client.HTTPConnection(
            host, int(port), timeout=self.timeout)

    def request(self, target: str, path: str,
                headers: dict | None = None,
                method: str = "GET") -> tuple[int, bytes]:
        """One request over a pooled keep-alive connection; a stale
        idle connection (peer closed/respawned between uses) is
        retried once on a fresh one, and its idle siblings are drained
        so the respawn poisons at most one round trip per caller, not
        one per pooled socket. Raises OSError flavors on failure."""
        for attempt in (0, 1):
            conn = self._pool.take(target)
            fresh = conn is None
            if fresh:
                conn = self._connect(target)
            try:
                conn.request(method, path, headers=headers or {})
                resp = conn.getresponse()
                body = resp.read()
                status = resp.status
            except (http.client.HTTPException, OSError) as e:
                conn.close()
                if fresh or attempt:
                    raise PoolError(
                        f"{method} {target}{path}: {e}") from e
                glog.V(2).infof("connpool %s: stale keep-alive (%s), "
                                "retrying fresh", target, e)
                self._pool.drop_target(target)
                continue
            if resp.will_close:
                conn.close()
            else:
                self._pool.give(target, conn)
            return status, body
        raise PoolError(f"{method} {target}{path}: unreachable")

    def close(self) -> None:
        self._pool.close()


class _FrameConn:
    """One handshaken sync frame connection (a single request in
    flight at a time — executor threads don't pipeline; the async
    FrameChannel is the multiplexed form)."""

    __slots__ = ("sock", "dec", "queue", "next_id")

    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock
        self.dec = FrameDecoder()
        self.queue: list = []          # decoded-but-unconsumed frames
        self.next_id = 1

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class SyncFramePool:
    """Frame-protocol twin of SyncHttpPool for executor-thread
    fetches (the EC shard gather). Same pooling/stale-retry/idle
    discipline; handshake failures raise :class:`FrameUnsupported` so
    the caller downgrades the TARGET to the HTTP pool."""

    def __init__(self, timeout: float = 30.0, per_target: int = 4,
                 max_idle_s: float = 30.0, token: str = "",
                 jwt_key: str = ""):
        self._pool = _IdlePool(per_target, max_idle_s)
        self.timeout = timeout
        self.token = token
        self.jwt_key = jwt_key          # mints the HELLO identity claim

    def _connect(self, target: str) -> _FrameConn:
        host, _, port = target.rpartition(":")
        try:
            sock = socket.create_connection((host, int(port)),
                                            timeout=self.timeout)
        except OSError as e:
            raise PoolError(f"frame connect {target}: {e}") from e
        ctx = tls.client_ctx()
        if ctx is not None:
            try:
                sock = ctx.wrap_socket(sock, server_hostname=host)
            except OSError as e:
                _quiet_close(sock)
                raise PoolError(f"frame tls {target}: {e}") from e
        conn = _FrameConn(sock)
        try:
            hello_meta: dict = {"v": VERSION, "token": self.token}
            if self.jwt_key:
                from ..security.jwt import gen_jwt
                hello_meta["id"] = gen_jwt(self.jwt_key,
                                           HELLO_IDENTITY_FID,
                                           HELLO_IDENTITY_TTL_S)
            sock.sendall(MAGIC + encode_frame(HELLO, 0, hello_meta))
            fr = self._read_frame(conn)
            if fr.type != HELLO_OK:
                raise FrameUnsupported(
                    f"frame handshake with {target}: type {fr.type}")
        except FrameUnsupported:
            conn.close()
            raise
        except (OSError, FrameError) as e:
            conn.close()
            # anything short of HELLO_OK — an old peer parsing the
            # magic as garbage HTTP, a torn stream — means "speak HTTP
            # to this target"
            raise FrameUnsupported(
                f"frame handshake with {target}: {e}") from e
        return conn

    def _read_frame(self, conn: _FrameConn):
        while not conn.queue:
            chunk = conn.sock.recv(1 << 18)
            if not chunk:
                raise PoolError("peer closed frame stream")
            conn.queue.extend(conn.dec.feed(chunk))
        return conn.queue.pop(0)

    def request(self, target: str, path: str,
                headers: dict | None = None, method: str = "GET",
                query: dict | None = None) -> tuple[int, bytes]:
        """One frame request over a pooled connection; stale pooled
        sockets retried once fresh (and the target's idle set
        drained), exactly like the HTTP pool."""
        for attempt in (0, 1):
            conn = self._pool.take(target)
            fresh = conn is None
            if fresh:
                conn = self._connect(target)
            req_id = conn.next_id
            conn.next_id = (conn.next_id + 1) & 0xFFFFFFFF or 1
            meta: dict = {"m": method, "p": path}
            if query:
                meta["q"] = query
            if headers:
                meta["h"] = headers
            try:
                conn.sock.sendall(encode_frame(REQ, req_id, meta))
                while True:
                    fr = self._read_frame(conn)
                    if fr.type == RESP and fr.req_id == req_id:
                        break
            except (OSError, FrameError) as e:
                conn.close()
                if fresh or attempt:
                    raise PoolError(
                        f"frame {method} {target}{path}: {e}") from e
                glog.V(2).infof("framepool %s: stale connection (%s), "
                                "retrying fresh", target, e)
                self._pool.drop_target(target)
                continue
            if conn.dec.pending or conn.queue:
                # leftover bytes/frames would desync the next request
                conn.close()
            else:
                self._pool.give(target, conn)
            from .frame import FLAG_FALLBACK
            if fr.flags & FLAG_FALLBACK:
                raise FrameUnsupported(
                    f"frame {method} {target}{path}: peer asked for "
                    f"HTTP fallback")
            return int(fr.meta.get("s", 500)), fr.payload
        raise PoolError(f"frame {method} {target}{path}: unreachable")

    def close(self) -> None:
        self._pool.close()
