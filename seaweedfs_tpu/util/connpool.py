"""Keep-alive HTTP connection pool for SYNC (executor-thread) fetches.

The EC degraded-read path runs inside executor threads and cannot use
the server's aiohttp session; it used to open a fresh
urllib/TCP(+TLS) connection PER shard interval — exactly the k-fetch
fan-out cost the repair-bandwidth literature (arxiv 1309.0186) says
dominates recovery. This pool keeps idle `http.client` connections per
target so a degraded-read burst pays one handshake per holder, not one
per interval.

Thread-safe; connections are returned to the pool only after a clean
response, so a torn keep-alive stream is never reused.
"""

from __future__ import annotations

import http.client
import threading

from ..security import tls
from . import glog


class PoolError(OSError):
    pass


class SyncHttpPool:
    def __init__(self, timeout: float = 30.0, per_target: int = 4):
        self._idle: dict[str, list[http.client.HTTPConnection]] = {}
        self._lock = threading.Lock()
        self.timeout = timeout
        self.per_target = per_target

    def _connect(self, target: str) -> http.client.HTTPConnection:
        host, _, port = target.rpartition(":")
        ctx = tls.client_ctx()
        if ctx is not None:
            return http.client.HTTPSConnection(
                host, int(port), timeout=self.timeout, context=ctx)
        return http.client.HTTPConnection(
            host, int(port), timeout=self.timeout)

    def _take(self, target: str) -> http.client.HTTPConnection | None:
        with self._lock:
            conns = self._idle.get(target)
            if conns:
                return conns.pop()
        return None

    def _give(self, target: str,
              conn: http.client.HTTPConnection) -> None:
        with self._lock:
            conns = self._idle.setdefault(target, [])
            if len(conns) < self.per_target:
                conns.append(conn)
                return
        conn.close()

    def request(self, target: str, path: str,
                headers: dict | None = None,
                method: str = "GET") -> tuple[int, bytes]:
        """One request over a pooled keep-alive connection; a stale
        idle connection (peer closed it between uses) is retried once
        on a fresh one. Raises OSError flavors on failure."""
        for attempt in (0, 1):
            conn = self._take(target)
            fresh = conn is None
            if fresh:
                conn = self._connect(target)
            try:
                conn.request(method, path, headers=headers or {})
                resp = conn.getresponse()
                body = resp.read()
                status = resp.status
            except (http.client.HTTPException, OSError) as e:
                conn.close()
                if fresh or attempt:
                    raise PoolError(
                        f"{method} {target}{path}: {e}") from e
                glog.V(2).infof("connpool %s: stale keep-alive (%s), "
                                "retrying fresh", target, e)
                continue
            if resp.will_close:
                conn.close()
            else:
                self._give(target, conn)
            return status, body
        raise PoolError(f"{method} {target}{path}: unreachable")

    def close(self) -> None:
        with self._lock:
            for conns in self._idle.values():
                for c in conns:
                    c.close()
            self._idle.clear()
