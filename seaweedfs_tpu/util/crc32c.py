"""CRC32-Castagnoli with the reference's stored-value masking.

Matches weed/storage/needle/crc.go: the stored checksum is the "masked"
rotation used by snappy/leveldb: rotl(crc, 17) + 0xa282ead8. Raw CRC is the
reflected Castagnoli polynomial, same as klauspost/crc32's table.

Fast path is the native C library (SSE4.2 hardware CRC); fallback is a
pure-Python slicing table (slow, correctness-only).
"""

from __future__ import annotations

from ..native.build import load as _load_native

_POLY = 0x82F63B78


def _make_table() -> list[int]:
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (_POLY ^ (c >> 1)) if (c & 1) else (c >> 1)
        table.append(c)
    return table


_TABLE = _make_table()


def _crc32c_py(crc: int, data: bytes) -> int:
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = _TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# Resolved ONCE at import, not lazily per call: load() may shell out
# to g++ when the cached .so is stale, and a first-call build used to
# be reachable from every async etag/checksum path — a compiler run on
# the event loop, mid-request. Import time is before any loop exists.
_NATIVE = _load_native()


def crc32c(data: bytes | bytearray | memoryview, crc: int = 0) -> int:
    """Raw CRC32C of data, continuing from crc."""
    if _NATIVE is not None:
        data = bytes(data) if not isinstance(data, bytes) else data
        return _NATIVE.swtpu_crc32c(crc, data, len(data))
    return _crc32c_py(crc, bytes(data))


def masked(crc: int) -> int:
    """Stored-checksum masking (crc.go Value())."""
    crc &= 0xFFFFFFFF
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


def checksum_value(data: bytes | bytearray | memoryview) -> int:
    """Masked CRC32C as written into a needle footer."""
    return masked(crc32c(data))


def unmasked(value: int) -> int:
    """Inverse of masked(): recover the raw CRC32C from a stored
    footer checksum (zero-copy reads derive the ETag from the footer
    without pulling the body into userspace)."""
    v = (value - 0xA282EAD8) & 0xFFFFFFFF
    return ((v >> 17) | (v << 15)) & 0xFFFFFFFF
