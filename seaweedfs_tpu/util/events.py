"""Structured cluster event journal — the flight recorder's black box.

Metrics say HOW MUCH and traces say WHERE THE TIME WENT, but neither
answers "what state transitions happened around the bad minute":
breaker trips, retry-budget exhaustion, EC holder-map refreshes, scrub
corruption reports, volume mounts/vacuums, worker respawns and
group-commit fsync upgrades all used to vanish into glog.  This module
is a typed, bounded, per-process ring of exactly those transitions.

Each event records:

- ``type``    — one of :data:`TYPES` (the documented vocabulary;
  ROBUSTNESS.md catalogs what each means and which subsystem emits it)
- ``wall``    — ``time.time()`` seconds, the cross-process timeline key
  (same discipline as span ``start_ms``: wall for ALIGNMENT only)
- ``mono``    — ``time.perf_counter()`` at record time, so in-process
  deltas between events are NTP-step-proof
- ``trace``   — the active trace id when the transition happened inside
  a traced request (empty otherwise), the cross-link into
  ``/debug/traces``
- free-form small fields (upstream, vid, offset, ...)

Recording is cheap (one lock + deque append), never raises into the
caller (a breaker trip must not fail the request that tripped it), and
feeds ``SeaweedFS_events_total{type}`` so the journal and Prometheus
agree by construction.  Exposed as ``/debug/events`` on every daemon
(``/__debug__/events`` on the path-shadowing gateways), whole-host
merged under ``-workers`` like ``/metrics``.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from . import glog

# the documented event vocabulary; an unknown type is recorded anyway
# (losing evidence is worse than a typo) but logged once so the typo
# gets fixed — ROBUSTNESS.md is the human-facing catalog
TYPES = frozenset({
    "breaker_open",             # circuit breaker closed/half-open -> open
    "breaker_close",            # breaker recovered -> closed
    "retry_budget_exhausted",   # RetryPolicy denied a retry: budget empty
    "holder_refresh",           # EC holder map invalidated + forced re-lookup
    "scrub_corruption",         # parity scrubber found a corrupt window
    "volume_mount",             # store mounted/loaded a volume
    "volume_unmount",
    "volume_vacuum",            # compaction committed (offsets moved)
    "ec_mount",                 # EC shards mounted
    "ec_unmount",
    "worker_respawn",           # supervisor respawned a dead worker
    "fsync_upgrade",            # deepest-yet group-commit batch shared
                                # one durable fsync point
    "autopilot_action",         # maintenance plane executed (or
                                # dry-ran) a repair/vacuum/tier action,
                                # with the planner's `reason`
    "autopilot_defer",          # an action was planned but NOT run
                                # (unrepairable, no target, cooldown,
                                # queue-full, paused-too-long)
    "autopilot_pause",          # repair parked: /debug/health paged
    "raft_leader_change",       # this master observed a new quorum
                                # leader (election win, or a pulse from
                                # a successor) — wall_ms deltas across
                                # the fleet bound the failover window
    "raft_step_down",           # a LEADER lost its standing (lease
                                # expiry under partition, or a higher
                                # term appeared) and stopped assigning
    "frame_downgrade",          # a peer refused the frame handshake:
                                # its requests ride HTTP until the
                                # jittered re-probe window expires
    "tenant_shed",              # QoS admission throttled (429) or shed
                                # (503) a tenant's request — rate-
                                # bounded per tenant so an abuser can't
                                # flood the ring holding its evidence
    "arbiter_yield",            # the bandwidth arbiter squeezed a
                                # background consumer below its base
                                # rate under foreground pressure
    "shard_split",              # filer shard split phase transition
                                # (flip = routing cut over in one raft
                                # apply; done = tombstone complete)
    "shard_move",               # cross-shard rename phase transition
                                # of the journaled two-phase move
})

_MAX_FIELDS = 16                # per-event field cap (bounded memory)

_lock = threading.Lock()
_ring: deque = deque(maxlen=1024)
_seq = 0
_warned_types: set = set()

# lazily-bound prometheus counter (+ label-children cache), the same
# shape as tracing._observe
_counter: object = None
_counter_children: dict = {}


def init(ring: int = 1024) -> None:
    """Resize the journal ring (tests / future flag)."""
    global _ring
    with _lock:
        if ring != _ring.maxlen:
            _ring = deque(_ring, maxlen=max(16, ring))


def reset() -> None:
    """Drop all recorded events (tests)."""
    global _seq
    with _lock:
        _ring.clear()
        _seq = 0


def record(etype: str, **fields) -> None:
    """Append one state transition to the journal.

    Never raises: the emit sites sit inside breaker transitions, store
    mutations and supervisor loops, where an observability bug must not
    become a data-plane bug."""
    try:
        if etype not in TYPES and etype not in _warned_types:
            _warned_types.add(etype)
            glog.warning("events: unknown event type %r (recording "
                         "anyway; add it to util/events.TYPES)", etype)
        trace = ""
        try:
            from . import tracing
            trace = tracing.current().trace
        except ImportError:  # pragma: no cover - tracing always present
            pass
        if len(fields) > _MAX_FIELDS:
            fields = dict(list(fields.items())[:_MAX_FIELDS])
        global _seq
        with _lock:
            _seq += 1
            _ring.append({
                "seq": _seq,
                "type": etype,
                "wall_ms": round(time.time() * 1000.0, 3),
                "mono": time.perf_counter(),
                "trace": trace,
                **fields,
            })
        _count(etype)
    except Exception as e:  # noqa: BLE001 — see docstring: the journal
        # must never take down the path it observes, but stay visible
        glog.warning("events.record(%s) failed: %s", etype, e)


def _count(etype: str) -> None:
    global _counter
    if _counter is None:
        try:
            from ..stats import metrics
            _counter = (metrics.EVENTS_TOTAL
                        if metrics.HAVE_PROMETHEUS else False)
        except ImportError:
            _counter = False
    if not _counter:
        return
    child = _counter_children.get(etype)
    if child is None:
        if len(_counter_children) > 256:
            _counter_children.clear()   # runaway label cardinality bound
        child = _counter_children[etype] = _counter.labels(etype)
    child.inc()


def events_dict(n: int = 100, types: "set[str] | None" = None,
                since_ms: float = 0.0) -> dict:
    """The /debug/events JSON body for THIS process's ring: newest
    first, optionally filtered by type and a wall-clock floor."""
    n = max(0, min(int(n), 10_000))
    with _lock:
        rows = list(_ring)
    if types:
        rows = [r for r in rows if r["type"] in types]
    if since_ms > 0:
        rows = [r for r in rows if r["wall_ms"] >= since_ms]
    rows = rows[-n:] if n else []
    rows.reverse()
    # copies, not the live ring rows: aggregators stamp worker tags on
    # what we hand out, and a caller's mutation must never rewrite the
    # journal every later surface (worker hops, slo evidence) reads
    return {"events": [dict(r) for r in rows], "recorded": _seq}


def merge_payloads(payloads: "list[dict]", n: int = 100) -> dict:
    """Fold several workers' /debug/events bodies into one whole-host
    view, newest first on the shared wall clock (rows keep whatever
    ``worker`` tag the aggregator stamped)."""
    n = max(0, min(int(n), 10_000))
    rows: list[dict] = []
    recorded = 0
    for p in payloads:
        rows.extend(p.get("events", ()))
        recorded += int(p.get("recorded", 0) or 0)
    rows.sort(key=lambda r: -r.get("wall_ms", 0.0))
    return {"events": rows[:n], "recorded": recorded}


def events_query(query) -> dict:
    """events_dict driven by a ?n=&type=&since_ms= query mapping — the
    one parser shared by every server's /debug/events handler (raises
    ValueError on malformed values)."""
    types = None
    if query.get("type"):
        types = {t for t in str(query["type"]).split(",") if t}
    return events_dict(n=int(query.get("n", 100)), types=types,
                       since_ms=float(query.get("since_ms", 0) or 0))


def window(from_ms: float, to_ms: float,
           types: "set[str] | None" = None) -> "list[dict]":
    """Events whose wall stamp falls in [from_ms, to_ms] — the SLO
    engine's evidence correlator."""
    with _lock:
        rows = list(_ring)
    return [r for r in rows
            if from_ms <= r["wall_ms"] <= to_ms
            and (not types or r["type"] in types)]


def debug_handler():
    """One aiohttp handler over THIS process's ring — registered by
    every non-worker-aggregating server (master, filer, S3, WebDAV) so
    the events contract cannot drift between surfaces."""
    from aiohttp import web

    async def h_events(req):
        try:
            return web.json_response(events_query(req.query))
        except ValueError:
            return web.json_response({"error": "bad n/type/since_ms"},
                                     status=400)

    return h_events
