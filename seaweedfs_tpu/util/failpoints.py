"""Named fault-injection points ("failpoints") for the whole cluster.

Reference: the reference tree has no fault-injection layer at all — a
stalled or lying peer can only be reproduced with external tooling.
This module is the missing harness: code plants *named sites* on the
needle write/read path, the heartbeat, the worker sibling proxy, the
replicated-write fan-out and the replication sinks; tests and the chaos
driver (tools/chaos.py) *arm* those sites with an action.

Actions (spec grammar ``action[=arg][:count][@probability]``):

    error          raise/return an injected error (arg = HTTP status)
    latency=MS     add MS milliseconds of delay, then proceed normally
    truncate       cut the payload (arg = keep-fraction, default 0.5);
                   on the volume read path this serves a partial body
                   with a full Content-Length, then drops the socket
    drop           sever the connection / raise a connection error
    flip           silently corrupt the payload: XOR 0xFF into the
                   first N bytes (arg = N, default 1) — bit-rot the
                   EC scrubber must detect (payload sites only; a
                   non-payload site treats it as a no-op)

``count`` bounds how many times the site fires before auto-disarming
(default 1; ``*`` = unlimited); ``@probability`` makes each pass fire
with that chance (e.g. ``@0.05`` = 5%).

Arming:

    WEED_FAILPOINTS=store.read=error@0.05,volume.heartbeat=drop:3
    POST  /debug/failpoints?site=store.write&spec=latency=200:10
    GET   /debug/failpoints                  (list armed sites + hits)
    DELETE /debug/failpoints[?site=...]      (disarm one / all)

Disarmed cost: every planted site is a single module-level dict
emptiness check (``if not _sites``) — no allocation, no lock, no
string formatting — so production hot paths pay nothing.
"""

from __future__ import annotations

import asyncio
import os
import random
import threading
import time

__all__ = [
    "FailpointError", "FailpointDrop", "arm", "disarm", "reset",
    "armed", "list_armed", "take", "sync_fail", "fail", "corrupt",
    "pending", "load_env", "handle_debug",
]


class FailpointError(OSError):
    """Injected failure. Subclasses OSError on purpose: every network
    error path in the tree already handles OSError, so an injected
    fault flows through exactly the handling a real one would."""

    def __init__(self, site: str, status: int = 500):
        super().__init__(f"failpoint {site}")
        self.site = site
        self.status = status


class FailpointDrop(ConnectionResetError):
    """Injected connection drop (ConnectionResetError => OSError)."""

    def __init__(self, site: str):
        super().__init__(f"failpoint drop {site}")
        self.site = site


class _Armed:
    __slots__ = ("site", "action", "arg", "count", "prob", "hits")

    def __init__(self, site: str, action: str, arg: str,
                 count: int, prob: float):
        self.site = site
        self.action = action
        self.arg = arg
        self.count = count          # remaining fires; -1 = unlimited
        self.prob = prob
        self.hits = 0

    def to_dict(self) -> dict:
        return {"site": self.site, "action": self.action, "arg": self.arg,
                "count": self.count, "probability": self.prob,
                "hits": self.hits}


_sites: dict[str, _Armed] = {}
_lock = threading.Lock()
_rng = random.Random()

_ACTIONS = ("error", "latency", "truncate", "drop", "flip")


def parse_spec(site: str, spec: str) -> _Armed:
    """``action[=arg][:count][@probability]`` -> _Armed."""
    prob = 1.0
    if "@" in spec:
        spec, _, p = spec.rpartition("@")
        prob = float(p)
        if not 0.0 < prob <= 1.0:
            raise ValueError(f"failpoint {site}: probability {p} "
                             f"not in (0, 1]")
    count = 1
    explicit_count = False
    head, _, tail = spec.rpartition(":")
    if head and (tail == "*" or tail.isdigit()):
        spec = head
        count = -1 if tail == "*" else int(tail)
        explicit_count = True
    if prob < 1.0 and not explicit_count:
        count = -1                  # probabilistic default: unlimited
    action, _, arg = spec.partition("=")
    if action not in _ACTIONS:
        raise ValueError(f"failpoint {site}: unknown action {action!r} "
                         f"(want one of {_ACTIONS})")
    if action == "latency":
        float(arg or 0)             # validate now, not at fire time
    if action == "error" and arg:
        int(arg)
    if action == "truncate" and arg:
        f = float(arg)
        if not 0.0 <= f < 1.0:
            raise ValueError(f"failpoint {site}: truncate fraction {arg} "
                             f"not in [0, 1)")
    if action == "flip" and arg:
        if int(arg) < 1:
            raise ValueError(f"failpoint {site}: flip byte count {arg} "
                             f"must be >= 1")
    return _Armed(site, action, arg, count, prob)


def arm(site: str, spec: str) -> None:
    """Arm `site` with `spec` (see module docstring for the grammar)."""
    a = parse_spec(site, spec)
    with _lock:
        _sites[site] = a


def disarm(site: str) -> bool:
    with _lock:
        return _sites.pop(site, None) is not None


def reset() -> None:
    with _lock:
        _sites.clear()


def armed() -> bool:
    return bool(_sites)


def pending(site: str) -> bool:
    """True when `site` is armed (without consuming a fire)."""
    return site in _sites


def list_armed() -> list[dict]:
    with _lock:
        return [a.to_dict() for a in _sites.values()]


def take(site: str) -> _Armed | None:
    """Consume one fire of `site` if armed (respecting probability and
    remaining count). The fast path is the unlocked emptiness check."""
    if not _sites:
        return None
    with _lock:
        a = _sites.get(site)
        if a is None:
            return None
        if a.prob < 1.0 and _rng.random() >= a.prob:
            return None
        if a.count == 0:
            del _sites[site]
            return None
        if a.count > 0:
            a.count -= 1
            if a.count == 0:
                del _sites[site]
        a.hits += 1
        return a


def _raise_for(a: _Armed) -> None:
    if a.action == "error":
        raise FailpointError(a.site, int(a.arg or 500))
    if a.action == "drop":
        raise FailpointDrop(a.site)
    # flip is payload-only: at a non-payload site it is a no-op (the
    # fire is still consumed, so counts stay honest)


def sync_fail(site: str) -> None:
    """Synchronous site (storage layer, executor threads): error/drop
    raise; latency blocks the calling thread; truncate is a no-op here
    (use corrupt() for payload sites)."""
    if not _sites:
        return
    a = take(site)
    if a is None:
        return
    if a.action == "latency":
        time.sleep(float(a.arg or 0) / 1000.0)
        return
    _raise_for(a)


async def fail(site: str) -> None:
    """Async site (event-loop paths): like sync_fail but latency does
    not block the loop."""
    if not _sites:
        return
    a = take(site)
    if a is None:
        return
    if a.action == "latency":
        await asyncio.sleep(float(a.arg or 0) / 1000.0)
        return
    _raise_for(a)


def corrupt(site: str, data: bytes) -> bytes:
    """Payload site: `truncate` cuts data to the armed keep-fraction
    (default half); `flip` XORs 0xFF into the first N bytes (silent
    bit-rot — same length, wrong content); other actions behave as in
    sync_fail."""
    if not _sites:
        return data
    a = take(site)
    if a is None:
        return data
    if a.action == "truncate":
        keep = float(a.arg) if a.arg else 0.5
        return data[:int(len(data) * keep)]
    if a.action == "flip":
        n = min(int(a.arg or 1), len(data))
        return bytes(b ^ 0xFF for b in data[:n]) + data[n:]
    if a.action == "latency":
        time.sleep(float(a.arg or 0) / 1000.0)
        return data
    _raise_for(a)
    return data


async def http_respond(req, site: str, *, body: bytes, headers: dict,
                       content_type: str, status: int):
    """Volume read-path site with response-level actions. Returns an
    aiohttp Response to send instead of the normal one, or None to
    proceed normally (latency sleeps first).

    `truncate` is the interesting one: it declares the full
    Content-Length, streams a prefix, then severs the socket — exactly
    the shape of a volume server dying mid-read, which is what the
    degraded-read failover path must survive."""
    if not _sites:
        return None
    a = take(site)
    if a is None:
        return None
    from aiohttp import web
    if a.action == "latency":
        await asyncio.sleep(float(a.arg or 0) / 1000.0)
        return None
    if a.action == "error":
        return web.json_response({"error": f"failpoint {site}"},
                                 status=int(a.arg or 500))
    if a.action == "drop":
        if req.transport is not None:
            req.transport.close()
        return web.Response(status=500)
    # truncate: full headers, partial body, dead socket
    keep = float(a.arg) if a.arg else 0.5
    resp = web.StreamResponse(status=status, headers={
        **headers, "Content-Length": str(len(body))})
    resp.content_type = content_type
    await resp.prepare(req)
    await resp.write(body[:int(len(body) * keep)])
    if req.transport is not None:
        req.transport.close()
    return resp


def load_env(value: str | None = None) -> int:
    """Arm sites from WEED_FAILPOINTS (site=spec,site=spec). Returns the
    number armed. Malformed entries raise — a chaos run silently
    arming nothing would 'pass' while testing nothing."""
    raw = os.environ.get("WEED_FAILPOINTS", "") if value is None else value
    n = 0
    for item in raw.split(","):
        item = item.strip()
        if not item:
            continue
        site, sep, spec = item.partition("=")
        if not sep or not site:
            raise ValueError(f"WEED_FAILPOINTS entry {item!r}: "
                             f"want site=spec")
        arm(site, spec)
        n += 1
    return n


async def handle_debug(req):
    """Shared /debug/failpoints admin endpoint for master, volume and
    filer servers:

        GET                       -> {"failpoints": [...]}
        POST ?site=S&spec=SPEC    -> arm one site
        POST {"S": "SPEC", ...}   -> arm many (JSON body)
        DELETE [?site=S]          -> disarm one / all
    """
    from aiohttp import web
    if req.method == "GET":
        return web.json_response({"failpoints": list_armed()})
    if req.method == "DELETE":
        site = req.query.get("site", "")
        if site:
            return web.json_response({"disarmed": disarm(site)})
        n = len(list_armed())
        reset()
        return web.json_response({"disarmed": n})
    if req.method in ("POST", "PUT"):
        specs: dict[str, str] = {}
        if req.query.get("site"):
            specs[req.query["site"]] = req.query.get("spec", "error")
        elif req.can_read_body:
            try:
                body = await req.json()
            except ValueError:
                return web.json_response({"error": "bad json"}, status=400)
            if not isinstance(body, dict):
                return web.json_response(
                    {"error": "want {site: spec, ...}"}, status=400)
            specs = {str(k): str(v) for k, v in body.items()}
        if not specs:
            return web.json_response({"error": "no site given"},
                                     status=400)
        try:
            for site, spec in specs.items():
                arm(site, spec)
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=400)
        return web.json_response({"armed": list_armed()})
    return web.json_response({"error": "method not allowed"}, status=405)


# Arm from the environment at import: server subprocesses (chaos soak,
# -workers fleets) inherit WEED_FAILPOINTS without any plumbing.
if os.environ.get("WEED_FAILPOINTS"):
    load_env()
