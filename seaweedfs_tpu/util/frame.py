"""Binary framed RPC for the cluster data fabric: ONE wire, no HTTP.

The `-workers` sibling hop (and the client's pipelined multi-read)
used to re-serialize a full HTTP request/response per needle through
aiohttp — per-hop header parsing, header re-emission and one
round-trip per request. This module replaces that hop — and, since
the frame-fabric PR, every inter-host hop (replication fan-out,
client uploads/deletes, EC shard gather, master heartbeat/lookup,
raft vote/append/snapshot) — with a compact length-prefixed frame
spoken over persistent connections:

    u32  length      bytes after this field (= 12 + meta + payload)
    u8   type        HELLO / HELLO_OK / REQ / RESP / GOAWAY
    u8   flags       FLAG_FALLBACK: peer cannot serve this over frames
    u16  meta_len    compact-JSON meta blob length
    u64  req_id      multiplexing id (responses interleave freely)
    meta bytes       {"m","p","q","h"} request / {"s","h","ct"} response
    payload bytes    raw body — never escaped, never chunked

A connection opens with the ``MAGIC`` preamble (not a valid HTTP
method, so the volume server's raw listener — and the master's fast
assign listener — sniffs it and swaps the connection onto the frame
protocol in place), then a HELLO frame carrying the worker launch
token (empty for plain clients — reads are open exactly like the HTTP
listeners; JWT write tokens ride in the request meta headers like any
other header) and, on jwt-secured clusters, a signed ``id`` claim
minted from the cluster signing key: a HELLO presenting neither a
valid worker token nor a valid identity is refused with GOAWAY before
any payload is served. Requests are MULTIPLEXED: many in-flight
req_ids per connection, responses complete out of order, and a
pipelining client keeps the socket full instead of paying a round
trip per needle. The in-flight window is congestion-aware: an AIMD
controller fed by per-request round-trip times shrinks it when RTTs
rise above the channel's observed floor (queue building at the peer)
and grows it additively as responses drain.

Server side terminates frames in server/frameserver.py — a thin
adapter over server/wire.py exactly like the two HTTP listeners, so
cache/span/failpoint/Range/group-commit semantics stay wired once.

Failure discipline: `worker.frame` failpoint at every request send;
transport errors raise :class:`FrameChannelError` (an OSError) and the
callers fall back to the HTTP hop, so a peer that predates the
protocol — or a chaos run severing it — degrades to exactly the
pre-frame behavior. Each channel shares util/resilience.py's
CircuitBreaker: repeated channel failures open the breaker so callers
fail fast to HTTP, and the half-open probe re-tries frames instead of
downgrading forever.
"""

from __future__ import annotations

import asyncio
import collections
import json
import struct

from . import failpoints, glog
from .resilience import Backoff, BreakerRegistry, CircuitBreaker

MAGIC = b"SWFR1\n"

# the fid-shaped claim a HELLO identity token is minted for
# (security/jwt.py gen_jwt binds every token to a fid; the handshake's
# "fid" is this constant, so a stolen per-needle write token can never
# double as a channel identity)
HELLO_IDENTITY_FID = "frame:hello"
HELLO_IDENTITY_TTL_S = 30

HELLO = 1
HELLO_OK = 2
REQ = 3
RESP = 4
GOAWAY = 7

# RESP flag: the peer understood the request but cannot serve it over
# frames (manifest assembly, jwt-guarded write on a token-less hop,
# ...) — the caller must retry over HTTP
FLAG_FALLBACK = 1

VERSION = 1

_HDR = struct.Struct(">IBBHQ")
HEADER_SIZE = _HDR.size            # 16 incl. the length field itself

# one frame may carry a whole /batch response (64MB budget) plus meta
MAX_FRAME = (64 << 20) + (1 << 20)
MAX_META = 256 * 1024

_COMPACT = {"separators": (",", ":")}


class FrameError(ValueError):
    """Corrupt/hostile frame stream: torn header, oversized or
    negative lengths, non-JSON meta. The connection must be dropped —
    framing never resynchronizes."""


class FrameChannelError(OSError):
    """Transport-level channel failure (peer down, handshake refused,
    timeout): the caller's cue to fall back to the HTTP hop."""


def encode_frame(ftype: int, req_id: int, meta: dict | None = None,
                 payload: bytes = b"", flags: int = 0) -> bytes:
    mb = json.dumps(meta, **_COMPACT).encode() if meta else b""
    if len(mb) > MAX_META:
        raise FrameError(f"meta blob {len(mb)}B exceeds {MAX_META}")
    return _HDR.pack(12 + len(mb) + len(payload), ftype, flags,
                     len(mb), req_id) + mb + payload


class Frame:
    __slots__ = ("type", "flags", "req_id", "meta", "payload")

    def __init__(self, ftype: int, flags: int, req_id: int,
                 meta: dict, payload: bytes) -> None:
        self.type = ftype
        self.flags = flags
        self.req_id = req_id
        self.meta = meta
        self.payload = payload


class FrameDecoder:
    """Incremental frame reassembler: feed() arbitrary chunks, get the
    complete frames back. Raises :class:`FrameError` on anything a
    well-formed peer could never send (the stream is then garbage and
    the connection must close — there is no resync point)."""

    __slots__ = ("_buf", "overhead_bytes", "frames")

    def __init__(self) -> None:
        self._buf = bytearray()
        self.overhead_bytes = 0        # header+meta bytes decoded
        self.frames = 0

    def feed(self, data: bytes) -> list[Frame]:
        self._buf += data
        out: list[Frame] = []
        while len(self._buf) >= HEADER_SIZE:
            length, ftype, flags, meta_len, req_id = _HDR.unpack_from(
                self._buf)
            if length < 12:
                raise FrameError(f"frame length {length} < fixed 12")
            if length > MAX_FRAME:
                raise FrameError(f"frame length {length} exceeds "
                                 f"{MAX_FRAME}")
            if meta_len > length - 12 or meta_len > MAX_META:
                raise FrameError(f"meta length {meta_len} exceeds "
                                 f"frame {length}")
            total = 4 + length
            if len(self._buf) < total:
                return out
            meta: dict = {}
            if meta_len:
                try:
                    meta = json.loads(bytes(self._buf[16:16 + meta_len]))
                except ValueError as e:
                    raise FrameError(f"bad frame meta: {e}") from e
                if not isinstance(meta, dict):
                    raise FrameError("frame meta is not an object")
            payload = bytes(self._buf[16 + meta_len:total])
            del self._buf[:total]
            self.overhead_bytes += HEADER_SIZE + meta_len
            self.frames += 1
            out.append(Frame(ftype, flags, req_id, meta, payload))
        return out

    @property
    def pending(self) -> bool:
        return bool(self._buf)


class FrameFallback(FrameChannelError):
    """The peer answered FLAG_FALLBACK: this request must ride HTTP."""


class ChannelStats:
    """Deterministic per-channel accounting (tools/bench_needle.py's
    sibling-hop scoreboard): every number is a plain event count, so
    two runs of the same workload produce the same values."""

    __slots__ = ("requests", "responses", "overhead_out", "overhead_in",
                 "payload_out", "payload_in", "connects", "writes",
                 "reads", "fallbacks", "window_shrinks", "window_grows")

    def __init__(self) -> None:
        self.requests = 0
        self.responses = 0
        self.overhead_out = 0          # header+meta bytes sent
        self.overhead_in = 0           # header+meta bytes received
        self.payload_out = 0
        self.payload_in = 0
        self.connects = 0
        self.writes = 0                # socket write calls
        self.reads = 0                 # socket read calls with data
        self.fallbacks = 0
        self.window_shrinks = 0        # AIMD multiplicative decreases
        self.window_grows = 0          # AIMD additive increases

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


class FrameChannel:
    """One persistent multiplexed frame connection to a peer.

    ``request()`` is safe to call concurrently — that IS the pipeline:
    each call takes the next req_id, registers a future and writes its
    frame; the single reader task completes futures as RESP frames
    arrive, in whatever order the peer answers.

    Reconnects lazily with jittered backoff (util/resilience.Backoff):
    while the backoff window is open, requests fail fast with
    :class:`FrameChannelError` so callers hit their HTTP fallback in
    microseconds instead of a connect timeout. An idle connection
    (no traffic for ``idle_s``) is closed client-side and transparently
    reopened by the next request.

    The in-flight window is congestion-aware (AIMD): every completed
    request feeds its round-trip time to :meth:`_observe_rtt`; RTTs
    rising past twice the channel's observed floor shrink the window
    multiplicatively, drained responses grow it additively. Callers
    that pipeline harder than the window simply queue on the channel,
    bounded by the request timeout."""

    CWND_INIT = 8.0
    CWND_MIN = 1
    CWND_MAX = 64

    def __init__(self, target: str = "", uds_path: str = "",
                 token: str = "", connect_timeout: float = 5.0,
                 request_timeout: float = 30.0, idle_s: float = 60.0,
                 ssl=None, jwt_key: str = "", hop: str = "",
                 breaker: CircuitBreaker | None = None):
        if not target and not uds_path:
            raise ValueError("FrameChannel needs a tcp target or a "
                             "unix socket path")
        self.target = target            # "ip:port" (TCP fallback)
        self.uds_path = uds_path        # preferred intra-host transport
        self.token = token
        self.jwt_key = jwt_key          # mints the HELLO identity claim
        # sibling = intra-host worker hop, interhost = cluster fabric
        self.hop = hop or ("sibling" if uds_path else "interhost")
        self.breaker = breaker
        self.connect_timeout = connect_timeout
        self.request_timeout = request_timeout
        self.idle_s = idle_s
        self._ssl = ssl
        self.stats = ChannelStats()
        self._writer: asyncio.StreamWriter | None = None
        self._reader_task: asyncio.Task | None = None
        self._pending: dict[int, asyncio.Future] = {}
        self._next_id = 1
        self._conn_lock = asyncio.Lock()
        self._backoff = Backoff(base=0.05, cap=2.0)
        self._retry_at = 0.0            # monotonic fail-fast gate
        self._closed = False
        self._cwnd = float(self.CWND_INIT)
        self._rtt_best = float("inf")   # per-connection RTT floor
        self._inflight = 0
        self._win_waiters: collections.deque[asyncio.Future] = \
            collections.deque()
        self._gauge_open = False

    @property
    def connected(self) -> bool:
        return self._writer is not None

    @property
    def window(self) -> int:
        """Current congestion window (max in-flight requests)."""
        return max(self.CWND_MIN, int(self._cwnd))

    def _label(self) -> str:
        return self.uds_path or self.target

    # ---- congestion window (AIMD) ----

    def _observe_rtt(self, rtt: float) -> None:
        """One completed request's round trip. RTT above 2x the
        connection's floor means queueing at the peer: shrink the
        window multiplicatively; otherwise grow it additively (classic
        AIMD, deterministic given the sample sequence)."""
        if rtt < self._rtt_best:
            self._rtt_best = rtt
        if rtt > self._rtt_best * 2 and self._cwnd > self.CWND_MIN:
            self._cwnd = max(float(self.CWND_MIN), self._cwnd * 0.7)
            self.stats.window_shrinks += 1
        elif self._cwnd < self.CWND_MAX:
            self._cwnd = min(float(self.CWND_MAX),
                             self._cwnd + 1.0 / max(self._cwnd, 1.0))
            self.stats.window_grows += 1
        self._wake_waiters()

    def _wake_waiters(self) -> None:
        while self._win_waiters and self._inflight < self.window:
            fut = self._win_waiters.popleft()
            if not fut.done():
                # reserve the slot for the woken waiter so a burst of
                # releases cannot over-admit past the window
                self._inflight += 1
                fut.set_result(None)

    async def _acquire_slot(self, timeout: float) -> None:
        if self._inflight < self.window:
            self._inflight += 1
            return
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._win_waiters.append(fut)
        acquired = False
        try:
            await asyncio.wait_for(fut, timeout)
            acquired = True
        except asyncio.TimeoutError as e:
            raise FrameChannelError(
                f"frame channel {self._label()}: congestion window "
                f"wait timed out (window={self.window}, "
                f"in flight={self._inflight})") from e
        finally:
            if not acquired:
                # timeout OR caller cancellation must leave no trace:
                # drop the queue entry, and if _wake_waiters already
                # reserved a slot for this fut in the same tick, give
                # the slot back — a cancelled waiter used to leak its
                # reservation and permanently shrink the window
                try:
                    self._win_waiters.remove(fut)
                except ValueError:
                    if fut.done() and not fut.cancelled() \
                            and fut.exception() is None:
                        self._release_slot()

    def _release_slot(self) -> None:
        self._inflight -= 1
        self._wake_waiters()

    async def _connect(self) -> None:
        loop = asyncio.get_running_loop()
        if self._retry_at and loop.time() < self._retry_at:
            raise FrameChannelError(
                f"frame channel {self._label()}: reconnect backoff "
                f"window open")
        writer = None
        try:
            if self.uds_path:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_unix_connection(self.uds_path),
                    self.connect_timeout)
            else:
                host, _, port = self.target.rpartition(":")
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(host, int(port),
                                            ssl=self._ssl),
                    self.connect_timeout)
            hello_meta: dict = {"v": VERSION, "token": self.token}
            if self.jwt_key:
                from ..security.jwt import gen_jwt
                hello_meta["id"] = gen_jwt(self.jwt_key,
                                           HELLO_IDENTITY_FID,
                                           HELLO_IDENTITY_TTL_S)
            writer.write(MAGIC + encode_frame(HELLO, 0, hello_meta))
            await asyncio.wait_for(writer.drain(), self.connect_timeout)
            dec = FrameDecoder()
            hello: Frame | None = None
            while hello is None:
                chunk = await asyncio.wait_for(reader.read(65536),
                                               self.connect_timeout)
                if not chunk:
                    raise FrameChannelError(
                        f"frame channel {self._label()}: peer closed "
                        f"during handshake (predates the protocol?)")
                frames = dec.feed(chunk)
                if frames:
                    hello = frames[0]
            if hello.type != HELLO_OK:
                why = str((hello.meta or {}).get("error", "")) \
                    if hello.type == GOAWAY else ""
                raise FrameChannelError(
                    f"frame channel {self._label()}: handshake "
                    f"refused (type {hello.type}"
                    + (f": {why}" if why else "") + ")")
        except (OSError, asyncio.TimeoutError, FrameError,
                asyncio.IncompleteReadError) as e:
            # the just-opened socket must not leak on a failed
            # handshake (a pre-frame peer holds it open forever)
            if writer is not None:
                try:
                    writer.close()
                except OSError:
                    pass
            self._retry_at = loop.time() + self._backoff.next()
            if isinstance(e, FrameChannelError):
                raise
            raise FrameChannelError(
                f"frame channel {self._label()}: {e}") from e
        self._backoff.reset()
        self._retry_at = 0.0
        self._writer = writer
        self.stats.connects += 1
        self._rtt_best = float("inf")  # fresh RTT floor per connection
        from ..stats import metrics
        if metrics.HAVE_PROMETHEUS and not self._gauge_open:
            metrics.FRAME_OPEN_CHANNELS.labels(self._label()).inc()
            self._gauge_open = True
        self._reader_task = loop.create_task(
            self._read_loop(reader, writer, dec))
        # frames the peer pipelined behind HELLO_OK in the same chunk
        for fr in frames[1:]:
            self._dispatch(fr)

    async def _read_loop(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter,
                         dec: FrameDecoder) -> None:
        err: BaseException | None = None
        try:
            while True:
                try:
                    chunk = await asyncio.wait_for(
                        reader.read(1 << 18),
                        self.idle_s if not self._pending else
                        self.request_timeout)
                except asyncio.TimeoutError:
                    if self._pending:
                        err = FrameChannelError(
                            f"frame channel {self._label()}: response "
                            f"timeout with {len(self._pending)} "
                            f"in flight")
                        return
                    return                     # idle: close quietly
                if not chunk:
                    err = FrameChannelError(
                        f"frame channel {self._label()}: peer closed")
                    return
                self.stats.reads += 1
                before = dec.overhead_bytes
                for fr in dec.feed(chunk):
                    self._dispatch(fr)
                self.stats.overhead_in += dec.overhead_bytes - before
        except FrameError as e:
            err = FrameChannelError(
                f"frame channel {self._label()}: {e}")
        except asyncio.CancelledError:
            err = FrameChannelError(
                f"frame channel {self._label()}: closed")
            raise
        finally:
            self._teardown(writer, err)

    def _dispatch(self, fr: Frame) -> None:
        fut = self._pending.pop(fr.req_id, None)
        if fut is None or fut.done():
            return                      # late response for a timed-out id
        self.stats.responses += 1
        self.stats.payload_in += len(fr.payload)
        if fr.flags & FLAG_FALLBACK:
            self.stats.fallbacks += 1
            fut.set_exception(FrameFallback(
                f"frame peer {self._label()} asked for HTTP fallback"))
            return
        hdrs = dict(fr.meta.get("h") or {})
        ct = fr.meta.get("ct")
        if ct and not any(k.lower() == "content-type" for k in hdrs):
            hdrs["content-type"] = str(ct)
        fut.set_result((int(fr.meta.get("s", 500)), hdrs, fr.payload,
                        fr.meta))

    def _teardown(self, writer: asyncio.StreamWriter,
                  err: BaseException | None) -> None:
        if self._writer is writer:
            self._writer = None
            self._reader_task = None
            if self._gauge_open:
                from ..stats import metrics
                if metrics.HAVE_PROMETHEUS:
                    metrics.FRAME_OPEN_CHANNELS.labels(
                        self._label()).dec()
                self._gauge_open = False
        try:
            writer.close()
        except OSError:
            pass
        # ALWAYS fail whatever is pending — a request that raced the
        # idle close (registered after the reader's last pending
        # check) must fall back to HTTP now, not stall to its 30s
        # response timeout on a dead socket
        if self._pending:
            msg = str(err) if err is not None else \
                f"frame channel {self._label()}: closed while idle"
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(FrameChannelError(msg))
            self._pending.clear()

    async def request(self, method: str, path: str,
                      query: dict | None = None,
                      headers: dict | None = None, body: bytes = b"",
                      timeout: float | None = None
                      ) -> tuple[int, dict, bytes]:
        """One multiplexed request; returns (status, headers, body).
        Raises FrameFallback when the peer wants this over HTTP and
        FrameChannelError on any transport-level failure. A transport
        failure is an HTTP downgrade THIS process observed, counted in
        SeaweedFS_frame_fallbacks_total — the severed-wire alert
        signal (FLAG_FALLBACK answers are counted by the SERVER that
        sent them, so one logical downgrade never counts twice on a
        merged host). An open circuit breaker (repeated channel
        failures) fails fast here without touching the socket; its
        half-open window admits a probe so frames resume on their own
        once the peer heals."""
        br = self.breaker
        if br is not None and not br.allow():
            from ..stats import metrics
            if metrics.HAVE_PROMETHEUS:
                metrics.FRAME_FALLBACKS.labels(self.hop).inc()
            raise FrameChannelError(
                f"frame channel {self._label()}: circuit open")
        try:
            out = await self._request(method, path, query, headers,
                                      body, timeout)
        except FrameFallback:
            # server-advised downgrade: the peer is alive and counted
            # it — not a channel failure, the breaker stays closed
            if br is not None:
                br.record_success()
            raise
        except FrameChannelError:
            from ..stats import metrics
            if metrics.HAVE_PROMETHEUS:
                metrics.FRAME_FALLBACKS.labels(self.hop).inc()
            if br is not None:
                br.record_failure()
            raise
        if br is not None:
            br.record_success()
        return out

    async def _request(self, method: str, path: str,
                       query: dict | None, headers: dict | None,
                       body: bytes, timeout: float | None
                       ) -> tuple[int, dict, bytes]:
        if self._closed:
            raise FrameChannelError(
                f"frame channel {self._label()}: closed")
        # chaos site: injected frame-hop faults take the exact
        # fallback-to-HTTP path a dead sibling does (FailpointError is
        # a plain OSError — rewrap so callers' single except arm sees
        # a channel failure)
        try:
            await failpoints.fail("worker.frame")
        except OSError as e:
            raise FrameChannelError(
                f"frame channel {self._label()}: {e}") from e
        deadline = timeout if timeout is not None else \
            self.request_timeout
        await self._acquire_slot(deadline)
        try:
            if self._writer is None:
                async with self._conn_lock:
                    if self._writer is None and not self._closed:
                        await self._connect()
            writer = self._writer
            if writer is None:
                raise FrameChannelError(
                    f"frame channel {self._label()}: not connected")
            req_id = self._next_id
            self._next_id = (self._next_id + 1) & 0xFFFFFFFF or 1
            meta: dict = {"m": method, "p": path}
            if query:
                meta["q"] = query
            if headers:
                meta["h"] = headers
            # encode BEFORE registering the future: an oversize-meta
            # FrameError must not leak a pending entry (which would
            # flip the reader loop onto the response timeout forever)
            frame = encode_frame(REQ, req_id, meta, body)
            loop = asyncio.get_running_loop()
            fut: asyncio.Future = loop.create_future()
            self._pending[req_id] = fut
            self.stats.requests += 1
            from ..stats import metrics
            if metrics.HAVE_PROMETHEUS:
                metrics.FRAME_REQUESTS.labels("client", self.hop).inc()
            self.stats.overhead_out += len(frame) - len(body)
            self.stats.payload_out += len(body)
            self.stats.writes += 1
            t0 = loop.time()
            try:
                writer.write(frame)
                await writer.drain()
                status, hdrs, payload, _ = await asyncio.wait_for(
                    fut, deadline)
                self._observe_rtt(loop.time() - t0)
                return status, hdrs, payload
            except asyncio.TimeoutError as e:
                raise FrameChannelError(
                    f"frame channel {self._label()}: request timeout") \
                    from e
            except (OSError, ConnectionResetError) as e:
                if isinstance(e, FrameChannelError):
                    raise
                raise FrameChannelError(
                    f"frame channel {self._label()}: {e}") from e
            finally:
                # drop the pending entry on EVERY exit — the success
                # path's _dispatch already popped it (idempotent), but
                # a caller cancelled inside drain()/wait_for() used to
                # leak the entry until response arrival or teardown,
                # pinning the reader loop's timeout accounting
                self._pending.pop(req_id, None)
        finally:
            self._release_slot()

    async def close(self) -> None:
        self._closed = True
        task = self._reader_task
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
            except OSError as e:
                glog.V(2).infof("frame channel %s close: %s",
                                self._label(), e)
        writer = self._writer
        if writer is not None:
            self._teardown(writer, FrameChannelError("channel closed"))


class FrameHub:
    """Channel cache keyed by destination — the per-sibling (and
    per-volume-server, for client pipelining) persistent connections.
    Bounded; replacing a key (a sibling respawned on a new private
    port / unix socket) schedules the old channel's close."""

    MAX_CHANNELS = 64

    def __init__(self, token: str = "", request_timeout: float = 30.0,
                 ssl=None, jwt_key: str = "",
                 breakers: BreakerRegistry | None = None):
        self.token = token
        self.jwt_key = jwt_key
        self.request_timeout = request_timeout
        self._ssl = ssl
        # repeated channel failures open the per-peer breaker: callers
        # fail fast to HTTP, the half-open probe re-tries frames
        # (threshold/reset sized to match the connect Backoff cap)
        self.breakers = breakers if breakers is not None else \
            BreakerRegistry(threshold=5, reset_timeout=2.0,
                            half_open_max=2)
        self._channels: dict[str, FrameChannel] = {}

    def get(self, target: str = "", uds_path: str = "",
            hop: str = "") -> FrameChannel:
        key = uds_path or target
        ch = self._channels.get(key)
        if ch is None:
            if len(self._channels) >= self.MAX_CHANNELS:
                old_key, old = next(iter(self._channels.items()))
                del self._channels[old_key]
                _close_soon(old)
            ch = self._channels[key] = FrameChannel(
                target=target, uds_path=uds_path, token=self.token,
                request_timeout=self.request_timeout, ssl=self._ssl,
                jwt_key=self.jwt_key, hop=hop,
                breaker=self.breakers.get(f"frame:{key}"))
        return ch

    def stats_dict(self) -> dict:
        return {key: ch.stats.to_dict()
                for key, ch in self._channels.items()}

    async def close(self) -> None:
        chans = list(self._channels.values())
        self._channels.clear()
        for ch in chans:
            await ch.close()


def _close_soon(ch: FrameChannel) -> None:
    """Schedule an evicted channel's close without awaiting it (the
    eviction happens inside a sync get()); the task handle is retained
    on the channel so it cannot be GC'd mid-close."""
    try:
        loop = asyncio.get_running_loop()
    except RuntimeError:
        return
    ch._close_task = loop.create_task(ch.close())  # type: ignore[attr-defined]


def overhead_model(method: str, path: str, query: dict | None = None,
                   headers: dict | None = None,
                   resp_headers: dict | None = None,
                   resp_ct: str = "application/octet-stream") -> int:
    """Deterministic frame protocol overhead (bytes) for one logical
    request+response, excluding payload — the frame side of
    bench_needle's sibling-hop accounting, computed from the real
    codec so it can never drift from the wire."""
    req = encode_frame(REQ, 1, {"m": method, "p": path,
                                **({"q": query} if query else {}),
                                **({"h": headers} if headers else {})})
    resp = encode_frame(RESP, 1, {"s": 200, "h": resp_headers or {},
                                  "ct": resp_ct})
    return len(req) + len(resp)
