"""Leveled logging in the glog idiom.

Reference: weed/glog/ (vendored google/glog port, ~1,311 LoC): severity
levels INFO/WARNING/ERROR/FATAL, verbose `V(n)` guards compiled out by a
single integer comparison, `-v`/`-logtostderr` flags (weed.go:38,
glog.go:391+), size-based rotation of per-severity files.

Python re-expression: one module-level verbosity integer, `V(n)` returning
a no-op logger below threshold (so hot paths pay only an int compare), and
an optional log_dir with per-severity files rotated at max_size.
"""

from __future__ import annotations

import io
import os
import sys
import threading
import time

INFO, WARNING, ERROR, FATAL = "INFO", "WARNING", "ERROR", "FATAL"
_SEVERITIES = (INFO, WARNING, ERROR, FATAL)

_verbosity = 0
_log_dir: str | None = None
_max_size = 64 << 20  # glog.MaxSize analog (set weed.go:38)
_lock = threading.Lock()
_files: dict[str, io.TextIOBase] = {}
_to_stderr = True


def init(verbosity: int = 0, log_dir: str | None = None,
         logtostderr: bool = True, max_size: int = 64 << 20) -> None:
    """Wire from CLI flags: -v, -logdir, -logtostderr."""
    global _verbosity, _log_dir, _to_stderr, _max_size
    _verbosity = verbosity
    _log_dir = log_dir
    _to_stderr = logtostderr or not log_dir
    _max_size = max_size
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)


def _emit(severity: str, msg: str) -> None:
    line = (f"{severity[0]}{time.strftime('%m%d %H:%M:%S')} "
            f"{threading.get_ident() % 100000:05d} {msg}\n")
    if _to_stderr:
        sys.stderr.write(line)
    if _log_dir:
        with _lock:
            f = _files.get(severity)
            if f is None or (f.tell() > _max_size):
                if f is not None:
                    f.close()
                path = os.path.join(
                    _log_dir,
                    f"swtpu.{severity}.{time.strftime('%Y%m%d-%H%M%S')}.log")
                f = open(path, "a")
                _files[severity] = f
            # glog semantics: a message at severity s lands in every file
            # of lower-or-equal severity; keep it simple with one file per
            # severity and write only there (queries use grep anyway)
            f.write(line)
            f.flush()


def info(fmt: str, *args) -> None:
    _emit(INFO, fmt % args if args else fmt)


def warning(fmt: str, *args) -> None:
    _emit(WARNING, fmt % args if args else fmt)


def error(fmt: str, *args) -> None:
    _emit(ERROR, fmt % args if args else fmt)


def fatal(fmt: str, *args) -> None:
    _emit(FATAL, fmt % args if args else fmt)
    raise SystemExit(255)


class _Verbose:
    """Returned by V(n); truthy + has infof, so both idioms work:

        if glog.V(3): ...expensive...
        glog.V(3).infof("read vid=%d nid=%d", vid, nid)
    """

    __slots__ = ("on",)

    def __init__(self, on: bool):
        self.on = on

    def __bool__(self) -> bool:
        return self.on

    def infof(self, fmt: str, *args) -> None:
        if self.on:
            _emit(INFO, fmt % args if args else fmt)


_V_ON = _Verbose(True)
_V_OFF = _Verbose(False)


def V(level: int) -> _Verbose:
    return _V_ON if level <= _verbosity else _V_OFF


def verbosity() -> int:
    return _verbosity
