"""Shared HTTP Range header parsing for the read paths."""

from __future__ import annotations


class RangeError(ValueError):
    pass


def parse_range(header: str, size: int) -> tuple[int, int] | None:
    """'bytes=a-b' -> (offset, length) clipped to size, or None when the
    header is absent/not a bytes range. Raises RangeError for malformed or
    unsatisfiable ranges (callers answer 416)."""
    if not header or not header.startswith("bytes="):
        return None
    spec = header[6:].split(",")[0].strip()
    start_s, sep, end_s = spec.partition("-")
    if not sep:
        raise RangeError(f"malformed range {header!r}")
    try:
        if start_s:
            offset = int(start_s)
            end = int(end_s) if end_s else size - 1
        else:
            if not end_s:
                raise RangeError(f"malformed range {header!r}")
            offset = max(0, size - int(end_s))
            end = size - 1
    except ValueError as e:
        raise RangeError(str(e))
    end = min(end, size - 1)
    if offset >= size or offset < 0 or end < offset:
        raise RangeError(f"unsatisfiable range {header!r} for size {size}")
    return offset, end - offset + 1
