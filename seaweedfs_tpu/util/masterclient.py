"""wdclient: long-lived master subscriber maintaining a vid→locations map.

Reference: weed/wdclient/masterclient.go:15-119 (`KeepConnectedToMaster`
/ `tryConnectToMaster` consuming the KeepConnected stream, with leader-
redirect failover) and weed/wdclient/vid_map.go:23-116 (round-robin
location lookup). The wire here is the master's /cluster/watch NDJSON
stream: one initial full snapshot, then {url, public_url, new_vids,
deleted_vids} deltas as heartbeats mutate the topology.

Used by filer / shell / gateways so hot-path fid lookups never hit the
master — they read a locally-maintained map that self-heals on volume
moves and node deaths.
"""

from __future__ import annotations

from ..security import tls
from . import failpoints, glog
from .resilience import Backoff

import asyncio
import json
from dataclasses import dataclass

import aiohttp


class _LeaderRedirect(Exception):
    """Internal: the stream announced a different leader to follow."""


@dataclass(frozen=True)
class Location:
    url: str
    public_url: str


class MasterClient:
    def __init__(self, masters: list[str] | str, name: str = "client",
                 session: aiohttp.ClientSession | None = None):
        if isinstance(masters, str):
            masters = [masters]
        self.masters = masters
        self.current_master = masters[0]
        self.name = name
        self._session = session
        self._own = session is None
        self._vid_map: dict[int, list[Location]] = {}
        self._rr: dict[int, int] = {}
        self._task: asyncio.Task | None = None
        self._synced = asyncio.Event()
        self._stream_synced = False

    async def start(self) -> None:
        if self._session is None:
            # sock_read must outlast the master's 1s keepalive but fire on
            # a silently-dead peer, or failover never triggers
            self._session = tls.make_session(
                timeout=aiohttp.ClientTimeout(total=None, connect=10,
                                              sock_read=5.0))
        self._task = asyncio.create_task(self._keep_connected())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        if self._own and self._session:
            await self._session.close()

    async def wait_synced(self, timeout: float = 10.0) -> None:
        """Block until the initial snapshot of at least one connect has
        been consumed."""
        await asyncio.wait_for(self._synced.wait(), timeout)

    # ---- lookup (vid_map.go) ----

    def lookup(self, vid: int) -> list[Location]:
        return list(self._vid_map.get(vid, []))

    def lookup_file_id(self, fid: str) -> str | None:
        """fid -> one public read URL, round-robin over replicas
        (vid_map.go:23-116)."""
        try:
            vid = int(fid.split(",")[0])
        except ValueError:
            return None
        locs = self._vid_map.get(vid)
        if not locs:
            return None
        i = self._rr.get(vid, 0) % len(locs)
        self._rr[vid] = i + 1
        return tls.url(locs[i].public_url, f"/{fid}")

    @property
    def vid_count(self) -> int:
        return len(self._vid_map)

    # ---- stream consumption (masterclient.go:45-119) ----

    def _apply(self, update: dict) -> None:
        loc = Location(url=update["url"],
                       public_url=update.get("public_url", update["url"]))
        for vid in update.get("new_vids", []):
            locs = self._vid_map.setdefault(int(vid), [])
            if loc not in locs:
                locs.append(loc)
        for vid in update.get("deleted_vids", []):
            locs = self._vid_map.get(int(vid))
            if not locs:
                continue
            locs[:] = [x for x in locs if x.url != loc.url]
            if not locs:
                del self._vid_map[int(vid)]

    async def _keep_connected(self) -> None:
        i = 0
        # full-jitter exponential backoff between reconnect rounds: a
        # fixed 1s cadence from a whole fleet of watchers re-dials a
        # rebooting master in lockstep (resilience.Backoff resets once
        # a stream delivers its snapshot)
        backoff = Backoff(base=0.25, cap=10.0)
        while True:
            master = self.current_master
            redirected = False
            self._stream_synced = False
            try:
                await self._consume_stream(master)
                glog.V(1).infof("masterclient %s: watch stream to %s "
                                "ended", self.name, master)
            except asyncio.CancelledError:
                raise
            except _LeaderRedirect:
                # _consume_stream already pointed current_master at the
                # announced leader; follow it instead of rotating, but
                # pause briefly so mutually-redirecting masters (election
                # window) can't drive a tight reconnect loop
                redirected = True
                await asyncio.sleep(0.2)
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                    RuntimeError, ValueError) as e:
                # ValueError covers a malformed NDJSON line; a swallowed
                # stream death must at least be visible at -v 1
                glog.V(1).infof("masterclient %s: watch stream to %s "
                                "failed: %s", self.name, master, e)
            except Exception as e:  # noqa: BLE001 — the watcher must
                # NEVER die: an unexpected update shape (KeyError in
                # _apply, non-dict JSON) would otherwise kill this task
                # silently and freeze the vid map for the process life
                glog.warning("masterclient %s: watch stream to %s: "
                             "unexpected %s: %s", self.name, master,
                             type(e).__name__, e)
            if self._stream_synced:
                backoff.reset()     # that stream was healthy once
            if not redirected:
                # rotate to the next configured master (leader chasing:
                # tryConnectToMaster redirect loop)
                i += 1
                self.current_master = self.masters[i % len(self.masters)]
                await asyncio.sleep(backoff.next())

    async def _consume_stream(self, master: str) -> None:
        await failpoints.fail("masterclient.watch")
        async with self._session.get(
                tls.url(master, "/cluster/watch")) as resp:
            if resp.status != 200:
                raise RuntimeError(f"watch {master}: {resp.status}")
            # fresh connect: rebuild from the snapshot the stream opens
            # with, dropping state from the previous (dead) connection
            self._vid_map.clear()
            buf = b""
            async for chunk, _ in resp.content.iter_chunks():
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if not line.strip():
                        continue
                    update = json.loads(line)
                    if update.get("synced"):
                        # end-of-snapshot marker: map is now complete
                        self._synced.set()
                        self._stream_synced = True
                        continue
                    if update.get("leader"):
                        # explicit leader hint (sent by non-leader masters
                        # in an HA deployment): reconnect there, and fold
                        # the learned leader into the rotation so its own
                        # later death still rotates through every master
                        # this client ever met
                        lead = update["leader"]
                        if lead not in self.masters:
                            self.masters.append(lead)
                        self.current_master = lead
                        raise _LeaderRedirect(lead)
                    self._apply(update)
