"""CPU/memory profiling behind flags on every long-running command.

Reference: weed/util/pprof.go `SetupProfiling(cpuProfile, memProfile)`,
wired at command/master.go:74-75, volume.go, mount_std.go:28. Python
analog: cProfile stats dumped at exit for CPU, tracemalloc top-25 for
memory.
"""

from __future__ import annotations

import atexit


def setup_profiling(cpu_profile: str = "", mem_profile: str = "") -> None:
    if cpu_profile:
        import cProfile
        prof = cProfile.Profile()
        prof.enable()

        def _dump_cpu() -> None:
            prof.disable()
            prof.dump_stats(cpu_profile)

        atexit.register(_dump_cpu)
    if mem_profile:
        import tracemalloc
        tracemalloc.start(25)

        def _dump_mem() -> None:
            snap = tracemalloc.take_snapshot()
            with open(mem_profile, "w") as f:
                for stat in snap.statistics("lineno")[:100]:
                    f.write(f"{stat}\n")

        atexit.register(_dump_mem)
