"""CPU/memory profiling behind flags on every long-running command.

Reference: weed/util/pprof.go `SetupProfiling(cpuProfile, memProfile)`,
wired at command/master.go:74-75, volume.go, mount_std.go:28. Python
analog: cProfile stats dumped at exit for CPU, tracemalloc top-25 for
memory.

Under `-workers N` every worker process runs this same setup with the
same flag values; each dump path is therefore suffixed `.w<index>`
(e.g. `prof.out.w1`) so N workers don't clobber one file — the
supervisor's own process (workerIndex < 0) keeps the bare path.

Dumps happen at atexit AND on demand: a SIGKILLed or wedged worker
would lose an atexit-only profile, so ``dump_now()`` snapshots both
profiles mid-flight — reachable as ``/debug/pprof?dump=1`` (the volume
server fans it across ``-workers`` siblings) and on SIGUSR2 (the
classic "the process is wedged, dump what you have" escape hatch:
``kill -USR2 <pid>``). The cProfile dump disables the profiler only
for the dump_stats call and re-enables it, so sampling continues.
"""

from __future__ import annotations

import atexit
import signal
import threading

# (profile, dump path) registered by setup_profiling in THIS process
_cpu: "tuple[object, str] | None" = None
_mem_path = ""
_lock = threading.Lock()


def profile_path(path: str, worker_index: int = -1) -> str:
    """The actual dump path: `.w<index>`-suffixed under -workers."""
    return f"{path}.w{worker_index}" if worker_index >= 0 else path


def _dump_cpu(final: bool = False) -> "str | None":
    with _lock:
        if _cpu is None:
            return None
        prof, path = _cpu
        prof.disable()
        try:
            prof.dump_stats(path)
        finally:
            if not final:
                prof.enable()
    return path


def _dump_mem() -> "str | None":
    if not _mem_path:
        return None
    import tracemalloc
    if not tracemalloc.is_tracing():
        return None
    snap = tracemalloc.take_snapshot()
    with _lock:
        with open(_mem_path, "w") as f:
            for stat in snap.statistics("lineno")[:100]:
                f.write(f"{stat}\n")
    return _mem_path


def dump_now() -> dict:
    """Snapshot every armed profile to its path NOW and keep
    profiling. Returns {"cpu": path, "mem": path} for the dumps that
    actually happened ({} when neither flag was set)."""
    out: dict[str, str] = {}
    cpu = _dump_cpu()
    if cpu:
        out["cpu"] = cpu
    mem = _dump_mem()
    if mem:
        out["mem"] = mem
    return out


def _on_sigusr2(_signum, _frame) -> None:
    dump_now()


def setup_profiling(cpu_profile: str = "", mem_profile: str = "",
                    worker_index: int = -1) -> None:
    global _cpu, _mem_path
    if cpu_profile:
        import cProfile
        prof = cProfile.Profile()
        prof.enable()
        _cpu = (prof, profile_path(cpu_profile, worker_index))
        atexit.register(_dump_cpu, final=True)
    if mem_profile:
        import tracemalloc
        tracemalloc.start(25)
        _mem_path = profile_path(mem_profile, worker_index)
        atexit.register(_dump_mem)
    if cpu_profile or mem_profile:
        try:
            signal.signal(signal.SIGUSR2, _on_sigusr2)
        except ValueError:
            pass    # not the main thread (embedded/test loop): HTTP
            # dump-on-demand still works


def debug_handler():
    """One aiohttp /debug/pprof handler — GET reports what's armed,
    ``?dump=1`` snapshots to disk mid-flight. Registered by every
    non-worker-aggregating server; the volume server has a
    -workers-fanning twin."""
    from aiohttp import web
    from . import tracing

    async def h_pprof(req):
        dump = req.query.get("dump", "") in ("1", "true")
        # executor hop: the mem dump writes a file
        body = await tracing.run_in_executor(
            lambda: pprof_dict(dump=dump))
        return web.json_response(body)

    return h_pprof


def pprof_dict(dump: bool = False) -> dict:
    """The /debug/pprof body: which profiles are armed, and — with
    dump=True — the paths just written."""
    out: dict = {"cpu": bool(_cpu), "mem": bool(_mem_path)}
    if dump:
        out["dumped"] = dump_now()
    return out
