"""CPU/memory profiling behind flags on every long-running command.

Reference: weed/util/pprof.go `SetupProfiling(cpuProfile, memProfile)`,
wired at command/master.go:74-75, volume.go, mount_std.go:28. Python
analog: cProfile stats dumped at exit for CPU, tracemalloc top-25 for
memory.

Under `-workers N` every worker process runs this same setup with the
same flag values; each dump path is therefore suffixed `.w<index>`
(e.g. `prof.out.w1`) so N workers don't clobber one file — the
supervisor's own process (workerIndex < 0) keeps the bare path.
"""

from __future__ import annotations

import atexit


def profile_path(path: str, worker_index: int = -1) -> str:
    """The actual dump path: `.w<index>`-suffixed under -workers."""
    return f"{path}.w{worker_index}" if worker_index >= 0 else path


def setup_profiling(cpu_profile: str = "", mem_profile: str = "",
                    worker_index: int = -1) -> None:
    if cpu_profile:
        import cProfile
        prof = cProfile.Profile()
        prof.enable()
        cpu_path = profile_path(cpu_profile, worker_index)

        def _dump_cpu() -> None:
            prof.disable()
            prof.dump_stats(cpu_path)

        atexit.register(_dump_cpu)
    if mem_profile:
        import tracemalloc
        tracemalloc.start(25)
        mem_path = profile_path(mem_profile, worker_index)

        def _dump_mem() -> None:
            snap = tracemalloc.take_snapshot()
            with open(mem_path, "w") as f:
                for stat in snap.statistics("lineno")[:100]:
                    f.write(f"{stat}\n")

        atexit.register(_dump_mem)
