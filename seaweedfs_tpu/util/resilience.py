"""Retry policies and circuit breakers for every inter-server hop.

Reference: the reference client retries assign/upload in a fixed loop
(operation/upload_content.go) and survives dead masters via wdclient
leader-chasing (wdclient/masterclient.go:45-119); it has no backoff
discipline and no breaker, so a dead volume server is re-dialed at full
rate by every caller until its TCP timeouts drain the fleet.

This module gives the tree the two standard primitives:

* ``RetryPolicy`` — exponential backoff with FULL jitter (the AWS
  architecture-blog shape: sleep = uniform(0, min(cap, base·2^n))),
  a per-attempt deadline, a total deadline, and an optional shared
  ``RetryBudget`` so a brown-out cannot amplify into a retry storm.

* ``CircuitBreaker`` — per-upstream closed → open → half-open state
  machine: `threshold` consecutive failures open it, `reset_timeout`
  later a limited number of half-open probes are let through, one
  success closes it again. While open, callers fail (or skip the
  upstream) in microseconds instead of burning a connect timeout.

Both take injectable ``clock``/``rng`` so the state machines unit-test
without wall-clock sleeps.
"""

from __future__ import annotations

import asyncio
import random
import time

__all__ = ["RetryBudget", "RetryPolicy", "CircuitBreaker",
           "BreakerRegistry", "Backoff"]


class RetryBudget:
    """Token bucket bounding the fleet-wide retry amplification factor.

    Every first attempt deposits `ratio` tokens; every retry withdraws
    one. When the bucket is empty, retries are denied and the caller
    fails fast — under a full outage the extra load from retries is
    bounded at `ratio` of the offered load (the SRE-book discipline).

    The budget is KEYED (upstream + tenant class, util/client.py):
    each key gets its own token pool, so an abusive tenant burning
    retries against one flapping volume exhausts only its own pool —
    a paying tenant retrying against a healthy upstream is untouched.
    The un-keyed calls ("" key) keep the original process-global
    behavior. Pools are capped; past the cap everything shares an
    overflow pool rather than growing without bound."""

    MAX_POOLS = 256
    OVERFLOW = "~overflow"

    def __init__(self, ratio: float = 0.2, burst: float = 10.0):
        self.ratio = ratio
        self.burst = burst
        self._pools: dict[str, float] = {"": burst}

    def _key(self, key: str) -> str:
        if key in self._pools or len(self._pools) < self.MAX_POOLS:
            return key
        return self.OVERFLOW

    def record_attempt(self, key: str = "") -> None:
        k = self._key(key)
        self._pools[k] = min(self.burst,
                             self._pools.get(k, self.burst) + self.ratio)

    def allow_retry(self, key: str = "") -> bool:
        k = self._key(key)
        tokens = self._pools.get(k, self.burst)
        if tokens >= 1.0:
            self._pools[k] = tokens - 1.0
            return True
        self._pools[k] = tokens
        return False

    @property
    def tokens(self) -> float:
        """The process-global pool (back-compat introspection)."""
        return self._pools.get("", self.burst)

    @tokens.setter
    def tokens(self, value: float) -> None:
        self._pools[""] = value

    def to_dict(self) -> dict:
        return {k or "(global)": round(v, 3)
                for k, v in sorted(self._pools.items())}


class RetryPolicy:
    """Exponential backoff with full jitter + deadlines.

    Usage (attempt loop — break on success, `continue` retries):

        async for attempt in policy.attempts():
            try:
                return await do_thing()
            except TransientError as e:
                last = e
        raise OperationError(last)

    The generator sleeps the backoff BETWEEN yields, stops yielding
    when attempts or the total deadline run out, and consults the
    shared budget (when configured) before every retry.
    """

    def __init__(self, max_attempts: int = 4, base_delay: float = 0.05,
                 max_delay: float = 2.0, total_timeout: float = 30.0,
                 per_attempt_timeout: float | None = None,
                 budget: RetryBudget | None = None,
                 rng: random.Random | None = None,
                 clock=time.monotonic, sleep=None, name: str = ""):
        self.name = name            # journal attribution (events.py)
        self.max_attempts = max_attempts
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.total_timeout = total_timeout
        self.per_attempt_timeout = per_attempt_timeout
        self.budget = budget
        self._rng = rng or random
        self._clock = clock
        self._sleep = sleep or asyncio.sleep

    def backoff(self, attempt: int) -> float:
        """Full-jitter delay before retry number `attempt` (1-based)."""
        cap = min(self.max_delay, self.base_delay * (2 ** (attempt - 1)))
        return self._rng.uniform(0, cap)

    async def attempts(self, budget_key: str = ""):
        """Async generator of attempt indices 0..max_attempts-1.

        `budget_key` selects the retry-budget pool (upstream + tenant
        class, see RetryBudget) — callers that know their upstream
        pass it so one flapping target can't drain everyone's
        retries; the default keeps the process-global pool."""
        deadline = self._clock() + self.total_timeout
        for attempt in range(self.max_attempts):
            if attempt:
                if self.budget is not None and \
                        not self.budget.allow_retry(budget_key):
                    # budget exhausted: fail fast — and journal it,
                    # because a brown-out's retry storm hitting the
                    # ceiling is exactly the transition an operator
                    # reading /debug/health evidence needs to see
                    from . import events
                    events.record("retry_budget_exhausted",
                                  name=self.name, key=budget_key,
                                  attempt=attempt)
                    return
                delay = self.backoff(attempt)
                if self._clock() + delay >= deadline:
                    return
                await self._sleep(delay)
            elif self.budget is not None:
                self.budget.record_attempt(budget_key)
            if self._clock() >= deadline:
                return
            yield attempt


class CircuitBreaker:
    """Closed / open / half-open breaker for one upstream."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, threshold: int = 5, reset_timeout: float = 10.0,
                 half_open_max: int = 1, clock=time.monotonic,
                 name: str = ""):
        self.threshold = threshold
        self.reset_timeout = reset_timeout
        self.half_open_max = half_open_max
        self._clock = clock
        self.name = name            # upstream key, journal attribution
        self.state = self.CLOSED
        self.failures = 0
        self.opened_at = 0.0
        self.probes = 0             # in-flight half-open probes
        self.open_count = 0         # times the breaker tripped (stats)

    def blocking(self) -> bool:
        """Side-effect-free peek: is this upstream currently shed?
        (Unlike allow(), never transitions state nor consumes a
        half-open probe — safe for ordering/demotion decisions.)"""
        return self.state == self.OPEN and \
            self._clock() - self.opened_at < self.reset_timeout

    def allow(self) -> bool:
        """May a request be sent to this upstream right now?"""
        if self.state == self.CLOSED:
            return True
        if self.state == self.OPEN:
            if self._clock() - self.opened_at >= self.reset_timeout:
                self.state = self.HALF_OPEN
                self.probes = 0
            else:
                return False
        # half-open: a bounded number of probes
        if self.probes < self.half_open_max:
            self.probes += 1
            return True
        return False

    def record_success(self) -> None:
        # closes from ANY state: the read path tries demoted (open)
        # upstreams last instead of skipping them, and a success there
        # is direct evidence of health
        if self.state != self.CLOSED:
            from . import events
            events.record("breaker_close", upstream=self.name,
                          was=self.state)
        self.state = self.CLOSED
        self.failures = 0
        self.probes = 0

    def record_failure(self) -> None:
        if self.state == self.HALF_OPEN:
            # the probe failed: re-open and restart the reset clock
            self.state = self.OPEN
            self.opened_at = self._clock()
            self.open_count += 1
            return
        self.failures += 1
        if self.state == self.CLOSED and self.failures >= self.threshold:
            self.state = self.OPEN
            self.opened_at = self._clock()
            self.open_count += 1
            from . import events
            events.record("breaker_open", upstream=self.name,
                          failures=self.failures)

    def to_dict(self) -> dict:
        return {"state": self.state, "failures": self.failures,
                "open_count": self.open_count}


class BreakerRegistry:
    """One CircuitBreaker per upstream key (host:port)."""

    def __init__(self, threshold: int = 5, reset_timeout: float = 10.0,
                 half_open_max: int = 1, clock=time.monotonic):
        self.threshold = threshold
        self.reset_timeout = reset_timeout
        self.half_open_max = half_open_max
        self._clock = clock
        self._breakers: dict[str, CircuitBreaker] = {}

    def get(self, upstream: str) -> CircuitBreaker:
        b = self._breakers.get(upstream)
        if b is None:
            if len(self._breakers) > 4096:
                # upstream keys derive from lookups; bound the registry
                self._breakers.clear()
            b = self._breakers[upstream] = CircuitBreaker(
                self.threshold, self.reset_timeout, self.half_open_max,
                clock=self._clock, name=upstream)
        return b

    def to_dict(self) -> dict:
        return {k: b.to_dict() for k, b in self._breakers.items()}


class Backoff:
    """Stateful exponential backoff with full jitter, for reconnect
    loops (MasterClient stream, heartbeat seed rotation): `next()`
    returns the sleep before the next try, `reset()` after success."""

    def __init__(self, base: float = 0.5, cap: float = 15.0,
                 rng: random.Random | None = None):
        self.base = base
        self.cap = cap
        self._rng = rng or random
        self._n = 0

    def next(self) -> float:
        delay = self._rng.uniform(0, min(self.cap,
                                         self.base * (2 ** self._n)))
        if self.base * (2 ** self._n) < self.cap:
            self._n += 1
        return delay

    def reset(self) -> None:
        self._n = 0
