"""Graceful-shutdown signal handling for long-running commands.

Reference: weed/util/signal_handling.go:19-44 — `OnInterrupt` runs
registered cleanups on SIGINT/SIGTERM/SIGHUP before exit (profile dumps
at weed/util/pprof.go:18-31, store unregister at
weed/command/volume.go:184, graceful HTTP stop at
weed/util/httpdown/http_down.go:360-383).

asyncio re-design: instead of callback registration, the server runners
await `wait_for_interrupt()` and then call their servers' `stop()`
coroutines in order. When the runner returns, `asyncio.run` tears the
loop down and atexit hooks fire — which is what makes
`-cpuprofile`/`-memprofile` (util/pprof.py) produce output for server
commands instead of only for one-shot ones.
"""

from __future__ import annotations

import asyncio
import contextlib
import os
import signal


async def wait_for_interrupt() -> int:
    """Block until SIGINT/SIGTERM/SIGHUP; returns the signal number.

    Handlers are installed on the running loop (they replace any
    inherited disposition — a background job of a non-interactive shell
    starts with SIGINT ignored, and a server must still honor a
    deliberate signal the way the reference's signal.Notify does).
    """
    loop = asyncio.get_running_loop()
    got: asyncio.Future[int] = loop.create_future()
    sigs = (signal.SIGINT, signal.SIGTERM, signal.SIGHUP)

    def fire(num: int) -> None:
        if not got.done():
            got.set_result(num)
        else:
            # second signal while the graceful drain is running: force
            # quit with the conventional fatal-signal status. Handlers
            # stay installed through cleanup (the reference keeps
            # signal.Notify active for the process lifetime) so a
            # re-delivered SIGTERM can never hit the default disposition
            # mid needle-map commit.
            os._exit(128 + num)

    for sig in sigs:
        # non-main threads / exotic loops can't install handlers; a
        # server that can't catch signals still runs, it just exits
        # non-gracefully as before
        with contextlib.suppress(NotImplementedError, OSError,
                                 RuntimeError, ValueError):
            loop.add_signal_handler(sig, fire, int(sig))
    return await got
