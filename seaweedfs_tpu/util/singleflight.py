"""Call collapsing ("singleflight") for duplicate in-flight work.

Reference idiom: golang.org/x/sync/singleflight as used by the
reference's wdclient lookups and chunk fetches — when N callers ask for
the same key concurrently, ONE underlying call runs and every caller
shares its result (or its exception).

Asyncio-native: the collapse window is the leader's await, so this is
for coroutine call sites (client lookups, chunk fetches). Work that
runs in executor threads stays un-collapsed — the volume needle cache
doesn't need it because a disk pread is cheaper than cross-thread
coordination at that granularity.
"""

from __future__ import annotations

import asyncio

from .aio import detach


class SingleFlight:
    """Collapse concurrent ``do(key, fn)`` calls into one ``fn()``.

    The first caller for a key becomes the leader and runs ``fn``;
    followers await the leader's future. Exceptions propagate to every
    caller of that round. The key is forgotten the moment the round
    settles, so a later call retries fresh (errors are never cached
    here — negative caching is a policy the caller layers on top).
    """

    def __init__(self):
        self._inflight: dict[object, asyncio.Future] = {}
        # rounds that had at least one follower / total underlying calls
        self.collapsed = 0
        self.calls = 0

    def pending(self, key) -> bool:
        return key in self._inflight

    async def do(self, key, fn):
        task = self._inflight.get(key)
        if task is None:
            self.calls += 1
            # fn runs as a DETACHED task: cancelling any caller —
            # including the one that started the round — must not
            # cancel the shared work out from under the others (a
            # disconnecting client would otherwise abort every
            # concurrent reader of the same chunk); aio.detach also
            # retains the handle and consumes the terminal exception
            # so nothing logs "never retrieved"
            task = detach(self._run(key, fn))
            self._inflight[key] = task
        else:
            self.collapsed += 1
        # shield: a cancelled caller stops waiting; the task runs on
        return await asyncio.shield(task)

    async def _run(self, key, fn):
        try:
            return await fn()
        finally:
            self._inflight.pop(key, None)
