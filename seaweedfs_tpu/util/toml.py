"""TOML parser shim: stdlib `tomllib` is 3.11+; fall back to the
third-party `tomli` (same API) and finally to None, which callers
treat as "config discovery disabled" instead of crashing every
command on an older interpreter."""

from __future__ import annotations

try:
    import tomllib  # type: ignore[import-not-found]
except ModuleNotFoundError:  # pragma: no cover - version-dependent
    try:
        import tomli as tomllib  # type: ignore[no-redef]
    except ModuleNotFoundError:
        tomllib = None  # type: ignore[assignment]
