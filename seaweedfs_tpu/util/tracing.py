"""End-to-end distributed tracing + per-request introspection.

The cluster has a multi-core data plane, fault injection with retries
and breakers, and a tiered read cache — but when a request is slow
there was no way to tell WHICH tier ate the time: gateway, filer chunk
fan-out, client retries, sibling-proxy hop, volume worker, or GF(256)
reconstruction.  This module is the measurement substrate: every hop
opens a Span carrying a shared trace id, finished spans land in a
bounded in-memory ring per process, and the debug surface exposes them
as `/debug/traces` (recent + slowest traces) and `/debug/requests`
(currently in-flight spans, for spotting wedged requests).

Propagation follows the W3C `traceparent` idiom —

    traceparent: 00-<32 hex trace id>-<16 hex parent span id>-<2 hex flags>

— carried on every inter-process hop (client -> master, client ->
volume, worker sibling proxy, replication fan-out, remote EC shard
reads), so one logical request stays ONE trace across the whole fleet.
Within a process, parenthood rides a contextvar: `start()` silently
returns the no-op span when no trace is active, which is what makes
instrumentation free on untraced paths.

Design constraints honored here:

- zero-allocation no-op when disabled: at `-trace.sample 0` header-less
  requests (and child spans with no active parent) get the singleton
  `_NOOP`, whose every method is a pass — hot paths pay one contextvar
  read. An incoming SAMPLED traceparent is still joined (dropping it
  would orphan an upstream trace mid-chain), so silencing tracing
  end-to-end means sample 0 fleet-wide;
- monotonic-clock durations (`perf_counter`), wall-clock start stamps
  so rings from different processes merge on a shared timeline;
- bounded memory: ring (default 2048 spans), per-span event cap, and
  an in-flight table cap — a leak cannot grow past the caps;
- spans record (tier, op, status, bytes) and feed the
  `SeaweedFS_request_duration_seconds{tier,op,status}` histogram, so
  the trace ring and Prometheus agree by construction;
- entry spans slower than `-trace.slowms` emit one glog WARNING line
  carrying the trace id, the grep-able bridge from logs to traces.
"""

from __future__ import annotations

import contextvars
import random
import threading
import time
from collections import deque

from . import glog

TRACE_HEADER = "traceparent"

_sample = 1.0          # P(root span) for requests without a traceparent
_slow_ms = 0.0         # entry spans slower than this glog WARNING; 0 = off
_MAX_EVENTS = 64       # per-span event cap
_MAX_INFLIGHT = 4096   # in-flight table cap (leaked spans cannot grow it)

_lock = threading.Lock()
_ring: deque = deque(maxlen=2048)
_inflight: dict[int, "Span"] = {}

# worst (slowest) finished entry per (tier, op) since the last drain —
# the timeline exemplar feed: each histogram window links its worst
# trace id so /debug/timeline rows jump straight into
# /debug/cluster/trace/<id> (bounded like _hist_children)
_exemplars: dict[tuple[str, str], tuple[float, str]] = {}
_MAX_EXEMPLARS = 512

# per-thread tier stack for the sampling profiler (stats/profiler.py):
# a sampler thread cannot read another thread's contextvar, so span
# enter/exit maintains this map — only while tracking is on (the
# profiler armed), so unprofiled processes pay a single bool check
_track_tiers = False
_thread_tiers: dict[int, list[str]] = {}

_current: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "swtpu_trace_span", default=None)

# lazily-bound prometheus histogram (+ label-children cache: .labels()
# is a lock + dict lookup in prometheus_client; spans are hot)
_hist: object = None
_hist_children: dict = {}


def init(sample: float = 1.0, slow_ms: float = 0.0,
         ring: int = 2048) -> None:
    """Wire from CLI flags: -trace.sample, -trace.slowms."""
    global _sample, _slow_ms, _ring
    _sample = sample
    _slow_ms = slow_ms
    with _lock:
        if ring != _ring.maxlen:
            _ring = deque(_ring, maxlen=max(16, ring))


def reset() -> None:
    """Drop all recorded + in-flight spans (tests)."""
    with _lock:
        _ring.clear()
        _inflight.clear()
        _exemplars.clear()


def track_thread_tiers(on: bool) -> None:
    """Arm/disarm the per-thread tier map (profiler only)."""
    global _track_tiers
    _track_tiers = on
    if not on:
        _thread_tiers.clear()


def thread_tier(tid: int) -> str:
    """The tier of the span most recently entered on thread `tid`
    (empty when the thread is not inside a traced request)."""
    st = _thread_tiers.get(tid)
    return st[-1] if st else ""


def drain_exemplars() -> "dict[str, dict]":
    """Worst finished trace per ``tier.op`` since the last drain —
    consumed by timeline.snap() so each window carries its own
    exemplars ({\"tier.op\": {\"trace\": id, \"dur_ms\": ms}})."""
    with _lock:
        if not _exemplars:
            return {}
        out = {f"{tier}.{op}": {"trace": trace,
                                "dur_ms": round(dur, 3)}
               for (tier, op), (dur, trace) in _exemplars.items()}
        _exemplars.clear()
    return out


def enabled() -> bool:
    return _sample > 0


def parse_traceparent(value: str) -> "tuple[str, str, int] | None":
    """(trace_id, parent_span_id, flags) or None when malformed."""
    parts = value.strip().split("-")
    if len(parts) < 4 or parts[0] == "ff" or len(parts[0]) != 2 \
            or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    try:
        int(parts[1], 16)
        int(parts[2], 16)
        flags = int(parts[3][:2], 16)
    except ValueError:
        return None
    return parts[1], parts[2], flags


class _NoopSpan:
    """Falsy do-nothing span: the disabled/untraced fast path."""

    __slots__ = ("status", "nbytes")
    trace = ""
    span_id = ""
    parent = ""

    def __init__(self):
        self.status = None
        self.nbytes = 0

    def __bool__(self) -> bool:
        return False

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, key, value) -> None:
        pass

    def event(self, name, **fields) -> None:
        pass

    def finish(self, status=None, nbytes=None) -> None:
        pass

    def cancel(self) -> None:
        pass

    def traceparent(self) -> str:
        return ""


_NOOP = _NoopSpan()


class Span:
    __slots__ = ("trace", "span_id", "parent", "tier", "op", "status",
                 "nbytes", "attrs", "events", "t0", "wall0", "dur",
                 "entry", "_token", "_done", "_discard")

    def __init__(self, trace: str, parent: str, tier: str, op: str,
                 entry: bool, attrs: dict | None):
        self.trace = trace
        self.span_id = "%016x" % random.getrandbits(64)
        self.parent = parent
        self.tier = tier
        self.op = op
        self.status: str | None = None
        self.nbytes = 0
        self.attrs = attrs
        self.events: list | None = None
        self.t0 = time.perf_counter()
        self.wall0 = time.time()
        self.dur = 0.0
        self.entry = entry
        self._token = None
        self._done = False
        self._discard = False
        with _lock:
            if len(_inflight) < _MAX_INFLIGHT:
                _inflight[id(self)] = self

    # -- annotation --

    def set(self, key: str, value) -> None:
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value

    def event(self, name: str, **fields) -> None:
        """Point-in-time annotation (retry attempt, replica rotation,
        Range resume, breaker rejection, ...) with a span-relative
        millisecond timestamp."""
        evs = self.events
        if evs is None:
            evs = self.events = []
        if len(evs) < _MAX_EVENTS:
            evs.append((name, (time.perf_counter() - self.t0) * 1000.0,
                        fields or None))

    def traceparent(self) -> str:
        return f"00-{self.trace}-{self.span_id}-01"

    # -- lifecycle --

    def __enter__(self) -> "Span":
        self._token = _current.set(self)
        if _track_tiers:
            _thread_tiers.setdefault(
                threading.get_ident(), []).append(self.tier)
        return self

    def __exit__(self, et, ev, tb) -> bool:
        if _track_tiers:
            st = _thread_tiers.get(threading.get_ident())
            if st:
                st.pop()
        tok, self._token = self._token, None
        if tok is not None:
            try:
                _current.reset(tok)
            except ValueError:
                # token minted in another context (generator teardown
                # from a different task): the var is per-context anyway
                pass
        if et is not None and self.status is None:
            # an explicitly-set status (e.g. "404") survives the raise
            self.status = "error"
        self.finish()
        return False

    def cancel(self) -> None:
        """Discard without recording (e.g. a fast-path request replayed
        into the full handler, which records its own span)."""
        self._discard = True
        self._done = True
        with _lock:
            _inflight.pop(id(self), None)

    def finish(self, status: str | None = None,
               nbytes: int | None = None) -> None:
        if self._done:
            return
        self._done = True
        self.dur = (time.perf_counter() - self.t0) * 1000.0
        if status is not None:
            self.status = status
        elif self.status is None:
            self.status = "ok"
        if nbytes is not None:
            self.nbytes = nbytes
        with _lock:
            _inflight.pop(id(self), None)
            if not self._discard:
                _ring.append(self)
                # exemplar feed: every (tier, op) in the histogram
                # gets a worst-trace pointer, inner hops included
                key = (self.tier, self.op)
                ex = _exemplars.get(key)
                if ex is None or self.dur > ex[0]:
                    if len(_exemplars) > _MAX_EXEMPLARS:
                        _exemplars.clear()   # cardinality bound
                    _exemplars[key] = (self.dur, self.trace)
        if self._discard:
            return
        _observe(self.tier, self.op, self.status, self.dur / 1000.0)
        if self.entry and 0 < _slow_ms <= self.dur:
            glog.warning(
                "slow request: tier=%s op=%s status=%s %.1fms bytes=%d "
                "trace=%s", self.tier, self.op, self.status, self.dur,
                self.nbytes, self.trace)


def current():
    """The active span (never None: the no-op span when untraced)."""
    sp = _current.get()
    return sp if sp is not None else _NOOP


def start(tier: str, op: str, **attrs):
    """Child span of the context's active span; no-op when untraced."""
    parent = _current.get()
    if not parent:
        return _NOOP
    return Span(parent.trace, parent.span_id, tier, op, False,
                attrs or None)


def start_root(tier: str, op: str, headers=None,
               traceparent: str | None = None, **attrs):
    """Entry span for a request arriving at a server. An incoming
    sampled `traceparent` is ALWAYS joined (the trace was started
    upstream and losing this hop would orphan the tree); requests
    without one roll the local sample rate."""
    tp = traceparent
    if tp is None and headers is not None:
        tp = headers.get(TRACE_HEADER)
    if tp:
        parsed = parse_traceparent(tp)
        if parsed is not None:
            trace, parent, flags = parsed
            if not flags & 1:
                return _NOOP
            return Span(trace, parent, tier, op, True, attrs or None)
    if _sample <= 0.0 or (_sample < 1.0 and random.random() >= _sample):
        return _NOOP
    return Span("%032x" % random.getrandbits(128), "", tier, op, True,
                attrs or None)


def inject(headers: dict, span=None) -> None:
    """Stamp the traceparent header for an outgoing hop."""
    sp = span if span is not None else _current.get()
    if sp:
        headers[TRACE_HEADER] = sp.traceparent()


# ---------------------------------------------------------------------------
# prometheus bridge


def _observe(tier: str, op: str, status: str, dur_s: float) -> None:
    global _hist
    if _hist is None:
        try:
            from ..stats import metrics
            _hist = (metrics.REQUEST_DURATION
                     if metrics.HAVE_PROMETHEUS else False)
        except ImportError:
            _hist = False
    if not _hist:
        return
    key = (tier, op, status)
    child = _hist_children.get(key)
    if child is None:
        if len(_hist_children) > 512:
            _hist_children.clear()   # runaway label cardinality bound
        child = _hist_children[key] = _hist.labels(tier, op, status)
    child.observe(dur_s)


# ---------------------------------------------------------------------------
# debug surface (/debug/traces, /debug/requests)


def _span_dict(s: Span) -> dict:
    d = {"trace": s.trace, "span": s.span_id, "parent": s.parent,
         "tier": s.tier, "op": s.op, "status": s.status,
         "start_ms": round(s.wall0 * 1000.0, 3),
         "dur_ms": round(s.dur, 3), "bytes": s.nbytes}
    if s.attrs:
        d["attrs"] = dict(s.attrs)
    if s.events:
        d["events"] = [
            {"name": name, "t_ms": round(t, 3), **(fields or {})}
            for name, t, fields in s.events]
    return d


def _trace_groups(span_dicts: list) -> list[dict]:
    """Group span dicts by trace id (deduping repeated span ids from a
    cross-worker merge), compute per-span self-time and the per-tier
    self-time rollup — the 'which tier ate the time' attribution, which
    is non-overlapping and sums to ~the wall time of the trace."""
    groups: dict[str, dict] = {}
    for d in span_dicts:
        groups.setdefault(d["trace"], {}).setdefault(d["span"], d)
    out = []
    for tid, by_id in groups.items():
        spans = sorted(by_id.values(), key=lambda d: d["start_ms"])
        child_ms: dict[str, float] = {}
        for d in spans:
            p = d.get("parent", "")
            if p in by_id:
                child_ms[p] = child_ms.get(p, 0.0) + d["dur_ms"]
        tiers: dict[str, float] = {}
        for d in spans:
            d["self_ms"] = round(
                max(0.0, d["dur_ms"] - child_ms.get(d["span"], 0.0)), 3)
            tiers[d["tier"]] = round(
                tiers.get(d["tier"], 0.0) + d["self_ms"], 3)
        out.append({
            "trace_id": tid,
            "start_ms": min(d["start_ms"] for d in spans),
            "dur_ms": max(d["dur_ms"] for d in spans),
            "tiers": tiers,
            "spans": spans,
        })
    return out


def _payload(groups: list[dict], recent: int, slowest: int) -> dict:
    # clamp: groups[-0:] would be the WHOLE list, so ?n=0 must be an
    # explicit empty slice, and negative counts must not slice oddly
    recent = max(0, recent)
    slowest = max(0, slowest)
    groups.sort(key=lambda g: g["start_ms"])
    return {
        "spans": sum(len(g["spans"]) for g in groups),
        "traces": groups[-recent:][::-1] if recent else [],
        "slowest": sorted(groups, key=lambda g: -g["dur_ms"])[:slowest],
    }


def traces_dict(recent: int = 20, slowest: int = 10) -> dict:
    """The /debug/traces JSON body for THIS process's ring."""
    with _lock:
        spans = [_span_dict(s) for s in _ring]
    return _payload(_trace_groups(spans), recent, slowest)


def merge_payloads(payloads: list[dict], recent: int = 20,
                   slowest: int = 10) -> dict:
    """Fold several workers' /debug/traces bodies into one whole-host
    view (span ids dedupe, traces regroup across process rings)."""
    spans: list[dict] = []
    for p in payloads:
        for g in list(p.get("traces", ())) + list(p.get("slowest", ())):
            spans.extend(g.get("spans", ()))
    return _payload(_trace_groups(spans), recent, slowest)


def trace_spans_dict(trace_id: str) -> dict:
    """Every span of ONE trace known to THIS process: finished spans
    from the ring plus currently in-flight sightings (marked
    ``inflight`` with their age as dur_ms) — the per-node pull the
    cluster assembler (stats/introspect.py) fans out for."""
    now = time.perf_counter()
    with _lock:
        done = [_span_dict(s) for s in _ring if s.trace == trace_id]
        live = [s for s in _inflight.values() if s.trace == trace_id]
    for s in live:
        row = {"trace": s.trace, "span": s.span_id, "parent": s.parent,
               "tier": s.tier, "op": s.op, "status": "inflight",
               "start_ms": round(s.wall0 * 1000.0, 3),
               "dur_ms": round((now - s.t0) * 1000.0, 3),
               "bytes": s.nbytes, "inflight": True}
        attrs = s.attrs
        if attrs:
            try:
                row["attrs"] = dict(attrs)
            except RuntimeError:
                # live span: its owner may insert attrs mid-copy
                pass
        done.append(row)
    done.sort(key=lambda d: (d["start_ms"], d["span"]))
    return {"trace": trace_id, "spans": done}


def merge_trace_payloads(payloads: "list[dict]") -> dict:
    """Fold several processes' ``?trace=`` pull bodies into one: span
    ids dedupe (a finished record beats an in-flight sighting of the
    same span), ordering stays deterministic for byte-identical
    re-assembly."""
    by_id: dict[str, dict] = {}
    tid = ""
    for p in payloads:
        tid = tid or p.get("trace", "")
        for d in p.get("spans", ()):
            sid = d.get("span", "")
            cur = by_id.get(sid)
            if cur is None or (cur.get("inflight")
                               and not d.get("inflight")):
                by_id[sid] = d
    spans = sorted(by_id.values(),
                   key=lambda d: (d.get("start_ms", 0), d.get("span", "")))
    return {"trace": tid, "spans": spans}


def requests_dict() -> dict:
    """The /debug/requests JSON body: currently in-flight spans with
    their age — the wedged-request detector."""
    now = time.perf_counter()
    with _lock:
        live = list(_inflight.values())
    rows = []
    for s in live:
        row = {"trace": s.trace, "span": s.span_id, "parent": s.parent,
               "tier": s.tier, "op": s.op,
               "age_ms": round((now - s.t0) * 1000.0, 3),
               "start_ms": round(s.wall0 * 1000.0, 3)}
        attrs = s.attrs
        if attrs:
            try:
                row["attrs"] = dict(attrs)
            except RuntimeError:
                # the span is LIVE: its owner (possibly an executor
                # thread) may insert attrs mid-copy — skip them rather
                # than 500 the debug endpoint under load
                pass
        rows.append(row)
    rows.sort(key=lambda r: -r["age_ms"])
    return {"inflight": len(rows), "requests": rows}


MAX_QUERY_COUNT = 1000   # ?n=/?slowest= ceiling: the ring itself is
#                          bounded, but a huge count would still be
#                          interpolated into sibling fan-out URLs and
#                          serialized into one giant JSON body


def clamp_count(n: int, cap: int = MAX_QUERY_COUNT) -> int:
    """Clamp a user-supplied result count into [0, cap]: ?n=-5 must be
    an explicit empty slice (never a from-the-end slice) and ?n=10**9
    must not balloon the payload."""
    return max(0, min(int(n), cap))


def traces_query(query) -> dict:
    """traces_dict driven by a ?n=&slowest= query mapping — the one
    parser shared by every server's /debug/traces handler (raises
    ValueError on malformed counts; negative/huge counts clamped).
    ``?trace=<id>`` switches to the single-trace span pull instead —
    the hook cluster assembly fans out over."""
    tid = str(query.get("trace", "") or "").strip()
    if tid:
        return trace_spans_dict(tid[:64])
    return traces_dict(recent=clamp_count(query.get("n", 20)),
                       slowest=clamp_count(query.get("slowest", 10)))


def debug_handlers():
    """(h_traces, h_requests) aiohttp handlers over THIS process's
    ring — the one implementation every non-worker-aggregating server
    (filer, S3, WebDAV) registers, so the debug contract cannot drift
    between surfaces."""
    from aiohttp import web

    async def h_traces(req):
        try:
            return web.json_response(traces_query(req.query))
        except ValueError:
            return web.json_response({"error": "bad n/slowest"},
                                     status=400)

    async def h_requests(req):
        return web.json_response(requests_dict())

    return h_traces, h_requests


async def run_in_executor(fn, *args):
    """run_in_executor that carries the tracing context into the
    worker thread (asyncio does NOT propagate contextvars there), so
    store/EC spans parent under the request span; pays the context
    copy only while a trace is active."""
    import asyncio
    loop = asyncio.get_running_loop()
    if _current.get():
        ctx = contextvars.copy_context()
        return await loop.run_in_executor(None,
                                          lambda: ctx.run(fn, *args))
    return await loop.run_in_executor(None, lambda: fn(*args))
