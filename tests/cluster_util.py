"""In-proc fake cluster for integration tests (the multi-process-in-one-
binary harness the reference lacks — SURVEY.md §4 implication)."""

from __future__ import annotations

import asyncio
import contextlib
import os

import aiohttp

from seaweedfs_tpu.filer.filer import Filer
from seaweedfs_tpu.master.server import MasterServer
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.storage.store import Store


class Cluster:
    """Master + N volume servers in one event loop on ephemeral ports."""

    def __init__(self, tmpdir: str, n_servers: int = 3,
                 racks: list[tuple[str, str]] | None = None,
                 pulse: float = 0.2, max_volumes: int = 16,
                 ec_large_block: int = 16 * 1024,
                 ec_small_block: int = 1024,
                 master_kwargs: dict | None = None):
        self.tmpdir = tmpdir
        self.n = n_servers
        self.racks = racks or [("dc1", "rack1")] * n_servers
        self.pulse = pulse
        self.max_volumes = max_volumes
        self.ec_large_block = ec_large_block
        self.ec_small_block = ec_small_block
        self.master: MasterServer | None = None
        self.servers: list[VolumeServer] = []
        self.filer: FilerServer | None = None
        self.http: aiohttp.ClientSession | None = None
        self.with_filer = False
        self.filer_chunk_size = 256 * 1024
        self.master_kwargs = master_kwargs or {}

    async def __aenter__(self) -> "Cluster":
        self.master = MasterServer(port=0, pulse_seconds=self.pulse,
                                   volume_size_limit_mb=64,
                                   **self.master_kwargs)
        await self.master.start()
        for i in range(self.n):
            d = os.path.join(self.tmpdir, f"srv{i}")
            store = Store([d], max_volume_counts=[self.max_volumes],
                          ec_large_block=self.ec_large_block,
                          ec_small_block=self.ec_small_block)
            dc, rack = self.racks[i]
            vs = VolumeServer(store, self.master.url, port=0,
                              data_center=dc, rack=rack,
                              pulse_seconds=self.pulse)
            await vs.start()
            await vs.heartbeat_once()
            self.servers.append(vs)
        if self.with_filer:
            self.filer = FilerServer(Filer("memory"), self.master.url,
                                     port=0,
                                     chunk_size=self.filer_chunk_size)
            await self.filer.start()
        self.http = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=30))
        return self

    async def __aexit__(self, *exc) -> None:
        if self.http:
            await self.http.close()
        if self.filer:
            with contextlib.suppress(Exception):
                await self.filer.stop()
        for vs in self.servers:
            with contextlib.suppress(Exception):
                await vs.stop()
        with contextlib.suppress(Exception):
            await self.master.stop()

    # -- client helpers --

    async def assign(self, **params) -> dict:
        async with self.http.get(
                f"http://{self.master.url}/dir/assign",
                params=params) as resp:
            return await resp.json()

    async def put(self, fid: str, url: str, data: bytes,
                  **params) -> tuple[int, dict]:
        async with self.http.post(f"http://{url}/{fid}", data=data,
                                  params=params) as resp:
            return resp.status, await resp.json()

    async def get(self, fid: str, url: str) -> tuple[int, bytes]:
        async with self.http.get(f"http://{url}/{fid}",
                                 allow_redirects=True) as resp:
            return resp.status, await resp.read()

    async def delete(self, fid: str, url: str) -> int:
        async with self.http.delete(f"http://{url}/{fid}") as resp:
            return resp.status

    async def heartbeat_all(self) -> None:
        for vs in self.servers:
            await vs.heartbeat_once()


def run(coro):
    return asyncio.run(coro)
