"""Test harness configuration.

Forces an 8-device virtual CPU platform so multi-chip sharding
(jax.sharding.Mesh + shard_map) is exercised without TPU hardware, mirroring
how the driver dry-runs `__graft_entry__.dryrun_multichip`.

Must run before jax is imported anywhere in the test process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

# The axon sitecustomize force-sets JAX_PLATFORMS=axon (real TPU tunnel);
# override via config so tests run on the 8-device virtual CPU platform.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running soak/integration tests excluded "
        "from the tier-1 run (-m 'not slow')")


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs[:8]
