"""Autopilot maintenance plane: planner purity/determinism, executor
pacing + pause-on-page + leadership discipline, the rebuild-to-target
admin route, and live observe->plan->execute convergence on the
in-proc cluster (lost shard AND scrub-localized rot)."""

import asyncio
import copy
import os
import random

import pytest

from cluster_util import Cluster, run

from seaweedfs_tpu.autopilot import (Action, ClusterSnapshot,
                                     CorruptionReport, EcVolumeState,
                                     NodeState, PlannerConfig,
                                     VolumeState, plan)
from seaweedfs_tpu.autopilot.execute import ActionError, Executor
from seaweedfs_tpu.ec import gf
from seaweedfs_tpu.shell import ec_commands as ec
from seaweedfs_tpu.shell.env import CommandEnv
from seaweedfs_tpu.topology.layout import rank_repair_targets


# ---------------------------------------------------------------------------
# planner: pure + deterministic
# ---------------------------------------------------------------------------


def _random_snapshot(rng: random.Random) -> ClusterSnapshot:
    n_nodes = rng.randint(1, 8)
    nodes = tuple(NodeState(
        url=f"10.0.0.{i}:80{i}", data_center=f"dc{rng.randint(0, 2)}",
        rack=f"r{rng.randint(0, 3)}", free_slots=rng.randint(0, 10))
        for i in range(n_nodes))
    urls = [n.url for n in nodes]
    volumes = []
    for vid in range(1, rng.randint(1, 6)):
        holders = tuple(sorted(rng.sample(
            urls, rng.randint(1, len(urls)))))
        size = rng.randint(0, 1 << 20)
        volumes.append(VolumeState(
            vid=vid, collection=rng.choice(("", "c")), size=size,
            deleted_bytes=rng.randint(0, size) if size else 0,
            read_only=rng.random() < 0.3, remote=rng.random() < 0.2,
            replica_count=rng.randint(1, 3), holders=holders))
    ec_volumes = []
    corruptions = []
    for vid in range(100, 100 + rng.randint(0, 4)):
        shards = []
        for sid in range(gf.TOTAL_SHARDS):
            if rng.random() < 0.85:
                shards.append((sid, (rng.choice(urls),)))
        if shards:
            ec_volumes.append(EcVolumeState(
                vid=vid, collection="", shards=tuple(shards)))
        if rng.random() < 0.4:
            corruptions.append(CorruptionReport(
                vid=vid, offset=rng.randrange(0, 4) << 20, size=1 << 20,
                shards=(rng.randrange(gf.TOTAL_SHARDS),)
                if rng.random() < 0.7 else ()))
    return ClusterSnapshot(
        nodes=nodes, volumes=tuple(volumes),
        ec_volumes=tuple(ec_volumes), corruptions=tuple(corruptions),
        volume_size_limit=8 << 20, paging=rng.random() < 0.1)


def test_planner_deterministic_property():
    """Identical snapshots -> identical ordered plans, and planning
    mutates nothing — over 60 randomized cluster states."""
    cfg = PlannerConfig(tier_backend="mmap.hot")
    for seed in range(60):
        snap = _random_snapshot(random.Random(seed))
        before = copy.deepcopy(snap)
        a1, d1 = plan(snap, cfg)
        a2, d2 = plan(snap, cfg)
        assert a1 == a2 and d1 == d2, f"seed {seed} not deterministic"
        assert snap == before, f"seed {seed} mutated its snapshot"
        # plans are in execution order: priorities never decrease
        prios = [a.priority for a in a1]
        assert prios == sorted(prios), f"seed {seed} order broken"


def test_planner_input_order_independent():
    """The same cluster state presented with shuffled tuple orderings
    must plan identically (canonicalization lives in the planner)."""
    rng = random.Random(7)
    snap = _random_snapshot(rng)
    shuffled = ClusterSnapshot(
        nodes=tuple(reversed(snap.nodes)),
        volumes=tuple(reversed(snap.volumes)),
        ec_volumes=tuple(reversed(snap.ec_volumes)),
        corruptions=tuple(reversed(snap.corruptions)),
        volume_size_limit=snap.volume_size_limit,
        paging=snap.paging)
    cfg = PlannerConfig()
    assert plan(snap, cfg) == plan(shuffled, cfg)


def _nodes(n=4, racks=2):
    return tuple(NodeState(url=f"h{i}:80", data_center="dc",
                           rack=f"r{i % racks}", free_slots=5)
                 for i in range(n))


def test_single_missing_shard_outranks_everything():
    ecv = EcVolumeState(vid=5, shards=tuple(
        (sid, (f"h{sid % 3}:80",)) for sid in range(13)))
    vol = VolumeState(vid=1, size=100, deleted_bytes=90,
                      replica_count=2, holders=("h0:80",))
    snap = ClusterSnapshot(nodes=_nodes(), volumes=(vol,),
                           ec_volumes=(ecv,), volume_size_limit=8 << 20)
    actions, _ = plan(snap, PlannerConfig())
    assert actions[0].kind == "rebuild_shard"
    assert actions[0].shards == (13,)
    assert actions[0].priority == 0
    # target is the node holding NO shard of this volume (h3)
    assert actions[0].target == "h3:80"
    # gather map carries exactly the clean survivors
    assert len(actions[0].sources) == 13


def test_rotten_shard_rebuilds_in_place():
    ecv = EcVolumeState(vid=5, shards=tuple(
        (sid, (f"h{sid % 4}:80",)) for sid in range(14)))
    snap = ClusterSnapshot(
        nodes=_nodes(), ec_volumes=(ecv,),
        corruptions=(CorruptionReport(vid=5, offset=0, size=1 << 20,
                                      shards=(12,)),),
        volume_size_limit=8 << 20)
    actions, defer = plan(snap, PlannerConfig())
    assert len(actions) == 1 and not defer
    a = actions[0]
    assert a.kind == "rebuild_shard" and a.shards == (12,)
    assert a.target == "h0:80"      # shard 12's current holder
    # the rotten shard is NOT in the gather sources
    assert all(sid != 12 for sid, _ in a.sources)


def test_unlocalized_corruption_defers():
    ecv = EcVolumeState(vid=5, shards=tuple(
        (sid, ("h0:80",)) for sid in range(14)))
    snap = ClusterSnapshot(
        nodes=_nodes(), ec_volumes=(ecv,),
        corruptions=(CorruptionReport(vid=5, shards=()),),
        volume_size_limit=8 << 20)
    actions, defer = plan(snap, PlannerConfig())
    assert not actions
    assert any(d.reason == "corruption-unlocalized" for d in defer)


def test_unlocalized_window_poisons_all_rebuilds_for_the_vid():
    """Review regression: a vid with one LOCALIZED rotten shard AND one
    ambiguous window must defer everything — a rebuild of the
    localized shard would regenerate from survivors the ambiguous
    window says may be rotten, overwriting good bytes with garbage."""
    ecv = EcVolumeState(vid=5, shards=tuple(
        (sid, (f"h{sid % 4}:80",)) for sid in range(13)))  # 13 missing
    snap = ClusterSnapshot(
        nodes=_nodes(), ec_volumes=(ecv,),
        corruptions=(
            CorruptionReport(vid=5, offset=0, size=1 << 20,
                             shards=(12,)),
            CorruptionReport(vid=5, offset=1 << 20, size=1 << 20,
                             shards=()),
        ),
        volume_size_limit=8 << 20)
    actions, defer = plan(snap, PlannerConfig())
    assert not actions
    assert any(d.reason == "corruption-unlocalized" and d.vid == 5
               for d in defer)


def test_multi_holder_rotten_shard_defers():
    """Review regression: rot localized to a shard held by TWO nodes
    must defer — the report cannot say which copy is rotten, and
    regenerating the clean one would leave the rot serving forever."""
    shards = [(sid, (f"h{sid % 4}:80",)) for sid in range(14)]
    shards[12] = (12, ("h0:80", "h1:80"))
    snap = ClusterSnapshot(
        nodes=_nodes(), ec_volumes=(EcVolumeState(
            vid=5, shards=tuple(shards)),),
        corruptions=(CorruptionReport(vid=5, offset=0, size=1 << 20,
                                      shards=(12,)),),
        volume_size_limit=8 << 20)
    actions, defer = plan(snap, PlannerConfig())
    assert not actions
    assert [d.reason for d in defer] == ["rot-multi-holder"]


def test_unrepairable_defers():
    ecv = EcVolumeState(vid=5, shards=tuple(
        (sid, ("h0:80",)) for sid in range(9)))   # < k survivors
    snap = ClusterSnapshot(nodes=_nodes(), ec_volumes=(ecv,),
                           volume_size_limit=8 << 20)
    actions, defer = plan(snap, PlannerConfig())
    assert not actions
    assert [d.reason for d in defer] == ["unrepairable"]


def test_multi_missing_spreads_targets():
    """Four lost shards must not all land on one rebuild target."""
    ecv = EcVolumeState(vid=5, shards=tuple(
        (sid, (f"h{sid % 2}:80",)) for sid in range(10)))
    snap = ClusterSnapshot(nodes=_nodes(n=6, racks=3),
                           ec_volumes=(ecv,),
                           volume_size_limit=8 << 20)
    actions, _ = plan(snap, PlannerConfig())
    rebuilds = [a for a in actions if a.kind == "rebuild_shard"]
    covered = sorted(s for a in rebuilds for s in a.shards)
    assert covered == [10, 11, 12, 13]
    assert len({a.target for a in rebuilds}) > 1
    assert all(a.priority == 1 for a in rebuilds)


def test_replicate_vacuum_tier_and_remote_skip():
    nodes = _nodes()
    vols = (
        VolumeState(vid=1, size=100, replica_count=2,
                    holders=("h0:80",)),                 # under-replicated
        VolumeState(vid=2, size=100, deleted_bytes=40,
                    holders=("h1:80",)),                 # dirty
        VolumeState(vid=3, size=100, read_only=True,
                    holders=("h2:80",)),                 # sealed -> tier
        VolumeState(vid=4, size=100, read_only=True, remote=True,
                    holders=("h3:80",)),                 # already tiered
    )
    snap = ClusterSnapshot(nodes=nodes, volumes=vols,
                           volume_size_limit=8 << 20)
    actions, _ = plan(snap, PlannerConfig(garbage_threshold=0.3,
                                          tier_backend="mmap.hot"))
    kinds = [(a.kind, a.vid) for a in actions]
    assert kinds == [("replicate_volume", 1), ("vacuum_volume", 2),
                     ("tier_seal", 3)]
    rep = actions[0]
    assert rep.target != "h0:80" and rep.holders == ("h0:80",)
    # no tier backend configured -> no tier action at all
    a2, _ = plan(snap, PlannerConfig())
    assert all(a.kind != "tier_seal" for a in a2)


def test_rank_repair_targets_rack_aware():
    nodes = [NodeState(url=f"h{i}:80", data_center="dc",
                       rack="r0" if i < 2 else "r1", free_slots=5 - i)
             for i in range(4)]
    # holders both in r0 -> r1 nodes must rank first
    ranked = rank_repair_targets(nodes, {"h0:80", "h1:80"})
    assert ranked[0].startswith("h2") or ranked[0].startswith("h3")
    assert set(ranked) == {"h2:80", "h3:80"}
    # full nodes are excluded
    nodes2 = [NodeState(url="a:1", rack="r0", free_slots=0),
              NodeState(url="b:1", rack="r1", free_slots=1)]
    assert rank_repair_targets(nodes2, set()) == ["b:1"]


# ---------------------------------------------------------------------------
# executor: dry-run ledger, pacing, pause, leadership, fallback targets
# ---------------------------------------------------------------------------


def _sample_actions():
    return [
        Action(kind="rebuild_shard", vid=7, priority=0, shards=(3,),
               target="t1:80", targets=("t1:80", "t2:80"),
               sources=((0, "s0:80"),), bytes_est=1000),
        Action(kind="vacuum_volume", vid=2, priority=3,
               holders=("h0:80", "h1:80"), bytes_est=500),
        Action(kind="tier_seal", vid=3, priority=4, target="mmap.hot",
               holders=("h0:80",), bytes_est=200),
    ]


def test_dryrun_ledger_matches_live_execution():
    """-autopilot.dryrun emits the EXACT action list live mode
    executes: same actions, same order — only nothing is sent."""
    async def body():
        calls = []

        async def recorder(url, path, params, timeout_s=60.0):
            calls.append((url, path, params.get("volume")))
            return {"ok": True}

        actions = _sample_actions()
        live = Executor(recorder, mbps=0, concurrency=1)
        live_results = await live.execute(actions)
        dry = Executor(recorder, mbps=0, concurrency=1, dryrun=True)
        n_calls = len(calls)
        dry_results = await dry.execute(actions)
        assert len(calls) == n_calls          # dry-run sent NOTHING
        assert [r["action"] for r in dry_results] == \
               [r["action"] for r in live_results]
        assert all(r["status"] == "dryrun" for r in dry_results)
        assert all(r["status"] == "ok" for r in live_results)
        # live dispatches hit the right routes
        assert ("t1:80", "/admin/ec/rebuild_shard", "7") in calls
        assert ("h0:80", "/admin/tier/upload", "3") in calls
        assert ("h1:80", "/admin/vacuum/commit", "2") in calls
    run(body())


def test_executor_falls_back_to_next_target():
    async def body():
        calls = []

        async def flaky(url, path, params, timeout_s=60.0):
            calls.append(url)
            if url == "t1:80":
                raise ActionError("partition mismatch")
            return {"ok": True}

        ex = Executor(flaky, mbps=0, concurrency=1)
        [res] = await ex.execute([_sample_actions()[0]])
        assert res["status"] == "ok"
        assert res["target"] == "t2:80"
        assert calls == ["t1:80", "t2:80"]
    run(body())


def test_executor_pays_token_bucket():
    """Every action's bytes are paid BEFORE dispatch: at 1 MB/s, 3 MB
    of estimated repair must accumulate ~2 s of pacing sleep (burst
    covers the first MB)."""
    async def body():
        slept = []

        async def fake_sleep(s):
            slept.append(s)

        async def ok(url, path, params, timeout_s=60.0):
            return {"ok": True}

        ex = Executor(ok, mbps=1.0, concurrency=1, sleep=fake_sleep)
        actions = [Action(kind="tier_seal", vid=i, priority=4,
                          target="b", holders=("h:1",),
                          bytes_est=1 << 20) for i in range(3)]
        await ex.execute(actions)
        assert ex.bytes_paid == 3 << 20
        # injected sleep never advances the clock, so the deficit
        # accumulates: >= (bytes - burst) / rate of pacing sleep
        assert 1.5 <= ex.paced_sleep_s <= 3.5, ex.paced_sleep_s
        assert slept, "bucket never slept"
    run(body())


def test_executor_pauses_on_page_and_defers_when_stuck():
    async def body():
        state = {"paging": True, "polls": 0}

        async def paging():
            state["polls"] += 1
            if state["polls"] > 3:
                state["paging"] = False
            return state["paging"]

        async def ok(url, path, params, timeout_s=60.0):
            return {"ok": True}

        async def fake_sleep(s):
            pass

        ex = Executor(ok, mbps=0, concurrency=1, paging=paging,
                      sleep=fake_sleep)
        [res] = await ex.execute([_sample_actions()[2]])
        assert res["status"] == "ok"          # ran after the page cleared
        assert ex.paused_s > 0

        # paging forever -> the cycle defers instead of wedging
        async def always(): return True
        ex2 = Executor(ok, mbps=0, concurrency=1, paging=always,
                       sleep=fake_sleep, pause_max_s=0.0)
        [r2] = await ex2.execute([_sample_actions()[2]])
        assert r2["status"] == "deferred"
    run(body())


def test_executor_halts_on_leadership_loss():
    async def body():
        state = {"n": 0}

        def leader():
            state["n"] += 1
            # the executor consults leadership around the pause gate
            # (twice per action): depose after the first action's pair
            return state["n"] <= 2

        async def ok(url, path, params, timeout_s=60.0):
            return {"ok": True}

        ex = Executor(ok, mbps=0, concurrency=1, is_leader=leader)
        results = await ex.execute(_sample_actions())
        statuses = [r["status"] for r in results]
        assert statuses[0] == "ok"
        assert set(statuses[1:]) == {"halted"}
    run(body())


def test_cycle_halts_when_deposed_between_plan_and_execute():
    """HA quorum discipline: a leader deposed mid-cycle (after its
    observation, before execution) must execute NOTHING — the planned
    actions were derived from a leadership that no longer exists, and
    the successor's autopilot owns the cluster from ITS observation."""
    async def body():
        from seaweedfs_tpu.autopilot.controller import Autopilot

        state = {"leader": True}

        class FakeMaster:
            @property
            def is_leader(self):
                return state["leader"]

        ap = Autopilot(FakeMaster())
        dispatched = []

        async def fake_snapshot():
            return ClusterSnapshot(), []
        ap.observer.snapshot = fake_snapshot

        def fake_plan(snap, cfg):
            # deposition lands exactly between plan and execute
            state["leader"] = False
            return [Action(kind="vacuum_volume", vid=1,
                           holders=("10.0.0.1:801",))], []
        import seaweedfs_tpu.autopilot.controller as ctl
        orig_plan = ctl.plan
        ctl.plan = fake_plan

        async def spy_post(url, path, params, timeout_s=60.0):
            dispatched.append((url, path))
            return {"ok": True}
        ap.executor.node_post = spy_post
        try:
            report = await ap.run_cycle()
        finally:
            ctl.plan = orig_plan
        assert report["halted"] == "lost leadership"
        assert report["executed"] == []
        assert len(report["planned"]) == 1   # the plan WAS made...
        assert dispatched == []              # ...and nothing ran
        assert ap.state == "follower"
        assert ap.actions_ok == 0 and ap.actions_failed == 0
    run(body())


# ---------------------------------------------------------------------------
# live cluster: the rebuild-to-target route + full heal cycles
# ---------------------------------------------------------------------------


async def _encode_one_volume(c: Cluster, n_files: int = 30):
    rng = random.Random(11)
    files = []
    for _ in range(n_files):
        a = await c.assign(collection="ap")
        data = bytes(rng.getrandbits(8)
                     for _ in range(rng.randint(500, 6000)))
        st, _ = await c.put(a["fid"], a["url"], data)
        assert st == 201
        files.append((a["fid"], a["publicUrl"], data))
    await c.heartbeat_all()
    async with CommandEnv(c.master.url, c.http) as env:
        vids = sorted({int(f.split(",")[0]) for f, _, _ in files})
        await ec.ec_encode(env, collection="ap", vids=vids)
    return files, vids


def test_rebuild_shard_route_and_heal_cycle(tmp_path):
    """Kill one holder's shards on disk; one forced autopilot cycle
    must re-host them on live nodes via /admin/ec/rebuild_shard, after
    which reads verify and the registry is whole again."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=4) as c:
            files, vids = await _encode_one_volume(c)
            vid = vids[0]
            async with CommandEnv(c.master.url, c.http) as env:
                smap = await ec.ec_shard_map(env)
            victim_url = smap[vid]["shards"][0][0]
            victim = next(v for v in c.servers if v.url == victim_url)
            lost = sorted(victim.store.ec_volumes[vid].shards)
            # the holder DIES (shards with it) — the autopilot must
            # re-host its shards on the surviving nodes
            c.servers.remove(victim)
            await victim.stop()
            # outlive the liveness window so the observer sees 3 nodes
            await asyncio.sleep(3 * c.pulse + 0.3)
            await c.heartbeat_all()

            report = await c.master.autopilot.run_cycle()
            planned = report["planned"]
            assert planned, report
            assert all(a["kind"] == "rebuild_shard" for a in planned)
            covered = sorted(s for a in planned for s in a["shards"])
            assert covered == lost
            # executed ledger rides the same cycle report, in order
            assert [r["action"] for r in report["executed"]] == planned
            assert all(r["status"] == "ok"
                       for r in report["executed"]), report["executed"]

            await c.heartbeat_all()
            async with CommandEnv(c.master.url, c.http) as env:
                smap = await ec.ec_shard_map(env)
            assert len(smap[vid]["shards"]) == gf.TOTAL_SHARDS
            # rebuilt shards live on surviving nodes, never the victim
            for sid in lost:
                assert victim_url not in smap[vid]["shards"][sid]
            for fid, url, data in files[:8]:
                server = next(s for s in c.servers
                              if s.url != victim_url)
                st, got = await c.get(fid, server.url)
                assert st == 200 and got == data, fid

            # convergence: the NEXT cycle observes a whole cluster and
            # plans nothing (modulo cooldown, which also plans nothing)
            report2 = await c.master.autopilot.run_cycle()
            assert report2["planned"] == [], report2["planned"]
    run(body())


def test_heal_rotten_shard_localized_by_scrub(tmp_path):
    """Plant real on-disk rot in one parity shard; a scrub cycle must
    LOCALIZE it (reported_windows carries the shard id), and the next
    autopilot cycle must rebuild that shard in place — after which a
    fresh scrub reports the volume clean."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=3) as c:
            files, vids = await _encode_one_volume(c, n_files=20)
            vid = vids[0]
            # find the holder of parity shard 12 and flip a byte
            import seaweedfs_tpu.ec.pipeline as pl
            holder = next(v for v in c.servers
                          if 12 in v.store.ec_volumes.get(
                              vid, type("e", (), {"shards": {}})()).shards)
            path = holder._base_name(vid, "ap") + pl.to_ext(12)

            def flip():
                with open(path, "r+b") as f:
                    f.seek(100)
                    b = f.read(1)
                    f.seek(100)
                    f.write(bytes([b[0] ^ 0xFF]))
            await asyncio.get_running_loop().run_in_executor(None, flip)

            # scrub runs on the shard-0 holder (ownership rule)
            owner = next(v for v in c.servers
                         if 0 in v.store.ec_volumes[vid].shards)
            rep = await owner.scrubber.run_cycle()
            assert rep["corrupt"] >= 1, rep
            rows = [w for w in rep["corrupt_windows"]
                    if w["volume"] == vid]
            assert rows and rows[0]["shards"] == [12], rows
            st = owner.scrubber.status()
            assert st["reported_windows"], "structured ring empty"
            for key in ("volume", "window", "offset", "size",
                        "shards", "wall"):
                assert key in st["reported_windows"][0], key

            report = await c.master.autopilot.run_cycle()
            acts = [a for a in report["planned"]
                    if a["kind"] == "rebuild_shard" and a["vid"] == vid]
            assert acts and acts[0]["shards"] == [12], report["planned"]
            assert acts[0]["target"] == holder.url  # in-place repair
            assert all(r["status"] == "ok"
                       for r in report["executed"]), report["executed"]

            rep2 = await owner.scrubber.run_cycle()
            mine = [w for w in rep2["corrupt_windows"]
                    if w["volume"] == vid]
            assert not mine, rep2
            for fid, url, data in files[:5]:
                st_, got = await c.get(fid, url)
                assert st_ == 200 and got == data, fid
    run(body())


def test_debug_autopilot_surface(tmp_path):
    """GET /debug/autopilot schema + POST ?run=1 forced dry-run cycle."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            c.master.autopilot.dryrun = True
            c.master.autopilot.executor.dryrun = True
            async with c.http.get(
                    f"http://{c.master.url}/debug/autopilot") as r:
                body_ = await r.json()
                assert r.status == 200
            ap = body_["autopilot"]
            for key in ("enabled", "leader", "dryrun", "state",
                        "cycles", "budget_mbps", "actions_ok",
                        "in_flight", "history", "last_cycle"):
                assert key in ap, key
            assert ap["enabled"] is False     # loop off by default
            async with c.http.post(
                    f"http://{c.master.url}/debug/autopilot",
                    params={"run": "1"}) as r:
                forced = await r.json()
                assert r.status == 200, forced
            for key in ("planned", "deferred", "executed", "observed",
                        "dryrun"):
                assert key in forced["cycle"], key
            assert forced["status"]["cycles"] == 1
    run(body())


def test_rebuild_shard_failed_gather_keeps_rotten_copy(tmp_path):
    """Review regression: /admin/ec/rebuild_shard must confirm k clean
    inputs on local disk BEFORE destroying a local (rotten) copy of a
    requested shard — a failed gather answers 409 with the
    mostly-good shard still mounted and its file intact, never
    converting one corrupt window into a lost shard."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=3) as c:
            _files, vids = await _encode_one_volume(c, n_files=15)
            vid = vids[0]
            holder = c.servers[0]
            local = sorted(holder.store.ec_volumes[vid].shards)
            assert len(local) < gf.DATA_SHARDS  # spread over 3 nodes
            sid = local[0]
            import seaweedfs_tpu.ec.pipeline as pl
            path = holder._base_name(vid, "ap") + pl.to_ext(sid)
            # every remote source is unreachable: the gather cannot
            # reach k inputs (local survivors alone are < 10)
            sources = ",".join(
                f"{s}:127.0.0.1:1" for s in range(gf.TOTAL_SHARDS)
                if s != sid)
            async with c.http.post(
                    f"http://{holder.url}/admin/ec/rebuild_shard",
                    params={"volume": str(vid), "collection": "ap",
                            "shards": str(sid),
                            "sources": sources}) as resp:
                body_ = await resp.json()
                assert resp.status == 409, body_
            assert sid in holder.store.ec_volumes[vid].shards
            assert os.path.exists(path)
    run(body())


def test_no_holder_map_triggers_rate_bounded_reresolve(tmp_path):
    """Heal-soak regression: a shard-location map cached while a shard
    had NO holders (the outage window) used to be served for the full
    7-minute TTL with no invalidation — hiding the shard the autopilot
    had long since re-hosted. A fetch that finds no listed holder must
    now schedule the (rate-bounded) re-resolve, single and batched."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            vs = c.servers[0]
            calls = []

            class StubLocations:
                def get(self, vid):
                    return {"1": ["somewhere:1"]}   # nothing for sid 0

                def invalidate(self, vid):
                    calls.append(vid)

            vs._ec_locations = StubLocations()
            loop = asyncio.get_running_loop()
            out = await loop.run_in_executor(
                None, vs._sync_fetch_remote_shard, 9, 0, 0, 1)
            assert out is None
            assert calls == [9]
            out = await loop.run_in_executor(
                None, vs._sync_fetch_remote_shard_batch, 9, [(0, 0, 1)])
            assert out is None
            assert calls == [9, 9]
    run(body())


def test_unknown_action_kind_errors():
    async def body():
        async def ok(url, path, params, timeout_s=60.0):
            return {"ok": True}
        ex = Executor(ok, mbps=0)
        [res] = await ex.execute([Action(kind="nope", vid=1)])
        assert res["status"] == "error"
    run(body())
