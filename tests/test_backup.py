"""Incremental volume backup / tail sync.

Reference: weed/storage/volume_backup.go (BinarySearchByAppendAtNs,
IncrementalBackup), weed/command/backup.go, VolumeTailSender/Receiver.
"""

import asyncio

from cluster_util import Cluster, run

from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage import volume_backup as vb
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume


def _write(v: Volume, key: int, data: bytes, cookie: int = 0x42) -> None:
    v.write_needle(Needle(cookie=cookie, id=key, data=data))


def test_binary_search_by_append_at_ns(tmp_path):
    v = Volume(str(tmp_path), "", 1)
    for i in range(1, 21):
        _write(v, i, b"x" * i)
    # remember the watermark halfway
    mid_ts = v.last_append_at_ns
    for i in range(21, 31):
        _write(v, i, b"y" * i)
    off = vb.binary_search_by_append_at_ns(v, mid_ts)
    assert off is not None
    tail = list(vb.tail_needles(v, mid_ts))
    assert [n.id for n in tail] == list(range(21, 31))
    # nothing newer than the final watermark
    assert vb.binary_search_by_append_at_ns(v, v.last_append_at_ns) is None
    assert list(vb.tail_needles(v, v.last_append_at_ns)) == []
    # everything from 0
    assert len(list(vb.tail_needles(v, 0))) == 30
    v.close()


def test_tail_includes_tombstones_and_apply(tmp_path):
    src = Volume(str(tmp_path / "src"), "", 1)
    for i in range(1, 6):
        _write(src, i, f"data{i}".encode())
    ts = src.last_append_at_ns

    dst = Volume(str(tmp_path / "dst"), "", 1)
    for n, is_del in vb.tail_records(src, 0):
        vb.apply_needle(dst, n, is_del)
    assert dst.read_needle(3).data == b"data3"

    # overwrite + delete on source, incremental replay
    _write(src, 2, b"data2-v2")
    src.delete_needle(Needle(cookie=0x42, id=4))
    for n, is_del in vb.tail_records(src, ts):
        vb.apply_needle(dst, n, is_del)
    assert dst.read_needle(2).data == b"data2-v2"
    import pytest
    from seaweedfs_tpu.storage.volume import AlreadyDeleted
    with pytest.raises(AlreadyDeleted):
        dst.read_needle(4)
    # watermarks converge
    assert dst.last_append_at_ns == src.last_append_at_ns
    src.close()
    dst.close()


def test_zero_byte_write_is_not_a_delete(tmp_path):
    """A legitimate empty-file write must not replicate as a tombstone;
    the tail frame carries an explicit delete flag (reference tail RPC
    semantics)."""
    src = Volume(str(tmp_path / "src"), "", 1)
    _write(src, 1, b"")          # zero-byte file
    _write(src, 2, b"real")
    src.delete_needle(Needle(cookie=0x42, id=2))
    recs = list(vb.tail_records(src, 0))
    flags = {n.id: is_del for n, is_del in recs}
    assert flags[1] is False
    assert [is_del for n, is_del in recs if n.id == 2] == [False, True]
    # wire round-trip preserves the flag
    wire = b"".join(vb.frame_needle(n, d) for n, d in recs)
    decoded = list(vb.iter_frames([wire]))
    assert [(n.id, d) for n, d in decoded] == [(n.id, d) for n, d in recs]
    dst = Volume(str(tmp_path / "dst"), "", 1)
    for n, d in decoded:
        vb.apply_needle(dst, n, d)
    assert dst.read_needle(1).data == b""
    src.close()
    dst.close()


def test_watermark_survives_reopen(tmp_path):
    v = Volume(str(tmp_path), "", 7)
    _write(v, 1, b"hello")
    ts = v.last_append_at_ns
    assert ts > 0
    v.close()
    v2 = Volume(str(tmp_path), "", 7, create_if_missing=False)
    assert v2.last_append_at_ns == ts
    v2.close()


def test_server_tail_and_receive(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=2) as c:
            a = await c.assign()
            st, _ = await c.put(a["fid"], a["url"], b"needle one")
            assert st == 201
            vid = int(a["fid"].split(",")[0])
            src = next(vs for vs in c.servers
                       if vs.store.has_volume(vid))
            dst = next(vs for vs in c.servers if vs is not src)
            # allocate an empty copy of the volume on dst
            async with c.http.post(
                    f"http://{dst.url}/admin/volume/allocate",
                    params={"volume": str(vid)}) as resp:
                assert resp.status == 200
            # status endpoint
            async with c.http.get(
                    f"http://{src.url}/admin/volume/status",
                    params={"volume": str(vid)}) as resp:
                stat = await resp.json()
            assert stat["last_append_at_ns"] > 0
            # pull the tail into the dst copy
            async with c.http.post(
                    f"http://{dst.url}/admin/volume/tail_receive",
                    params={"volume": str(vid),
                            "source": src.url}) as resp:
                assert resp.status == 200
                assert (await resp.json())["applied"] == 1
            # dst now serves the needle locally
            stc, data = await c.get(a["fid"], dst.url)
            assert stc == 200 and data == b"needle one"
            # incremental: second write then second receive applies only 1
            a2 = await c.assign()  # may land elsewhere; write to same fid vol
            st, _ = await c.put(a["fid"].split(",")[0] + ",02deadbeef",
                                src.url, b"needle two")
            assert st == 201
            async with c.http.post(
                    f"http://{dst.url}/admin/volume/tail_receive",
                    params={"volume": str(vid),
                            "source": src.url}) as resp:
                assert (await resp.json())["applied"] == 1
    run(body())
