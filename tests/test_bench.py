"""Tests for bench.py's measurement-honesty guards.

Round 3 published a physically impossible 83,886,080 GB/s headline because
a clamp turned short timings into exactly bytes/ns. These tests pin the
round-4 fixes: a rate above the HBM ceiling raises instead of being
reported, a real measurement returns a plausible positive rate with the
chain length it actually timed, and the degraded-read stage reports
coherent percentiles (store_ec.go:319-373 analog path).
"""

import jax.numpy as jnp
import numpy as np
import pytest

import bench


def _consts(rows: int, k: int) -> np.ndarray:
    return np.zeros((rows, k, 8), np.uint8)


def test_hbm_bound_rejects_impossible_rate():
    # an identity-ish transform with an absurd claimed byte count: the
    # computed GB/s exceeds the v5e HBM ceiling and must raise, never
    # land in the published result
    words = [jnp.zeros((8, 128), jnp.uint32) for _ in range(3)]
    with pytest.raises(bench.ImplausibleResult):
        bench._chained_gbs(lambda c, ws: [ws[0], ws[1]], _consts(2, 3),
                           words, n=1 << 50, chain_len=2, rtt=0.0)


def test_chained_gbs_returns_plausible_rate():
    words = [jnp.ones((8, 128), jnp.uint32) for _ in range(3)]

    def xor2(c, ws):
        return [ws[0] ^ jnp.uint32(1), ws[1] ^ jnp.uint32(2)]

    gbs, dt, used = bench._chained_gbs(xor2, _consts(2, 3), words,
                                       n=8 * 512, chain_len=2, rtt=0.0)
    assert 0.0 < gbs <= bench.HBM_BOUND_GBPS
    assert dt > 0.0
    # the chain may only GROW to dominate dispatch latency — a shrunken
    # chain would mean dividing by a length that was never run
    assert used >= 2


def test_degraded_read_percentiles_coherent():
    res = bench.bench_degraded_read(n_needles=8, payload=1 << 10, reads=25)
    assert res["degraded_read_reads"] == 25
    assert 0.0 < res["degraded_read_p50_ms"] <= res["degraded_read_p99_ms"]


def test_cpu_baseline_positive():
    gbs, kind = bench.bench_cpu(n_bytes_per_shard=64 << 10)
    assert gbs > 0.0
    assert kind in ("native-avx2", "numpy")
