"""Cache-churn smoke (marked slow — excluded from tier-1): a short
tools/soak.py cache-churn run against a real -workers 2 cluster with
the hot-needle + chunk caches on and failpoints armed. Every read is
byte-verified; any stale read (old bytes after an overwrite, success
after a delete) fails the soak, so cache-invalidation regressions are
caught by the suite."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_cache_churn_quick(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
               SWTPU_CHURN_SECONDS="8", SWTPU_CHURN_FILES="120")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "soak.py"),
         "cache-churn"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)
    sys.stdout.write(out.stdout)
    sys.stderr.write(out.stderr)
    assert out.returncode == 0, "cache churn soak reported stale/lost reads"
    assert "stale" in out.stdout        # the verifier actually ran
