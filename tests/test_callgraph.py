"""Tier-1 gates for weedlint phase 2: the whole-program symbol table
+ call graph (resolution of methods, attr types, imports, MRO,
executor boundaries, generators, cycles), positive AND negative
fixtures for each interprocedural rule, the docs-drift cross-artifact
pass, --changed plumbing, and the unresolved-call precision ceiling
over the real tree — so resolution power can't silently rot.

Fixture trees live under ``<tmp>/seaweedfs_tpu`` so scope-gated rules
(timeout-discipline, sanctioned sinks, artifact extraction) see the
same package layout the enforced tree has, while the symbol table
stays hermetic (program_roots never mixes a fixture with the repo).
"""

import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.weedlint import make_rules, run_paths  # noqa: E402
from tools.weedlint import artifacts  # noqa: E402
from tools.weedlint.callgraph import Program  # noqa: E402
from tools.weedlint.cli import changed_files  # noqa: E402
from tools.weedlint.program import DEFAULT_ROOTS  # noqa: E402
from tools.weedlint.symbols import SymbolTable  # noqa: E402


def tree(tmp_path, files: dict) -> str:
    root = tmp_path / "seaweedfs_tpu"
    for rel, src in files.items():
        f = root / rel
        f.parent.mkdir(parents=True, exist_ok=True)
        f.write_text(textwrap.dedent(src))
    return str(root)


def lint_tree(root: str, select):
    found = run_paths([root], make_rules(select=select),
                      check_unused=False)
    return [f for f in found if not f.suppressed]


def rule_ids(findings):
    return sorted(f.rule for f in findings)


def build(tmp_path, files: dict) -> Program:
    return Program(SymbolTable.build([tree(tmp_path, files)]))


def fn(program: Program, qual_tail: str):
    hits = [f for q, f in program.table.functions.items()
            if q.endswith(qual_tail)]
    assert len(hits) == 1, (qual_tail, list(program.table.functions))
    return hits[0]


def resolved_targets(program: Program, qual_tail: str):
    return sorted(s.target.qual for s in
                  program.calls[fn(program, qual_tail).qual]
                  if s.kind == "resolved" and s.target is not None)


# ---------------------------------------------------------------------
# call resolution
# ---------------------------------------------------------------------

def test_resolves_self_methods_and_attr_types(tmp_path):
    p = build(tmp_path, {"a.py": """
        class Client:
            def upload(self):
                pass

        class Server:
            def __init__(self):
                self.client = Client()
            def top(self):
                self.helper()            # self method
                self.client.upload()     # via the attr-type heuristic
            def helper(self):
                pass
    """})
    assert resolved_targets(p, "Server.top") == [
        "seaweedfs_tpu.a.Client.upload",
        "seaweedfs_tpu.a.Server.helper",
    ]


def test_resolves_imports_locals_and_mro(tmp_path):
    p = build(tmp_path, {
        "util/client.py": """
            class Base:
                def ping(self):
                    pass
            class WeedClient(Base):
                pass
            def helper():
                pass
        """,
        "b.py": """
            from seaweedfs_tpu.util.client import WeedClient, helper
            from seaweedfs_tpu.util import client

            def top():
                helper()                 # from-import function
                client.helper()          # module-alias function
                c = WeedClient()         # ctor (resolves __init__/None)
                c.ping()                 # local var type + MRO walk
        """})
    assert "seaweedfs_tpu.util.client.Base.ping" \
        in resolved_targets(p, "b.top")
    assert "seaweedfs_tpu.util.client.helper" \
        in resolved_targets(p, "b.top")


def test_annotation_typed_parameters_resolve(tmp_path):
    p = build(tmp_path, {"a.py": """
        class Store:
            def write(self):
                pass
        def use(store: "Store"):
            store.write()
    """})
    assert resolved_targets(p, "a.use") == ["seaweedfs_tpu.a.Store.write"]


def test_unresolved_is_reported_not_guessed(tmp_path):
    p = build(tmp_path, {"a.py": """
        def top(thing):
            thing.mystery()              # untyped parameter
            get_handle().close()         # call-result receiver
    """})
    kinds = [s.kind for s in p.calls[fn(p, "a.top").qual]]
    # thing.mystery, the inner get_handle(), and <call>.close are all
    # honestly unresolved — never guessed at
    assert kinds.count("unresolved") == 3
    assert p.unresolved_rate() > 0


def test_builtin_methods_are_external_not_unresolved(tmp_path):
    p = build(tmp_path, {"a.py": """
        def top(d, items):
            d.get("x")
            items.append(1)
            "a,b".split(",")
    """})
    kinds = [s.kind for s in p.calls[fn(p, "a.top").qual]]
    assert kinds == ["external"] * 3


def test_call_cycles_terminate(tmp_path):
    p = build(tmp_path, {"a.py": """
        import os
        def ping(n):
            return pong(n - 1)
        def pong(n):
            if n:
                return ping(n)
            return os.pread(3, 1, 0)
    """})
    path = p.blocking_path(fn(p, "a.ping"))
    assert path is not None and path[-1][2] == "os.pread"


# ---------------------------------------------------------------------
# transitive-blocking
# ---------------------------------------------------------------------

# THE acceptance fixture: a 3-deep sync helper chain below an async
# def. The per-file blocking-io rule provably misses it (the blocking
# call is in a sync function); the whole-program pass walks the chain.
THREE_DEEP = {
    "server/handler.py": """
        from seaweedfs_tpu.storage.meta import load_meta

        async def h(req):
            return load_meta(req.vid)        # sync, one file away
    """,
    "storage/meta.py": """
        from seaweedfs_tpu.storage.disk import read_meta_blob

        def load_meta(vid):
            return read_meta_blob(vid)       # sync, two deep
    """,
    "storage/disk.py": """
        def read_meta_blob(vid):
            with open(f"/v/{vid}.meta") as f:   # three deep: blocks
                return f.read()
    """,
}


def test_old_blocking_io_rule_provably_misses_the_chain(tmp_path):
    assert lint_tree(tree(tmp_path, THREE_DEEP),
                     ["blocking-io"]) == []


def test_transitive_blocking_catches_the_three_deep_chain(tmp_path):
    found = lint_tree(tree(tmp_path, THREE_DEEP),
                      ["transitive-blocking"])
    assert rule_ids(found) == ["transitive-blocking"]
    f = found[0]
    assert f.rel.endswith("server/handler.py")
    assert "open()" in f.message
    assert "load_meta" in f.message and "read_meta_blob" in f.message


def test_executor_boundary_terminates_the_walk(tmp_path):
    found = lint_tree(tree(tmp_path, {
        "a.py": """
            from seaweedfs_tpu.util import tracing

            def blocking_helper(vid):
                return open(f"/v/{vid}").read()

            async def h(req):
                return await tracing.run_in_executor(
                    blocking_helper, req.vid)
        """}), ["transitive-blocking"])
    assert found == []


def test_async_callees_terminate_the_walk(tmp_path):
    # an async callee's own blocking is ITS finding (analyzed at its
    # root), not every transitive caller's — one bug, one report
    found = lint_tree(tree(tmp_path, {
        "a.py": """
            import time

            async def inner():
                time.sleep(1)

            async def outer():
                await inner()
        """}), ["transitive-blocking"])
    assert found == []


def test_generator_calls_do_not_propagate(tmp_path):
    found = lint_tree(tree(tmp_path, {
        "a.py": """
            def records(path):
                with open(path) as f:        # runs at next(), not call
                    yield from f

            async def h(req):
                it = records(req.path)
                return it
        """}), ["transitive-blocking"])
    assert found == []


def test_sanctioned_sink_cuts_propagation(tmp_path):
    # same shape as the three-deep chain, but the leaf is glog._emit —
    # the one documented sanctioned sink
    found = lint_tree(tree(tmp_path, {
        "util/glog.py": """
            def _emit(severity, msg):
                with open("/log/x", "a") as f:
                    f.write(msg)
            def warning(fmt, *args):
                _emit("W", fmt % args)
        """,
        "b.py": """
            from seaweedfs_tpu.util import glog

            async def h(req):
                glog.warning("slow request %s", req)
        """}), ["transitive-blocking"])
    assert found == []


def test_phase2_findings_honor_line_suppressions(tmp_path):
    root = tree(tmp_path, dict(THREE_DEEP))
    handler = os.path.join(root, "server", "handler.py")
    with open(handler, encoding="utf-8") as f:
        src = f.read()
    src = src.replace(
        "return load_meta(req.vid)        # sync, one file away",
        "return load_meta(req.vid)  "
        "# weedlint: ignore[transitive-blocking] boot path, loop idle")
    with open(handler, "w", encoding="utf-8") as f:
        f.write(src)
    found = run_paths([root], make_rules(
        select=["transitive-blocking"]), check_unused=False)
    assert len(found) == 1 and found[0].suppressed


# ---------------------------------------------------------------------
# lock-order
# ---------------------------------------------------------------------

INVERSION = {
    "storage/store.py": """
        import threading

        class Store:
            def __init__(self):
                self._vol_lock = threading.Lock()
                self._map_lock = threading.Lock()

            def write(self):
                with self._vol_lock:
                    with self._map_lock:
                        pass
    """,
    "storage/vacuum.py": """
        from seaweedfs_tpu.storage.store import Store

        def compact(store: Store):
            with store._map_lock:
                with store._vol_lock:        # opposite order
                    pass
    """,
}


def test_lock_order_catches_two_module_inversion(tmp_path):
    found = lint_tree(tree(tmp_path, INVERSION), ["lock-order"])
    assert "lock-order" in rule_ids(found)
    rels = {f.rel.rsplit("/", 1)[-1] for f in found}
    assert rels == {"store.py", "vacuum.py"}
    assert any("opposite order" in f.message for f in found)


def test_lock_order_quiet_on_consistent_order(tmp_path):
    files = dict(INVERSION)
    files["storage/vacuum.py"] = """
        from seaweedfs_tpu.storage.store import Store

        def compact(store: Store):
            with store._vol_lock:
                with store._map_lock:        # same global order
                    pass
    """
    assert lint_tree(tree(tmp_path, files), ["lock-order"]) == []


def test_lock_order_sees_acquisitions_inside_callees(tmp_path):
    found = lint_tree(tree(tmp_path, {
        "a.py": """
            import threading

            class S:
                def __init__(self):
                    self._a_lock = threading.Lock()
                    self._b_lock = threading.Lock()
                def one(self):
                    with self._a_lock:
                        self._grab_b()           # nested via a call
                def _grab_b(self):
                    with self._b_lock:
                        pass
                def two(self):
                    with self._b_lock:
                        with self._a_lock:
                            pass
        """}), ["lock-order"])
    assert "lock-order" in rule_ids(found)
    assert any("via " in f.message for f in found)


def test_cycle_query_order_does_not_poison_blocking_memo(tmp_path):
    """Regression: querying blocking_path(a) first used to memoize
    b=None (a None computed while a sat on the in-progress stack), so
    a later query for b — e.g. from an async caller — silently lost
    the real path b -> a -> time.sleep."""
    p = build(tmp_path, {"a.py": """
        import time
        def a():
            b()
            time.sleep(1)
        def b():
            a()
    """})
    assert p.blocking_path(fn(p, "a.a")) is not None
    path = p.blocking_path(fn(p, "a.b"))
    assert path is not None and path[-1][2] == "time.sleep"


def test_lock_closure_cycle_query_order_keeps_edges(tmp_path):
    """Regression: computing closure(a) first used to memoize cycle
    member b's transitive lock set as empty, so `with c_lock:
    self.b()` produced no c_lock->b_lock edge and a real inversion
    elsewhere went unreported."""
    found = lint_tree(tree(tmp_path, {"m.py": """
        import threading

        class S:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()
                self._c_lock = threading.Lock()
            def a(self):
                self.b()
                with self._b_lock:
                    pass
            def b(self):
                self.a()
            def first(self):
                with self._a_lock:
                    self.a()         # closure(a) computed first
            def second(self):
                with self._c_lock:
                    self.b()         # needs closure(b) = {b_lock}
            def inverse(self):
                with self._b_lock:
                    with self._c_lock:
                        pass
    """}), ["lock-order"])
    assert "lock-order" in rule_ids(found)
    assert any("via m.S.b" in f.message for f in found)


def test_lock_order_skips_unpinnable_bare_parameters(tmp_path):
    # a bare `lock` parameter aliases anything — guessing would
    # fabricate deadlocks, so identity-less acquisitions are skipped
    found = lint_tree(tree(tmp_path, {
        "a.py": """
            def f(lock, other_lock):
                with lock:
                    with other_lock:
                        pass
            def g(lock, other_lock):
                with other_lock:
                    with lock:
                        pass
        """}), ["lock-order"])
    assert found == []


# ---------------------------------------------------------------------
# timeout-discipline
# ---------------------------------------------------------------------

def test_timeout_missing_everywhere_fires(tmp_path):
    found = lint_tree(tree(tmp_path, {"a.py": """
        class C:
            def __init__(self, make_session):
                self._http = make_session()

            async def probe(self, url):
                async with self._http.get(url) as r:
                    return r.status
    """}), ["timeout-discipline"])
    assert rule_ids(found) == ["timeout-discipline"]
    assert "no timeout in reach" in found[0].message


def test_timeout_owned_by_session_constructor(tmp_path):
    found = lint_tree(tree(tmp_path, {"a.py": """
        import aiohttp

        class C:
            def __init__(self, make_session):
                self._http = make_session(
                    timeout=aiohttp.ClientTimeout(total=60))

            async def probe(self, url):
                async with self._http.get(url) as r:
                    return r.status
    """}), ["timeout-discipline"])
    assert found == []


def test_timeout_explicit_none_fires(tmp_path):
    found = lint_tree(tree(tmp_path, {"a.py": """
        import aiohttp

        class C:
            def __init__(self, make_session):
                self._http = make_session(
                    timeout=aiohttp.ClientTimeout(total=60))

            async def probe(self, url):
                async with self._http.get(url, timeout=None) as r:
                    return r.status
    """}), ["timeout-discipline"])
    assert rule_ids(found) == ["timeout-discipline"]
    assert "timeout=None" in found[0].message


def test_timeout_obligation_follows_wrapper_to_callers(tmp_path):
    found = lint_tree(tree(tmp_path, {"a.py": """
        class W:
            def __init__(self, make_session):
                self._http = make_session()

            async def fetch(self, url, timeout=None):
                async with self._http.get(url, timeout=timeout) as r:
                    return r

        async def caller_bad(w: "W"):
            return await w.fetch("http://x/")      # leaves the default

        async def caller_ok(w: "W"):
            return await w.fetch("http://x/", timeout=5)
    """}), ["timeout-discipline"])
    assert len(found) == 1
    assert "forwards the timeout obligation" in found[0].message
    # anchored at caller_bad's call site, not inside the wrapper
    assert "caller_bad" not in found[0].code  # code is the call line
    assert "w.fetch" in found[0].code


def test_timeout_owned_through_property_alias(tmp_path):
    found = lint_tree(tree(tmp_path, {"a.py": """
        import aiohttp

        class Env:
            def __init__(self, make_session):
                self._session = make_session(
                    timeout=aiohttp.ClientTimeout(total=300))

            @property
            def http(self):
                return self._session

        async def ls(env: "Env", url):
            async with env.http.get(url) as r:
                return await r.json()
    """}), ["timeout-discipline"])
    assert found == []


# ---------------------------------------------------------------------
# transitive-orphan-span
# ---------------------------------------------------------------------

def test_span_dropped_on_the_floor_fires(tmp_path):
    found = lint_tree(tree(tmp_path, {"a.py": """
        from seaweedfs_tpu.util import tracing

        def h(req):
            tracing.start("volume", "read")
    """}), ["transitive-orphan-span"])
    assert rule_ids(found) == ["transitive-orphan-span"]
    assert "dropped" in found[0].message


def test_span_handed_to_callee_that_never_finishes_fires(tmp_path):
    found = lint_tree(tree(tmp_path, {"a.py": """
        from seaweedfs_tpu.util import tracing

        class S:
            def h(self, req):
                sp = tracing.start("volume", "read")
                self._serve(req, sp)

            def _serve(self, req, sp):
                return req.body              # never finishes sp
    """}), ["transitive-orphan-span"])
    assert rule_ids(found) == ["transitive-orphan-span"]
    assert "_serve" in found[0].message


def test_span_finished_by_callee_in_finally_is_quiet(tmp_path):
    found = lint_tree(tree(tmp_path, {"a.py": """
        from seaweedfs_tpu.util import tracing

        class S:
            def h(self, req):
                sp = tracing.start("volume", "read")
                self._serve(req, sp)

            def _serve(self, req, sp):
                try:
                    return req.body
                finally:
                    sp.finish()
    """}), ["transitive-orphan-span"])
    assert found == []


def test_span_with_statement_and_returns_are_quiet(tmp_path):
    found = lint_tree(tree(tmp_path, {"a.py": """
        from seaweedfs_tpu.util import tracing

        def ctx(req):
            with tracing.start("volume", "read"):
                return req.body

        def handoff(req):
            return tracing.start("volume", "read")   # caller owns it
    """}), ["transitive-orphan-span"])
    assert found == []


# ---------------------------------------------------------------------
# docs-drift
# ---------------------------------------------------------------------

def test_metric_token_expansion():
    assert artifacts._expand_metric_token(
        "SeaweedFS_disk_{free,used}_bytes") == [
            "SeaweedFS_disk_free_bytes", "SeaweedFS_disk_used_bytes"]
    # a trailing brace group is a label set, not alternatives
    assert artifacts._expand_metric_token(
        "SeaweedFS_request_duration_seconds{tier,op,status}") == [
            "SeaweedFS_request_duration_seconds"]
    assert artifacts._expand_metric_token("SeaweedFS_") == []
    # labeled PromQL examples: the source regex stops at '=' so the
    # token arrives with an unclosed brace — the name must survive
    assert artifacts._expand_metric_token(
        'SeaweedFS_volume_read_total{volume') == [
            "SeaweedFS_volume_read_total"]
    assert artifacts._expand_metric_token(
        'SeaweedFS_disk_{free,used}_bytes{path') == [
            "SeaweedFS_disk_free_bytes", "SeaweedFS_disk_used_bytes"]
    assert artifacts.metric_documented(
        "SeaweedFS_slo_status", ["SeaweedFS_slo_*"])
    assert artifacts.metric_claim_live(
        "SeaweedFS_slo_*", {"SeaweedFS_slo_status": None})
    assert not artifacts.metric_claim_live("SeaweedFS_slo_*", {})


DRIFT_CODE = {
    "cli.py": """
        def build(p):
            p.add_argument("-documented", default=1)
            p.add_argument("-ghostflag", default=2)
    """,
    "m.py": """
        from prometheus_client import Counter
        M1 = Counter("SeaweedFS_known_total", "help")
        M2 = Counter("SeaweedFS_ghost_metric_total", "help")

        def boot(events, failpoints, app):
            events.record("known_event", x=1)
            events.record("ghost_event", x=1)
            failpoints.sync_fail("known.site")
            failpoints.sync_fail("ghost.site")
            app.router.add_get("/debug/known", h)
            app.router.add_get("/debug/ghostroute", h)
    """,
}

DRIFT_DOC = """# catalog
| flag | meaning |
|---|---|
| `-documented` | a real flag |
| `-deadflag` | dropped from the code |

`SeaweedFS_known_total` and `SeaweedFS_dead_total` are metrics.

| type | emitted by |
|---|---|
| `known_event` | somewhere |
| `dead_event` | nowhere |

| site | layer |
|---|---|
| `known.site` | here |
| `dead.site` | gone |

Routes: `/debug/known` and `/debug/deadroute`.
"""


def test_docs_drift_both_directions(tmp_path, monkeypatch):
    root = tree(tmp_path, DRIFT_CODE)
    docdir = tmp_path / "docs"
    docdir.mkdir()
    (docdir / "CATALOG.md").write_text(DRIFT_DOC)
    monkeypatch.setattr(artifacts, "REPO", str(docdir))
    monkeypatch.setattr(artifacts, "DOC_FILES", ("CATALOG.md",))
    found = lint_tree(root, ["docs-drift"])
    msgs = {f.message.split("'")[1]: f for f in found}
    # undocumented: in code, absent from the catalog — anchored in code
    for name in ("ghostflag", "SeaweedFS_ghost_metric_total",
                 "ghost_event", "ghost.site", "ghostroute"):
        assert name in msgs, sorted(msgs)
        assert msgs[name].rel.endswith(".py")
    # dead: claimed by the catalog, absent from code — anchored in the doc
    for name in ("deadflag", "SeaweedFS_dead_total", "dead_event",
                 "dead.site", "deadroute"):
        assert name in msgs, sorted(msgs)
        assert msgs[name].rel == "CATALOG.md"
    # documented + live names never fire
    for name in ("documented", "SeaweedFS_known_total", "known_event",
                 "known.site", "known"):
        assert name not in msgs
    assert len(found) == 10


def test_docs_drift_real_tree_is_clean():
    """The acceptance bar the satellites fixed: flags, metrics,
    journal events, failpoint sites and /debug routes all match their
    catalogs right now."""
    table = SymbolTable.build(DEFAULT_ROOTS)
    code = artifacts.extract_code(table)
    docs = artifacts.extract_docs()
    missing = [n for n in code.flags if n not in docs.flag_mentions]
    assert missing == [], f"undocumented flags: {missing}"
    missing = [n for n in code.failpoints
               if n not in docs.failpoint_mentions]
    assert missing == [], f"undocumented failpoint sites: {missing}"
    missing = [n for n in code.metrics
               if not artifacts.metric_documented(
                   n, docs.metric_mentions)]
    assert missing == [], f"undocumented metrics: {missing}"
    dead = [c.name for c in docs.failpoint_claims
            if c.name not in code.failpoints]
    assert dead == [], f"dead failpoint claims: {dead}"
    dead = [c.name for c in docs.flag_claims
            if c.name not in code.flags]
    assert dead == [], f"dead flag claims: {dead}"


def test_failpoint_extraction_sees_take_and_pending():
    """wire.py's volume.read.http plants via take()/pending(), not
    fail() — the regression that produced the first dead-claim false
    positive."""
    table = SymbolTable.build(DEFAULT_ROOTS)
    code = artifacts.extract_code(table)
    assert "volume.read.http" in code.failpoints


# ---------------------------------------------------------------------
# --changed mode
# ---------------------------------------------------------------------

def test_changed_files_scratch_repo(tmp_path):
    repo = str(tmp_path)
    def git(*args):
        subprocess.run(["git", "-c", "user.email=t@t",
                        "-c", "user.name=t", *args],
                       cwd=repo, check=True, capture_output=True)
    git("init", "-q")
    (tmp_path / "pkg").mkdir()
    (tmp_path / "pkg" / "a.py").write_text("x = 1\n")
    (tmp_path / "pkg" / "kept.py").write_text("k = 1\n")
    (tmp_path / "doc.md").write_text("hi\n")
    git("add", "-A")
    git("commit", "-qm", "init")
    (tmp_path / "pkg" / "a.py").write_text("x = 2\n")       # modified
    (tmp_path / "pkg" / "b.py").write_text("y = 1\n")       # untracked
    (tmp_path / "doc.md").write_text("hi2\n")               # md, out of scope
    got = changed_files("HEAD", [os.path.join(repo, "pkg")], repo=repo)
    names = sorted(os.path.basename(p) for p in got)
    # changed .py in scope + changed .md anywhere; kept.py untouched
    assert names == ["a.py", "b.py", "doc.md"]


def test_program_roots_never_use_the_repo_root(tmp_path):
    """Regression: scanning '.' (or a repo-top file) used to collapse
    the roots into REPO itself, prefixing every module qual with the
    checkout dir's name — which silently defeated SANCTIONED_SINKS
    and flooded transitive-blocking false positives."""
    from tools.weedlint.program import (DEFAULT_ROOTS, REPO as WREPO,
                                        program_roots)
    for scan in ([WREPO], [os.path.join(WREPO, "bench.py")]):
        roots = program_roots(scan)
        assert WREPO not in roots, scan
        for d in DEFAULT_ROOTS:
            assert d in roots, scan
    assert os.path.join(WREPO, "tests") in program_roots([WREPO])


def test_changed_files_git_failure_is_loud(tmp_path):
    """Regression: a typo'd ref (or a shallow checkout missing it)
    used to yield empty stdout -> 'clean' -> exit 0. The pre-commit
    gate must refuse, not silently lint nothing."""
    repo = str(tmp_path)
    subprocess.run(["git", "init", "-q"], cwd=repo, check=True,
                   capture_output=True)
    with pytest.raises(RuntimeError, match="no-such-ref"):
        changed_files("no-such-ref", [repo], repo=repo)


def test_restrict_rels_filters_phase2_reporting(tmp_path):
    """--changed semantics: the symbol table covers everything, the
    report lands only in the restricted set."""
    root = tree(tmp_path, THREE_DEEP)
    all_found = lint_tree(root, ["transitive-blocking"])
    assert len(all_found) == 1
    handler_rel = all_found[0].rel
    kept = run_paths([root], make_rules(select=["transitive-blocking"]),
                     check_unused=False, restrict_rels={handler_rel})
    assert [f.rel for f in kept] == [handler_rel]
    dropped = run_paths([root],
                        make_rules(select=["transitive-blocking"]),
                        check_unused=False,
                        restrict_rels={"somewhere/else.py"})
    assert dropped == []


# ---------------------------------------------------------------------
# precision: the unresolved-call ceiling over the real tree
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def real_program():
    return Program(SymbolTable.build(DEFAULT_ROOTS))


# The bounded resolver's measured rate is ~0.42 (resolved ~3.5k of
# ~6.1k candidates; bound-method aliases and functools.partial now
# resolve). The ceiling is a RATCHET: if a refactor or a new idiom
# pushes the rate past it, teach symbols.py the idiom (or consciously
# raise this with a PR note) — precision must not rot silently,
# because every phase-2 pass is blind at unresolved edges.
UNRESOLVED_CEILING = 0.45


def test_unresolved_rate_stays_under_ceiling(real_program):
    rate = real_program.unresolved_rate()
    assert rate <= UNRESOLVED_CEILING, (
        f"unresolved-call rate {rate:.1%} blew the "
        f"{UNRESOLVED_CEILING:.0%} ceiling — the whole-program passes "
        f"just lost visibility; teach symbols.py the new idiom")
    # and the metric is meaningful, not vacuously tiny
    assert real_program.stats["resolved"] > 1500
    assert real_program.stats["external"] > 2000


def test_advisory_unresolved_call_never_gates(tmp_path):
    from tools.weedlint.cli import main as weedlint_main
    root = tree(tmp_path, {"a.py": """
        def top(thing):
            thing.mystery()
    """})
    assert weedlint_main([root, "--no-baseline"]) == 0


# ---------------------------------------------------------------------
# PR 20: alias / functools.partial resolution (the phase-3 rules lean
# on these edges — a registration or undo may hide behind `f = self.x`)
# ---------------------------------------------------------------------

def test_bound_method_alias_resolves(tmp_path):
    p = build(tmp_path, {"a.py": """
        class Chan:
            def _undo(self):
                pass
            async def top(self):
                f = self._undo
                f()
    """})
    assert resolved_targets(p, "Chan.top") == ["seaweedfs_tpu.a.Chan._undo"]


def test_functools_partial_alias_resolves(tmp_path):
    p = build(tmp_path, {"a.py": """
        import functools
        class Chan:
            def _retry(self, n):
                pass
            async def top(self):
                g = functools.partial(self._retry, 3)
                g()
    """})
    assert resolved_targets(p, "Chan.top") == ["seaweedfs_tpu.a.Chan._retry"]


def test_plain_function_alias_resolves(tmp_path):
    p = build(tmp_path, {"a.py": """
        def helper():
            pass
        def top():
            h = helper
            h()
    """})
    assert resolved_targets(p, "a.top") == ["seaweedfs_tpu.a.helper"]


# ---------------------------------------------------------------------
# PR 20: phase-3 rule fixtures — cancel-leak
# ---------------------------------------------------------------------

def test_cancel_leak_fires_on_straight_line_pop(tmp_path):
    """The historical FrameChannel._request shape: register, await,
    pop on the straight path only — a caller cancelled mid-await
    leaks the entry."""
    found = lint_tree(tree(tmp_path, {"chan.py": """
        class Chan:
            async def request(self, rid, fut, w):
                self._pending[rid] = fut
                await w.drain()
                self._pending.pop(rid, None)
    """}), select=["cancel-leak"])
    assert rule_ids(found) == ["cancel-leak"]
    assert "_pending" in found[0].message


def test_cancel_leak_quiet_with_finally(tmp_path):
    found = lint_tree(tree(tmp_path, {"chan.py": """
        class Chan:
            async def request(self, rid, fut, w):
                self._pending[rid] = fut
                try:
                    await w.drain()
                    await fut
                finally:
                    self._pending.pop(rid, None)
    """}), select=["cancel-leak"])
    assert found == []


def test_cancel_leak_quiet_with_cancellish_handler(tmp_path):
    """An except CancelledError (or BaseException) handler that undoes
    the registration covers the await too."""
    found = lint_tree(tree(tmp_path, {"chan.py": """
        import asyncio
        class Chan:
            async def request(self, rid, fut, w):
                self._pending[rid] = fut
                try:
                    await w.drain()
                except asyncio.CancelledError:
                    self._pending.pop(rid, None)
                    raise
                self._pending.pop(rid, None)
    """}), select=["cancel-leak"])
    assert found == []


def test_cancel_leak_sees_registration_one_call_deep(tmp_path):
    found = lint_tree(tree(tmp_path, {"chan.py": """
        class Chan:
            def _track(self, rid, fut):
                self._pending[rid] = fut
            async def request(self, rid, fut, w):
                self._track(rid, fut)
                await w.drain()
                self._pending.pop(rid, None)
    """}), select=["cancel-leak"])
    assert rule_ids(found) == ["cancel-leak"]


def test_cancel_leak_quiet_when_undo_one_call_deep_in_finally(tmp_path):
    found = lint_tree(tree(tmp_path, {"chan.py": """
        class Chan:
            def _forget(self, rid):
                self._pending.pop(rid, None)
            async def request(self, rid, fut, w):
                self._pending[rid] = fut
                try:
                    await w.drain()
                finally:
                    self._forget(rid)
    """}), select=["cancel-leak"])
    assert found == []


def test_cancel_leak_fires_on_inflight_counter(tmp_path):
    """The _acquire_slot shape: an in-flight counter incremented
    before the await and decremented after is the same leak."""
    found = lint_tree(tree(tmp_path, {"chan.py": """
        class Chan:
            async def send(self, w):
                self._inflight += 1
                await w.drain()
                self._inflight -= 1
    """}), select=["cancel-leak"])
    assert rule_ids(found) == ["cancel-leak"]
    assert "incremented" in found[0].message


def test_cancel_leak_quiet_for_detached_value(tmp_path):
    """Registering a sanctioned detached task moves the cleanup
    obligation into that task's own body — the singleflight fix."""
    found = lint_tree(tree(tmp_path, {"sf.py": """
        from seaweedfs_tpu.util import aio
        class SF:
            async def do(self, key, fn):
                t = aio.detach(self._run(key, fn))
                self._inflight[key] = t
                await t
                self._inflight.pop(key, None)
            async def _run(self, key, fn):
                pass
    """}), select=["cancel-leak"])
    assert found == []


# ---------------------------------------------------------------------
# PR 20: phase-3 rule fixtures — await-atomicity
# ---------------------------------------------------------------------

def test_await_atomicity_fires_on_unfenced_fill(tmp_path):
    """The pre-token cache-fill shape: check, await, write — the
    guard is stale by write time (the gen-fence bug)."""
    found = lint_tree(tree(tmp_path, {"cache.py": """
        class Cache:
            async def fill(self, fid, fetch):
                if fid not in self._cache:
                    data = await fetch(fid)
                    self._cache[fid] = data
    """}), select=["await-atomicity"])
    assert rule_ids(found) == ["await-atomicity"]
    assert "_cache" in found[0].message


def test_await_atomicity_fires_on_collapsed_assign(tmp_path):
    """`self.X[k] = await f()` awaits inside the write statement —
    equally stale."""
    found = lint_tree(tree(tmp_path, {"cache.py": """
        class Cache:
            async def fill(self, fid, fetch):
                if fid not in self._cache:
                    self._cache[fid] = await fetch(fid)
    """}), select=["await-atomicity"])
    assert rule_ids(found) == ["await-atomicity"]


def test_await_atomicity_quiet_when_guard_rechecked(tmp_path):
    found = lint_tree(tree(tmp_path, {"cache.py": """
        class Cache:
            async def fill(self, fid, fetch):
                if fid not in self._cache:
                    data = await fetch(fid)
                    if fid not in self._cache:
                        self._cache[fid] = data
    """}), select=["await-atomicity"])
    assert found == []


def test_await_atomicity_quiet_through_fenced_helper(tmp_path):
    """A compare-and-set helper that re-reads the guarded attr inside
    (set_if) re-validates one resolved call deep."""
    found = lint_tree(tree(tmp_path, {"cache.py": """
        class Cache:
            def _set_if(self, fid, data):
                if fid in self._cache:
                    return
                self._cache[fid] = data
            async def fill(self, fid, fetch):
                if fid not in self._cache:
                    data = await fetch(fid)
                    self._set_if(fid, data)
    """}), select=["await-atomicity"])
    assert found == []


def test_await_atomicity_quiet_without_await_in_branch(tmp_path):
    found = lint_tree(tree(tmp_path, {"cache.py": """
        class Cache:
            async def fill(self, fid, data):
                if fid not in self._cache:
                    self._cache[fid] = data
                await self._flush()
            async def _flush(self):
                pass
    """}), select=["await-atomicity"])
    assert found == []


# ---------------------------------------------------------------------
# PR 20: phase-3 rule fixtures — detach-discipline
# ---------------------------------------------------------------------

def test_detach_discipline_fires_on_documented_detach(tmp_path):
    """A create_task whose adjacent comment promises survive/outlive
    semantics re-implements the sanctioned helper ad hoc — the PR-3
    singleflight leader shape."""
    found = lint_tree(tree(tmp_path, {"sf.py": """
        import asyncio
        class SF:
            async def do(self, key):
                # runs DETACHED: the caller's cancellation must not
                # stop the shared fill
                t = asyncio.create_task(self._run(key))
                return t
            async def _run(self, key):
                pass
    """}), select=["detach-discipline"])
    assert rule_ids(found) == ["detach-discipline"]
    assert "aio.detach" in found[0].message


def test_detach_discipline_quiet_on_owned_loop_task(tmp_path):
    """A loop task whose handle the owner retains and cancels on
    shutdown is NOT detached and stays plain create_task."""
    found = lint_tree(tree(tmp_path, {"srv.py": """
        import asyncio
        class Srv:
            async def start(self):
                # the poll loop; cancelled in close()
                self._task = asyncio.create_task(self._poll())
            async def _poll(self):
                pass
    """}), select=["detach-discipline"])
    assert found == []


def test_detach_discipline_skips_sanctioned_helper_body(tmp_path):
    """util.aio.detach itself spawns with create_task under detach-y
    comments — the one sanctioned site must not self-flag."""
    found = lint_tree(tree(tmp_path, {"util/aio.py": """
        import asyncio
        def detach(coro):
            # detached: survives the caller, consumes the exception
            t = asyncio.create_task(coro)
            return t
    """}), select=["detach-discipline"])
    assert found == []


# ---------------------------------------------------------------------
# PR 20: cancel preset + --jobs byte-equality with phase 3
# ---------------------------------------------------------------------

def test_select_cancel_preset_expands_phase3_subset(tmp_path, capsys):
    from tools.weedlint.cli import main as weedlint_main
    from tools.weedlint.rules import CANCEL_RULE_IDS, SELECT_PRESETS
    assert set(SELECT_PRESETS["cancel"]) == set(CANCEL_RULE_IDS)
    assert {"cancel-leak", "await-atomicity",
            "detach-discipline"} == set(CANCEL_RULE_IDS)
    root = tree(tmp_path, {"m.py": """
        import time
        class Chan:
            async def request(self, rid, fut, w):
                time.sleep(0.1)
                self._pending[rid] = fut
                await w.drain()
                self._pending.pop(rid, None)
    """})
    rc = weedlint_main([root, "--select", "cancel", "--no-baseline"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "cancel-leak" in out and "blocking-io" not in out


def test_jobs_byte_equal_with_phase3_rules(tmp_path, capsys):
    """--jobs N must stay a pure speedup with the phase-3 program
    rules in the mix: byte-equal JSON, path-sorted findings."""
    from tools.weedlint.cli import main as weedlint_main
    files = {}
    for i in range(4):
        files[f"m{i}.py"] = """
            class Chan:
                async def request(self, rid, fut, w):
                    self._pending[rid] = fut
                    await w.drain()
                    self._pending.pop(rid, None)
            class Cache:
                async def fill(self, fid, fetch):
                    if fid not in self._cache:
                        self._cache[fid] = await fetch(fid)
        """
    root = tree(tmp_path, files)
    rc1 = weedlint_main([root, "--format", "json", "--no-baseline"])
    serial = capsys.readouterr().out
    rc2 = weedlint_main([root, "--format", "json", "--no-baseline",
                         "--jobs", "4"])
    parallel = capsys.readouterr().out
    assert (rc1, serial) == (rc2, parallel)
    import json as _json
    summary = _json.loads(serial)["summary"]
    assert summary["cancel-leak"] == 4
    assert summary["await-atomicity"] == 4
