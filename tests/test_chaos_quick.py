"""Chaos smoke (marked slow — excluded from tier-1): a short
tools/chaos.py run with real subprocesses, armed failpoints and a
volume-server SIGKILL must finish with zero acknowledged-write loss."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_chaos_quick(tmp_path):
    report_path = str(tmp_path / "chaos.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos.py"),
         "--quick", "--json", report_path],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=420)
    sys.stdout.write(out.stdout)
    sys.stderr.write(out.stderr)
    assert out.returncode == 0, "chaos soak failed"
    with open(report_path) as f:
        report = json.load(f)
    assert report["verdict"] == "PASS"
    assert report["lost"] == 0
    assert report["stats"]["writes_ok"] > 0
