"""Tiered read-cache primitives and their wiring.

Covers the ISSUE-3 cache contract: LRU eviction at the byte budget,
size-class routing into the mmap disk tier, singleflight collapsing N
concurrent callers into one underlying call, strict invalidation
(delete, vacuum) on the volume hot-needle cache, the client's negative
lookup cache, and the EC degraded-read reconstruction cache.
"""

import asyncio
import os
import random

import pytest

from seaweedfs_tpu.util.chunk_cache import (DISK_SLOT_SIZES, LruByteCache,
                                            NeedleCache, TieredChunkCache)
from seaweedfs_tpu.util.singleflight import SingleFlight


# ---- LRU byte budget ----

def test_lru_evicts_at_byte_budget():
    c = LruByteCache(1000)
    for i in range(5):
        c.put(i, bytes(300))           # 1500B total: oldest two must go
    assert c.used <= 1000
    assert c.get(0) is None and c.get(1) is None
    assert c.get(4) == bytes(300)
    assert c.counters.evictions == 2


def test_lru_recency_and_overwrite():
    c = LruByteCache(600)
    c.put("a", b"x" * 200)
    c.put("b", b"y" * 200)
    assert c.get("a") is not None      # refresh "a": "b" becomes LRU
    c.put("c", b"z" * 300)             # overflow evicts "b"
    assert c.get("b") is None
    assert c.get("a") is not None
    c.put("a", b"w" * 100)             # overwrite re-accounts bytes
    assert c.used == 100 + 300


def test_lru_item_larger_than_budget_not_cached():
    c = LruByteCache(100)
    c.put("big", bytes(500))
    assert c.get("big") is None
    assert c.used == 0


# ---- tiered cache: size classes + disk tier ----

def test_small_items_stay_in_memory(tmp_path):
    t = TieredChunkCache(1 << 20, disk_dir=str(tmp_path),
                         mem_item_max=1024)
    t.set("s", b"a" * 100)
    assert t.get("s") == b"a" * 100
    assert t._mem.used == 100          # memory tier holds it
    assert all(layer.used == 0 for layer in t._disk)
    t.close()


def test_large_items_route_to_disk_size_class(tmp_path):
    t = TieredChunkCache(1 << 20, disk_dir=str(tmp_path),
                         mem_item_max=1024)
    rng = random.Random(3)
    small_blob = rng.randbytes(100 << 10)     # > mem_item_max, class 0
    mid_blob = rng.randbytes(600 << 10)       # class 1 (1MB slots)
    t.set("small", small_blob)
    t.set("mid", mid_blob)
    assert t.get("small") == small_blob
    assert t.get("mid") == mid_blob
    assert t._mem.used == 0
    assert t._disk[0].used == len(small_blob)
    assert t._disk[1].used == len(mid_blob)
    # backing files exist, one per size class
    for slot in DISK_SLOT_SIZES:
        assert os.path.exists(str(tmp_path / f"cache_{slot}.dat"))
    # beyond the largest class: silently uncacheable
    t.set("huge", bytes((4 << 20) + 1))
    assert t.get("huge") is None
    t.delete("mid")
    assert t.get("mid") is None
    t.close()


def test_disk_ring_evicts_oldest(tmp_path):
    from seaweedfs_tpu.util.chunk_cache import DiskCacheLayer
    layer = DiskCacheLayer(str(tmp_path / "ring.dat"), 1024, 2)
    layer.put("a", b"A" * 1000)
    layer.put("b", b"B" * 1000)
    layer.put("c", b"C" * 1000)        # ring wraps: "a" evicted
    assert layer.get("a") is None
    assert layer.get("b") == b"B" * 1000
    assert layer.get("c") == b"C" * 1000
    layer.close()


def test_mem_only_without_disk_dir():
    t = TieredChunkCache(1 << 20, mem_item_max=1024)
    assert t.max_item_size == 1024
    t.set("big", bytes(2048))          # over mem_item_max, no disk tier
    assert t.get("big") is None
    t.close()


# ---- singleflight ----

def test_singleflight_collapses_concurrent_callers():
    sf = SingleFlight()
    calls = 0

    async def fn():
        nonlocal calls
        calls += 1
        await asyncio.sleep(0.02)
        return "payload"

    async def main():
        return await asyncio.gather(*(sf.do("k", fn) for _ in range(16)))

    results = asyncio.run(main())
    assert results == ["payload"] * 16
    assert calls == 1
    assert sf.collapsed == 15 and sf.calls == 1


def test_singleflight_propagates_errors_then_retries():
    sf = SingleFlight()
    calls = 0

    async def fn():
        nonlocal calls
        calls += 1
        await asyncio.sleep(0.01)
        if calls == 1:
            raise ValueError("boom")
        return 7

    async def main():
        round1 = await asyncio.gather(
            *(sf.do("k", fn) for _ in range(4)), return_exceptions=True)
        assert all(isinstance(r, ValueError) for r in round1)
        assert calls == 1              # the failure was shared, not retried
        assert await sf.do("k", fn) == 7   # next round runs fresh

    asyncio.run(main())


# ---- volume hot-needle cache: strict invalidation ----

@pytest.fixture
def cached_store(tmp_path):
    from seaweedfs_tpu.storage.store import Store
    s = Store([str(tmp_path / "v")], needle_cache_bytes=1 << 20)
    s.add_volume(1)
    yield s
    s.close()


def _needle(nid: int, data: bytes):
    from seaweedfs_tpu.storage.needle import Needle
    return Needle(cookie=nid ^ 0x5A, id=nid, data=data)


def test_needle_cache_hit_and_cookie_check(cached_store):
    s = cached_store
    s.write_needle(1, _needle(7, b"hot bytes"))
    assert s.read_needle(1, 7, 7 ^ 0x5A).data == b"hot bytes"
    hits0 = s.needle_cache.counters.hits
    assert s.read_needle(1, 7, 7 ^ 0x5A).data == b"hot bytes"
    assert s.needle_cache.counters.hits == hits0 + 1
    # event-loop peek: hit without touching disk
    assert s.cached_needle(1, 7, 7 ^ 0x5A).data == b"hot bytes"
    # wrong cookie never served from cache
    assert s.cached_needle(1, 7, 0xBAD) is None


def test_needle_cache_invalidated_on_overwrite_and_delete(cached_store):
    from seaweedfs_tpu.storage.volume import AlreadyDeleted
    s = cached_store
    s.write_needle(1, _needle(7, b"v1"))
    assert s.read_needle(1, 7).data == b"v1"       # populates
    s.write_needle(1, _needle(7, b"v2 new bytes"))
    assert s.read_needle(1, 7).data == b"v2 new bytes"  # never v1
    s.delete_needle(1, _needle(7, b""))
    with pytest.raises(AlreadyDeleted):
        s.read_needle(1, 7)
    assert s.cached_needle(1, 7) is None


def test_needle_cache_misses_after_vacuum(cached_store):
    from seaweedfs_tpu.storage import vacuum
    s = cached_store
    for i in range(1, 11):
        s.write_needle(1, _needle(i, b"data-%d" % i * 20))
    for i in range(1, 6):
        s.delete_needle(1, _needle(i, b""))
    survivor = s.read_needle(1, 8)                 # cached now
    assert s.needle_cache._lru.peek_contains((1, 8))
    v = s.volumes[1]
    vacuum.compact(v)
    s.commit_compaction(1)
    # the swap moved every offset: cached entries MUST be gone
    assert not s.needle_cache._lru.peek_contains((1, 8))
    misses0 = s.needle_cache.counters.misses
    again = s.read_needle(1, 8)
    assert s.needle_cache.counters.misses == misses0 + 1
    assert again.data == survivor.data


def test_cached_needle_declines_when_read_failpoint_armed(cached_store):
    from seaweedfs_tpu.util import failpoints
    s = cached_store
    s.write_needle(1, _needle(3, b"x"))
    s.read_needle(1, 3)
    assert s.cached_needle(1, 3) is not None
    failpoints.arm("store.read", "error:1")
    try:
        # armed chaos site: the peek must decline so the injected
        # fault actually fires on the slow path
        assert s.cached_needle(1, 3) is None
        with pytest.raises(failpoints.FailpointError):
            s.read_needle(1, 3)
    finally:
        failpoints.reset()


# ---- client: negative lookup cache + lookup singleflight ----

def _client(monkeypatch, responses):
    """WeedClient whose master round trips come from a canned list;
    records the number of real master calls."""
    from seaweedfs_tpu.util.client import WeedClient
    c = WeedClient("127.0.0.1:0", negative_lookup_ttl=0.2)
    calls = []

    async def fake_master_get(path, params):
        calls.append((path, dict(params)))
        return responses[min(len(calls) - 1, len(responses) - 1)]

    monkeypatch.setattr(c, "_master_get", fake_master_get)
    return c, calls


def test_negative_lookup_cache(monkeypatch):
    from seaweedfs_tpu.util.client import OperationError
    c, calls = _client(monkeypatch, [{"error": "volume 9 not found"}])

    async def main():
        for _ in range(5):
            with pytest.raises(OperationError):
                await c.lookup("9")
        assert len(calls) == 1          # 4 of 5 served from the neg cache
        assert c._neg_counters.hits == 4
        await asyncio.sleep(0.25)       # TTL expiry: master asked again
        with pytest.raises(OperationError):
            await c.lookup("9")
        assert len(calls) == 2

    asyncio.run(main())


def test_negative_lookup_invalidated_on_assign(monkeypatch):
    from seaweedfs_tpu.util.client import OperationError
    c, calls = _client(monkeypatch, [
        {"error": "volume 3 not found"},
        {"fid": "3,01637037d6", "url": "h:1", "publicUrl": "h:1",
         "count": 1},
        {"locations": [{"url": "h:1", "publicUrl": "h:1"}]},
    ])

    async def main():
        with pytest.raises(OperationError):
            await c.lookup("3")
        assert "3" in c._neg_vids
        await c.assign()                # grew volume 3: entry dropped
        assert "3" not in c._neg_vids
        locs = await c.lookup("3")      # hits the master, not the cache
        assert locs and len(calls) == 3

    asyncio.run(main())


def test_lookup_singleflight(monkeypatch):
    from seaweedfs_tpu.util.client import WeedClient
    c = WeedClient("127.0.0.1:0")
    calls = 0

    async def fake_master_get(path, params):
        nonlocal calls
        calls += 1
        await asyncio.sleep(0.02)
        return {"locations": [{"url": "h:1", "publicUrl": "h:1"}]}

    monkeypatch.setattr(c, "_master_get", fake_master_get)

    async def main():
        locs = await asyncio.gather(*(c.lookup("5") for _ in range(8)))
        assert all(l == locs[0] for l in locs)
        assert calls == 1

    asyncio.run(main())


# ---- client chunk cache ----

def test_chunk_bytes_cached_and_collapsed(monkeypatch):
    from seaweedfs_tpu.util.client import WeedClient
    cc = TieredChunkCache(1 << 20)
    c = WeedClient("127.0.0.1:0", chunk_cache=cc)
    fetches = 0

    async def fake_net(fid, offset=0, size=-1):
        nonlocal fetches
        fetches += 1
        await asyncio.sleep(0.01)
        yield b"chunk-"
        yield b"bytes"

    monkeypatch.setattr(c, "_read_stream_net", fake_net)

    async def main():
        out = await asyncio.gather(*(c.chunk_bytes("1,ab") for _ in
                                     range(6)))
        assert out == [b"chunk-bytes"] * 6
        assert fetches == 1             # singleflight collapsed the fan-in
        assert await c.read("1,ab") == b"chunk-bytes"
        assert fetches == 1             # whole-read served from cache
        # ranged read_stream slices the cached body without the network
        got = b"".join([p async for p in c.read_stream("1,ab", 6, 5)])
        assert got == b"bytes" and fetches == 1

    asyncio.run(main())


def test_stream_chunk_views_rides_chunk_cache():
    from seaweedfs_tpu.filer.filechunks import FileChunk
    from seaweedfs_tpu.filer.stream import stream_chunk_views

    class StubClient:
        def __init__(self):
            self.chunk_cache = TieredChunkCache(1 << 20)
            self.fetches = 0

        async def chunk_bytes(self, fid, size=-1):
            data = self.chunk_cache.get(fid)
            if data is not None:
                return data
            self.fetches += 1
            data = bytes((ord(fid[0]) + i) % 256 for i in range(size))
            self.chunk_cache.set(fid, data)
            return data

        async def read_stream(self, fid, offset, size):
            raise AssertionError("cacheable chunk must not stream")

    client = StubClient()
    chunks = [FileChunk("a,1", 0, 1000, 1), FileChunk("b,2", 1000, 500, 2)]

    async def main():
        one = b"".join([p async for p in
                        stream_chunk_views(client, chunks, 0, 1500)])
        two = b"".join([p async for p in
                        stream_chunk_views(client, chunks, 0, 1500)])
        assert one == two and len(one) == 1500
        assert client.fetches == 2      # second pass fully cache-served
        # ranged read served as slices of the cached chunks
        part = b"".join([p async for p in
                         stream_chunk_views(client, chunks, 900, 200)])
        assert part == one[900:1100]
        assert client.fetches == 2

    asyncio.run(main())


# ---- EC degraded-read reconstruction cache ----

def test_ec_recover_cache_reuses_reconstruction(tmp_path, monkeypatch):
    from seaweedfs_tpu.ec import ec_volume as ecv
    from seaweedfs_tpu.ec import pipeline as pl
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.volume import Volume

    d = str(tmp_path)
    v = Volume(d, "", 5)
    rng = random.Random(11)
    contents = {}
    for i in range(1, 30):
        data = rng.randbytes(rng.randint(100, 3000))
        v.write_needle(Needle(cookie=i ^ 0x5A, id=i, data=data))
        contents[i] = data
    v.close()
    base = os.path.join(d, "5")
    enc = pl.get_encoder("cpu")
    pl.write_ec_files(base, encoder=enc, large_block=16 * 1024,
                      small_block=1024, buffer_size=1024)
    pl.write_sorted_file_from_idx(base)

    decodes = 0
    real = ecv._transform_buffers

    def counting(*a, **kw):
        nonlocal decodes
        decodes += 1
        return real(*a, **kw)

    monkeypatch.setattr(ecv, "_transform_buffers", counting)
    cache = LruByteCache(8 << 20, name="ec_recover_test")
    ev = ecv.EcVolume(d, "", 5, large_block=16 * 1024, small_block=1024,
                      encoder=enc, recover_cache=cache)
    ev.shards.pop(0).close()            # lose a data shard
    nid = next(iter(contents))
    first = ev.read_needle(nid)
    assert first.data == contents[nid]
    assert decodes > 0
    after_first = decodes
    second = ev.read_needle(nid)        # hot interval: decoder NOT re-run
    assert second.data == contents[nid]
    assert decodes == after_first
    assert cache.counters.hits > 0
    ev.close()


# ---- race regressions (code-review findings) ----

def test_needle_cache_refuses_fill_racing_a_write(cached_store):
    """A reader that fetched old bytes from disk must NOT re-populate
    the cache after a writer's invalidation (generation fencing)."""
    s = cached_store
    s.write_needle(1, _needle(9, b"old bytes"))
    nc = s.needle_cache
    gen = nc.generation(1)                 # reader snapshots...
    old = s.volumes[1].read_needle(9)      # ...and reads from disk
    s.write_needle(1, _needle(9, b"new bytes!"))   # racing write lands
    nc.put(1, 9, old, gen=gen)             # stale fill must be refused
    hit = s.cached_needle(1, 9)
    assert hit is None or hit.data == b"new bytes!"
    assert s.read_needle(1, 9).data == b"new bytes!"


def test_chunk_bytes_refuses_fill_racing_an_overwrite(monkeypatch):
    """upload()'s invalidation mid-fetch must win over the in-flight
    fetch's set() (TieredChunkCache.gen fencing)."""
    from seaweedfs_tpu.util.client import WeedClient
    cc = TieredChunkCache(1 << 20)
    c = WeedClient("127.0.0.1:0", chunk_cache=cc)

    async def fake_net(fid, offset=0, size=-1):
        yield b"old body"
        cc.delete(fid)      # what a concurrent upload(fid) does

    monkeypatch.setattr(c, "_read_stream_net", fake_net)

    async def main():
        assert await c.chunk_bytes("1,x") == b"old body"
        assert cc.get("1,x") is None       # stale blob NOT re-pinned

    asyncio.run(main())


def test_singleflight_leader_cancel_spares_followers():
    sf = SingleFlight()

    async def fn():
        await asyncio.sleep(0.05)
        return "shared"

    async def main():
        leader = asyncio.create_task(sf.do("k", fn))
        await asyncio.sleep(0.01)
        follower = asyncio.create_task(sf.do("k", fn))
        await asyncio.sleep(0.01)
        leader.cancel()                    # e.g. its client disconnected
        assert await follower == "shared"  # the round still completes

    asyncio.run(main())


def test_ec_recover_cache_dropped_on_ec_unmount(tmp_path):
    from seaweedfs_tpu.storage.store import Store
    s = Store([str(tmp_path / "v")], needle_cache_bytes=1 << 20)
    s.ec_recover_cache.put((5, 0, 0, 10), b"x" * 10, 10)
    s.ec_recover_cache.put((6, 0, 0, 10), b"y" * 10, 10)
    s.unmount_ec_shards(5)
    assert s.ec_recover_cache.get((5, 0, 0, 10)) is None
    assert s.ec_recover_cache.get((6, 0, 0, 10)) == b"y" * 10
    s.close()


def test_negative_lookup_cache_bounded(monkeypatch):
    from seaweedfs_tpu.util.client import OperationError, WeedClient
    c = WeedClient("127.0.0.1:0", negative_lookup_ttl=60.0)

    async def fake_master_get(path, params):
        return {"error": "not found"}

    monkeypatch.setattr(c, "_master_get", fake_master_get)

    async def main():
        for vid in range(1500):
            with pytest.raises(OperationError):
                await c.lookup(str(vid))
        assert len(c._neg_vids) <= 1024

    asyncio.run(main())


def test_upload_drops_chunk_entry_after_success_too(monkeypatch):
    """A chunk_bytes fetch that read the OLD body during upload's POST
    round trip must not leave it pinned: upload drops the entry (and
    bumps gen) again after the write succeeds."""
    from seaweedfs_tpu.util.client import WeedClient
    cc = TieredChunkCache(1 << 20)
    c = WeedClient("127.0.0.1:0", chunk_cache=cc)
    cc.set("1,x", b"fetched during the POST rtt")   # the racing fill

    class FakeResp:
        status = 201

        async def json(self):
            return {"size": 3}

        async def __aenter__(self):
            return self

        async def __aexit__(self, *a):
            return False

    class FakeSession:
        def post(self, *a, **kw):
            return FakeResp()

    c._session = FakeSession()

    async def main():
        await c.upload("1,x", "h:1", b"new")
        assert cc.get("1,x") is None    # stale fill dropped post-write

    asyncio.run(main())


def test_needle_cache_guard_atomic_with_insert(cached_store):
    """The gen check runs under the LRU lock: a bump-and-delete that
    completes entirely between an outside check and the insert cannot
    happen, and a bump landing after the guarded insert still removes
    the entry via the invalidator's queued delete."""
    s = cached_store
    nc = s.needle_cache
    s.write_needle(1, _needle(4, b"old"))
    gen = nc.generation(1)
    old = s.volumes[1].read_needle(4)
    nc.invalidate(1, 4)                 # racing write's bump+delete
    nc.put(1, 4, old, gen=gen)
    assert not nc._lru.peek_contains((1, 4))


def test_cache_dir_exclusive_lock(tmp_path):
    a = TieredChunkCache(1 << 20, disk_dir=str(tmp_path / "d"))
    with pytest.raises(RuntimeError, match="already in use"):
        TieredChunkCache(1 << 20, disk_dir=str(tmp_path / "d"))
    a.close()
    # released on close; a stale lock from a dead pid is also taken over
    b = TieredChunkCache(1 << 20, disk_dir=str(tmp_path / "d"))
    b.close()


def test_stream_cold_small_range_stays_ranged():
    """A cold small range of a big chunk must NOT pull the whole chunk
    through the cache (bandwidth amplification); once the chunk is
    resident, ranges slice it for free."""
    from seaweedfs_tpu.filer.filechunks import FileChunk
    from seaweedfs_tpu.filer.stream import stream_chunk_views

    class StubClient:
        def __init__(self):
            self.chunk_cache = TieredChunkCache(1 << 20)
            self.whole_fetches = 0
            self.ranged = 0

        def _body(self, fid, size):
            return bytes((ord(fid[0]) + i) % 256 for i in range(size))

        async def chunk_bytes(self, fid, size=-1):
            data = self.chunk_cache.get(fid)
            if data is None:
                self.whole_fetches += 1
                data = self._body(fid, size)
                self.chunk_cache.set(fid, data)
            return data

        async def read_stream(self, fid, offset, size):
            self.ranged += 1
            yield self._body(fid, 4000)[offset:offset + size]

    client = StubClient()
    chunks = [FileChunk("a,1", 0, 4000, 1)]

    async def main():
        # cold 100B of a 4000B chunk: ranged, no whole-chunk pull
        p1 = b"".join([x async for x in
                       stream_chunk_views(client, chunks, 50, 100)])
        assert client.ranged == 1 and client.whole_fetches == 0
        # big view (>= half): whole-chunk path warms the cache
        full = b"".join([x async for x in
                         stream_chunk_views(client, chunks, 0, 4000)])
        assert client.whole_fetches == 1
        # now resident: the same small range slices the cached chunk
        p2 = b"".join([x async for x in
                       stream_chunk_views(client, chunks, 50, 100)])
        assert client.ranged == 1 and client.whole_fetches == 1
        assert p1 == p2 == full[50:150]

    asyncio.run(main())


def test_fill_tokens_are_per_fid():
    """An unrelated fid's invalidation must NOT suppress this fid's
    fill (a global counter would zero the hit rate under mixed
    write/read load), while the same fid's invalidation must."""
    cc = TieredChunkCache(1 << 20)
    tok = cc.fill_token("a,1")
    cc.delete("b,2")                    # unrelated write traffic
    assert cc.set_if("a,1", b"mine", tok)
    assert cc.get("a,1") == b"mine"
    tok2 = cc.fill_token("a,1")
    cc.delete("a,1")                    # same-fid overwrite
    assert not cc.set_if("a,1", b"stale", tok2)
    assert cc.get("a,1") is None


def test_fill_token_epoch_sweep_is_conservative():
    cc = TieredChunkCache(1 << 20)
    tok = cc.fill_token("x")
    cc.delete("x")
    for i in range(5000):               # overflow the gen table
        cc.delete(f"fid-{i}")
    # the sweep forgot x's counter, but the epoch moved: still refused
    assert not cc.set_if("x", b"stale", tok)


def test_post_write_reader_never_joins_stale_round(monkeypatch):
    """A reader arriving AFTER upload() invalidated the cache must
    start a fresh fetch, not join the in-flight pre-write round."""
    from seaweedfs_tpu.util.client import WeedClient
    cc = TieredChunkCache(1 << 20)
    c = WeedClient("127.0.0.1:0", chunk_cache=cc)
    gate = asyncio.Event()
    bodies = iter([b"old", b"new"])
    fetches = 0

    async def fake_net(fid, offset=0, size=-1):
        nonlocal fetches
        fetches += 1
        await gate.wait()
        yield next(bodies)

    monkeypatch.setattr(c, "_read_stream_net", fake_net)

    async def main():
        t_old = asyncio.create_task(c.chunk_bytes("1,f"))
        await asyncio.sleep(0.01)       # old round in flight
        cc.delete("1,f")                # what upload() does on ack
        t_new = asyncio.create_task(c.chunk_bytes("1,f"))
        await asyncio.sleep(0.01)
        gate.set()
        old, new = await asyncio.gather(t_old, t_new)
        assert old == b"old" and new == b"new"
        assert fetches == 2             # post-write reader re-fetched
        assert cc.get("1,f") == b"new"  # only the fresh fill landed

    asyncio.run(main())


def test_needle_cache_unservable_entry_not_a_hit(cached_store):
    s = cached_store
    s.write_needle(1, _needle(6, b"x"))
    s.read_needle(1, 6)                 # populate
    h0, m0 = (s.needle_cache.counters.hits, s.needle_cache.counters.misses)
    assert s.cached_needle(1, 6, 0xBAD) is None   # wrong cookie
    assert s.needle_cache.counters.hits == h0     # NOT a hit
    assert s.needle_cache.counters.misses == m0   # peek defers the miss


def test_ec_recover_fill_fenced_against_remount():
    from seaweedfs_tpu.util.chunk_cache import EcRecoverCache
    rc = EcRecoverCache(1 << 20)
    gen = rc.generation(5)
    rc.drop_volume(5)           # re-encode/remount raced the gather
    rc.put_fenced((5, 0, 0, 4), b"old!", gen)
    assert rc.get((5, 0, 0, 4)) is None
    rc.put_fenced((5, 0, 0, 4), b"new!", rc.generation(5))
    assert rc.get((5, 0, 0, 4)) == b"new!"


def test_cache_mem_budget_is_total(tmp_path):
    """-cache.mem is the TOTAL volume-side budget: needle 3/4 + EC 1/4,
    never more than the flag."""
    from seaweedfs_tpu.storage.store import Store
    s = Store([str(tmp_path / "v")], needle_cache_bytes=16 << 20)
    assert (s.needle_cache._lru.budget
            + s.ec_recover_cache.budget) == 16 << 20
    s.close()


def test_aio_detach_survives_caller_and_consumes_exception():
    """util.aio.detach is the one sanctioned detachment spelling: the
    handle is retained until the task settles, cancelling the caller
    does not cancel the work, and a terminal exception is consumed
    even when no awaiter ever looks at it."""
    from seaweedfs_tpu.util import aio

    async def main():
        ran = []

        async def work():
            await asyncio.sleep(0.02)
            ran.append(True)
            return "done"

        async def caller():
            t = aio.detach(work())
            assert aio.detached_count() >= 1
            await asyncio.sleep(1)           # cancelled long before

        c = asyncio.create_task(caller())
        await asyncio.sleep(0.005)
        c.cancel()                           # caller dies...
        await asyncio.sleep(0.05)
        assert ran == [True]                 # ...the work does not
        assert aio.detached_count() == 0     # handle released on settle

        async def boom():
            raise ValueError("nobody awaits me")

        aio.detach(boom())
        await asyncio.sleep(0.01)            # settles; exception is
        assert aio.detached_count() == 0     # consumed, not logged

    asyncio.run(main())
