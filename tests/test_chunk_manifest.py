"""Chunk-manifest files on raw volumes (no filer): auto-split upload,
manifest-resolved reads (full + ranged), cascading delete.

Reference: operation/submit.go:112-199, chunked_file.go,
volume_server_handlers_read.go:170-199.
"""

import os

from cluster_util import Cluster, run

from seaweedfs_tpu.util.chunked import (ChunkInfo, ChunkManifest,
                                        upload_in_chunks)
from seaweedfs_tpu.util.client import WeedClient


def test_manifest_marshal_load_resolve():
    cm = ChunkManifest(name="f.bin", mime="application/x-thing", size=25,
                       chunks=[ChunkInfo("1,02", 10, 10),
                               ChunkInfo("1,01", 0, 10),
                               ChunkInfo("1,03", 20, 5)])
    back = ChunkManifest.load(cm.marshal())
    assert back.size == 25 and back.name == "f.bin"
    assert [c.fid for c in back.chunks] == ["1,01", "1,02", "1,03"]  # sorted
    # range resolution straddling chunk boundaries
    pieces = back.resolve(5, 12)
    assert pieces == [("1,01", 5, 5, 5), ("1,02", 0, 7, 10)]
    assert back.resolve(0, 25)[-1] == ("1,03", 0, 5, 20)
    # gzip-aware load (LoadChunkManifest)
    import gzip
    assert ChunkManifest.load(gzip.compress(cm.marshal()),
                              is_gzipped=True).size == 25


def test_chunked_upload_read_range_delete(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=2) as c:
            blob = os.urandom(300_000)
            async with WeedClient(c.master.url, session=c.http) as wc:
                fid, cm = await upload_in_chunks(
                    wc, blob, max_mb=1, name="big.bin",
                    mime="application/x-big")
                assert len(cm.chunks) == 1  # 300KB fits one 1MB chunk

                url = await wc.lookup_file_id(fid)
                # full read resolves the manifest transparently
                async with c.http.get(url) as resp:
                    assert resp.status == 200
                    assert resp.content_type == "application/x-big"
                    assert await resp.read() == blob

                # cm=false returns the raw manifest JSON
                async with c.http.get(url, params={"cm": "false"}) as resp:
                    body_ = await resp.read()
                    assert b'"chunks"' in body_

                # manifest fid reports the LOGICAL size on HEAD
                async with c.http.head(url) as resp:
                    assert int(resp.headers["Content-Length"]) == len(blob)
    run(body())


def test_chunked_multichunk_range_and_cascade_delete(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=2) as c:
            blob = os.urandom(3 * 1024 * 1024 + 12345)  # 4 chunks at 1MB
            async with WeedClient(c.master.url, session=c.http) as wc:
                fid, cm = await upload_in_chunks(
                    wc, blob, max_mb=1, name="huge.bin")
                assert len(cm.chunks) == 4
                url = await wc.lookup_file_id(fid)

                async with c.http.get(url) as resp:
                    assert await resp.read() == blob

                # ranged read straddling a chunk boundary
                lo, ln = 1024 * 1024 - 100, 200
                async with c.http.get(
                        url, headers={"Range":
                                      f"bytes={lo}-{lo + ln - 1}"}) as resp:
                    assert resp.status == 206
                    assert await resp.read() == blob[lo:lo + ln]
                # suffix range
                async with c.http.get(
                        url, headers={"Range": "bytes=-50"}) as resp:
                    assert resp.status == 206
                    assert await resp.read() == blob[-50:]

                # deleting the manifest cascades to every chunk
                chunk_fids = [ch.fid for ch in cm.chunks]
                async with c.http.delete(url) as resp:
                    assert resp.status == 200
                for cf in chunk_fids:
                    curl = await wc.lookup_file_id(cf)
                    async with c.http.get(
                            curl, params={"cm": "false"}) as resp:
                        assert resp.status == 404, cf
    run(body())
