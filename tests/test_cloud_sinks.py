"""Fake-driver contract tests for the GCS/Azure/B2 replication sinks.

A real in-proc source cluster feeds chunk bytes; the cloud drivers are
replaced by fakes exposing the exact client surface gcs_sink.go /
azure_sink.go / b2_sink.go use, so the full create/update/delete logic
executes in CI.
"""

import os

from cluster_util import Cluster, run

from seaweedfs_tpu.notification.queues import SqliteQueue, attach_to_filer
from seaweedfs_tpu.replication.cloud_sinks import (AzureSink, B2Sink,
                                                   GcsSink)
from seaweedfs_tpu.replication.replicator import Replicator
from seaweedfs_tpu.replication.runner import replicate_from_queue
from seaweedfs_tpu.replication.sink import SINKS
from seaweedfs_tpu.replication.source import FilerSource


# ---- fake drivers ---------------------------------------------------------


class FakeGcsBlob:
    def __init__(self, bucket, name):
        self.bucket, self.name = bucket, name

    def upload_from_string(self, data):
        self.bucket.objects[self.name] = (
            data.encode() if isinstance(data, str) else bytes(data))

    def delete(self):
        if self.name not in self.bucket.objects:
            raise KeyError(self.name)
        del self.bucket.objects[self.name]


class FakeGcsBucket:
    def __init__(self):
        self.objects = {}

    def blob(self, name):
        return FakeGcsBlob(self, name)


class FakeGcsClient:
    def __init__(self):
        self.buckets = {}

    def bucket(self, name):
        return self.buckets.setdefault(name, FakeGcsBucket())


class FakeAzureContainer:
    def __init__(self):
        self.blobs = {}

    def upload_blob(self, name, data, overwrite=False):
        if name in self.blobs and not overwrite:
            raise ValueError("exists")
        self.blobs[name] = bytes(data)

    def delete_blob(self, name):
        del self.blobs[name]


class FakeAzureServiceClient:
    def __init__(self):
        self.containers = {}

    def get_container_client(self, name):
        return self.containers.setdefault(name, FakeAzureContainer())


class _B2Version:
    def __init__(self, id_, name):
        self.id_, self.file_name = id_, name


class FakeB2Bucket:
    def __init__(self, api):
        self.api = api
        self.files = {}
        self._next = 0

    def upload_bytes(self, data, name):
        self._next += 1
        self.files[name] = (f"v{self._next}", bytes(data))

    def list_file_versions(self, prefix):
        for name, (vid, _) in list(self.files.items()):
            if name.startswith(prefix):
                yield _B2Version(vid, name), None


class FakeB2Api:
    def __init__(self):
        self.bucket = FakeB2Bucket(self)

    def get_bucket_by_name(self, name):
        return self.bucket

    def delete_file_version(self, id_, name):
        self.bucket.files.pop(name, None)


# ---- the shared contract scenario ----------------------------------------


def _drive_sink(tmp_path, sink, fetch, absent):
    """create -> overwrite -> delete through the replicator runner, then
    assert the fake cloud store saw the right objects."""
    async def body():
        c = Cluster(str(tmp_path / "src"), n_servers=1)
        c.with_filer = True
        async with c:
            queue = SqliteQueue(str(tmp_path / "q.db"))
            attach_to_filer(c.filer.filer, queue)

            blob = os.urandom(300 * 1024)  # multi-chunk at 256KB
            async with c.http.post(f"http://{c.filer.url}/docs/x.bin",
                                   data=blob) as r:
                assert r.status == 201
            async with c.http.post(f"http://{c.filer.url}/docs/y.txt",
                                   data=b"first") as r:
                assert r.status == 201
            async with c.http.post(f"http://{c.filer.url}/docs/y.txt",
                                   data=b"second!") as r:
                assert r.status == 201
            async with c.http.delete(
                    f"http://{c.filer.url}/docs/x.bin") as r:
                assert r.status == 204

            async with FilerSource(c.master.url, "/docs") as src:
                await sink.start()
                n = await replicate_from_queue(
                    queue, Replicator(src, sink),
                    str(tmp_path / "p.json"), once=True)
                await sink.close()
            assert n >= 4
            assert fetch("y.txt") == b"second!"
            assert absent("x.bin")
            queue.close()
    run(body())


def test_gcs_sink_contract(tmp_path):
    fake = FakeGcsClient()
    sink = GcsSink("bkt", client=fake)
    _drive_sink(tmp_path, sink,
                fetch=lambda k: fake.buckets["bkt"].objects.get(k),
                absent=lambda k: k not in fake.buckets["bkt"].objects)


def test_azure_sink_contract(tmp_path):
    fake = FakeAzureServiceClient()
    sink = AzureSink("ctr", client=fake)
    _drive_sink(tmp_path, sink,
                fetch=lambda k: fake.containers["ctr"].blobs.get(k),
                absent=lambda k: k not in fake.containers["ctr"].blobs)


def test_b2_sink_contract(tmp_path):
    fake = FakeB2Api()
    sink = B2Sink("bkt", client=fake)
    _drive_sink(
        tmp_path, sink,
        fetch=lambda k: (fake.bucket.files.get(k) or (None, None))[1],
        absent=lambda k: k not in fake.bucket.files)


def test_sink_registry_has_cloud_sinks():
    assert SINKS["google_cloud_storage"] is GcsSink
    assert SINKS["azure"] is AzureSink
    assert SINKS["backblaze"] is B2Sink
