"""Cluster integration tests: assign/write/read/delete, replication,
redirects, topology — against a real in-proc master + volume servers.

These exercise the distributed paths the reference leaves untested
(SURVEY.md §4): heartbeat-driven topology sync, on-demand volume growth,
replica fan-out.
"""

import asyncio

from cluster_util import Cluster, run


def test_assign_write_read_delete(tmp_path):
    async def body():
        async with Cluster(str(tmp_path)) as c:
            a = await c.assign()
            assert "fid" in a, a
            st, r = await c.put(a["fid"], a["url"], b"hello cluster")
            assert st == 201, r
            st, data = await c.get(a["fid"], a["publicUrl"])
            assert st == 200 and data == b"hello cluster"
            # wrong cookie -> 404
            vid, rest = a["fid"].split(",")
            bad = f"{vid},{rest[:-8]}{'0'*8}"
            st, _ = await c.get(bad, a["publicUrl"])
            assert st == 404
            assert await c.delete(a["fid"], a["url"]) == 200
            st, _ = await c.get(a["fid"], a["publicUrl"])
            assert st == 404
    run(body())


def test_topology_status(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=3,
                           racks=[("dc1", "r1"), ("dc1", "r2"),
                                  ("dc2", "r1")]) as c:
            async with c.http.get(
                    f"http://{c.master.url}/dir/status") as resp:
                topo = (await resp.json())["topology"]
            dcs = {d["id"] for d in topo["datacenters"]}
            assert dcs == {"dc1", "dc2"}
            n_nodes = sum(len(r["nodes"]) for d in topo["datacenters"]
                          for r in d["racks"])
            assert n_nodes == 3
    run(body())


def test_replication_001(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=3) as c:
            a = await c.assign(replication="001")
            assert "fid" in a, a
            st, _ = await c.put(a["fid"], a["url"], b"replicated!")
            assert st == 201
            await c.heartbeat_all()
            # find the two servers holding the volume
            vid = int(a["fid"].split(",")[0])
            holders = [vs for vs in c.servers
                       if vid in vs.store.volumes]
            assert len(holders) == 2
            for vs in holders:
                n = vs.store.read_needle(
                    vid, int(a["fid"].split(",")[1][:-8], 16))
                assert n.data == b"replicated!"
            # delete propagates to both replicas
            assert await c.delete(a["fid"], a["url"]) == 200
            for vs in holders:
                st, _ = await c.get(a["fid"], vs.url)
                assert st == 404
    run(body())


def test_read_redirect_from_wrong_server(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=2) as c:
            a = await c.assign()
            st, _ = await c.put(a["fid"], a["url"], b"redirect me")
            assert st == 201
            await c.heartbeat_all()
            other = next(vs for vs in c.servers if vs.url != a["url"])
            st, data = await c.get(a["fid"], other.url)  # follows 301
            assert st == 200 and data == b"redirect me"
    run(body())


def test_lookup_and_growth(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            a = await c.assign(collection="photos")
            vid = a["fid"].split(",")[0]
            async with c.http.get(f"http://{c.master.url}/dir/lookup",
                                  params={"volumeId": vid}) as resp:
                locs = (await resp.json())["locations"]
            assert locs and locs[0]["url"] == a["url"]
            # unknown vid -> 404
            async with c.http.get(f"http://{c.master.url}/dir/lookup",
                                  params={"volumeId": "9999"}) as resp:
                assert resp.status == 404
    run(body())


def test_placement_rejects_impossible_replication(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=2) as c:
            # 2 servers in one rack cannot satisfy diff-DC replication
            a = await c.assign(replication="100")
            assert "error" in a
    run(body())


def test_sequencer_syncs_from_heartbeat(tmp_path):
    async def body():
        async with Cluster(str(tmp_path)) as c:
            a1 = await c.assign()
            await c.put(a1["fid"], a1["url"], b"x")
            key1 = int(a1["fid"].split(",")[1][:-8], 16)
            await c.heartbeat_all()
            a2 = await c.assign()
            key2 = int(a2["fid"].split(",")[1][:-8], 16)
            assert key2 > key1
    run(body())
