"""Cluster integration tests: assign/write/read/delete, replication,
redirects, topology — against a real in-proc master + volume servers.

These exercise the distributed paths the reference leaves untested
(SURVEY.md §4): heartbeat-driven topology sync, on-demand volume growth,
replica fan-out.
"""

import asyncio

from cluster_util import Cluster, run


def test_assign_write_read_delete(tmp_path):
    async def body():
        async with Cluster(str(tmp_path)) as c:
            a = await c.assign()
            assert "fid" in a, a
            st, r = await c.put(a["fid"], a["url"], b"hello cluster")
            assert st == 201, r
            st, data = await c.get(a["fid"], a["publicUrl"])
            assert st == 200 and data == b"hello cluster"
            # wrong cookie -> 404
            vid, rest = a["fid"].split(",")
            bad = f"{vid},{rest[:-8]}{'0'*8}"
            st, _ = await c.get(bad, a["publicUrl"])
            assert st == 404
            assert await c.delete(a["fid"], a["url"]) == 200
            st, _ = await c.get(a["fid"], a["publicUrl"])
            assert st == 404
    run(body())


def test_topology_status(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=3,
                           racks=[("dc1", "r1"), ("dc1", "r2"),
                                  ("dc2", "r1")]) as c:
            async with c.http.get(
                    f"http://{c.master.url}/dir/status") as resp:
                topo = (await resp.json())["topology"]
            dcs = {d["id"] for d in topo["datacenters"]}
            assert dcs == {"dc1", "dc2"}
            n_nodes = sum(len(r["nodes"]) for d in topo["datacenters"]
                          for r in d["racks"])
            assert n_nodes == 3
    run(body())


def test_replication_001(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=3) as c:
            a = await c.assign(replication="001")
            assert "fid" in a, a
            st, _ = await c.put(a["fid"], a["url"], b"replicated!")
            assert st == 201
            await c.heartbeat_all()
            # find the two servers holding the volume
            vid = int(a["fid"].split(",")[0])
            holders = [vs for vs in c.servers
                       if vid in vs.store.volumes]
            assert len(holders) == 2
            for vs in holders:
                n = vs.store.read_needle(
                    vid, int(a["fid"].split(",")[1][:-8], 16))
                assert n.data == b"replicated!"
            # delete propagates to both replicas
            assert await c.delete(a["fid"], a["url"]) == 200
            for vs in holders:
                st, _ = await c.get(a["fid"], vs.url)
                assert st == 404
    run(body())


def test_read_redirect_from_wrong_server(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=2) as c:
            a = await c.assign()
            st, _ = await c.put(a["fid"], a["url"], b"redirect me")
            assert st == 201
            await c.heartbeat_all()
            other = next(vs for vs in c.servers if vs.url != a["url"])
            st, data = await c.get(a["fid"], other.url)  # follows 301
            assert st == 200 and data == b"redirect me"
    run(body())


def test_lookup_and_growth(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            a = await c.assign(collection="photos")
            vid = a["fid"].split(",")[0]
            async with c.http.get(f"http://{c.master.url}/dir/lookup",
                                  params={"volumeId": vid}) as resp:
                locs = (await resp.json())["locations"]
            assert locs and locs[0]["url"] == a["url"]
            # unknown vid -> 404
            async with c.http.get(f"http://{c.master.url}/dir/lookup",
                                  params={"volumeId": "9999"}) as resp:
                assert resp.status == 404
    run(body())


def test_placement_rejects_impossible_replication(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=2) as c:
            # 2 servers in one rack cannot satisfy diff-DC replication
            a = await c.assign(replication="100")
            assert "error" in a
    run(body())


def test_sequencer_syncs_from_heartbeat(tmp_path):
    async def body():
        async with Cluster(str(tmp_path)) as c:
            a1 = await c.assign()
            await c.put(a1["fid"], a1["url"], b"x")
            key1 = int(a1["fid"].split(",")[1][:-8], 16)
            await c.heartbeat_all()
            a2 = await c.assign()
            key2 = int(a2["fid"].split(",")[1][:-8], 16)
            assert key2 > key1
    run(body())


def test_conditional_reads_304(tmp_path):
    """ETag + Last-Modified conditional GETs
    (volume_server_handlers_read.go:102-116)."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            a = await c.assign()
            st, _ = await c.put(a["fid"], a["url"], b"conditional-body")
            assert st == 201
            url = f"http://{a['url']}/{a['fid']}"
            async with c.http.get(url) as resp:
                assert resp.status == 200
                etag = resp.headers["Etag"]
                lm = resp.headers["Last-Modified"]
            async with c.http.get(
                    url, headers={"If-None-Match": etag}) as resp:
                assert resp.status == 304
                assert await resp.read() == b""
            async with c.http.get(
                    url, headers={"If-None-Match": '"deadbeef"'}) as resp:
                assert resp.status == 200
            async with c.http.get(
                    url, headers={"If-Modified-Since": lm}) as resp:
                assert resp.status == 304
            async with c.http.get(
                    url, headers={"If-Modified-Since":
                                  "Thu, 01 Jan 1970 00:00:00 GMT"}) as resp:
                assert resp.status == 200
            # garbage date: served normally, not an error
            async with c.http.get(
                    url, headers={"If-Modified-Since": "not-a-date"}) as resp:
                assert resp.status == 200
    run(body())


def test_pairs_headers_and_md5_etag(tmp_path):
    """Seaweed-* upload headers round-trip as needle pairs and come back
    as response headers (needle.go:19 PairNamePrefix,
    volume_server_handlers_read.go:117-132)."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            a = await c.assign()
            url = f"http://{a['url']}/{a['fid']}"
            async with c.http.post(
                    url, data=b"paired",
                    headers={"Seaweed-X-Trace": "t-123",
                             "Seaweed-Owner": "alice"}) as resp:
                assert resp.status == 201, await resp.text()
            async with c.http.get(url) as resp:
                assert resp.status == 200
                assert resp.headers["Seaweed-X-Trace"] == "t-123"
                assert resp.headers["Seaweed-Owner"] == "alice"
                crc_etag = resp.headers["Etag"]
            import hashlib
            async with c.http.get(
                    url, headers={"ETag-MD5": "True"}) as resp:
                md5 = hashlib.md5(b"paired").hexdigest()
                assert resp.headers["Etag"] == f'"{md5}"'
                assert resp.headers["Etag"] != crc_etag

            # lowercase prefix counts (Go canonicalizes header casing)
            a2 = await c.assign()
            url2 = f"http://{a2['url']}/{a2['fid']}"
            async with c.http.post(
                    url2, data=b"x",
                    headers={"seaweed-lower": "yes"}) as resp:
                assert resp.status == 201
            async with c.http.get(url2) as resp:
                assert resp.headers["Seaweed-Lower"] == "yes"

            # >64KB of pair headers: clean 400, not an unhandled 500
            a3 = await c.assign()
            async with c.http.post(
                    f"http://{a3['url']}/{a3['fid']}", data=b"x",
                    headers={f"Seaweed-K{i}": "v" * 7000
                             for i in range(10)}) as resp:
                assert resp.status == 400
                assert "pairs" in (await resp.json())["error"]
    run(body())


def test_filename_disposition_and_mime_guess(tmp_path):
    """Needle-name-derived Content-Disposition / mime, ?dl=true download
    (writeResponseContent, volume_server_handlers_read.go:229-248)."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            import aiohttp
            a = await c.assign()
            url = f"http://{a['url']}/{a['fid']}"
            form = aiohttp.FormData()
            # no part content-type: mime must be guessed from the name
            form.add_field("file", b"<html></html>", filename="page.html")
            async with c.http.post(url, data=form) as resp:
                assert resp.status == 201, await resp.text()
            async with c.http.get(url) as resp:
                assert resp.status == 200
                assert resp.content_type == "text/html"
                assert resp.headers["Content-Disposition"] == \
                    'inline; filename="page.html"'
            async with c.http.get(url + "?dl=true") as resp:
                assert resp.headers["Content-Disposition"].startswith(
                    "attachment;")
    run(body())


def test_batch_delete_endpoint(tmp_path):
    """Server-side batch tombstone with per-fid results
    (volume_grpc_batch_delete.go:13-75)."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            fids = []
            for i in range(3):
                a = await c.assign()
                st, _ = await c.put(a["fid"], a["url"], b"bd-%d" % i)
                assert st == 201
                fids.append((a["fid"], a["url"]))
            url = fids[0][1]
            gone = fids[0][0].split(",")[0] + ",ffffff00000000"
            async with c.http.post(
                    f"http://{url}/admin/batch_delete",
                    json={"fileIds": [f for f, _ in fids]
                          + ["not-a-fid", gone]}) as resp:
                assert resp.status == 200
                results = (await resp.json())["results"]
            by_fid = {r["fileId"]: r for r in results}
            for f, _ in fids:
                assert by_fid[f]["status"] == 202
                assert by_fid[f]["size"] > 0
            assert by_fid["not-a-fid"]["status"] == 400
            assert by_fid[gone]["status"] == 404
            for f, u in fids:
                st, _ = await c.get(f, u)
                assert st == 404
    run(body())
