"""Device CRC32C (GF(2)-matmul formulation) vs the host oracle.

The host path is itself fixture-proven against the reference's stored
checksums (test_interop_fixture reads the Go-written .dat), so equality
here chains to the reference's klauspost/crc32 (needle/crc.go:11-25)."""

from __future__ import annotations

import numpy as np
import pytest

from seaweedfs_tpu.ops.crc32c_jax import _pick_block, crc32c_batch
from seaweedfs_tpu.util.crc32c import crc32c, masked


@pytest.mark.parametrize("n", [1, 2, 7, 64, 255, 256, 1024, 4096, 12345])
def test_matches_host_oracle(n):
    rng = np.random.default_rng(n)
    data = rng.integers(0, 256, (4, n)).astype(np.uint8)
    got = np.asarray(crc32c_batch(data))
    want = np.array([crc32c(row.tobytes()) for row in data], np.uint32)
    assert np.array_equal(got, want)


def test_edge_patterns():
    # all-zeros, all-ones, single-bit messages: the affine constant and
    # every matrix column get exercised independently
    for row in (np.zeros(512, np.uint8),
                np.full(512, 0xFF, np.uint8),
                np.eye(1, 512, 0, dtype=np.uint8)[0] * 0x80):
        got = int(np.asarray(crc32c_batch(row[None, :]))[0])
        assert got == crc32c(row.tobytes())


def test_block_choice_is_irrelevant():
    rng = np.random.default_rng(9)
    data = rng.integers(0, 256, (2, 2048)).astype(np.uint8)
    want = np.asarray(crc32c_batch(data))
    for blk in (1, 2, 64, 256, 2048):
        assert np.array_equal(np.asarray(crc32c_batch(data, block=blk)),
                              want)
    assert _pick_block(2048) == 256
    assert _pick_block(12345) == 1


def test_masked_value_composes():
    # the needle footer stores the MASKED crc (crc.go Value()); device
    # raw crc + host masking must equal the host's stored value
    from seaweedfs_tpu.util.crc32c import checksum_value
    data = np.arange(300, dtype=np.uint8)[None, :]
    raw = int(np.asarray(crc32c_batch(data))[0])
    assert masked(raw) == checksum_value(data[0].tobytes())
