"""Degraded-read failover: the acknowledged copy of a replicated write
must stay readable when a holder dies — including a holder that dies
MID-BODY (the read resumes on the next replica via Range) — and the
client-side circuit breaker must be observed opening against the dead
upstream and recovering through half-open."""

import pytest

from seaweedfs_tpu.util import failpoints as fp
from seaweedfs_tpu.util.client import OperationError, WeedClient
from seaweedfs_tpu.util.resilience import BreakerRegistry, RetryPolicy

from cluster_util import Cluster, run


@pytest.fixture(autouse=True)
def _clean_registry():
    fp.reset()
    yield
    fp.reset()


async def _write_replicated(c: Cluster, data: bytes) -> tuple[str, list]:
    a = await c.assign(replication="001")
    assert "fid" in a, a
    st, _ = await c.put(a["fid"], a["url"], data)
    assert st == 201
    async with c.http.get(
            f"http://{c.master.url}/dir/lookup",
            params={"volumeId": a["fid"].split(",")[0]}) as r:
        locs = (await r.json())["locations"]
    assert len(locs) == 2, locs
    return a["fid"], locs


def _server_by_url(c: Cluster, url: str):
    for vs in c.servers:
        if vs.url == url:
            return vs
    raise AssertionError(f"no server {url}")


def test_holder_death_mid_replicated_write_leaves_ack_readable(tmp_path):
    """The regression the chaos soak generalizes: kill the PRIMARY
    holder (the server that acknowledged the write) and the read must
    fail over to the surviving replica location."""
    async def go():
        async with Cluster(str(tmp_path), n_servers=2) as c:
            data = b"ack-durability" * 1000
            fid, locs = await _write_replicated(c, data)
            # kill the first lookup location — the one a naive client
            # would dial first
            await _server_by_url(c, locs[0]["url"]).stop()
            async with WeedClient(c.master.url) as wc:
                got = await wc.read(fid)
                assert got == data
                # and the whole-file stream shape too
                got = b"".join([b async for b in wc.read_stream(
                    fid, 0, len(data))])
                assert got == data
    run(go())


def test_mid_stream_truncation_resumes_on_next_replica(tmp_path):
    """A holder that declares the full Content-Length, streams half
    the body and severs the socket (the `truncate` failpoint = a
    volume server dying mid-read) must not fail the read: the stream
    rotates to the other replica and resumes via Range."""
    async def go():
        async with Cluster(str(tmp_path), n_servers=2) as c:
            data = bytes(range(256)) * 2048       # 512 KiB, positional
            fid, _ = await _write_replicated(c, data)
            # one truncation: whichever holder serves first dies
            # mid-body; the registry is process-global so the count=1
            # guarantees the OTHER holder serves clean
            fp.arm("volume.read.http", "truncate=0.5:1")
            async with WeedClient(c.master.url) as wc:
                got = await wc.read(fid, offset=0, size=len(data))
            assert fp.pending("volume.read.http") is False  # it fired
            assert got == data                    # byte-exact despite cut
    run(go())


def test_breaker_opens_against_dead_holder_then_half_open_recovers(
        tmp_path):
    """Acceptance: the client-side circuit breaker is observed opening
    (dead upstream) and half-open-recovering (after reset_timeout a
    probe closes it again)."""
    async def go():
        async with Cluster(str(tmp_path), n_servers=2) as c:
            data = b"breaker-bytes" * 200
            fid, locs = await _write_replicated(c, data)
            dead_url = locs[0]["publicUrl"]
            await _server_by_url(c, locs[0]["url"]).stop()
            breakers = BreakerRegistry(threshold=2, reset_timeout=0.0)
            async with WeedClient(c.master.url,
                                  breakers=breakers) as wc:
                for _ in range(3):
                    assert await wc.read(fid) == data
                br = breakers.get(dead_url)
                # two+ connect failures against the dead holder: OPEN
                assert br.state == br.OPEN
                assert br.open_count >= 1
                # reads keep succeeding off the survivor; the dead
                # holder stays demoted (OPEN) but is never skipped
                failures_before = br.failures
                assert await wc.read(fid) == data
                assert br.state == br.OPEN
                assert br.failures >= failures_before
            # half-open RECOVERY against a live upstream
            live = breakers.get(locs[1]["publicUrl"])
            live.record_failure()
            live.record_failure()
            assert live.state == live.OPEN
            assert live.allow()                # reset_timeout=0: probe
            assert live.state == live.HALF_OPEN
            live.record_success()
            assert live.state == live.CLOSED
    run(go())


def test_all_holders_dead_raises_operation_error(tmp_path):
    async def go():
        async with Cluster(str(tmp_path), n_servers=2) as c:
            data = b"gone" * 100
            fid, _ = await _write_replicated(c, data)
            for vs in list(c.servers):
                await vs.stop()
            async with WeedClient(c.master.url, retry=RetryPolicy(
                    max_attempts=2, base_delay=0.01,
                    total_timeout=5.0)) as wc:
                with pytest.raises(OperationError):
                    await wc.read(fid)
    run(go())


def test_filer_stream_survives_mid_chunk_death(tmp_path):
    """The filer->volume streaming read path: a replica failing
    mid-chunk rotates instead of aborting the response."""
    async def go():
        cluster = Cluster(str(tmp_path), n_servers=2)
        cluster.with_filer = True
        cluster.filer_chunk_size = 64 * 1024
        async with cluster as c:
            c.filer.replication = "001"
            data = bytes(range(256)) * 1024      # 256 KiB, 4 chunks
            async with c.http.post(
                    f"http://{c.filer.url}/big.bin", data=data,
                    params={"replication": "001"}) as r:
                assert r.status == 201, await r.text()
            # every chunk read dies mid-body once; Range-resume must
            # reassemble the exact bytes
            fp.arm("volume.read.http", "truncate=0.5:4")
            async with c.http.get(
                    f"http://{c.filer.url}/big.bin") as r:
                assert r.status == 200
                got = await r.read()
            assert got == data
    run(go())
