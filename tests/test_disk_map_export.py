"""DiskNeedleMap (LevelDbNeedleMap analog), vacuum throttler, tar export."""

from __future__ import annotations

import io
import tarfile
import time

from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.needle_map import DiskNeedleMap, MemoryNeedleMap
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.vacuum import _Throttler
from seaweedfs_tpu.storage.volume import Volume


def test_disk_needle_map_matches_memory(tmp_path):
    ops = [("put", k, k * 8, 100 + k) for k in range(1, 200)]
    ops += [("del", k, 10_000 + k * 8) for k in range(1, 200, 5)]
    ops += [("put", k, 20_000 + k * 8, 77) for k in range(1, 200, 9)]

    def replay(cls, path):
        nm = cls(path)
        for op in ops:
            if op[0] == "put":
                nm.put(op[1], op[2], op[3])
            else:
                nm.delete(op[1], op[2])
        return nm

    a = replay(MemoryNeedleMap, str(tmp_path / "a.idx"))
    b = replay(DiskNeedleMap, str(tmp_path / "b.idx"))
    try:
        assert len(a) == len(b)
        assert (a.file_count, a.deleted_count, a.deleted_bytes,
                a.max_file_key) == (b.file_count, b.deleted_count,
                                    b.deleted_bytes, b.max_file_key)
        for k in range(1, 200):
            va, vb = a.get(k), b.get(k)
            assert (va is None) == (vb is None)
            if va:
                assert (va.offset, va.size) == (vb.offset, vb.size)
    finally:
        a.close()
        b.close()

    # reopen from .idx: state survives (sqlite rebuilt by replay)
    b2 = DiskNeedleMap(str(tmp_path / "b.idx"))
    try:
        assert len(b2) == len(a)
        assert b2.get(10).size == a.get(10).size
    finally:
        b2.close()


def test_store_index_type_disk(tmp_path):
    from seaweedfs_tpu.storage.needle_map import DiskNeedleMap
    from seaweedfs_tpu.storage.store import Store
    st = Store([str(tmp_path)], index_type="disk")
    v = st.add_volume(1, "", "")
    assert isinstance(v.nm, DiskNeedleMap)
    v.write_needle(Needle(id=5, cookie=2, data=b"disk-map", name=b"x"))
    got = st.read_needle(1, 5, cookie=2)
    assert bytes(got.data) == b"disk-map"
    st.close()


def test_truncated_aws_chunked_rejected():
    import pytest
    from seaweedfs_tpu.s3.auth import AuthError, decode_aws_chunked
    # missing the terminal 0-size chunk: must not decode as complete
    with pytest.raises(AuthError):
        decode_aws_chunked(b"5;chunk-signature=aa\r\nhello\r\n")


def test_throttler_paces_copy():
    th = _Throttler(1_000_000)  # 1 MB/s
    t0 = time.monotonic()
    for _ in range(4):
        th.maybe_sleep(100_000)  # 400KB total -> ~0.4s at 1MB/s
    elapsed = time.monotonic() - t0
    assert elapsed >= 0.25, elapsed
    # unthrottled: no sleep at all
    th0 = _Throttler(0)
    t0 = time.monotonic()
    th0.maybe_sleep(10**9)
    assert time.monotonic() - t0 < 0.05


def test_export_tar_and_pattern(tmp_path):
    v = Volume(str(tmp_path), "", 7)
    for i, name in enumerate([b"a.txt", b"b.log", b"c.txt"], start=1):
        n = Needle(id=i, cookie=0x11, data=b"data-" + name, name=name)
        v.write_needle(n)
    v.close()

    from seaweedfs_tpu.cli import main
    out = tmp_path / "dump.tar"
    main(["export", "-dir", str(tmp_path), "-volumeId", "7",
          "-o", str(out), "-pattern", "*.txt"])
    with tarfile.open(out) as tar:
        names = tar.getnames()
        assert sorted(names) == ["a.txt", "c.txt"]
        data = tar.extractfile("a.txt").read()
        assert data == b"data-a.txt"


def test_export_tar_skips_deleted_and_stale(tmp_path):
    """Overwritten and deleted needle data must never be resurrected by
    export (the scan sees every historical .dat record)."""
    v = Volume(str(tmp_path), "", 9)
    v.write_needle(Needle(id=1, cookie=1, data=b"OLD", name=b"a.txt"))
    v.write_needle(Needle(id=1, cookie=1, data=b"NEW", name=b"a.txt"))
    v.write_needle(Needle(id=2, cookie=1, data=b"SECRET", name=b"b.txt"))
    v.delete_needle(Needle(id=2, cookie=1))
    v.close()

    from seaweedfs_tpu.cli import main
    out = tmp_path / "dump.tar"
    main(["export", "-dir", str(tmp_path), "-volumeId", "9",
          "-o", str(out)])
    with tarfile.open(out) as tar:
        assert tar.getnames() == ["a.txt"]
        assert tar.extractfile("a.txt").read() == b"NEW"
