"""Stripe-batch engine property tests: random window batches and random
missing-sets through every available backend (cpu-numpy / cpu-native /
jax) must be byte-identical to the per-window numpy oracle — encode,
verify verdicts, and reconstruction alike (ec/batch.py + the batched
encoder surface). Runs under JAX_PLATFORMS=cpu in tier-1; jax and the
native kernel skip cleanly when unavailable."""

import hashlib
import os
import random

import numpy as np
import pytest

from seaweedfs_tpu.ec import batch as ecb
from seaweedfs_tpu.ec import gf
from seaweedfs_tpu.ec import pipeline as pl
from seaweedfs_tpu.ec.ec_volume import EcVolume
from seaweedfs_tpu.ec.encoder_cpu import CpuEncoder
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume

BACKENDS = ("cpu-numpy", "cpu-native", "jax")


def make_encoder(name):
    if name == "cpu-numpy":
        return CpuEncoder(use_native=False)
    if name == "cpu-native":
        from seaweedfs_tpu.native import gf256 as _native
        if not _native.available():
            pytest.skip("native GF(256) kernel not built on this host")
        return CpuEncoder(use_native=True)
    jax = pytest.importorskip("jax")
    del jax
    from seaweedfs_tpu.ec.encoder_jax import JaxEncoder
    return JaxEncoder(use_pallas=False)


@pytest.fixture(params=BACKENDS)
def backend(request):
    return request.param, make_encoder(request.param)


@pytest.fixture(scope="module")
def oracle():
    return CpuEncoder(use_native=False)


def _oracle_full(oracle, block):
    """Per-window numpy encode: THE byte-identity reference."""
    return np.stack([np.stack(oracle.encode(list(w))) for w in block])


# ---------------------------------------------------------------------
# encode
# ---------------------------------------------------------------------

def test_batch_encode_matches_perwindow_oracle(backend, oracle):
    name, enc = backend
    rng = np.random.default_rng(101)
    # batch sizes incl. B=1 and non-multiples of device counts; window
    # lengths incl. odd (not a block-quantum multiple)
    for bsz, n in [(1, 512), (3, 1000), (8, 4096), (5, 64)]:
        block = rng.integers(0, 256, (bsz, gf.DATA_SHARDS, n)
                             ).astype(np.uint8)
        want = _oracle_full(oracle, block)
        got = np.asarray(enc.encode_batch(block))
        assert got.shape == (bsz, gf.TOTAL_SHARDS, n), (name, got.shape)
        assert np.array_equal(got, want), (name, bsz, n)
        # the engine counts exactly one dispatch per block
        stats = {}
        par = ecb.transform_block(enc, gf.parity_matrix(), block, stats)
        assert np.array_equal(par, want[:, gf.DATA_SHARDS:, :])
        assert stats == {"dispatches": 1, "batches": 1, "windows": bsz,
                         "bytes_in": block.nbytes}


# ---------------------------------------------------------------------
# verify
# ---------------------------------------------------------------------

def test_batch_verify_localizes_random_corruption(backend, oracle):
    name, enc = backend
    rng = np.random.default_rng(202)
    block = rng.integers(0, 256, (6, gf.DATA_SHARDS, 777)).astype(np.uint8)
    full = _oracle_full(oracle, block)
    assert ecb.verify_block(enc, full) == [True] * 6, name
    for _ in range(8):
        bad = full.copy()
        hits = sorted({int(rng.integers(0, 6))
                       for _ in range(rng.integers(1, 4))})
        for w in hits:
            sid = int(rng.integers(0, gf.TOTAL_SHARDS))
            off = int(rng.integers(0, 777))
            bad[w, sid, off] ^= int(rng.integers(1, 256))
        verdicts = ecb.verify_block(enc, bad)
        assert verdicts == [w not in hits for w in range(6)], \
            (name, hits, verdicts)


def test_unified_verify_signature(backend, oracle):
    """Satellite: every backend answers the same verify(block) -> bool
    for a list of rows AND a stacked array — the shape
    EcVolume.verify_window relies on with no per-encoder branching."""
    name, enc = backend
    rng = np.random.default_rng(303)
    window = rng.integers(0, 256, (gf.DATA_SHARDS, 640)).astype(np.uint8)
    full = np.stack(oracle.encode(list(window)))
    assert bool(enc.verify(full)) is True, name
    assert bool(enc.verify([r for r in full])) is True, name
    bad = full.copy()
    bad[11, 3] ^= 0x40
    assert bool(enc.verify(bad)) is False, name
    assert bool(enc.verify([r for r in bad])) is False, name


# ---------------------------------------------------------------------
# reconstruct
# ---------------------------------------------------------------------

def test_batch_reconstruct_random_missing_sets(backend, oracle):
    """Random missing-sets of size 1..4: rebuilding the lost rows from
    k survivors must be byte-identical to the originals on every
    backend, for every window of the batch."""
    name, enc = backend
    rng = np.random.default_rng(404)
    block = rng.integers(0, 256, (4, gf.DATA_SHARDS, 1536)
                         ).astype(np.uint8)
    full = _oracle_full(oracle, block)
    cases = [(0,), (13,), (0, 1, 2, 3), (10, 11, 12, 13), (0, 5, 11, 13)]
    for _ in range(10):
        m = int(rng.integers(1, gf.PARITY_SHARDS + 1))
        cases.append(tuple(sorted(
            rng.choice(gf.TOTAL_SHARDS, size=m, replace=False).tolist())))
    for missing in cases:
        present = [i for i in range(gf.TOTAL_SHARDS)
                   if i not in missing][:gf.DATA_SHARDS]
        rec = np.asarray(enc.reconstruct_batch(present, list(missing),
                                               full[:, present, :]))
        assert np.array_equal(rec, full[:, list(missing), :]), \
            (name, missing)


# ---------------------------------------------------------------------
# the three bulk paths: batched == per-window on a real volume
# ---------------------------------------------------------------------

LB, SB = 16 * 1024, 1024


@pytest.fixture(scope="module")
def small_volume(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("ecbatch"))
    v = Volume(d, "", 9)
    rng = random.Random(7)
    # big enough to span BOTH striping areas: >= 2 large-block rows
    # (where consecutive windows are contiguous per shard and preads
    # coalesce) plus a small-block tail
    for i in range(1, 120):
        v.write_needle(Needle(cookie=i, id=i,
                              data=rng.randbytes(rng.randint(2000, 5000))))
    v.close()
    return d, os.path.join(d, "9")


def _shard_digest(base):
    h = hashlib.sha256()
    for sid in range(gf.TOTAL_SHARDS):
        with open(base + pl.to_ext(sid), "rb") as f:
            h.update(f.read())
    return h.hexdigest()


def test_encode_volume_batched_is_byte_identical(small_volume):
    d, base = small_volume
    sums, stats = {}, {}
    for bw in (1, 8):
        s: dict = {}
        pl.encode_volume(base, encoder=pl.get_encoder("cpu"),
                         large_block=LB, small_block=SB, buffer_size=SB,
                         batch_windows=bw, stats=s)
        sums[bw], stats[bw] = _shard_digest(base), s
    assert sums[1] == sums[8]
    w = stats[1]["windows"]
    assert stats[1]["dispatches"] == w
    assert stats[8]["dispatches"] <= -(-w // 8)
    assert stats[8]["preads"] < stats[1]["preads"]
    pl.write_sorted_file_from_idx(base)


def test_verify_parity_batched_matches_perwindow(small_volume):
    d, base = small_volume
    if not os.path.exists(base + ".ecx"):
        pl.write_sorted_file_from_idx(base)
    window = 4 * 1024
    ev = EcVolume(d, "", 9, large_block=LB, small_block=SB,
                  encoder=pl.get_encoder("cpu"))
    try:
        # plant rot in two windows of a parity shard (bytes no
        # foreground read visits)
        p = base + pl.to_ext(11)
        with open(p, "r+b") as f:
            for off in (window + 3, 3 * window + 9):
                f.seek(off)
                b = f.read(1)
                f.seek(off)
                f.write(bytes([b[0] ^ 0x55]))
        r1 = ev.verify_parity(window, batch_windows=1)
        rb = ev.verify_parity(window, batch_windows=8)
        assert r1["bad_windows"] == rb["bad_windows"] == [window, 3 * window]
        assert r1["windows"] == rb["windows"]
        assert r1["dispatches"] == r1["windows"]
        assert rb["dispatches"] <= -(-r1["windows"] // 8)
        assert rb["preads"] < r1["preads"]
    finally:
        ev.close()


def test_rebuild_batched_is_byte_identical(small_volume):
    d, base = small_volume
    originals = {}
    for sid in (2, 12):
        with open(base + pl.to_ext(sid), "rb") as f:
            originals[sid] = f.read()
        os.remove(base + pl.to_ext(sid))
    stats: dict = {}
    rebuilt = pl.rebuild_ec_files(base, encoder=pl.get_encoder("cpu"),
                                  buffer_size=SB, batch_windows=8,
                                  stats=stats)
    assert sorted(rebuilt) == [2, 12]
    for sid, want in originals.items():
        with open(base + pl.to_ext(sid), "rb") as f:
            assert f.read() == want, sid
    w = -(-len(originals[2]) // SB)
    assert stats["launches"] <= -(-w // 8)
