"""Cluster-wide EC workflows: ec.encode -> distributed reads ->
shard loss -> degraded read over the network -> ec.rebuild -> ec.balance.

This is the reference's north-star flow (SURVEY.md §3.3/3.4) running on the
in-proc cluster.
"""

import asyncio
import os
import random

from cluster_util import Cluster, run

from seaweedfs_tpu.ec import gf
from seaweedfs_tpu.shell.env import CommandEnv
from seaweedfs_tpu.shell import ec_commands as ec


async def _fill_volume(c: Cluster, n_files: int = 40) -> list[tuple[str, str, bytes]]:
    rng = random.Random(5)
    out = []
    for i in range(n_files):
        a = await c.assign(collection="ectest")
        data = bytes(rng.getrandbits(8)
                     for _ in range(rng.randint(500, 8000)))
        st, _ = await c.put(a["fid"], a["url"], data)
        assert st == 201
        out.append((a["fid"], a["publicUrl"], data))
    return out


def test_ec_encode_spread_read_rebuild_balance(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=4) as c:
            files = await _fill_volume(c)
            await c.heartbeat_all()
            async with CommandEnv(c.master.url, c.http) as env:
                vids = sorted({int(f.split(",")[0]) for f, _, _ in files})
                res = await ec.ec_encode(env, collection="ectest", vids=vids)
                assert res, "ec.encode produced no results"
                # shards spread over all 4 servers
                assignments = res[0]["assignments"]
                assert len(assignments) == 4
                assert sum(len(s) for s in assignments.values()) == 14

            # NO heartbeat_all here: ec mount/unmount/delete push
            # immediate delta heartbeats, so reads that land ANYWHERE in
            # the cluster right after ec.encode must already succeed —
            # waiting a pulse used to hide a window where remote-shard
            # lookups found nothing and reconstruction failed with too
            # few sources (the round-4 soak's 783-bad-read bug)
            for vs in c.servers:
                assert not any(int(v.split(",")[0]) in vs.store.volumes
                               for v, _, _ in files)
            for fid, url, data in files[:10]:
                st, got = await c.get(fid, url)
                assert st == 200 and got == data, fid

            # destroy one server's shard files -> degraded read still works
            async with CommandEnv(c.master.url, c.http) as env:
                smap = await ec.ec_shard_map(env)
            vid = vids[0]
            victim_url = smap[vid]["shards"][0][0]
            import seaweedfs_tpu.ec.pipeline as pl
            victim = next(v for v in c.servers if v.url == victim_url)
            lost = sorted(victim.store.ec_volumes[vid].shards)
            base = victim._base_name(vid, "ectest")
            victim.store.unmount_ec_shards(vid)
            for sid in lost:
                os.remove(base + pl.to_ext(sid))
            await c.heartbeat_all()

            for fid, url, data in files[:5]:
                server = next(s for s in c.servers if s.url != victim_url)
                st, got = await c.get(fid, server.url)
                assert st == 200 and got == data, ("degraded", fid)

            # ec.rebuild regenerates the lost shards somewhere
            async with CommandEnv(c.master.url, c.http) as env:
                results = await ec.ec_rebuild(env, collection="ectest")
            assert any(r.get("rebuilt") for r in results), results
            await c.heartbeat_all()
            async with CommandEnv(c.master.url, c.http) as env:
                smap = await ec.ec_shard_map(env)
            assert len(smap[vid]["shards"]) == gf.TOTAL_SHARDS

            # ec.balance produces no moves or only valid ones, and reads
            # still succeed afterwards
            async with CommandEnv(c.master.url, c.http) as env:
                moves = await ec.ec_balance(env, collection="ectest")
            await c.heartbeat_all()
            for fid, url, data in files[:5]:
                st, got = await c.get(fid, url)
                assert st == 200 and got == data, ("post-balance", fid)

            # EC delete broadcasts the tombstone to every shard holder
            del_fid, del_url, _ = files[0]
            assert await c.delete(del_fid, del_url) == 200
            for vs in c.servers:
                st, _ = await c.get(del_fid, vs.url)
                assert st == 404, ("ec-delete", vs.url)
    run(body())


def test_ec_rebuild_unrepairable_reported(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=2) as c:
            files = await _fill_volume(c, n_files=10)
            await c.heartbeat_all()
            vids = sorted({int(f.split(",")[0]) for f, _, _ in files})
            async with CommandEnv(c.master.url, c.http) as env:
                await ec.ec_encode(env, collection="ectest", vids=vids)
            await c.heartbeat_all()
            vid = vids[0]
            # destroy shards until < 10 remain
            import seaweedfs_tpu.ec.pipeline as pl
            removed = 0
            for vs in c.servers:
                ev = vs.store.ec_volumes.get(vid)
                if ev is None:
                    continue
                sids = sorted(ev.shards)
                for sid in sids:
                    if removed >= 5:
                        break
                    vs.store.unmount_ec_shards(vid, [sid])
                    p = vs._base_name(vid, "ectest") + pl.to_ext(sid)
                    if os.path.exists(p):
                        os.remove(p)
                    removed += 1
            assert removed == 5
            await c.heartbeat_all()
            async with CommandEnv(c.master.url, c.http) as env:
                results = await ec.ec_rebuild(env, collection="ectest")
            assert any("unrepairable" in str(r.get("error", ""))
                       for r in results), results
    run(body())


def test_ec_decode_back_to_normal_volume(tmp_path):
    """The un-EC path (command_ec_decode.go + VolumeEcShardsToVolume):
    encode -> delete original -> lose a data shard -> ec.decode -> every
    needle reads back from the reassembled NORMAL volume."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=4) as c:
            files = await _fill_volume(c, n_files=25)
            await c.heartbeat_all()
            vids = sorted({int(f.split(",")[0]) for f, _, _ in files})
            async with CommandEnv(c.master.url, c.http) as env:
                await ec.ec_encode(env, collection="ectest", vids=vids)
            await c.heartbeat_all()
            vid = vids[0]
            # original volume is gone everywhere (sealed into shards)
            for vs in c.servers:
                assert vid not in vs.store.volumes

            # delete one needle through the EC path so the decode must
            # carry the tombstone into the rebuilt .idx
            del_fid, del_url, _ = files[-1]
            assert await c.delete(del_fid, del_url) == 200

            # lose one server's shards entirely: decode must gather +
            # reconstruct before reassembly
            import seaweedfs_tpu.ec.pipeline as pl
            async with CommandEnv(c.master.url, c.http) as env:
                smap = await ec.ec_shard_map(env)
            victim_url = smap[vid]["shards"][0][0]
            victim = next(v for v in c.servers if v.url == victim_url)
            lost = sorted(victim.store.ec_volumes[vid].shards)
            base = victim._base_name(vid, "ectest")
            victim.store.unmount_ec_shards(vid)
            for sid in lost:
                os.remove(base + pl.to_ext(sid))
            await c.heartbeat_all()

            async with CommandEnv(c.master.url, c.http) as env:
                results = await ec.ec_decode(env, collection="ectest",
                                             vids=[vid])
            assert results and "error" not in results[0], results
            target_url = results[0]["node"]
            await c.heartbeat_all()

            # the volume is back as a NORMAL volume on the target and the
            # EC shards are gone cluster-wide
            target = next(v for v in c.servers if v.url == target_url)
            assert vid in target.store.volumes
            for vs in c.servers:
                assert vid not in vs.store.ec_volumes
                b = vs._base_name(vid, "ectest")
                if b:
                    assert not any(
                        os.path.exists(b + pl.to_ext(s))
                        for s in range(14)), vs.url

            # every live needle reads back through the normal read path
            for fid, url, data in files[:-1]:
                if int(fid.split(",")[0]) != vid:
                    continue
                st, got = await c.get(fid, target.url)
                assert st == 200 and got == data, fid
            # the EC-deleted needle stays deleted in the rebuilt volume
            st, _ = await c.get(del_fid, target.url)
            assert st == 404
    run(body())


def test_ec_verify_scrub_detects_bit_rot(tmp_path):
    """ec.verify: clean volumes scrub clean; a single flipped byte in
    one shard file is reported as a corrupt window."""
    async def body():
        async with Cluster(str(tmp_path), n_servers=4) as c:
            files = await _fill_volume(c, n_files=20)
            await c.heartbeat_all()
            async with CommandEnv(c.master.url, c.http) as env:
                vids = sorted({int(f.split(",")[0]) for f, _, _ in files})
                await ec.ec_encode(env, collection="ectest", vids=vids)
                await c.heartbeat_all()
                reports = await ec.ec_verify(env, collection="ectest")
                assert reports, "no EC volumes scrubbed"
                for r in reports:
                    assert r.get("bad_windows") == [], r
                    assert r["windows"] >= 1

                # flip one byte in one mounted shard file, then re-scrub
                vid = reports[0]["volume"]
                victim = None
                for vs in c.servers:
                    ev = vs.store.ec_volumes.get(vid)
                    if ev and ev.shards:
                        sid = next(iter(ev.shards))
                        victim = ev.base_name + f".ec{sid:02d}"
                        break
                assert victim and os.path.getsize(victim) > 0
                with open(victim, "r+b") as f:
                    f.seek(os.path.getsize(victim) // 2)
                    b = f.read(1)
                    f.seek(-1, 1)
                    f.write(bytes([b[0] ^ 0xFF]))
                reports = await ec.ec_verify(env, volume_id=vid)
                assert len(reports) == 1
                # the scrubbing node may or may not be the corrupted
                # holder; verify through the node that holds the flipped
                # shard to pin detection
                bad = reports[0].get("bad_windows")
                if not bad:
                    for vs in c.servers:
                        ev = vs.store.ec_volumes.get(vid)
                        if ev and ev.shards:
                            rep = ev.verify_parity()
                            if rep["bad_windows"]:
                                bad = rep["bad_windows"]
                                break
                assert bad, "flipped byte not detected by any holder"
    run(body())
