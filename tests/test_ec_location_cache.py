"""EC shard-location cache staleness tiers (server/ec_locations.py).

Reference: weed/storage/store_ec.go:218-259 — 11s lookup suppression,
7m TTL, 37m stale-while-error window.
"""

import asyncio

from cluster_util import Cluster, run

from seaweedfs_tpu.server.ec_locations import EcLocationCache


class Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def _cache(results):
    """results: list mutated by tests; pop(0) per lookup; None = fail."""
    calls = []

    def lookup(vid):
        calls.append(vid)
        r = results.pop(0) if results else None
        if isinstance(r, Exception):
            raise r
        return r

    clock = Clock()
    return EcLocationCache(lookup, now=clock), calls, clock


def test_ttl_serves_without_lookup():
    locs = {"0": ["a:1"]}
    c, calls, clock = _cache([locs])
    assert c.get(5) == locs
    for _ in range(100):
        assert c.get(5) == locs
    assert len(calls) == 1          # one lookup for the whole burst
    clock.t += EcLocationCache.TTL_S + 1
    c2 = {"0": ["b:2"]}
    c._lookup = lambda vid: c2
    assert c.get(5) == c2           # TTL expiry re-resolves


def test_fresh_window_suppresses_lookup_after_failure():
    c, calls, clock = _cache([None])      # first lookup fails
    assert c.get(7) is None
    assert c.get(7) is None               # inside 11s: no second dial
    assert len(calls) == 1
    clock.t += EcLocationCache.FRESH_S + 1
    c._lookup = lambda vid: {"1": ["x:1"]}
    assert c.get(7) == {"1": ["x:1"]}     # after the window, retried


def test_stale_while_error_then_expire():
    locs = {"2": ["a:1"]}
    c, calls, clock = _cache([locs])
    assert c.get(9) == locs
    # TTL passes, every lookup now fails -> keep serving stale
    clock.t += EcLocationCache.TTL_S + 1
    c._lookup = lambda vid: (_ for _ in ()).throw(OSError("master down"))
    assert c.get(9) == locs
    # ... until the 37m expiry, then None
    clock.t += EcLocationCache.EXPIRE_S
    assert c.get(9) is None


def test_invalidate_forces_immediate_relookup_once_per_window():
    l1, l2 = {"0": ["dead:1"]}, {"0": ["alive:2"]}
    seq = [l1, l2]
    c, calls, clock = _cache(seq)
    assert c.get(3) == l1
    c.invalidate(3)
    # a shard move must not leave readers stuck on dead holders
    assert c.get(3) == l2
    assert len(calls) == 2
    # an every-holder-down storm: further invalidations inside the
    # FRESH window do NOT force more lookups (stale l2 keeps serving)
    for _ in range(50):
        c.invalidate(3)
        assert c.get(3) == l2
    assert len(calls) == 2
    # after the window, one more forced re-lookup is allowed
    clock.t += EcLocationCache.FRESH_S + 1
    c._lookup = lambda vid: (calls.append(vid), {"0": ["c:3"]})[1]
    c.invalidate(3)
    assert c.get(3) == {"0": ["c:3"]}
    assert len(calls) == 3


def test_degraded_read_burst_one_master_lookup(tmp_path):
    """Cluster-level: a burst of EC reads needing remote shard fetches
    costs each server ONE master ec_lookup per volume, not one per
    interval (the pre-cache behavior at volume_server.py round 3)."""
    import random

    from seaweedfs_tpu.shell import ec_commands as ec
    from seaweedfs_tpu.shell.env import CommandEnv

    async def body():
        async with Cluster(str(tmp_path), n_servers=3) as c:
            rng = random.Random(2)
            files = []
            for _ in range(12):
                a = await c.assign(collection="ecc")
                data = bytes(rng.getrandbits(8)
                             for _ in range(rng.randint(500, 6000)))
                st, _ = await c.put(a["fid"], a["url"], data)
                assert st == 201
                files.append((a["fid"], data))
            await c.heartbeat_all()
            async with CommandEnv(c.master.url, c.http) as env:
                vids = sorted({int(f.split(",")[0]) for f, _ in files})
                res = await ec.ec_encode(env, collection="ecc", vids=vids)
                assert res
            await c.heartbeat_all()

            # count master lookups issued by each server's cache
            counts = {vs.url: [] for vs in c.servers}
            for vs in c.servers:
                inner = vs._ec_locations._lookup

                def counting(vid, _inner=inner, _log=counts[vs.url]):
                    _log.append(vid)
                    return _inner(vid)
                vs._ec_locations._lookup = counting

            # read every file from every server, twice: plenty of remote
            # interval fetches
            for _ in range(2):
                for fid, data in files:
                    for vs in c.servers:
                        st, got = await c.get(fid, vs.url)
                        assert st == 200 and got == data
            for url, log in counts.items():
                # at most one lookup per (server, volume)
                assert len(log) == len(set(log)), (url, log)
    run(body())
