"""EC end-to-end oracle: the reference ec_test.go pattern.

Build a real volume of random needles, stripe it to 14 shard files, then
prove every needle reads back bit-identically (a) through direct stripe
math and (b) with shards destroyed, through on-the-fly reconstruction.
Then rebuild the missing shard files and compare byte-for-byte.
"""

import hashlib
import itertools
import os
import random

import numpy as np
import pytest

from seaweedfs_tpu.ec import gf
from seaweedfs_tpu.ec.ec_volume import EcVolume, NotFoundError
from seaweedfs_tpu.ec.locate import locate_data, shard_file_size
from seaweedfs_tpu.ec import pipeline as pl
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume

# Small geometry so tests exercise both large and small block areas fast.
LB = 16 * 1024   # large block
SB = 1024        # small block


@pytest.fixture(scope="module")
def ec_fixture(tmp_path_factory):
    """A volume with ~200 needles striped into 14 shards."""
    d = str(tmp_path_factory.mktemp("ecvol"))
    v = Volume(d, "", 5)
    rng = random.Random(11)
    contents = {}
    for i in range(1, 201):
        data = bytes(rng.getrandbits(8) for _ in range(rng.randint(1, 4096)))
        v.write_needle(Needle(cookie=i ^ 0x5A, id=i, data=data))
        contents[i] = data
    # tombstone a few
    for i in (17, 99):
        v.delete_needle(Needle(cookie=i ^ 0x5A, id=i))
        del contents[i]
    v.close()

    base = os.path.join(d, "5")
    enc = pl.get_encoder("cpu")
    pl.write_ec_files(base, encoder=enc, large_block=LB, small_block=SB,
                      buffer_size=SB)
    pl.write_sorted_file_from_idx(base)
    return d, base, contents


def test_shard_files_created(ec_fixture):
    d, base, _ = ec_fixture
    dat_size = os.path.getsize(base + ".dat")
    want = shard_file_size(dat_size, LB, SB)
    for i in range(14):
        assert os.path.getsize(base + pl.to_ext(i)) == want, i


def test_locate_data_unit():
    # mirrors TestLocateData (ec_test.go:187): intervals tile the request
    dat_size = 2 * LB * 10 + 3 * SB * 10 + 100
    for off, size in [(0, 1), (LB - 1, 2), (2 * LB * 10 - 1, 2),
                      (2 * LB * 10 + 5, SB * 3), (0, dat_size)]:
        ivs = locate_data(LB, SB, dat_size, off, size)
        assert sum(iv.size for iv in ivs) == size
        # re-read through shard mapping must cover contiguous logical range
        total = 0
        for iv in ivs:
            sid, soff = iv.to_shard_and_offset(LB, SB)
            assert 0 <= sid < 10
            assert soff >= 0
            total += iv.size
        assert total == size


def test_direct_reads_match(ec_fixture):
    d, base, contents = ec_fixture
    ev = EcVolume(d, "", 5, large_block=LB, small_block=SB,
                  encoder=pl.get_encoder("cpu"))
    for nid, data in contents.items():
        n = ev.read_needle(nid, cookie=nid ^ 0x5A)
        assert n.data == data, nid
    for nid in (17, 99):
        with pytest.raises(NotFoundError):
            ev.read_needle(nid)
    ev.close()


def test_degraded_reads_all_loss_patterns(ec_fixture, tmp_path):
    """Read through reconstruction with 4 shards gone (multiple patterns)."""
    d, base, contents = ec_fixture
    sample = dict(itertools.islice(contents.items(), 25))
    for missing in [(0, 1, 2, 3), (10, 11, 12, 13), (0, 5, 9, 12)]:
        ev = EcVolume(d, "", 5, large_block=LB, small_block=SB,
                      encoder=pl.get_encoder("cpu"))
        for sid in missing:
            ev.shards.pop(sid).close()
        for nid, data in sample.items():
            n = ev.read_needle(nid)
            assert n.data == data, (missing, nid)
        ev.close()


def test_rebuild_missing_shards(ec_fixture, tmp_path):
    d, base, contents = ec_fixture
    # copy shard files to a scratch dir, drop 4, rebuild, compare
    import shutil
    scratch = str(tmp_path / "rebuild")
    os.makedirs(scratch)
    nb = os.path.join(scratch, "5")
    originals = {}
    for i in range(14):
        src = base + pl.to_ext(i)
        with open(src, "rb") as f:
            originals[i] = hashlib.sha256(f.read()).hexdigest()
        if i not in (2, 6, 11, 13):
            shutil.copy(src, nb + pl.to_ext(i))
    rebuilt = pl.rebuild_ec_files(nb, encoder=pl.get_encoder("cpu"))
    assert sorted(rebuilt) == [2, 6, 11, 13]
    for i in rebuilt:
        with open(nb + pl.to_ext(i), "rb") as f:
            assert hashlib.sha256(f.read()).hexdigest() == originals[i], i


def test_rebuild_sequential_matches_batched(ec_fixture, tmp_path):
    """The bench baseline: sequential per-shard rebuild produces
    byte-identical shards to the batched one-matmul-per-window path,
    and the stats show batched reading the survivors ONCE while
    sequential re-reads them per lost shard."""
    import shutil
    d, base, _ = ec_fixture
    lost = (2, 6, 11, 13)
    originals = {}
    dirs = {}
    for mode in ("seq", "batch"):
        scratch = str(tmp_path / mode)
        os.makedirs(scratch)
        dirs[mode] = os.path.join(scratch, "5")
        for i in range(14):
            src = base + pl.to_ext(i)
            if mode == "seq":
                with open(src, "rb") as f:
                    originals[i] = hashlib.sha256(f.read()).hexdigest()
            if i not in lost:
                shutil.copy(src, dirs[mode] + pl.to_ext(i))
    stats = {"seq": {}, "batch": {}}
    assert sorted(pl.rebuild_ec_files(
        dirs["seq"], encoder=pl.get_encoder("cpu"), sequential=True,
        stats=stats["seq"])) == list(lost)
    assert sorted(pl.rebuild_ec_files(
        dirs["batch"], encoder=pl.get_encoder("cpu"),
        stats=stats["batch"])) == list(lost)
    for mode in ("seq", "batch"):
        for i in lost:
            with open(dirs[mode] + pl.to_ext(i), "rb") as f:
                assert hashlib.sha256(
                    f.read()).hexdigest() == originals[i], (mode, i)
    assert stats["seq"]["bytes_read"] == \
        len(lost) * stats["batch"]["bytes_read"]
    assert stats["seq"]["bytes_rebuilt"] == stats["batch"]["bytes_rebuilt"]
    assert stats["batch"]["launches"] < stats["seq"]["launches"]


def test_rebuild_unrepairable(tmp_path, ec_fixture):
    import shutil
    d, base, _ = ec_fixture
    nb = str(tmp_path / "5")
    for i in range(9):  # only 9 shards
        shutil.copy(base + pl.to_ext(i), nb + pl.to_ext(i))
    with pytest.raises(ValueError, match="unrepairable"):
        pl.rebuild_ec_files(nb, encoder=pl.get_encoder("cpu"))


def test_decode_back_to_dat(ec_fixture, tmp_path):
    import shutil
    d, base, contents = ec_fixture
    nb = str(tmp_path / "5")
    for i in range(10):
        shutil.copy(base + pl.to_ext(i), nb + pl.to_ext(i))
    shutil.copy(base + ".ecx", nb + ".ecx")
    dat_size = os.path.getsize(base + ".dat")
    # trailing tombstone records have no live .ecx entry, so the recovered
    # size covers the live prefix only (same as reference FindDatFileSize)
    found = pl.find_dat_file_size(nb)
    assert found <= dat_size
    pl.write_dat_file(nb, found, large_block=LB, small_block=SB)
    with open(base + ".dat", "rb") as a, open(nb + ".dat", "rb") as b:
        assert a.read(found) == b.read()


def test_ec_delete_journal(ec_fixture):
    d, base, contents = ec_fixture
    ev = EcVolume(d, "", 5, large_block=LB, small_block=SB,
                  encoder=pl.get_encoder("cpu"))
    victim = next(iter(contents))
    ev.read_needle(victim)
    ev.delete_needle(victim)
    with pytest.raises(NotFoundError):
        ev.read_needle(victim)
    ev.close()
    # journal recorded
    with open(base + ".ecj", "rb") as f:
        assert int.from_bytes(f.read(8), "big") == victim
    # reopening still sees the tombstone (persisted into .ecx)
    ev2 = EcVolume(d, "", 5, large_block=LB, small_block=SB)
    with pytest.raises(NotFoundError):
        ev2.read_needle(victim)
    ev2.close()


def test_batched_encode_matches_serial(tmp_path):
    """write_ec_files_batched must produce byte-identical shard files to
    the serial path for volumes of DIFFERENT sizes (rack-encode shape,
    uneven tail), including parity placement across flush groups."""
    import random as _random
    rng = _random.Random(23)
    sizes = [5 * LB * 10 + 3 * SB * 10 + 40,   # large rows + ragged tail
             2 * SB * 10 + 7,                  # small rows only
             LB * 10 + SB * 10]                # exact boundary
    serial, batched = [], []
    for i, size in enumerate(sizes):
        payload = bytes(rng.getrandbits(8) for _ in range(size))
        for tag, acc in (("s", serial), ("b", batched)):
            base = str(tmp_path / f"{tag}{i}")
            with open(base + ".dat", "wb") as f:
                f.write(payload)
            acc.append(base)
    enc = pl.get_encoder("cpu")
    for base in serial:
        pl.write_ec_files(base, encoder=enc, large_block=LB,
                          small_block=SB, buffer_size=SB)
    pl.write_ec_files_batched(batched, encoder=enc, large_block=LB,
                              small_block=SB, buffer_size=SB,
                              batch_volumes=4)
    for sbase, bbase in zip(serial, batched):
        for sid in range(14):
            with open(sbase + pl.to_ext(sid), "rb") as a, \
                    open(bbase + pl.to_ext(sid), "rb") as b:
                assert a.read() == b.read(), (sbase, sid)


def test_overlapped_pipeline_error_propagates(tmp_path):
    """A transform failure mid-stream must raise out of write_ec_files
    (not deadlock the reader/writer threads) and must not be swallowed."""
    import threading

    d = str(tmp_path)
    v = Volume(d, "", 7)
    rng = random.Random(3)
    for i in range(1, 30):
        v.write_needle(Needle(cookie=1, id=i,
                              data=bytes(rng.getrandbits(8)
                                         for _ in range(2000))))
    v.close()
    base = os.path.join(d, "7")

    from seaweedfs_tpu.ec.encoder_jax import JaxEncoder

    calls = {"n": 0}
    from seaweedfs_tpu.ec import pipeline as plmod
    from seaweedfs_tpu.ec.encoder_cpu import CpuEncoder
    orig = plmod.transform_block_async

    def exploding(encoder, coeff, block, stats=None):
        calls["n"] += 1
        if calls["n"] == 3:
            raise RuntimeError("kaboom")
        # stay off the device path under the fake: compute via numpy
        return orig(CpuEncoder(use_native=False), coeff, block, stats)

    plmod.transform_block_async = exploding
    try:
        before = threading.active_count()
        with pytest.raises(RuntimeError, match="kaboom"):
            # JaxEncoder selects the THREADED pipeline (_use_overlap),
            # which is the error path under test; batch_windows=1
            # keeps enough blocks in the stream for call 3 to land
            pl.write_ec_files(base, encoder=JaxEncoder(),
                              large_block=LB, small_block=SB,
                              buffer_size=SB, batch_windows=1)
        # pipeline threads joined, none leaked
        assert threading.active_count() <= before
    finally:
        plmod.transform_block_async = orig


def test_ec_backend_env_override(monkeypatch):
    """SWTPU_EC_BACKEND (the volume CLI's -ecBackend flag) pins the
    engine choice regardless of the attached accelerator."""
    from seaweedfs_tpu.ec.encoder_cpu import CpuEncoder

    monkeypatch.setenv("SWTPU_EC_BACKEND", "cpu")
    assert isinstance(pl.get_encoder(), CpuEncoder)
    # a tpu pin on a host without a TPU fails fast (tests run on cpu)
    monkeypatch.setenv("SWTPU_EC_BACKEND", "tpu")
    with pytest.raises(RuntimeError, match="no TPU is attached"):
        pl.get_encoder()
    # explicit argument still wins over the env
    assert isinstance(pl.get_encoder("cpu"), CpuEncoder)
    # garbage values are rejected, not silently mapped to cpu
    monkeypatch.setenv("SWTPU_EC_BACKEND", "gpu")
    with pytest.raises(ValueError, match="unknown EC backend"):
        pl.get_encoder()
