"""Minimal-fetch repair planning: survivor selection for every
missing-set of size 1..4 picks exactly k rows and prefers local, then
cached, then holder-grouped remote rows; the planned decode is
byte-identical to the naive first-k gather; plans are cached per
missing-set and invalidated on shard mount/unmount; a failed batch
gather refreshes the holder map ONCE (never per shard) before the
per-shard fallback; and survivor rows fetched for one lost shard are
reused — not re-moved — when a second lost shard of the same stripe
recovers."""

import itertools
import os
import random
import shutil

import numpy as np
import pytest

from seaweedfs_tpu.ec import gf
from seaweedfs_tpu.ec import pipeline as pl
from seaweedfs_tpu.ec.ec_volume import (EcVolume, EcVolumeError,
                                        select_survivors)
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume
from seaweedfs_tpu.util.chunk_cache import EcRecoverCache

K = gf.DATA_SHARDS
N = gf.TOTAL_SHARDS
LB = 16 * 1024
SB = 1024


# ---------------------------------------------------------------------
# pure selection: every missing-set of size 1..4
# ---------------------------------------------------------------------

def test_every_missing_set_selects_exactly_k_local_first():
    """Exhaustive over all C(14,1..4) missing-sets: the chosen subset
    has exactly k rows, every available local row is used before any
    remote one, and the coefficient schedule exists (any k rows of the
    RS matrix are independent)."""
    for m in range(1, gf.PARITY_SHARDS + 1):
        for missing in itertools.combinations(range(N), m):
            want = missing[0]
            survivors = [s for s in range(N) if s not in missing]
            # deterministic split: half the survivors are local
            local = survivors[::2]
            remote = [s for s in survivors if s not in local]
            rows = select_survivors(want, local, (), [remote])
            assert len(rows) == K
            assert len(set(rows)) == K
            assert want not in rows
            chosen_local = [s for s in rows if s in local]
            assert chosen_local == sorted(local)[:len(chosen_local)]
            # local rows exhausted before any remote row is moved
            assert len(chosen_local) == min(len(local), K)


def test_selection_prefers_cached_over_remote_and_groups_holders():
    # shard 0 lost; 4 local, 2 cached, rest on two holders
    rows = select_survivors(
        0, local=[10, 11, 12, 13], cached=[5, 7],
        remote_groups=[[1, 2, 3], [4, 6, 8, 9]])
    assert rows[:4] == [10, 11, 12, 13]
    assert rows[4:6] == [5, 7]
    # the larger holder group is drained first (fewest round trips)
    assert rows[6:] == [4, 6, 8, 9]


def test_selection_insufficient_survivors_raises():
    with pytest.raises(EcVolumeError):
        select_survivors(0, local=[1, 2, 3], cached=(),
                         remote_groups=[[4, 5, 6]])


def test_selected_rows_decode_byte_identically_to_naive(tmp_path):
    """Property test across random offsets: reconstructing a lost row
    from the PLANNED survivor subset equals reconstructing it from the
    naive first-k-of-sorted-survivors subset equals the true bytes."""
    rng = random.Random(7)
    size = 4096
    shards = [np.frombuffer(rng.randbytes(size), np.uint8)
              for _ in range(K)]
    from seaweedfs_tpu.ec.encoder_cpu import CpuEncoder
    enc = CpuEncoder()
    full = enc.encode(shards)
    sets = [frozenset({s}) for s in range(N)]
    all_sets = [frozenset(c) for m in (2, 3, 4)
                for c in itertools.combinations(range(N), m)]
    sets += rng.sample(all_sets, 24)
    for missing in sets:
        survivors = sorted(s for s in range(N) if s not in missing)
        local = survivors[1::3]
        remote = [s for s in survivors if s not in local]
        for want in missing:
            for off in (0, rng.randrange(1, size - 64), size - 64):
                w = rng.randrange(16, 64)
                planned = select_survivors(want, local, (), [remote])
                naive = survivors[:K]
                for rows in (planned, sorted(planned), naive):
                    coeff = gf.cached_shard_rows(
                        (want,), tuple(rows))
                    got = enc._apply(
                        np.asarray(coeff),
                        [full[s][off:off + w] for s in rows])[0]
                    assert bytes(got) == \
                        bytes(full[want][off:off + w]), (missing, want)


# ---------------------------------------------------------------------
# EcVolume integration fixtures
# ---------------------------------------------------------------------

@pytest.fixture()
def ec_dir(tmp_path):
    """A tiny encoded volume: all 14 shard files + .ecx in one dir."""
    d = str(tmp_path / "vol")
    os.makedirs(d)
    v = Volume(d, "", 9)
    rng = random.Random(3)
    contents = {}
    for i in range(1, 61):
        data = rng.randbytes(rng.randint(100, 3000))
        v.write_needle(Needle(cookie=i * 7, id=i, data=data))
        contents[i] = data
    v.close()
    base = os.path.join(d, "9")
    pl.write_ec_files(base, encoder=pl.get_encoder("cpu"),
                      large_block=LB, small_block=SB, buffer_size=SB)
    pl.write_sorted_file_from_idx(base)
    return d, base, contents


def _holder_view(ec_dir, tmp_path, local_sids, lost_sids,
                 recover_cache=None, holder_peek=None,
                 fail_batches: int = 0):
    """EcVolume seeing only `local_sids` locally; other surviving
    shards served by counting remote hooks; `lost_sids` are gone
    everywhere. Returns (ev, counters dict)."""
    d, base, _ = ec_dir
    hd = str(tmp_path / "holder")
    os.makedirs(hd, exist_ok=True)
    for ext in (".ecx", ".ecj"):
        if os.path.exists(base + ext):
            shutil.copy(base + ext, os.path.join(hd, "9" + ext))
    for sid in local_sids:
        shutil.copy(base + pl.to_ext(sid),
                    os.path.join(hd, "9" + pl.to_ext(sid)))
    counters = {"batch_calls": 0, "batch_rows": 0, "single": 0,
                "bytes": 0, "refreshes": 0, "fail_left": fail_batches}

    def fetch(sid, off, size):
        if sid in lost_sids or sid in local_sids:
            return None
        counters["single"] += 1
        counters["bytes"] += size
        with open(base + pl.to_ext(sid), "rb") as f:
            f.seek(off)
            raw = f.read(size)
        return raw + b"\x00" * (size - len(raw))

    def fetch_batch(reads):
        counters["batch_calls"] += 1
        if counters["fail_left"] > 0:
            counters["fail_left"] -= 1
            return None
        out = {}
        for sid, off, size in reads:
            if sid in lost_sids:
                continue
            counters["batch_rows"] += 1
            counters["bytes"] += size
            with open(base + pl.to_ext(sid), "rb") as f:
                f.seek(off)
                raw = f.read(size)
            out[sid] = raw + b"\x00" * (size - len(raw))
        return out

    def refresh():
        counters["refreshes"] += 1

    ev = EcVolume(hd, "", 9, large_block=LB, small_block=SB,
                  encoder=pl.get_encoder("cpu"),
                  fetch_remote=fetch, fetch_remote_batch=fetch_batch,
                  recover_cache=recover_cache, holder_peek=holder_peek,
                  refresh_holders=refresh)
    return ev, counters


def test_degraded_read_fetches_at_most_k_rows(ec_dir, tmp_path):
    """Every recover moves exactly the shortfall: with 4 local parity
    rows, at most 6 remote rows per batch, and needles read back
    byte-identically."""
    _, _, contents = ec_dir
    ev, counters = _holder_view(ec_dir, tmp_path,
                                local_sids=[10, 11, 12, 13],
                                lost_sids=[0])
    try:
        for nid, data in contents.items():
            assert ev.read_needle(nid, nid * 7).data == data
    finally:
        ev.close()
    assert counters["batch_calls"] > 0
    # never more than the k - local shortfall per gather
    assert counters["batch_rows"] <= counters["batch_calls"] * (K - 4)
    assert counters["refreshes"] == 0


def test_plan_cached_per_missing_set_and_invalidated(ec_dir, tmp_path):
    ev, _ = _holder_view(ec_dir, tmp_path, local_sids=[10, 11, 12, 13],
                         lost_sids=[0])
    try:
        p1 = ev._repair_plan(0)
        assert ev._repair_plan(0) is p1          # cached
        assert ev._repair_plan(1) is p1          # same missing-set
        ev.invalidate_plans()
        p2 = ev._repair_plan(0)
        assert p2 is not p1
        # shard unmount changes the missing-set => a fresh plan even
        # without an explicit invalidate (keyed on the live set)
        f = ev.shards.pop(13)
        f.close()
        p3 = ev._repair_plan(0)
        assert p3 is not p2 and 13 not in p3.local
    finally:
        ev.close()


def test_store_unmount_invalidates_plans(ec_dir, tmp_path):
    from seaweedfs_tpu.storage.store import Store
    d, base, _ = ec_dir
    # shard files + .ecx only (no .dat): the store mounts vid 9 as EC
    sd = str(tmp_path / "store")
    os.makedirs(sd)
    for sid in range(N):
        shutil.copy(base + pl.to_ext(sid),
                    os.path.join(sd, "9" + pl.to_ext(sid)))
    shutil.copy(base + ".ecx", os.path.join(sd, "9.ecx"))
    store = Store([sd])
    try:
        ev = store.ec_volumes[9]
        ev._repair_plan(0)
        assert ev._plans
        store.unmount_ec_shards(9, [13])
        assert not ev._plans
    finally:
        store.close()


def test_holder_grouping_orders_remote_rows(ec_dir, tmp_path):
    holders = {1: "hA", 2: "hA", 3: "hA", 4: "hB", 5: "hB", 6: "hC",
               7: "hC", 8: "hC", 9: "hC", 0: "hD"}
    ev, _ = _holder_view(ec_dir, tmp_path, local_sids=[10, 11, 12, 13],
                         lost_sids=[], holder_peek=lambda: holders)
    try:
        plan = ev._repair_plan(0)
        assert plan.local == [10, 11, 12, 13]
        # biggest holder group (hC: 6,7,8,9) first, then hA, hB, hD
        assert plan.remote == [6, 7, 8, 9, 1, 2, 3, 4, 5, 0]
    finally:
        ev.close()


def test_failed_batch_gather_refreshes_holder_map_once(ec_dir, tmp_path):
    """THE satellite regression: a failed batch gather triggers ONE
    holder-map refresh and one batch retry — the per-shard fallback
    never replays a stale holder for every shard in the batch."""
    with open(os.path.join(ec_dir[0], "9" + pl.to_ext(0)), "rb") as f:
        truth = f.read(512)
    # first batch fails -> refresh once -> retry batch serves all rows
    ev, counters = _holder_view(ec_dir, tmp_path,
                                local_sids=[10, 11, 12, 13],
                                lost_sids=[0], fail_batches=1)
    try:
        assert ev._recover_interval(0, 0, 512) == truth
        assert counters["refreshes"] == 1
        assert counters["batch_calls"] == 2
        assert counters["single"] == 0   # no per-shard storm
    finally:
        ev.close()
    # BOTH batches fail -> still exactly one refresh, then the
    # per-shard fallback covers the shortfall
    ev, counters = _holder_view(ec_dir, tmp_path / "b",
                                local_sids=[10, 11, 12, 13],
                                lost_sids=[0], fail_batches=2)
    try:
        assert ev._recover_interval(0, 0, 512) == truth
        assert counters["refreshes"] == 1
        assert counters["batch_calls"] == 2
        assert counters["single"] == K - 4
    finally:
        ev.close()


def test_partial_batch_gather_retry_rows_are_admitted(ec_dir, tmp_path):
    """Review regression: the first batch serves only SOME of the
    needed rows (two holders down); the post-refresh retry batch
    serves the rest and its rows must be ADMITTED — with no per-shard
    fetcher wired, recovery must still succeed on batches alone."""
    d, base, _ = ec_dir
    with open(base + pl.to_ext(0), "rb") as f:
        truth = f.read(512)
    hd = str(tmp_path / "holder")
    os.makedirs(hd)
    shutil.copy(base + ".ecx", os.path.join(hd, "9.ecx"))
    for sid in (10, 11, 12, 13):
        shutil.copy(base + pl.to_ext(sid),
                    os.path.join(hd, "9" + pl.to_ext(sid)))
    calls = {"n": 0, "refreshes": 0}

    def fetch_batch(reads):
        calls["n"] += 1
        out = {}
        for i, (sid, off, size) in enumerate(reads):
            if calls["n"] == 1 and i >= len(reads) - 2:
                continue          # two rows' holders are down
            with open(base + pl.to_ext(sid), "rb") as f:
                f.seek(off)
                out[sid] = f.read(size)
        return out

    ev = EcVolume(hd, "", 9, large_block=LB, small_block=SB,
                  encoder=pl.get_encoder("cpu"),
                  fetch_remote=None, fetch_remote_batch=fetch_batch,
                  refresh_holders=lambda: calls.__setitem__(
                      "refreshes", calls["refreshes"] + 1))
    try:
        assert ev._recover_interval(0, 0, 512) == truth
        assert calls["n"] == 2           # partial batch + one retry
        assert calls["refreshes"] == 1
    finally:
        ev.close()


def test_local_shard_unmounted_mid_recover_demoted_to_remote(
        ec_dir, tmp_path):
    """Review regression: a plan may go stale between planning and the
    local-row preads (unmount race). A planned-local row whose fd is
    gone must be demoted to a remote candidate — with exactly k
    survivors alive, dropping it would fail the recover."""
    d, base, _ = ec_dir
    with open(base + pl.to_ext(10), "rb") as f:
        truth = f.read(256)
    local = list(range(10))
    ev, counters = _holder_view(ec_dir, tmp_path,
                                local_sids=local,
                                lost_sids=[11, 12, 13])
    try:
        missing = frozenset({10, 11, 12, 13})
        stale_plan = ev._repair_plan(10)
        assert 5 in stale_plan.local
        f = ev.shards.pop(5)       # raced unmount AFTER planning
        f.close()
        local.remove(5)            # ...because it migrated to a peer
        #                            (the emulated holders now serve it)
        # pin the stale plan under the NEW missing-set key, emulating
        # the in-flight recover that planned before the unmount
        ev._plans[missing | {5}] = stale_plan
        assert ev._recover_interval(10, 0, 256) == truth
        # the demoted row was fetched remotely (batch or fallback),
        # not silently dropped
        assert counters["batch_rows"] + counters["single"] == 1
    finally:
        ev.close()


def test_cached_survivor_rows_not_refetched_for_second_lost_shard(
        ec_dir, tmp_path):
    """Survivor intervals moved for one lost shard are cached; a
    recover of ANOTHER lost shard over the same interval consumes the
    cached rows instead of re-moving them."""
    rc = EcRecoverCache(8 << 20)
    ev, counters = _holder_view(ec_dir, tmp_path,
                                local_sids=[10, 11, 12, 13],
                                lost_sids=[0, 1], recover_cache=rc)
    try:
        off, size = 0, 512
        truth = {}
        with open(os.path.join(ec_dir[0], "9" + pl.to_ext(0)),
                  "rb") as f:
            truth[0] = f.read(size)
        with open(os.path.join(ec_dir[0], "9" + pl.to_ext(1)),
                  "rb") as f:
            truth[1] = f.read(size)
        assert ev._recover_interval(0, off, size) == truth[0]
        moved_first = counters["bytes"]
        assert moved_first == 6 * size     # exactly the k - 4 shortfall
        assert ev._recover_interval(1, off, size) == truth[1]
        # second recover: 4 local + 6 cached survivor rows -> 0 new bytes
        assert counters["bytes"] == moved_first
    finally:
        ev.close()
