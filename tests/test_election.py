"""Multi-master leader election (raft_server.go analog) integration tests."""

from __future__ import annotations

import asyncio
import os
import socket

import aiohttp

from cluster_util import run

from seaweedfs_tpu.master.server import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.storage.store import Store


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


async def _make_cluster(n: int = 3) -> list[MasterServer]:
    ports = _free_ports(n)
    urls = [f"127.0.0.1:{p}" for p in ports]
    masters = []
    for p in ports:
        # generous margins: under full-suite load the event loop can stall
        # past a tight lease window and flake the test with leader churn
        m = MasterServer(port=p, pulse_seconds=0.1,
                         peers=urls,
                         election_timeout=(0.4, 0.8),
                         election_pulse=0.1)
        await m.start()
        masters.append(m)
    return masters


async def _wait_single_leader(masters, timeout: float = 10.0) -> MasterServer:
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        leaders = [m for m in masters if m.is_leader]
        agreed = {m.leader_url for m in masters}
        if len(leaders) == 1 and agreed == {leaders[0].url}:
            return leaders[0]
        await asyncio.sleep(0.05)
    raise AssertionError(
        f"no stable leader: roles={[m.election.role for m in masters]}")


def test_single_leader_elected_and_agreed():
    run(_body_single_leader())


async def _body_single_leader():
    masters = await _make_cluster(3)
    try:
        leader = await _wait_single_leader(masters)
        for m in masters:
            assert m.leader_url == leader.url
        terms = {m.election.term for m in masters}
        assert len(terms) == 1
    finally:
        for m in masters:
            await m.stop()


def test_follower_redirects_assign_and_status(tmp_path):
    run(_body_redirect(tmp_path))


async def _body_redirect(tmp_path):
    masters = await _make_cluster(3)
    vs = None
    try:
        leader = await _wait_single_leader(masters)
        follower = next(m for m in masters if not m.is_leader)

        store = Store([os.path.join(str(tmp_path), "v0")],
                      max_volume_counts=[8])
        # point the volume server at a follower: the 307 lands THIS
        # pulse on the leader (re-homing costs zero pulses) and the
        # hint re-points master_url for the next one
        vs = VolumeServer(store, follower.url, port=0, pulse_seconds=0.1)
        await vs.start()
        assert await vs.heartbeat_once()   # redirected => registered
        assert vs.master_url == leader.url
        assert any(n.url == vs.url for n in leader.topo.all_nodes())

        async with aiohttp.ClientSession() as http:
            async with http.get(
                    f"http://{follower.url}/cluster/status") as resp:
                st = await resp.json()
            assert st["isLeader"] is False
            assert st["leader"] == leader.url
            # assign via follower: 307-redirect-to-leader with the
            # X-Raft-Leader hint on the wire...
            async with http.post(f"http://{follower.url}/dir/assign",
                                 allow_redirects=False) as resp:
                assert resp.status == 307
                assert resp.headers["X-Raft-Leader"] == leader.url
                assert leader.url in resp.headers["Location"]
                hint = await resp.json()
            assert hint["leader"] == leader.url
            # ...which a default client follows transparently
            async with http.post(
                    f"http://{follower.url}/dir/assign") as resp:
                body = await resp.json()
            assert resp.status == 200, body
            assert "fid" in body, body

            # /submit through a follower: the proxy must preserve the
            # multipart Content-Type or the envelope gets stored raw
            form = aiohttp.FormData()
            form.add_field("file", b"via-follower", filename="f.bin")
            async with http.post(f"http://{follower.url}/submit",
                                 data=form) as resp:
                sub = await resp.json()
                assert resp.status == 200, sub
            assert sub["size"] == 12 and sub["fileName"] == "f.bin"

            # follower GET /<fid> bounces the client to the leader
            # (302) rather than proxy-buffering the blob...
            async with http.get(f"http://{follower.url}/{sub['fid']}",
                                allow_redirects=False) as resp:
                assert resp.status == 302
                assert leader.url in resp.headers["Location"]
            # ...and following the chain serves the exact bytes
            async with http.get(
                    f"http://{follower.url}/{sub['fid']}") as resp:
                assert resp.status == 200
                assert await resp.read() == b"via-follower"
    finally:
        if vs:
            await vs.stop()
        for m in masters:
            await m.stop()


def test_leader_steps_down_without_quorum():
    run(_body_quorum_loss())


async def _body_quorum_loss():
    masters = await _make_cluster(3)
    try:
        leader = await _wait_single_leader(masters)
        followers = [m for m in masters if m is not leader]
        for f in followers:
            await f.stop()
        # partitioned from every peer, the leader must drop its lease
        # instead of keeping a second writable master alive
        deadline = asyncio.get_event_loop().time() + 3.0
        while asyncio.get_event_loop().time() < deadline:
            if not leader.is_leader:
                break
            await asyncio.sleep(0.05)
        assert not leader.is_leader
        # and writes through it are refused, not misapplied
        async with aiohttp.ClientSession() as http:
            async with http.post(
                    f"http://{leader.url}/dir/assign") as resp:
                assert resp.status == 503
    finally:
        for m in masters:
            await m.stop()


def test_leader_failover_and_max_volume_id_survives(tmp_path):
    run(_body_failover(tmp_path))


async def _body_failover(tmp_path):
    masters = await _make_cluster(3)
    vs = None
    try:
        leader = await _wait_single_leader(masters)
        survivors = [m for m in masters if m is not leader]

        seeds = ",".join(m.url for m in masters)
        store = Store([os.path.join(str(tmp_path), "v0")],
                      max_volume_counts=[8])
        vs = VolumeServer(store, seeds, port=0, pulse_seconds=0.1)
        await vs.start()
        for _ in range(4):
            await vs.heartbeat_once()
        assert vs.master_url == leader.url

        # grow a volume so MaxVolumeId advances on the leader, then verify
        # the replicated value reached followers via leader pulses
        issued = []
        async with aiohttp.ClientSession() as http:
            for _ in range(4):
                async with http.post(
                        f"http://{leader.url}/dir/assign") as resp:
                    fid = (await resp.json()).get("fid")
                    assert fid
                    issued.append(fid)
        await asyncio.sleep(0.3)
        grown_vid = leader.topo.max_volume_id
        assert grown_vid >= 1
        for m in survivors:
            assert m.topo.max_volume_id >= grown_vid

        await leader.stop()
        new_leader = await _wait_single_leader(survivors)
        assert new_leader.url != leader.url
        assert new_leader.election.term > leader.election.term
        # the new leader must not reissue already-used volume ids
        assert new_leader.topo.max_volume_id >= grown_vid

        # volume server finds the new leader via seed rotation + hint
        for _ in range(60):
            try:
                await vs.heartbeat_once()
            except Exception:
                vs._seed_idx = (vs._seed_idx + 1) % len(vs.master_seeds)
                vs.master_url = vs.master_seeds[vs._seed_idx]
            if vs.master_url == new_leader.url \
                    and new_leader.topo.all_nodes():
                break
            await asyncio.sleep(0.05)
        assert vs.master_url == new_leader.url
        assert any(n.url == vs.url for n in new_leader.topo.all_nodes())

        # zero duplicate fids across the failover: every (vid, key) the
        # old leader issued came from a quorum-committed reservation
        # window, so the successor's assigns land strictly above them
        from seaweedfs_tpu.storage.types import FileId
        async with aiohttp.ClientSession() as http:
            for _ in range(6):
                async with http.post(
                        f"http://{new_leader.url}/dir/assign") as resp:
                    body = await resp.json()
                    assert "fid" in body, body
                    issued.append(body["fid"])
        keys = [(f.volume_id, f.key)
                for f in map(FileId.parse, issued)]
        assert len(set(keys)) == len(keys), f"duplicate fid: {issued}"
    finally:
        if vs:
            await vs.stop()
        for m in masters:
            await m.stop()


def test_vote_state_survives_restart(tmp_path):
    """A restarted master must not grant a second vote in a term it
    already voted in (durable term/votedFor, raft_server.go:60-76)."""
    from seaweedfs_tpu.master.election import Election

    path = str(tmp_path / "raft_state.json")
    peers = ["a:1", "b:2", "c:3"]
    e1 = Election("a:1", peers, state_path=path)
    r = e1.on_vote_request(term=5, candidate="b:2", max_volume_id=10)
    assert r["granted"] and e1.term == 5
    # durability rides flush() — the RPC handler awaits it before the
    # reply leaves the node (the fsync itself runs on the executor)
    asyncio.run(e1.flush())

    # crash + restart: state reloads from disk
    e2 = Election("a:1", peers, state_path=path)
    assert e2.term == 5
    assert e2.voted_for == "b:2"
    # a competing candidate in the SAME term must be refused
    r = e2.on_vote_request(term=5, candidate="c:3", max_volume_id=10)
    assert not r["granted"]
    # re-voting for the same candidate stays idempotent
    r = e2.on_vote_request(term=5, candidate="b:2", max_volume_id=10)
    assert r["granted"]
    # a HIGHER term resets votedFor and persists the new term
    r = e2.on_vote_request(term=6, candidate="c:3", max_volume_id=10)
    assert r["granted"]
    asyncio.run(e2.flush())
    e3 = Election("a:1", peers, state_path=path)
    assert e3.term == 6 and e3.voted_for == "c:3"


def test_stale_snapshot_still_persists_term_bump(tmp_path):
    """ADVICE round 5: on_install_snapshot adopted a higher term but
    only persisted when the snapshot was actually installed — a STALE
    snapshot (last_index <= local) lost the bump on restart, so the
    node could vote twice in that term after a crash."""
    from seaweedfs_tpu.master.election import Election

    path = str(tmp_path / "raft_state.json")
    peers = ["a:1", "b:2", "c:3"]
    e1 = Election("a:1", peers, state_path=path)
    # local log already ahead of the snapshot's last_index
    e1.on_append(term=3, leader="b:2", prev_index=0, prev_term=0,
                 entries=[{"term": 3, "cmd": {"max_volume_id": 7}}],
                 leader_commit=1)
    assert e1.last_index() == 1
    r = e1.on_install_snapshot(term=9, leader="c:3", last_index=0,
                               last_term=0, value=0)
    assert r["ok"] and e1.term == 9
    asyncio.run(e1.flush())   # what h_raft_snapshot awaits pre-reply
    # crash + restart: the term bump must have been durable
    e2 = Election("a:1", peers, state_path=path)
    assert e2.term == 9
    r = e2.on_vote_request(term=9, candidate="b:2", max_volume_id=0,
                           last_log_index=5, last_log_term=3)
    # whatever the vote outcome, the term must not have regressed
    assert e2.term == 9


def test_corrupt_election_state_is_fatal(tmp_path):
    from seaweedfs_tpu.master.election import Election

    path = str(tmp_path / "raft_state.json")
    with open(path, "w") as f:
        f.write("{not json")
    try:
        Election("a:1", ["a:1", "b:2"], state_path=path)
        raise AssertionError("corrupt state silently ignored")
    except SystemExit:
        pass
