"""JAX + Pallas encoder vs CPU oracle (runs on the 8-device CPU platform)."""

import itertools

import numpy as np
import pytest

from seaweedfs_tpu.ec import gf
from seaweedfs_tpu.ec.encoder_cpu import CpuEncoder
from seaweedfs_tpu.ec.encoder_jax import JaxEncoder


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(7)


def test_jax_encode_matches_cpu(rng):
    cpu, tpu = CpuEncoder(), JaxEncoder(use_pallas=False)
    data = rng.integers(0, 256, (10, 1024)).astype(np.uint8)
    want = cpu.encode([d for d in data])
    got = np.asarray(tpu.encode(data))
    assert got.shape == (14, 1024)
    for i in range(14):
        assert np.array_equal(got[i], want[i]), f"shard {i}"


def test_jax_encode_batched(rng):
    tpu = JaxEncoder(use_pallas=False)
    cpu = CpuEncoder()
    batch = rng.integers(0, 256, (3, 10, 256)).astype(np.uint8)
    got = np.asarray(tpu.encode(batch))
    assert got.shape == (3, 14, 256)
    for b in range(3):
        want = cpu.encode([d for d in batch[b]])
        for i in range(14):
            assert np.array_equal(got[b, i], want[i])


def test_jax_reconstruct_subsets(rng):
    tpu = JaxEncoder(use_pallas=False)
    cpu = CpuEncoder()
    shards = cpu.encode([rng.integers(0, 256, 128).astype(np.uint8)
                         for _ in range(10)])
    # a representative set of loss patterns incl. worst case (all parity lost
    # is trivial; all-4-losses-in-data is the hard one)
    for missing in [(0,), (13,), (0, 1, 2, 3), (10, 11, 12, 13),
                    (0, 5, 11, 13)]:
        partial = [None if i in missing else shards[i] for i in range(14)]
        out = tpu.reconstruct(partial)
        for i in range(14):
            assert np.array_equal(out[i], shards[i]), (missing, i)


def test_jax_verify(rng):
    tpu = JaxEncoder(use_pallas=False)
    data = rng.integers(0, 256, (10, 64)).astype(np.uint8)
    full = np.array(tpu.encode(data))
    assert tpu.verify(full)
    full[11, 3] ^= 0x40
    assert not tpu.verify(full)


def test_pallas_interpret_matches_cpu(rng):
    """Pallas kernel in interpreter mode (CPU) vs oracle, incl. padding."""
    from seaweedfs_tpu.ops.gf256_pallas import gf256_matmul_pallas

    cpu = CpuEncoder()
    coeff = gf.parity_matrix()
    consts = gf.bitplane_constants(coeff)
    # n deliberately not a multiple of the 128KB block quantum
    n = 1000
    data = rng.integers(0, 256, (10, n)).astype(np.uint8)
    got = np.asarray(gf256_matmul_pallas(consts, data, block_bm=8,
                                         interpret=True))
    want = cpu.encode([d for d in data])[10:]
    assert got.shape == (4, n)
    for p in range(4):
        assert np.array_equal(got[p], want[p]), f"parity {p}"


def test_pallas_interpret_reconstruct_coeff(rng):
    from seaweedfs_tpu.ops.gf256_pallas import gf256_matmul_pallas

    cpu = CpuEncoder()
    shards = cpu.encode([rng.integers(0, 256, 512).astype(np.uint8)
                         for _ in range(10)])
    present = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10]  # shard 0 lost, use parity 10
    coeff = gf.shard_rows([0], present)
    consts = gf.bitplane_constants(coeff)
    stacked = np.stack([shards[i] for i in present])
    got = np.asarray(gf256_matmul_pallas(consts, stacked, block_bm=8,
                                         interpret=True))
    assert np.array_equal(got[0], shards[0])


def test_stacked_transform_matches_oracle():
    """gf256_stacked_transform: the (B, k, wm, 128) single-ref batch
    kernel (the mesh path's workhorse) against the CPU oracle, including
    a wm that forces the gcd block-size fallback."""
    import jax
    from seaweedfs_tpu.ec.encoder_cpu import CpuEncoder
    from seaweedfs_tpu.ops.gf256_pallas import (gf256_stacked_transform,
                                                u8_to_words, words_to_u8)

    rng = np.random.default_rng(9)
    cpu = CpuEncoder(use_native=False)
    for b, n in ((1, 512), (3, 5 * 512), (2, 3 * 512)):
        data = rng.integers(0, 256, (b, 10, n)).astype(np.uint8)
        x = u8_to_words(jax.numpy.asarray(data))
        out = words_to_u8(gf256_stacked_transform(
            gf.bitplane_constants(gf.parity_matrix()), x, block_bm=2))
        got = np.asarray(out)
        for v in range(b):
            want = cpu.encode(list(data[v]))[10:]
            for p in range(4):
                assert np.array_equal(got[v, p], want[p]), (b, n, v, p)
