"""Structured event journal (util/events.py) and its emit sites:
breaker transitions, retry-budget exhaustion, EC holder refresh —
the state transitions /debug/health correlates into violation
evidence."""

from __future__ import annotations

import asyncio

import pytest

from seaweedfs_tpu.util import events, tracing
from seaweedfs_tpu.util.resilience import (BreakerRegistry, CircuitBreaker,
                                           RetryBudget, RetryPolicy)


@pytest.fixture(autouse=True)
def _fresh_ring():
    events.init(ring=1024)
    events.reset()
    yield
    events.reset()


def test_record_and_query():
    events.record("volume_mount", vid=3, kind="mount")
    events.record("volume_unmount", vid=3, kind="unmount")
    out = events.events_dict()
    assert out["recorded"] == 2
    assert [e["type"] for e in out["events"]] == \
        ["volume_unmount", "volume_mount"]   # newest first
    e = out["events"][1]
    assert e["vid"] == 3 and e["wall_ms"] > 0 and "mono" in e
    # type filter + since_ms floor
    only = events.events_dict(types={"volume_mount"})
    assert [e["type"] for e in only["events"]] == ["volume_mount"]
    assert events.events_dict(
        since_ms=e["wall_ms"] + 10 ** 9)["events"] == []


def test_ring_is_bounded():
    events.init(ring=16)
    for i in range(100):
        events.record("volume_mount", vid=i)
    out = events.events_dict(n=1000)
    assert len(out["events"]) == 16
    assert out["recorded"] == 100
    assert out["events"][0]["vid"] == 99    # newest survives


def test_query_rows_are_copies_not_the_live_ring():
    # aggregators stamp worker tags on what events_dict hands out
    # (volume_server._merged_events); a caller mutation must never
    # rewrite the journal every later surface reads (regression: the
    # first merged /debug/events query permanently tagged every ring
    # row with that worker's index)
    events.record("volume_mount", vid=7)
    out = events.events_dict()
    out["events"][0]["worker"] = 3
    again = events.events_dict()
    assert "worker" not in again["events"][0]


def test_unknown_type_recorded_with_warning():
    events.record("definitely_not_a_type", x=1)
    assert events.events_dict()["events"][0]["type"] == \
        "definitely_not_a_type"


def test_trace_id_stamped_inside_span():
    tracing.init(sample=1.0)
    with tracing.start_root("volume", "read") as sp:
        events.record("holder_refresh", vid=1)
    events.record("holder_refresh", vid=2)
    rows = events.events_dict()["events"]
    assert rows[1]["trace"] == sp.trace     # inside the span
    assert rows[0]["trace"] == ""           # outside


def test_window_correlation():
    events.record("breaker_open", upstream="a")
    rows = events.events_dict()["events"]
    wall = rows[0]["wall_ms"]
    assert events.window(wall - 1, wall + 1) == rows
    assert events.window(wall - 1, wall + 1,
                         types={"scrub_corruption"}) == []


def test_merge_payloads_orders_on_wall():
    events.record("volume_mount", vid=1)
    p1 = events.events_dict()
    for r in p1["events"]:
        r["worker"] = 0
    events.record("volume_mount", vid=2)
    p2 = events.events_dict(types={"volume_mount"})
    merged = events.merge_payloads([p1, p2], n=10)
    vids = [e["vid"] for e in merged["events"]]
    assert vids[0] == 2                     # newest first across rings
    assert merged["recorded"] == p1["recorded"] + p2["recorded"]


def test_events_query_parses_and_raises():
    events.record("volume_mount", vid=1)
    out = events.events_query({"n": "5", "type": "volume_mount"})
    assert len(out["events"]) == 1
    with pytest.raises(ValueError):
        events.events_query({"n": "zz"})


# ---------------------------------------------------------------------------
# emit sites


def test_breaker_transitions_journaled():
    clock = [0.0]
    br = CircuitBreaker(threshold=2, reset_timeout=1.0,
                        clock=lambda: clock[0], name="vol:8080")
    br.record_failure()
    assert events.events_dict()["events"] == []     # not yet open
    br.record_failure()
    rows = events.events_dict()["events"]
    assert rows[0]["type"] == "breaker_open"
    assert rows[0]["upstream"] == "vol:8080"
    assert rows[0]["failures"] == 2
    clock[0] = 2.0
    assert br.allow()                               # half-open probe
    br.record_success()
    rows = events.events_dict()["events"]
    assert rows[0]["type"] == "breaker_close"
    assert rows[0]["upstream"] == "vol:8080"
    # a healthy success journals nothing
    br.record_success()
    assert events.events_dict()["events"][0]["type"] == "breaker_close"


def test_breaker_registry_names_breakers():
    reg = BreakerRegistry(threshold=1)
    b = reg.get("10.0.0.1:8080")
    assert b.name == "10.0.0.1:8080"
    b.record_failure()
    assert events.events_dict()["events"][0]["upstream"] == \
        "10.0.0.1:8080"


def test_retry_budget_exhaustion_journaled():
    budget = RetryBudget(ratio=0.0, burst=0.0)      # always empty
    policy = RetryPolicy(max_attempts=3, base_delay=0.0,
                         budget=budget, name="client.read",
                         sleep=lambda _t: asyncio.sleep(0))

    async def drive():
        attempts = 0
        async for _ in policy.attempts():
            attempts += 1
        return attempts

    assert asyncio.run(drive()) == 1                # no retry allowed
    rows = events.events_dict()["events"]
    assert rows[0]["type"] == "retry_budget_exhausted"
    assert rows[0]["name"] == "client.read"


def test_holder_refresh_journaled_and_rate_bounded():
    from seaweedfs_tpu.server.ec_locations import EcLocationCache
    clock = [100.0]
    cache = EcLocationCache(lambda vid: {"0": ["a:1"]},
                            now=lambda: clock[0])
    cache.get(7)
    assert cache.invalidate(7) is True              # forced -> journaled
    assert cache.invalidate(7) is False             # suppressed window
    rows = events.events_dict(types={"holder_refresh"})["events"]
    assert len(rows) == 1 and rows[0]["vid"] == 7
    clock[0] += EcLocationCache.FRESH_S + 1
    assert cache.invalidate(7) is True
    assert len(events.events_dict(
        types={"holder_refresh"})["events"]) == 2


def test_record_never_raises(monkeypatch):
    # an emit site inside a breaker transition must survive a broken
    # metrics layer
    monkeypatch.setattr(events, "_count",
                        lambda t: (_ for _ in ()).throw(RuntimeError()))
    events.record("breaker_open", upstream="x")     # must not raise
    assert events.events_dict()["events"][0]["type"] == "breaker_open"
