"""util/failpoints.py: spec grammar, arming/expiry, env loading, and
the live /debug/failpoints admin endpoint + injected faults end-to-end
against an in-proc cluster."""

import random

import pytest

from seaweedfs_tpu.util import failpoints as fp

from cluster_util import Cluster, run


@pytest.fixture(autouse=True)
def _clean_registry():
    fp.reset()
    yield
    fp.reset()


# ---- spec grammar ----

def test_parse_spec_forms():
    a = fp.parse_spec("s", "error")
    assert (a.action, a.arg, a.count, a.prob) == ("error", "", 1, 1.0)
    a = fp.parse_spec("s", "error=503:3")
    assert (a.action, a.arg, a.count) == ("error", "503", 3)
    a = fp.parse_spec("s", "latency=250")
    assert (a.action, a.arg) == ("latency", "250")
    a = fp.parse_spec("s", "drop:*")
    assert a.count == -1
    a = fp.parse_spec("s", "truncate=0.25@0.5")
    assert (a.action, a.arg, a.prob) == ("truncate", "0.25", 0.5)
    # probabilistic sites default to unlimited count
    assert fp.parse_spec("s", "error@0.05").count == -1
    # ...unless a count is explicit
    assert fp.parse_spec("s", "error:2@0.5").count == 2
    a = fp.parse_spec("s", "flip=3:2")
    assert (a.action, a.arg, a.count) == ("flip", "3", 2)


def test_parse_spec_rejects_garbage():
    for bad in ("explode", "error@1.5", "error@0", "truncate=2",
                "latency=abc", "error=xyz", "flip=0", "flip=-1"):
        with pytest.raises(ValueError):
            fp.parse_spec("s", bad)


def test_arm_take_expiry_and_counting():
    fp.arm("x", "error:2")
    assert fp.pending("x")
    assert fp.take("x").action == "error"
    assert fp.take("x") is not None
    assert fp.take("x") is None          # expired after 2 fires
    assert not fp.pending("x")


def test_probability_respects_rng():
    fp.arm("p", "error@0.5")
    fp._rng = random.Random(7)
    fired = sum(fp.take("p") is not None for _ in range(400))
    assert 120 < fired < 280             # ~200 expected
    assert fp.pending("p")               # unlimited count


def test_sync_fail_and_exception_lineage():
    fp.arm("e", "error=503")
    with pytest.raises(fp.FailpointError) as ei:
        fp.sync_fail("e")
    assert isinstance(ei.value, OSError)
    assert ei.value.status == 503
    fp.arm("d", "drop")
    with pytest.raises(fp.FailpointDrop) as ei:
        fp.sync_fail("d")
    assert isinstance(ei.value, ConnectionResetError)


def test_corrupt_truncates_payload():
    fp.arm("t", "truncate=0.25")
    assert fp.corrupt("t", b"x" * 100) == b"x" * 25
    assert fp.corrupt("t", b"x" * 100) == b"x" * 100  # expired


def test_corrupt_flips_payload_silently():
    """`flip` is bit-rot: same length, corrupt prefix — what the EC
    scrubber (ec/scrub.py) must catch without a foreground error."""
    fp.arm("f", "flip")
    out = fp.corrupt("f", b"\x0f" * 4)
    assert out == b"\xf0" + b"\x0f" * 3 and len(out) == 4
    fp.arm("f2", "flip=100")             # clamps to payload length
    assert fp.corrupt("f2", b"\x00" * 3) == b"\xff" * 3


def test_disarmed_is_free_and_noop():
    assert not fp.armed()
    fp.sync_fail("whatever")             # must not raise
    assert fp.corrupt("whatever", b"ok") == b"ok"
    assert fp.take("whatever") is None


def test_load_env():
    n = fp.load_env("a=error:2, b=latency=10@0.5 ,")
    assert n == 2
    assert fp.pending("a") and fp.pending("b")
    with pytest.raises(ValueError):
        fp.load_env("justasite")
    with pytest.raises(ValueError):
        fp.load_env("a=unknownaction")


# ---- live admin endpoint + injection end-to-end ----

def test_debug_endpoint_and_injected_read_errors(tmp_path):
    async def go():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            a = await c.assign()
            st, _ = await c.put(a["fid"], a["url"], b"payload")
            assert st == 201
            vs = c.servers[0]
            base = f"http://{vs.url}"

            # arm over the wire: one injected read error
            async with c.http.post(
                    f"{base}/debug/failpoints",
                    params={"site": "store.read",
                            "spec": "error=503:1"}) as r:
                assert r.status == 200
                body = await r.json()
                assert body["armed"][0]["site"] == "store.read"

            # first read eats the injected fault (armed status honored)
            st, _ = await c.get(a["fid"], a["url"])
            assert st == 503
            # ...second succeeds (count expired)
            st, data = await c.get(a["fid"], a["url"])
            assert (st, data) == (200, b"payload")

            # list shows the hit; registry is empty again
            async with c.http.get(f"{base}/debug/failpoints") as r:
                assert (await r.json())["failpoints"] == []

            # DELETE disarms
            async with c.http.post(
                    f"{base}/debug/failpoints",
                    params={"site": "store.read", "spec": "error"}) as r:
                assert r.status == 200
            async with c.http.delete(f"{base}/debug/failpoints") as r:
                assert (await r.json())["disarmed"] == 1
            st, _ = await c.get(a["fid"], a["url"])
            assert st == 200
    run(go())


def test_injected_write_error_is_not_acked(tmp_path):
    async def go():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            a = await c.assign()
            fp.arm("store.write", "error:1")
            st, _ = await c.put(a["fid"], a["url"], b"data")
            assert st >= 500                  # injected: NOT acknowledged
            st, _ = await c.get(a["fid"], a["url"])
            assert st == 404                  # and really not stored
            st, _ = await c.put(a["fid"], a["url"], b"data")
            assert st == 201
    run(go())


def test_master_assign_failpoint(tmp_path):
    async def go():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            fp.arm("master.assign", "error:1")
            body = await c.assign()
            assert "error" in body
            body = await c.assign()
            assert "fid" in body
    run(go())
