"""Wire-level tests of the needle fast path (server/fasthttp.py).

Raw sockets, no HTTP client library: these pin the hand-rolled parser's
behaviors — keep-alive sequencing, pipelined requests, the in-place
upgrade to aiohttp for cold requests (and BACK-comparison that both
paths serve identical bytes), mid-request replay upgrade (needle with
pairs), whitelist 401 on the fast write path, ts/ttl query handling."""

from __future__ import annotations

import asyncio
import json

from cluster_util import Cluster, run


async def _raw(host: str, port: int, payload: bytes,
               expect_responses: int, timeout: float = 8.0) -> bytes:
    r, w = await asyncio.open_connection(host, port)
    w.write(payload)
    await w.drain()
    out = b""
    got = 0
    try:
        while got < expect_responses:
            # wait_for, not asyncio.timeout: 3.10 compatibility
            chunk = await asyncio.wait_for(r.read(65536), timeout)
            if not chunk:
                break
            out += chunk
            got = out.count(b"HTTP/1.1 ")
    finally:
        w.close()
    return out


def _req(method: str, path: str, host: str, body: bytes = b"",
         extra: str = "") -> bytes:
    head = (f"{method} {path} HTTP/1.1\r\nHost: {host}\r\n"
            + (f"Content-Length: {len(body)}\r\n" if body or
               method in ("POST", "PUT") else "")
            + extra + "\r\n")
    return head.encode() + body


def test_fast_path_wire_behaviors(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            a = await c.assign()
            vs = c.servers[0]
            host = f"127.0.0.1:{vs.port}"
            fid = a["fid"]

            # 1. fast POST (raw body) then TWO pipelined GETs on one
            # connection — all three served by the fast protocol
            data = b"wire-level needle"
            blob = (_req("POST", f"/{fid}", host, data)
                    + _req("GET", f"/{fid}", host)
                    + _req("GET", f"/{fid}", host))
            out = await _raw("127.0.0.1", vs.port, blob, 3)
            assert out.count(b"HTTP/1.1 201 ") == 1
            assert out.count(b"HTTP/1.1 200 ") == 2
            assert out.count(data) == 2
            assert b'"eTag"' in out

            # 2. ts query param on the fast write path (a 2009 ts with a
            # TTL would read back expired, so ts is tested alone)
            a2 = await c.assign()
            blob = _req("POST", f"/{a2['fid']}?ts=1234567890",
                        host, b"ts-needle")
            out = await _raw("127.0.0.1", vs.port, blob, 1)
            assert b"201" in out.split(b"\r\n", 1)[0]
            n = vs.store.read_needle(
                int(a2["fid"].split(",")[0]),
                int(a2["fid"].split(",")[1][:-8], 16))
            assert n.last_modified == 1234567890
            # ...and ttl= flows into the stored needle
            a2b = await c.assign(ttl="5m")
            blob = _req("POST", f"/{a2b['fid']}?ttl=5m", host, b"ttlset")
            out = await _raw("127.0.0.1", vs.port, blob, 1)
            assert b"201" in out.split(b"\r\n", 1)[0]
            from seaweedfs_tpu.storage import types as t
            n2 = vs.store.read_needle(
                int(a2b["fid"].split(",")[0]),
                int(a2b["fid"].split(",")[1][:-8], 16))
            assert n2.ttl == t.TTL.parse("5m")

            # 3. cold GET (Range header) upgrades in place and still
            # answers on the SAME connection, then a fast GET after the
            # upgrade keeps working through aiohttp
            blob = (_req("GET", f"/{fid}", host,
                         extra="Range: bytes=5-9\r\n")
                    + _req("GET", f"/{fid}", host))
            out = await _raw("127.0.0.1", vs.port, blob, 2)
            assert b"HTTP/1.1 206 " in out
            assert b"level" in out          # bytes 5-9 of the payload
            assert out.count(b"HTTP/1.1 200 ") == 1

            # 4. mid-request replay upgrade: a needle with pairs headers
            # must come back with its pair headers via the full handler
            a3 = await c.assign()
            async with c.http.post(
                    f"http://{a3['url']}/{a3['fid']}", data=b"paired",
                    headers={"Seaweed-Flavor": "umami"}) as resp:
                assert resp.status == 201
            out = await _raw("127.0.0.1", vs.port,
                             _req("GET", f"/{a3['fid']}", host), 1)
            assert b"Seaweed-Flavor: umami" in out
            assert b"paired" in out

            # 5. whitelist 401 applies on the fast write path — and its
            # declared Content-Length matches the body EXACTLY, so a
            # keep-alive client can fire a pipelined request right after
            # without blocking on a phantom byte (ADVICE round 5: the
            # header said 33 for a 32-byte body)
            from seaweedfs_tpu.security.guard import Guard
            vs.guard = Guard(["10.9.9.9"])
            out = await _raw("127.0.0.1", vs.port,
                             _req("POST", f"/{fid}", host, b"x")
                             + _req("POST", f"/{fid}", host, b"y"), 2)
            assert out.count(b"401") >= 2
            hdr, rest = out.split(b"\r\n\r\n", 1)
            declared = int(hdr.lower().split(b"content-length: ")[1]
                           .split(b"\r\n")[0])
            body_1 = rest.split(b"HTTP/1.1", 1)[0]
            assert len(body_1) == declared == \
                len(b'{"error": "ip not in whitelist"}')
            vs.guard = Guard(())

            # 5b. a handler that dies before answering must CLOSE the
            # connection instead of wedging it busy forever (the
            # create_task done-callback); later connections still work
            real_count = vs.count
            vs.count = lambda *a: (_ for _ in ()).throw(
                RuntimeError("boom"))
            try:
                r2, w2 = await asyncio.open_connection(
                    "127.0.0.1", vs.port)
                w2.write(_req("GET", f"/{fid}", host))
                await w2.drain()
                eof = await asyncio.wait_for(r2.read(), 8)
                assert eof == b""       # closed, not hung
                w2.close()
            finally:
                vs.count = real_count
            out = await _raw("127.0.0.1", vs.port,
                             _req("GET", f"/{fid}", host), 1)
            assert out.startswith(b"HTTP/1.1 200 ")

            # 6. 404 for a missing needle stays on the fast path
            missing = fid.split(",")[0] + ",ffffffffdeadbeef"
            out = await _raw("127.0.0.1", vs.port,
                             _req("GET", f"/{missing}", host), 1)
            assert out.startswith(b"HTTP/1.1 404 ")

    run(body())


def test_fast_assign_wire(tmp_path):
    async def body():
        async with Cluster(str(tmp_path), n_servers=1) as c:
            host = c.master.url
            port = int(host.split(":")[1])
            # fast /dir/assign straight off the socket, twice pipelined
            blob = (_req("GET", "/dir/assign", host)
                    + _req("GET", "/dir/assign?count=3", host))
            out = await _raw("127.0.0.1", port, blob, 2)
            bodies = [json.loads(part.split(b"\r\n\r\n", 1)[1]
                                 .split(b"HTTP/1.1", 1)[0])
                      for part in out.split(b"HTTP/1.1 200 OK")[1:]]
            assert len(bodies) == 2
            assert all("fid" in b for b in bodies)
            assert bodies[1]["count"] == 3
            # distinct file keys
            assert bodies[0]["fid"] != bodies[1]["fid"]
            # a cold master route upgrades on the same connection
            out = await _raw("127.0.0.1", port,
                             _req("GET", "/dir/status", host), 1)
            assert out.startswith(b"HTTP/1.1 200 ")

    run(body())
