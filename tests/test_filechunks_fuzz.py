"""Seeded randomized check of the chunk-overlay algebra against a
byte-wise oracle.

filechunks.py resolves overlapping chunk writes by mtime
(filechunks.go:121-222 NonOverlappingVisibleIntervals); a bug here
silently corrupts every filer read. The oracle paints (chunk id,
within-chunk offset) onto a byte canvas in mtime order and compares the
winner per byte with the intervals and ranged views the library
produces, across 300 random overlap patterns.
"""

from __future__ import annotations

import random

from seaweedfs_tpu.filer.filechunks import (FileChunk,
                                            non_overlapping_visible_intervals,
                                            view_from_chunks)


def _paint(chunks, size):
    canvas = [None] * size
    for c in sorted(chunks, key=lambda c: c.mtime):
        for b in range(c.offset, min(c.offset + c.size, size)):
            # (which chunk, which byte OF that chunk) — position matters:
            # an interval pointing at the right chunk but the wrong
            # chunk_offset still serves garbage
            canvas[b] = (c.file_id, b - c.offset)
    return canvas


def test_overlay_matches_bytewise_oracle():
    rng = random.Random(1234)
    for case in range(300):
        chunks = []
        for i in range(rng.randint(1, 12)):
            off = rng.randint(0, 400)
            size = rng.randint(1, 200)
            chunks.append(FileChunk(
                file_id=f"c{case}_{i}", offset=off, size=size,
                mtime=i + 1))  # strictly increasing like real overwrites
        total = max(c.offset + c.size for c in chunks)
        canvas = _paint(chunks, total)

        visibles = non_overlapping_visible_intervals(chunks)
        pos = 0
        got = [None] * total
        for v in visibles:
            assert 0 <= v.start < v.stop, (case, v)
            assert v.start >= pos, f"case {case}: unsorted/overlapping"
            pos = v.stop
            for b in range(v.start, v.stop):
                got[b] = (v.file_id, v.chunk_offset + (b - v.start))
        assert got == canvas, f"case {case}: overlay diverges from oracle"

        # ranged views must agree with the same oracle slice
        for _ in range(5):
            off = rng.randint(0, total - 1)
            ln = rng.randint(1, total - off)
            view = [None] * ln
            for cv in view_from_chunks(chunks, off, ln):
                assert off <= cv.logic_offset \
                    and cv.logic_offset + cv.size <= off + ln, (case, cv)
                for j in range(cv.size):
                    view[cv.logic_offset - off + j] = \
                        (cv.file_id, cv.offset + j)
            assert view == canvas[off:off + ln], \
                f"case {case}: ranged view diverges at [{off},{off+ln})"
